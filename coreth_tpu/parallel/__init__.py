"""Multi-chip scaling: meshes, shardings, collective replay.

Reference analog (SURVEY.md section 2.9): the reference's distributed
backend is gRPC + AppRequest/Gossip on the host; compute-side scaling in
the TPU build rides jax.sharding over ICI — the replay batch shards over
the ``dp`` mesh axis, account state shards over the same devices, and
per-account reductions cross shards with psum_scatter.
"""

from coreth_tpu.parallel.mesh import (  # noqa: F401
    _shard_map,
    collective_reduce,
    make_mesh,
    sharded_recover,
    sharded_slot_step,
    sharded_transfer_step,
)
from coreth_tpu.parallel.shard import (  # noqa: F401
    account_bucket,
    contract_bucket,
    exchange_mode,
    remap_rows,
    slot_bucket,
)
