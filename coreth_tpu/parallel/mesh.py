"""Device-mesh sharded replay step.

The single-chip batched transfer step (replay/engine.py) generalizes to a
mesh by sharding BOTH the tx batch and the account-state rows over one
``dp`` axis:

- each device computes full-width per-account totals from its local tx
  shard (segment-sum into the global account range);
- one ``psum_scatter`` over ``dp`` reduces the partial totals AND leaves
  them sharded by account row — the collective rides ICI, and its output
  layout matches the local balance shard exactly (no all-gather);
- validation flags combine with a scalar ``psum``.

This is the sharding recipe the scaling-book prescribes: annotate,
reduce-scatter into the layout you need next, never materialize the full
array.
"""

from __future__ import annotations

import inspect
from functools import partial

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from coreth_tpu.ops import u256

# `check_vma` landed well after the shard_map API stabilized; the
# installed JAX may predate it (ROADMAP open item: 3 tier-1 failures on
# older runtimes).  Passing it unconditionally would TypeError at
# module import, so feature-detect once and drop the kwarg when absent.
_SHARD_MAP_KWARGS = frozenset(
    inspect.signature(shard_map).parameters)


def _shard_map(fn, **kwargs):
    if "check_vma" not in _SHARD_MAP_KWARGS:
        v = kwargs.pop("check_vma", None)
        if v is not None and "check_rep" in _SHARD_MAP_KWARGS:
            # older jax spells the same knob check_rep; without the
            # translation a body containing lax.while_loop trips "No
            # replication rule for while"
            kwargs["check_rep"] = v
    return shard_map(fn, **kwargs)


def make_mesh(devices=None, axis: str = "dp") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    import numpy as np
    return Mesh(np.array(devices), (axis,))


def collective_reduce(x, axis: str, n_dev: int, mode: str = "psum",
                      op: str = "add"):
    """All-reduce `x` over the named mesh axis, as either ONE fused
    collective (``mode="psum"``: lax.psum / lax.pmax) or a RING of
    ``n_dev - 1`` point-to-point ``ppermute`` steps each device
    accumulates locally (``mode="ppermute"``).

    The ring moves the same payload as the all-reduce but as
    neighbor-to-neighbor sends — on real ICI the latency win for SMALL
    tensors (the sparse cross-shard exchange sets this repo ships) over
    the full all-reduce tree.  Every value reduced here is an int32
    add or max: associative + commutative, so both modes produce
    BIT-IDENTICAL results on every device (the exchange-equivalence
    tests pin this; do not reduce floats through the ring)."""
    if op not in ("add", "max"):
        raise ValueError(f"collective_reduce: unknown op {op!r}")
    if mode == "psum" or n_dev <= 1:
        if op == "add":
            return jax.lax.psum(x, axis)
        return jax.lax.pmax(x, axis)
    # ring all-reduce: rotate the payload one hop per step; after
    # n_dev - 1 steps every device has accumulated every shard's
    # contribution (in rotation order — exact for integer add/max)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    acc = x
    for _ in range(n_dev - 1):
        x = jax.tree_util.tree_map(
            lambda a: jax.lax.ppermute(a, axis, perm), x)
        acc = jax.tree_util.tree_map(
            (lambda a, b: a + b) if op == "add" else jnp.maximum,
            acc, x)
    return acc


def sharded_transfer_step(mesh: Mesh, num_accounts: int):
    """Build the mesh-sharded transfer step.

    Shapes (global): balances [A, 16], nonces [A], tx arrays [B, ...];
    A and B must divide by the mesh size.  Returns a jitted function
    (balances, nonces, sender_idx, recip_idx, value16, fee16, required16,
    tx_nonce, nonce_offset, mask) -> (new_balances, new_nonces, ok).

    Nonce-sequence validation is computed against gathered nonce rows for
    the local tx shard (an all_gather of one i32 row — cheap vs the limb
    traffic saved by psum_scatter on the totals).
    """
    n_dev = mesh.devices.size
    assert num_accounts % n_dev == 0

    def step(balances, nonces, sender_idx, recip_idx, value16, fee16,
             required16, tx_nonce, nonce_offset, mask, coinbase_idx):
        # local shards: balances [A/d, 16], tx arrays [B/d, ...]
        mask_i = mask.astype(jnp.int32)
        debit = u256.add(value16, fee16) * mask_i[:, None]
        required = required16 * mask_i[:, None]
        credit = value16 * mask_i[:, None]
        # full-width partial totals from the local tx shard
        debit_part = jax.ops.segment_sum(debit, sender_idx,
                                         num_segments=num_accounts)
        req_part = jax.ops.segment_sum(required, sender_idx,
                                       num_segments=num_accounts)
        credit_part = jax.ops.segment_sum(credit, recip_idx,
                                          num_segments=num_accounts)
        # tx fees accrue to the coinbase (state_transition.go:443)
        fee_local = jnp.sum(fee16 * mask_i[:, None], axis=0)
        credit_part = credit_part.at[coinbase_idx].add(fee_local)
        counts_part = jax.ops.segment_sum(mask_i, sender_idx,
                                          num_segments=num_accounts)
        # reduce across devices, scattering rows back onto the account
        # sharding (ICI collective; output [A/d, 16])
        debit_tot = u256.normalize(
            jax.lax.psum_scatter(debit_part, "dp", scatter_dimension=0,
                                 tiled=True))
        req_tot = u256.normalize(
            jax.lax.psum_scatter(req_part, "dp", scatter_dimension=0,
                                 tiled=True))
        credit_tot = u256.normalize(
            jax.lax.psum_scatter(credit_part, "dp", scatter_dimension=0,
                                 tiled=True))
        counts = jax.lax.psum_scatter(counts_part, "dp",
                                      scatter_dimension=0, tiled=True)
        # nonce check needs the global nonce row for local txs
        all_nonces = jax.lax.all_gather(nonces, "dp", tiled=True)
        expected = all_nonces[sender_idx] + nonce_offset
        nonce_ok = jnp.all(jnp.where(mask, tx_nonce == expected, True))
        solvent = u256.gte(balances, req_tot)
        ok_local = nonce_ok & jnp.all(solvent | (counts == 0))
        ok = jax.lax.psum(ok_local.astype(jnp.int32), "dp") == n_dev
        new_balances = u256.sub(u256.add(balances, credit_tot), debit_tot)
        new_nonces = nonces + counts
        return new_balances, new_nonces, ok

    spec_acc2 = PS("dp", None)
    spec_acc1 = PS("dp")
    spec_tx2 = PS("dp", None)
    spec_tx1 = PS("dp")
    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(spec_acc2, spec_acc1, spec_tx1, spec_tx1, spec_tx2,
                  spec_tx2, spec_tx2, spec_tx1, spec_tx1, spec_tx1, PS()),
        out_specs=(spec_acc2, spec_acc1, PS()),
        # psum_scatter/all_gather produce the vma the specs declare;
        # tracking adds nothing on these reduction-shaped bodies
        check_vma=False)
    return jax.jit(sharded)


def sharded_slot_step(mesh: Mesh, num_slots: int):
    """Mesh-sharded ERC-20 slot step: slot values sharded over dp, tx
    shards compute full-width partial debit/credit segment sums,
    psum_scatter reduces them back onto the slot sharding (the same
    annotate -> reduce-scatter recipe as the account step)."""
    n_dev = mesh.devices.size
    assert num_slots % n_dev == 0

    def step(slot_vals, from_slot, to_slot, amount16, mask):
        mask_i = mask.astype(jnp.int32)
        amt = amount16 * mask_i[:, None]
        debit_part = jax.ops.segment_sum(amt, from_slot,
                                         num_segments=num_slots)
        credit_part = jax.ops.segment_sum(amt, to_slot,
                                          num_segments=num_slots)
        debit_tot = u256.normalize(
            jax.lax.psum_scatter(debit_part, "dp", scatter_dimension=0,
                                 tiled=True))
        credit_tot = u256.normalize(
            jax.lax.psum_scatter(credit_part, "dp", scatter_dimension=0,
                                 tiled=True))
        solvent = u256.gte(slot_vals, debit_tot)
        ok = jax.lax.psum(jnp.all(solvent).astype(jnp.int32),
                          "dp") == n_dev
        new_vals = u256.sub(u256.add(slot_vals, credit_tot), debit_tot)
        return new_vals, ok

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(PS("dp", None), PS("dp"), PS("dp"), PS("dp", None),
                  PS("dp")),
        out_specs=(PS("dp", None), PS()),
        check_vma=False)
    return jax.jit(sharded)


def sharded_recover(mesh: Mesh):
    """Mesh-sharded batched ECDSA recovery: the signature batch shards
    over dp and every device runs the Shamir-ladder kernel on its
    shard (the sender_cacher fan-out, here across chips instead of
    goroutines — embarrassingly parallel, no collectives)."""
    from coreth_tpu.ops.secp import recover_kernel

    def step(x_bytes, parity, u1w, u2w):
        # pin dtypes: shard_map re-traces per shard and weak-typed
        # inputs would break the ladder's int32 carry scan
        return recover_kernel.__wrapped__(
            x_bytes.astype(jnp.uint8), parity.astype(jnp.int32),
            u1w.astype(jnp.int32), u2w.astype(jnp.int32))

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(PS("dp", None), PS("dp"), PS("dp", None),
                  PS("dp", None)),
        out_specs=PS("dp", None),
        # the ladder's internal scans build unvarying carries; this is
        # a per-shard elementwise kernel, so vma tracking adds nothing
        check_vma=False)
    return jax.jit(sharded)
