"""Shard-placement helpers for device-sharded replay state.

One definition of "which shard owns this state row" shared by every
sharded table in the system (DeviceState account/slot rows, the OCC
machine runner's per-shard slot tables, the windowed transfer kernel):

- **accounts** bucket by the first byte of keccak(address) — the same
  hash the secure trie keys by, so placement is uniform even for
  adversarially sequential addresses;
- **contracts** bucket the same way (one contract's storage lives
  wholly on one shard — the Reddio-style partition that makes machine
  lanes shard-local by construction, since a device-eligible tx touches
  exactly one contract's storage).

Rows are allocated shard-major: shard ``s`` owns rows
``[s*arena, (s+1)*arena)`` of a table with ``n_shards`` uniform arenas,
matching a ``PartitionSpec("dp")`` block sharding of the table, so a
device can translate a global row to its local row with one subtract.

Everything here is consensus-critical (bucket placement feeds the
packed effect exchange whose sums must be bit-identical at every mesh
width) and deliberately allocation-order-free: the bucket depends only
on the address, never on discovery order.
"""

from __future__ import annotations

import os


def account_bucket(addr_hash: bytes, n_shards: int) -> int:
    """Owning shard of an account row, from keccak256(address)."""
    if n_shards <= 1:
        return 0
    return addr_hash[0] % n_shards


def contract_bucket(addr_hash: bytes, n_shards: int) -> int:
    """Owning shard of a contract's storage (same rule as accounts —
    kept separate so a future asymmetric placement changes one line)."""
    return account_bucket(addr_hash, n_shards)


def slot_bucket(key_hash: bytes, n_shards: int) -> int:
    """Owning shard of ONE storage slot under KEY-RANGE placement
    (keccak256 of the raw 32-byte slot key): the intra-contract
    partition for HOT contracts (ISSUE 14 / the FAFO ceiling) — one
    contract's storage spreads over every shard instead of landing
    wholesale on ``contract_bucket``.  Hashing the key (rather than
    using ``key[0]`` directly) keeps PUSH-constant slots (0, 1, ...)
    as uniform as keccak-derived mapping keys."""
    if n_shards <= 1:
        return 0
    return key_hash[0] % n_shards


def remap_rows(rows, old_arena: int, new_arena: int):
    """Row ids after an arena doubling: shard-major layout means every
    row moves to ``shard*new_arena + local`` (shard = row//old_arena,
    local = row % old_arena)."""
    return [(r // old_arena) * new_arena + (r % old_arena)
            for r in rows]


def exchange_mode(touched: int, total: int, n_shards: int) -> str:
    """Which collective carries a window's cross-shard exchange:
    ``"psum"`` (one all-reduce of the packed effect tensor — the PR-8
    shape) or ``"ppermute"`` (a ring of n-1 point-to-point permutes
    accumulating the same integer sums — cheaper on real ICI when the
    touched cross-shard set is small relative to the table).  Integer
    adds/maxes are associative and commutative, so BOTH modes produce
    bit-identical tensors at every mesh width (pinned by the
    equivalence tests); the choice is performance-only and
    deterministic: CORETH_EXCHANGE=psum|ppermute forces it (the A/B
    override), otherwise density = touched/total under
    CORETH_EXCHANGE_DENSITY (default 0.25) selects ppermute for the
    sparse common case."""
    if n_shards <= 1:
        return "psum"
    forced = os.environ.get("CORETH_EXCHANGE", "")
    if forced in ("psum", "ppermute"):
        return forced
    thresh = float(  # noqa: DET002 — selects between BIT-IDENTICAL collectives (performance only); no consensus value flows through it
        os.environ.get("CORETH_EXCHANGE_DENSITY", "0.25"))
    return "ppermute" if touched <= thresh * max(1, total) else "psum"
