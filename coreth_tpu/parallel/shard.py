"""Shard-placement helpers for device-sharded replay state.

One definition of "which shard owns this state row" shared by every
sharded table in the system (DeviceState account/slot rows, the OCC
machine runner's per-shard slot tables, the windowed transfer kernel):

- **accounts** bucket by the first byte of keccak(address) — the same
  hash the secure trie keys by, so placement is uniform even for
  adversarially sequential addresses;
- **contracts** bucket the same way (one contract's storage lives
  wholly on one shard — the Reddio-style partition that makes machine
  lanes shard-local by construction, since a device-eligible tx touches
  exactly one contract's storage).

Rows are allocated shard-major: shard ``s`` owns rows
``[s*arena, (s+1)*arena)`` of a table with ``n_shards`` uniform arenas,
matching a ``PartitionSpec("dp")`` block sharding of the table, so a
device can translate a global row to its local row with one subtract.

Everything here is consensus-critical (bucket placement feeds the
packed effect exchange whose sums must be bit-identical at every mesh
width) and deliberately allocation-order-free: the bucket depends only
on the address, never on discovery order.
"""

from __future__ import annotations


def account_bucket(addr_hash: bytes, n_shards: int) -> int:
    """Owning shard of an account row, from keccak256(address)."""
    if n_shards <= 1:
        return 0
    return addr_hash[0] % n_shards


def contract_bucket(addr_hash: bytes, n_shards: int) -> int:
    """Owning shard of a contract's storage (same rule as accounts —
    kept separate so a future asymmetric placement changes one line)."""
    return account_bucket(addr_hash, n_shards)


def remap_rows(rows, old_arena: int, new_arena: int):
    """Row ids after an arena doubling: shard-major layout means every
    row moves to ``shard*new_arena + local`` (shard = row//old_arena,
    local = row % old_arena)."""
    return [(r // old_arena) * new_arena + (r % old_arena)
            for r in rows]
