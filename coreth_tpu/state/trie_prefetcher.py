"""Concurrent trie-path prefetcher.

Twin of reference core/state/trie_prefetcher.go (:47 triePrefetcher,
:73 newTriePrefetcher, :208 prefetch, :275 subfetcher): while a block
executes, warm the trie paths its hashing phase will touch so
``intermediate_root`` hits pre-pulled nodes instead of cold storage.

Architecture mapping: the shared cache being warmed is the Database's
node store — ``rawdb.PersistentNodeDict`` pulls node RLP from the KV
store into its in-memory dict on first resolve — so subfetchers can
run on *private* Trie instances (the reference's db.CopyTrie trick,
trie_prefetcher.go:302) and still benefit the StateDB's own tries.
Prefetching is therefore only scheduled when the backing node store is
KV-backed; a fully memory-resident Database has nothing to warm (this
host design keeps every node byte in dicts — the latency the reference
hides behind goroutines does not exist here, which is also why one
worker thread suffices on the 1-core eval host).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Tuple

from coreth_tpu.mpt.trie import Trie


class TriePrefetcher:
    """Schedules (trie-root, hashed-key) path warms onto a worker.

    prefetch() never blocks; close() drains the queue, stops the
    worker, and reports how many keys were resolved vs deduplicated
    (the reference's fetch/skip metrics, trie_prefetcher.go:110-140).
    """

    def __init__(self, node_db):
        self.node_db = node_db
        self._queue: "queue.Queue[Optional[Tuple[bytes, bytes]]]" = \
            queue.Queue()
        self._seen: set = set()
        self._tries: Dict[bytes, Trie] = {}
        # corethlint: shared single-writer counter — only the warm worker increments it; drain() joins the queue before the caller reads it
        self.loaded = 0
        self.duped = 0
        # exactly one worker: Trie instances mutate while resolving,
        # so sharing _tries across workers would need per-root locking
        # the 1-core eval host could never profit from
        self._workers = [threading.Thread(target=self._run, daemon=True,
                                          name="trie-prefetch")]
        for w in self._workers:
            w.start()

    def prefetch(self, root: bytes, keys: List[bytes]) -> None:
        """Schedule hashed keys for path-warming under [root]."""
        for key in keys:
            token = (root, key)
            if token in self._seen:
                self.duped += 1
                continue
            self._seen.add(token)
            self._queue.put(token)

    def _run(self) -> None:
        while True:
            token = self._queue.get()
            if token is None:
                self._queue.task_done()
                return
            root, key = token
            try:
                trie = self._tries.get(root)
                if trie is None:
                    trie = Trie(root_hash=root, db=self.node_db)
                    self._tries[root] = trie
                trie.get(key)  # resolves the path, pulling KV nodes
                self.loaded += 1
            except Exception:  # noqa: BLE001 — missing/partial tries are fine; warming is best-effort
                pass
            finally:
                self._queue.task_done()

    def drain(self) -> dict:
        """Block until every scheduled warm resolved; reset per-block
        state so the instance is reusable across inserts (the
        reference allocates one prefetcher per block — we keep one
        worker alive per chain because thread spin-up per block costs
        more than it hides on this host)."""
        self._queue.join()
        self._seen.clear()
        self._tries.clear()
        return {"loaded": self.loaded, "duped": self.duped}

    def close(self) -> dict:
        """Drain + stop the workers; returns {loaded, duped}."""
        stats = self.drain()
        for _ in self._workers:
            self._queue.put(None)
        for w in self._workers:
            w.join()
        return stats
