"""Journaled world state.

Semantic twin of reference core/state/statedb.go + state_object.go +
journal.go:

- every mutation appends an undo thunk to the journal; ``snapshot()`` /
  ``revert_to_snapshot()`` replay undos (journal.go revert semantics);
- ``finalise(delete_empty)`` moves per-tx dirty storage into the pending
  set, deletes suicided/empty accounts, clears journal+refund
  (statedb.go:945);
- ``intermediate_root()`` pushes pending storage into storage tries,
  re-encodes dirty accounts into the account trie and returns the root
  (statedb.go:994);
- multicoin balances live in the account storage trie under coin-IDs with
  bit 0 of byte 0 set; normal state keys have that bit cleared
  (state_object.go:548-563 NormalizeCoinID/NormalizeStateKey);
- access list (EIP-2929), transient storage (EIP-1153), refunds, logs and
  predicate storage slots all journal-revert correctly.

Same-tx destruct+resurrect: unreachable through the EVM — a CREATE2
onto an address self-destructed earlier in the same tx fails the
address-collision check (the account keeps its code until the tx-end
Finalise), which matches geth; the destructed account's state stays
readable until tx end and is deleted at Finalise (both geth-matching,
pinned by tests/test_statetests.py).  Cross-tx destruct+resurrect
creates a fresh object with wiped storage.  Callers driving the
StateDB API directly (not through the EVM) should use create_account
for resurrection, which also wipes storage.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from coreth_tpu import rlp
from coreth_tpu.crypto import keccak256
from coreth_tpu.mpt import SecureTrie, EMPTY_ROOT
from coreth_tpu.state.database import Database
from coreth_tpu.types.account import EMPTY_CODE_HASH, StateAccount
from coreth_tpu.types.receipt import Log

HASH_ZERO = b"\x00" * 32


def normalize_coin_id(coin_id: bytes) -> bytes:
    """OR bit 0 of byte 0 — multicoin storage partition."""
    return bytes([coin_id[0] | 0x01]) + coin_id[1:]


def normalize_state_key(key: bytes) -> bytes:
    """AND-out bit 0 of byte 0 — normal storage partition."""
    return bytes([key[0] & 0xFE]) + key[1:]


class StateObject:
    __slots__ = ("address", "account", "code", "origin_storage",
                 "dirty_storage", "pending_storage", "written_storage",
                 "suicided", "deleted", "dirty_code", "fresh",
                 "initial_root")

    def __init__(self, address: bytes, account: StateAccount,
                 fresh: bool) -> None:
        self.address = address
        self.account = account
        self.code: Optional[bytes] = None
        # committed (trie) values cache; authoritative when fresh
        self.origin_storage: Dict[bytes, bytes] = {}
        # writes inside the currently-executing tx
        self.dirty_storage: Dict[bytes, bytes] = {}
        # finalised writes from earlier txs in this block
        self.pending_storage: Dict[bytes, bytes] = {}
        # every slot actually written over the object's lifetime (the
        # snapshot diff feed — origin_storage also caches pure reads)
        self.written_storage: Dict[bytes, bytes] = {}
        self.suicided = False
        self.deleted = False
        self.dirty_code = False
        self.fresh = fresh  # created in this block — no backing trie
        self.initial_root = EMPTY_ROOT if fresh else account.root

    def empty(self) -> bool:
        return (self.account.nonce == 0 and self.account.balance == 0
                and self.account.code_hash == EMPTY_CODE_HASH
                and not self.account.is_multi_coin)


class StateDB:
    def __init__(self, root: bytes, db: Optional[Database] = None,
                 snap=None, flat=None):
        """snap: optional snapshot layer (state.snapshot DiskLayer/
        DiffLayer) — O(1) account/storage reads that bypass the trie
        (the snapshot read-path acceleration, statedb.go:147 New with
        snaps).  flat: optional flat-state view (state.flat
        FlatStateView, duck-typed) — same role, raw-keyed, consulted
        BEFORE snap/trie and back-filled on trie fallthrough; its
        ``check`` flag arms the differential oracle (every flat hit
        re-derived from the trie).  The trie stays authoritative for
        hashing."""
        self.db = db if db is not None else Database()
        self.original_root = root
        self.snap = snap
        self.flat = flat
        # optional TriePrefetcher warming paths during execution
        # (StartPrefetcher, blockchain.go:1319)
        self.prefetcher = None
        self._trie = self.db.open_trie(root)
        self._objects: Dict[bytes, StateObject] = {}
        self._destructed: Set[bytes] = set()
        self._pending: Set[bytes] = set()
        # addresses that ever went dirty (survives commit clearing
        # _pending — the snapshot diff feed)
        self._mutated: Set[bytes] = set()
        self._journal: List = []  # (undo_fn, dirty_addr | None)
        self._dirty_counts: Dict[bytes, int] = {}
        self.refund = 0
        self.logs: List[Log] = []
        self._tx_hash = HASH_ZERO
        self._tx_index = 0
        self.created_this_tx: Set[bytes] = set()
        self._log_index = 0
        self.access_list_addresses: Set[bytes] = set()
        self.access_list_slots: Set[Tuple[bytes, bytes]] = set()
        self.transient: Dict[Tuple[bytes, bytes], bytes] = {}
        self.predicate_storage_slots: Dict[bytes, List[bytes]] = {}
        self._storage_tries: Dict[bytes, SecureTrie] = {}
        # monotone counter bumped on every mutation that can change
        # what a (contract, slot) or code resolution returns (storage
        # writes, deploys, journal reverts, suicides).  The hostexec
        # bridge compares it across txs to keep its native session's
        # committed-storage cache alive within a block and invalidate
        # it the moment an interpreter-path tx moves state under it.
        self.storage_gen = 0
        # companion counter for ACCOUNT-SHAPE changes storage_gen cannot
        # see: existence/emptiness transitions (object creation, balance
        # or nonce crossing zero, deploys, suicides, EIP-158 deletions,
        # journal reverts).  A pure balance transfer that creates an
        # account bumps this but not storage_gen — the hostexec bridge
        # keeps its cached EOA verdicts alive across txs only while
        # BOTH generations hold (PR-4 follow-up: EOA-verdict
        # invalidation without the per-tx re-resolution).
        self.account_gen = 0

    # ------------------------------------------------------------- journal
    def _append_journal(self, undo, addr: Optional[bytes] = None) -> None:
        self._journal.append((undo, addr))
        if addr is not None:
            self._dirty_counts[addr] = self._dirty_counts.get(addr, 0) + 1

    def snapshot(self) -> int:
        return len(self._journal)

    def revert_to_snapshot(self, snap: int) -> None:
        if snap > len(self._journal) or snap < 0:
            raise ValueError(f"invalid snapshot id {snap} "
                             f"(journal length {len(self._journal)})")
        if len(self._journal) > snap:
            self.storage_gen += 1  # undone writes may reappear changed
            self.account_gen += 1  # undone creations/balances too
        while len(self._journal) > snap:
            undo, addr = self._journal.pop()
            undo()
            if addr is not None:
                self._dirty_counts[addr] -= 1
                if self._dirty_counts[addr] == 0:
                    del self._dirty_counts[addr]

    # ------------------------------------------------------------- objects
    def _load_account(self, addr: bytes) -> Optional[StateAccount]:
        fl = self.flat
        if fl is not None:
            v = fl.account_state(addr)
            if v is not None:
                account = None if v is fl.DELETED else v
                if fl.check:
                    data = self._trie.get(addr)
                    want = StateAccount.from_rlp(data) \
                        if data is not None else None
                    if (want is None) != (account is None) or (
                            want is not None
                            and want.rlp() != account.rlp()):
                        from coreth_tpu.obs import recorder as _fr
                        _fr.note_trigger(
                            _fr.TR_FLAT,
                            "flat oracle divergence (statedb account)",
                            tx_index=self._tx_index, contract=addr,
                            got=account, want=want)
                        raise ValueError(
                            f"flat oracle divergence (statedb "
                            f"account) at {addr.hex()}: "
                            f"flat={account!r} trie={want!r}")
                return account
        if self.snap is not None:
            data = self.snap.account(keccak256(addr))
        else:
            data = self._trie.get(addr)
        if data is None:
            if fl is not None:
                fl.fill_account(addr, None)
            return None
        account = StateAccount.from_rlp(data)
        if fl is not None:
            fl.fill_account(addr, account)
        return account

    def _get_object(self, addr: bytes) -> Optional[StateObject]:
        obj = self._objects.get(addr)
        if obj is not None:
            return None if obj.deleted else obj
        account = self._load_account(addr)
        if account is None:
            return None
        obj = StateObject(addr, account, fresh=False)
        self._objects[addr] = obj
        return obj

    def _get_or_new_object(self, addr: bytes) -> StateObject:
        obj = self._get_object(addr)
        if obj is None:
            obj = self._create_object(addr)
        return obj

    def _create_object(self, addr: bytes) -> StateObject:
        prev = self._objects.get(addr)
        prev_trie = self._storage_tries.pop(addr, None)
        obj = StateObject(addr, StateAccount(), fresh=True)
        self._objects[addr] = obj

        def undo():
            if prev is not None:
                self._objects[addr] = prev
            else:
                self._objects.pop(addr, None)
            if prev_trie is not None:
                self._storage_tries[addr] = prev_trie
            else:
                self._storage_tries.pop(addr, None)

        self._append_journal(undo, addr)
        self.account_gen += 1  # a fresh object changes existence
        return obj

    def create_account(self, addr: bytes) -> None:
        """Explicit account creation; preserves balance (statedb.go:744)."""
        prev = self._get_object(addr)
        obj = self._create_object(addr)
        if prev is not None:
            obj.account.balance = prev.account.balance

    def exist(self, addr: bytes) -> bool:
        return self._get_object(addr) is not None

    def empty(self, addr: bytes) -> bool:
        obj = self._get_object(addr)
        return obj is None or obj.empty()

    # ------------------------------------------------------------- balance
    def get_balance(self, addr: bytes) -> int:
        obj = self._get_object(addr)
        return obj.account.balance if obj else 0

    def add_balance(self, addr: bytes, amount: int) -> None:
        obj = self._get_or_new_object(addr)
        if amount == 0:
            # touch: journal dirtiness so empty accounts die at finalise
            self._append_journal(lambda: None, addr)
            return
        self._set_balance(obj, obj.account.balance + amount)

    def sub_balance(self, addr: bytes, amount: int) -> None:
        if amount == 0:
            obj = self._get_object(addr)
            if obj is not None:
                self._append_journal(lambda: None, addr)
            return
        obj = self._get_or_new_object(addr)
        self._set_balance(obj, obj.account.balance - amount)

    def set_balance(self, addr: bytes, amount: int) -> None:
        self._set_balance(self._get_or_new_object(addr), amount)

    def _set_balance(self, obj: StateObject, amount: int) -> None:
        prev = obj.account.balance

        def undo():
            obj.account.balance = prev

        self._append_journal(undo, obj.address)
        if prev == 0 or amount == 0:
            # emptiness may flip (EIP-158): EOA verdicts go stale
            self.account_gen += 1
        obj.account.balance = amount

    # ----------------------------------------------------------- multicoin
    def get_balance_multi_coin(self, addr: bytes, coin_id: bytes) -> int:
        return int.from_bytes(
            self.get_state(addr, normalize_coin_id(coin_id),
                           _normalize=False), "big")

    def add_balance_multi_coin(self, addr: bytes, coin_id: bytes,
                               amount: int) -> None:
        if amount == 0:
            self.add_balance(addr, 0)  # touch
            return
        self.set_balance_multi_coin(
            addr, coin_id,
            self.get_balance_multi_coin(addr, coin_id) + amount)

    def sub_balance_multi_coin(self, addr: bytes, coin_id: bytes,
                               amount: int) -> None:
        if amount == 0:
            return
        self.set_balance_multi_coin(
            addr, coin_id,
            self.get_balance_multi_coin(addr, coin_id) - amount)

    def set_balance_multi_coin(self, addr: bytes, coin_id: bytes,
                               amount: int) -> None:
        obj = self._get_or_new_object(addr)
        if not obj.account.is_multi_coin:
            prev_flag = obj.account.is_multi_coin

            def undo():
                obj.account.is_multi_coin = prev_flag

            self._append_journal(undo, addr)
            obj.account.is_multi_coin = True
        self._set_state(obj, normalize_coin_id(coin_id),
                        amount.to_bytes(32, "big"))

    # --------------------------------------------------------------- nonce
    def get_nonce(self, addr: bytes) -> int:
        obj = self._get_object(addr)
        return obj.account.nonce if obj else 0

    def set_nonce(self, addr: bytes, nonce: int) -> None:
        obj = self._get_or_new_object(addr)
        prev = obj.account.nonce

        def undo():
            obj.account.nonce = prev

        self._append_journal(undo, addr)
        if prev == 0 or nonce == 0:
            self.account_gen += 1  # emptiness may flip
        obj.account.nonce = nonce

    # ---------------------------------------------------------------- code
    def get_code(self, addr: bytes) -> bytes:
        obj = self._get_object(addr)
        if obj is None:
            return b""
        if obj.code is None:
            obj.code = self.db.contract_code(obj.account.code_hash)
        return obj.code

    def get_code_hash(self, addr: bytes) -> bytes:
        obj = self._get_object(addr)
        return obj.account.code_hash if obj else HASH_ZERO

    def get_code_size(self, addr: bytes) -> int:
        return len(self.get_code(addr))

    def set_code(self, addr: bytes, code: bytes) -> None:
        obj = self._get_or_new_object(addr)
        prev_code, prev_hash = obj.code, obj.account.code_hash

        def undo():
            obj.code, obj.account.code_hash = prev_code, prev_hash
            obj.dirty_code = False

        self._append_journal(undo, addr)
        self.storage_gen += 1  # a deploy changes code resolution
        self.account_gen += 1  # ... and the account's kind
        obj.code = code
        obj.account.code_hash = keccak256(code)
        obj.dirty_code = True

    # ------------------------------------------------------------- storage
    def _origin_value(self, obj: StateObject, key: bytes) -> bytes:
        if key in obj.origin_storage:
            return obj.origin_storage[key]
        fl = self.flat
        if obj.fresh:
            value = HASH_ZERO
        elif fl is not None \
                and (v := fl.storage_value(obj.address, key)) is not None:
            value = v.to_bytes(32, "big")
            if fl.check:
                trie = self._open_storage_trie(obj)
                raw = trie.get(key)
                want = rlp.decode(raw).rjust(32, b"\x00") \
                    if raw is not None else HASH_ZERO
                if want != value:
                    from coreth_tpu.obs import recorder as _fr
                    _fr.note_trigger(
                        _fr.TR_FLAT,
                        "flat oracle divergence (statedb slot)",
                        tx_index=self._tx_index,
                        contract=obj.address, key=key,
                        got=value.hex(), want=want.hex(),
                        pre_value=want)
                    raise ValueError(
                        f"flat oracle divergence (statedb slot) at "
                        f"{obj.address.hex()}/{key.hex()}: "
                        f"flat={value.hex()} trie={want.hex()}")
        elif self.snap is not None:
            raw = self.snap.storage_slot(keccak256(obj.address),
                                         keccak256(key))
            value = rlp.decode(raw).rjust(32, b"\x00") \
                if raw is not None else HASH_ZERO
        else:
            trie = self._open_storage_trie(obj)
            raw = trie.get(key)
            if raw is None:
                value = HASH_ZERO
            else:
                value = rlp.decode(raw).rjust(32, b"\x00")
            if fl is not None:
                fl.fill_storage(obj.address, key,
                                int.from_bytes(value, "big"))
        obj.origin_storage[key] = value
        return value

    def _open_storage_trie(self, obj: StateObject) -> SecureTrie:
        trie = self._storage_tries.get(obj.address)
        if trie is None:
            trie = self.db.open_trie(obj.initial_root)
            self._storage_tries[obj.address] = trie
        return trie

    def get_state(self, addr: bytes, key: bytes, _normalize=True) -> bytes:
        if _normalize:
            key = normalize_state_key(key)
        obj = self._get_object(addr)
        if obj is None:
            return HASH_ZERO
        if key in obj.dirty_storage:
            return obj.dirty_storage[key]
        if key in obj.pending_storage:
            return obj.pending_storage[key]
        return self._origin_value(obj, key)

    def get_committed_state(self, addr: bytes, key: bytes) -> bytes:
        """Pre-tx value: pending else trie (state_object.go
        GetCommittedState).  No key normalization (statedb.go:419)."""
        obj = self._get_object(addr)
        if obj is None:
            return HASH_ZERO
        if key in obj.pending_storage:
            return obj.pending_storage[key]
        return self._origin_value(obj, key)

    def get_committed_state_ap1(self, addr: bytes, key: bytes) -> bytes:
        return self.get_committed_state(addr, normalize_state_key(key))

    def set_state(self, addr: bytes, key: bytes, value: bytes) -> None:
        obj = self._get_or_new_object(addr)
        self._set_state(obj, normalize_state_key(key), value)

    def _set_state(self, obj: StateObject, key: bytes, value: bytes) -> None:
        prev = self.get_state(obj.address, key, _normalize=False)
        if prev == value:
            return
        had_dirty = key in obj.dirty_storage
        prev_dirty = obj.dirty_storage.get(key)

        def undo():
            if had_dirty:
                obj.dirty_storage[key] = prev_dirty
            else:
                obj.dirty_storage.pop(key, None)

        self._append_journal(undo, obj.address)
        self.storage_gen += 1
        obj.dirty_storage[key] = value

    # ----------------------------------------------------------- transient
    def get_transient_state(self, addr: bytes, key: bytes) -> bytes:
        return self.transient.get((addr, key), HASH_ZERO)

    def set_transient_state(self, addr: bytes, key: bytes,
                            value: bytes) -> None:
        prev = self.get_transient_state(addr, key)
        if prev == value:
            return

        def undo():
            if prev == HASH_ZERO:
                self.transient.pop((addr, key), None)
            else:
                self.transient[(addr, key)] = prev

        self._append_journal(undo)
        self.transient[(addr, key)] = value

    # -------------------------------------------------------------- suicide
    def suicide(self, addr: bytes) -> bool:
        obj = self._get_object(addr)
        if obj is None:
            return False
        prev_suicided, prev_balance = obj.suicided, obj.account.balance

        def undo():
            obj.suicided = prev_suicided
            obj.account.balance = prev_balance

        self._append_journal(undo, addr)
        self.storage_gen += 1  # storage of addr vanishes at finalise
        self.account_gen += 1  # existence vanishes at finalise
        obj.suicided = True
        obj.account.balance = 0
        return True

    def has_suicided(self, addr: bytes) -> bool:
        obj = self._get_object(addr)
        return obj.suicided if obj else False

    # -------------------------------------------------------------- refund
    def add_refund(self, amount: int) -> None:
        prev = self.refund

        def undo():
            self.refund = prev

        self._append_journal(undo)
        self.refund += amount

    def sub_refund(self, amount: int) -> None:
        prev = self.refund
        if amount > prev:
            raise ValueError("refund counter below zero")

        def undo():
            self.refund = prev

        self._append_journal(undo)
        self.refund -= amount

    # ---------------------------------------------------------------- logs
    def set_tx_context(self, tx_hash: bytes, tx_index: int) -> None:
        self._tx_hash = tx_hash
        self._tx_index = tx_index
        # per-tx contract-creation marks (EIP-6780: SELFDESTRUCT only
        # deletes contracts created in the same transaction)
        self.created_this_tx = set()

    def mark_created_this_tx(self, addr: bytes) -> None:
        """Journaled EIP-6780 creation mark (geth createObjectChange)."""
        self.created_this_tx.add(addr)

        def undo():
            self.created_this_tx.discard(addr)
        self._append_journal(undo)

    def add_log(self, log: Log) -> None:
        log.tx_hash = self._tx_hash
        log.tx_index = self._tx_index
        log.index = self._log_index

        def undo():
            self.logs.pop()
            self._log_index -= 1

        self._append_journal(undo)
        self.logs.append(log)
        self._log_index += 1

    def get_logs(self) -> List[Log]:
        return list(self.logs)

    def tx_logs(self) -> List[Log]:
        """Logs of the current tx context."""
        return [l for l in self.logs if l.tx_hash == self._tx_hash
                and l.tx_index == self._tx_index]

    # ---------------------------------------------------------- access list
    def add_address_to_access_list(self, addr: bytes) -> None:
        if addr in self.access_list_addresses:
            return

        def undo():
            self.access_list_addresses.discard(addr)

        self._append_journal(undo)
        self.access_list_addresses.add(addr)

    def add_slot_to_access_list(self, addr: bytes, slot: bytes) -> None:
        self.add_address_to_access_list(addr)
        key = (addr, slot)
        if key in self.access_list_slots:
            return

        def undo():
            self.access_list_slots.discard(key)

        self._append_journal(undo)
        self.access_list_slots.add(key)

    def address_in_access_list(self, addr: bytes) -> bool:
        return addr in self.access_list_addresses

    def slot_in_access_list(self, addr: bytes, slot: bytes) -> Tuple[bool, bool]:
        return (addr in self.access_list_addresses,
                (addr, slot) in self.access_list_slots)

    # -------------------------------------------------------------- prepare
    def prepare(self, rules, sender: bytes, coinbase: bytes,
                dst: Optional[bytes], precompiles: List[bytes],
                access_list) -> None:
        """Per-tx setup (statedb.go:1219 Prepare)."""
        if rules.is_apricot_phase2:
            self.access_list_addresses = set()
            self.access_list_slots = set()
            self.access_list_addresses.add(sender)
            if dst is not None:
                self.access_list_addresses.add(dst)
            for p in precompiles:
                self.access_list_addresses.add(p)
            for addr, keys in access_list:
                self.access_list_addresses.add(addr)
                for k in keys:
                    self.access_list_slots.add((addr, k))
            if rules.is_durango:  # EIP-3651 warm coinbase
                self.access_list_addresses.add(coinbase)
            self.predicate_storage_slots = _prepare_predicate_slots(
                rules, access_list)
        self.transient = {}

    def get_predicate_storage_slots(self, addr: bytes):
        return self.predicate_storage_slots.get(addr)

    def set_predicate_storage_slots(self, addr: bytes, slots) -> None:
        self.predicate_storage_slots[addr] = slots

    # ------------------------------------------------------------- finalise
    def finalise(self, delete_empty_objects: bool) -> None:
        for addr in list(self._dirty_counts):
            obj = self._objects.get(addr)
            if obj is None:
                continue
            if obj.suicided or (delete_empty_objects and obj.empty()):
                if not obj.deleted:
                    self.account_gen += 1  # EIP-158 deletion
                obj.deleted = True
                self._destructed.add(addr)
            else:
                obj.pending_storage.update(obj.dirty_storage)
                obj.dirty_storage = {}
            self._pending.add(addr)
            self._mutated.add(addr)
            if self.prefetcher is not None:
                # warm the paths intermediate_root will rewrite
                # (statedb.go Finalise -> prefetcher.prefetch)
                self.prefetcher.prefetch(self.original_root,
                                         [keccak256(addr)])
                if obj.pending_storage and not obj.fresh:
                    self.prefetcher.prefetch(
                        obj.initial_root,
                        [keccak256(k) for k in obj.pending_storage])
        self._journal = []
        self._dirty_counts = {}
        self.refund = 0

    # ----------------------------------------------------------- root/commit
    def intermediate_root(self, delete_empty_objects: bool) -> bytes:
        self.finalise(delete_empty_objects)
        for addr in sorted(self._pending):
            obj = self._objects.get(addr)
            if obj is None:
                continue
            if obj.deleted:
                self._trie.delete(addr)
                continue
            if obj.pending_storage:
                trie = self._open_storage_trie(obj)
                for key, value in obj.pending_storage.items():
                    if value == HASH_ZERO:
                        trie.delete(key)
                    else:
                        trie.update(key, rlp.encode(value.lstrip(b"\x00")))
                    obj.origin_storage[key] = value
                    obj.written_storage[key] = value
                obj.pending_storage = {}
                obj.account.root = trie.hash()
            self._trie.update(addr, obj.account.rlp())
        self._pending.clear()
        return self._trie.hash()

    def commit(self, delete_empty_objects: bool = True) -> bytes:
        """Hash + persist into the backing Database; returns the root."""
        root = self.intermediate_root(delete_empty_objects)
        for addr, strie in self._storage_tries.items():
            obj = self._objects.get(addr)
            if obj is None or obj.deleted:
                continue
            srot = strie.commit()
            self.db.cache_trie(srot, strie)
        self._trie.commit()
        self.db.cache_trie(root, self._trie)
        for obj in self._objects.values():
            if obj.dirty_code and obj.code is not None:
                self.db.write_code(obj.account.code_hash, obj.code)
                obj.dirty_code = False
        return root

    # ---------------------------------------------------------------- copy
    def copy(self) -> "StateDB":
        """Deep copy for speculative execution (statedb.go:809 Copy).

        Dirty accounts carry over (so finalise/intermediate_root on the
        copy see them), but the undo journal does not — its thunks close
        over the original's objects.  snapshot() on the copy starts
        fresh; reverting the copy to a snapshot taken on the original
        raises (revert_to_snapshot validates ids).  geth's Copy has the
        same one-way contract: "Snapshots of the copied state cannot be
        applied to the copy."
        """
        new = StateDB(self.original_root, self.db, snap=self.snap,
                      flat=self.flat)
        new._trie = self._trie.copy()
        new._dirty_counts = dict(self._dirty_counts)
        for addr, obj in self._objects.items():
            cp = StateObject(addr, obj.account.copy(), obj.fresh)
            cp.code = obj.code
            cp.origin_storage = dict(obj.origin_storage)
            cp.dirty_storage = dict(obj.dirty_storage)
            cp.pending_storage = dict(obj.pending_storage)
            cp.written_storage = dict(obj.written_storage)
            cp.suicided = obj.suicided
            cp.deleted = obj.deleted
            cp.dirty_code = obj.dirty_code
            cp.initial_root = obj.initial_root
            new._objects[addr] = cp
        new._destructed = set(self._destructed)
        new._mutated = set(self._mutated)
        new._pending = set(self._pending)
        new.refund = self.refund
        new.logs = [Log(l.address, list(l.topics), l.data, l.block_number,
                        l.tx_hash, l.tx_index, l.block_hash, l.index,
                        l.removed) for l in self.logs]
        new._log_index = self._log_index
        new._tx_hash, new._tx_index = self._tx_hash, self._tx_index
        new.access_list_addresses = set(self.access_list_addresses)
        new.access_list_slots = set(self.access_list_slots)
        new.transient = dict(self.transient)
        new.predicate_storage_slots = dict(self.predicate_storage_slots)
        new._storage_tries = {a: t.copy()
                              for a, t in self._storage_tries.items()}
        return new


def _prepare_predicate_slots(rules, access_list) -> Dict[bytes, List[bytes]]:
    """Collect access-list storage slots addressed to active predicate
    precompiles (reference predicate/predicate_slots.go)."""
    out: Dict[bytes, List[bytes]] = {}
    for addr, keys in access_list:
        if addr in rules.predicaters:
            out.setdefault(addr, []).append(b"".join(keys))
    return out
