"""Asynchronous flat-state layer (Reddio-style execution/storage split).

A flat ``address -> account`` / ``(address, slot) -> value`` store kept
incrementally current from the commit pipeline's already-deduped window
effects, with three jobs (see store.py / exporter.py):

1. **O(1) cold reads** — engine cold reads, device table fills, and
   StateDB resolution hit a dict instead of walking the Merkle trie
   (the reference's ``core/state/snapshot/`` fast path, raw-keyed in
   memory, hash-keyed on disk);
2. **background checkpoints** — the execute thread only stamps a
   generation boundary; a worker thread re-derives the trie from frozen
   diff generations and writes the durable checkpoint record
   (Merkleization fully off the critical path);
3. **reorg-capable rollback** — per-commit-unit generations carry undo
   logs, so a quarantined block can be popped and the engine
   re-converged to the strict-mode root.
"""

from coreth_tpu.state.flat.store import (  # noqa: F401
    DELETED, FlatGeneration, FlatStateView, FlatStore,
    flat_diff_from_statedb,
)
from coreth_tpu.state.flat.exporter import FlatExporter  # noqa: F401
