"""Background checkpoint exporter: Merkleization off the execute thread.

PR 10's CheckpointManager ran the whole durability event on the
execute thread: flush the commit pipeline, export the engine's trie
nodes, fsync, write the record.  This exporter moves everything but
the O(1) generation stamp to a worker thread, the Reddio decoupling
carried to durability:

- it owns SHADOW tries (plain Python mpt over the engine Database's
  node store) seeded at the engine's start root, and re-derives each
  sealed flat generation's state by folding the generation's deduped
  diffs — account trie + per-contract storage tries — verifying the
  resulting root against the generation's recorded (header) root, so
  a divergence between the flat layer and the chain can never become
  a durable checkpoint;
- at a checkpoint marker it commits the shadow nodes into the
  node store, flushes them to the KV log, and only THEN writes the
  flat meta stamp and the checkpoint record — the PR-10 write-order
  argument (record implies full node closure) is preserved verbatim,
  just on this thread;
- it writes each generation's flat entries (hash-keyed, number-
  stamped) as it goes, so the persisted flat base trails the live
  view by at most the queue depth.

Crash consistency: a SIGKILL anywhere leaves the previous record
authoritative (nodes flushed before the record; flat entries newer
than the record are skipped on reload via their number stamps).  The
``checkpoint/crash_gap`` seam fires at the same node-flush/record
boundary as the synchronous path; ``flat/torn_write`` fires between a
generation's flat-entry writes and the meta/record write.
"""

from __future__ import annotations

import os
import threading
import time  # noqa: DET003 — host-side export-thread waits/instrumentation, never consensus data
from typing import Dict, Optional

from coreth_tpu import faults, obs
from coreth_tpu.mpt import EMPTY_ROOT
from coreth_tpu.rawdb import schema
from coreth_tpu.state.flat.store import (
    DELETED, FlatGeneration, FlatStore,
)
from coreth_tpu.types import StateAccount

# the torn-flat-write seam: a crash (or injected error) between a
# generation's flat-entry writes and the meta/record write must leave
# the previous record authoritative; a transient error retries the
# durable step (entry puts are idempotent)
PT_TORN = faults.declare(
    "flat/torn_write",
    "crash window between flat-entry writes and the meta/record write")

# the export queue hands back an already-exported (stale) generation —
# the queue-races-rollback shape; the exporter must detect and skip it
# instead of double-applying diffs to the shadow tries
PT_STALE = faults.declare(
    "flat/stale_generation",
    "export queue hands back an already-exported generation")

# the node-flush/record boundary — the SAME point name replay/
# checkpoint.py declares for the synchronous path (declare() is
# idempotent; naming it here keeps this package below replay in the
# layer map), so one fault plan covers both paths
PT_CRASH_GAP = faults.declare(
    "checkpoint/crash_gap",
    "crash window between trie-node flush and checkpoint-record write")


class ExporterError(Exception):
    pass


# host-side poll cadences for the worker loop / drain spin (wall-clock
# by nature; no consensus data flows through them)
_POLL_S = 0.05        # noqa: DET001 — export-thread poll cadence
_DRAIN_POLL_S = 0.005  # noqa: DET001 — drain spin cadence


class FlatExporter:
    """Drains a FlatStore's sealed generations on a worker thread and
    turns checkpoint markers into durable records."""

    DURABLE_RETRIES = 3

    def __init__(self, flat: FlatStore, db, kv, start_root: bytes,
                 worker: Optional[str] = None):
        self.flat = flat
        self.db = db
        self.kv = kv
        # lane scope for the checkpoint record (cluster workers write
        # ReplayCheckpoint/<lane>); None = the legacy unscoped key
        self.worker = worker
        # shadow account trie + lazily-opened per-contract storage
        # tries over the SAME node store the engine commits into, so
        # the start root's closure is readable.  The fold itself runs
        # through the selected trie backend (CORETH_TRIE): native C++
        # tries shrink the export cost (and thus the stream-shutdown
        # drain tail) the same ~4.5x they bought the commit pipeline;
        # CORETH_TRIE=py keeps the pure-Python twin, and
        # CORETH_TRIE_CHECK=1 re-derives every shadow root on it.
        from coreth_tpu.mpt import native_trie
        self._backend = native_trie.backend()
        self._check = native_trie.trie_check_armed()
        self.trie = self._open_shadow(start_root)
        self.storage_tries: Dict[bytes, object] = {}
        self.on_record = None     # callback(gen) after a record lands
        self.error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ---- counters (bench flat_state: export cost vs stamp cost)
        # mutated by the export worker, read by snapshot()/drain() on
        # the caller's thread — every write holds _mu
        self._mu = threading.Lock()
        self.exports = 0
        self.records = 0
        self.stale_skips = 0
        self.entries_written = 0
        self.export_ns = 0        # worker wall time applying+writing

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.flat.attach_exporter()
        self._thread = threading.Thread(
            target=self._loop, name="flat-exporter", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)

    def drain(self, timeout_s: int = 60) -> None:
        """Block until every sealed generation is exported (the
        synchronous tail of a stream: the final checkpoint).  Raises
        the exporter's error, if any."""
        deadline = time.monotonic_ns() \
            + timeout_s * 1_000_000_000  # noqa: DET003 — drain wall-clock deadline, host-side only
        while not self.flat.drained():
            if self.error is not None:
                raise ExporterError(
                    "flat exporter failed") from self.error
            if time.monotonic_ns() > deadline:  # noqa: DET003 — drain wall-clock deadline, host-side only
                raise ExporterError("flat exporter drain timed out")
            time.sleep(_DRAIN_POLL_S)  # noqa: DET003 — drain spin wait, host-side only
        if self.error is not None:
            raise ExporterError("flat exporter failed") from self.error

    # --------------------------------------------------------------- loop
    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.error is not None:
                time.sleep(_POLL_S)  # noqa: DET003 — failed-exporter idle wait, host-side only
                continue
            gen = self.flat.next_for_export(_POLL_S)
            if gen is None:
                continue
            if gen.exported or gen.rolled_back:
                # a stale handout (the flat/stale_generation shape):
                # double-applying its diffs would corrupt the shadow
                # tries — detect by flag and skip
                with self._mu:
                    self.stale_skips += 1
                continue
            t0 = time.monotonic_ns()  # noqa: DET003 — export-cost instrumentation, host-side only
            try:
                self._export(gen)
            except BaseException as exc:  # noqa: BLE001 — a wedged exporter must not kill the stream; drain()/stamp surfaces the error
                with self._mu:
                    self.error = exc
            finally:
                dt = time.monotonic_ns() - t0  # noqa: DET003 — export-cost instrumentation, host-side only
                with self._mu:
                    self.export_ns += dt

    # ------------------------------------------------------------- export
    def _open_shadow(self, root: bytes):
        """A shadow trie at `root` through the selected backend: the
        python mpt reads the node closure, and under CORETH_TRIE=native
        the fold/rehash work moves to the C++ trie seeded from it."""
        base = self.db.open_trie(root)
        if self._backend != "native":
            return base
        from coreth_tpu.mpt.native_trie import (
            CheckedSecureTrie, NativeSecureTrie)
        if self._check:
            return CheckedSecureTrie(base)
        return NativeSecureTrie.from_python_trie(base)

    def _commit_shadow(self, trie) -> None:
        """Persist a shadow trie's hashed nodes into the node store
        (the python trie commits in place; the native trie exports)."""
        if self._backend == "native":
            trie.commit_into(self.db.node_db)
        else:
            trie.commit()

    def _storage_trie(self, addr: bytes):
        st = self.storage_tries.get(addr)
        if st is None:
            raw = self.trie.get(addr)
            root = StateAccount.from_rlp(raw).root if raw is not None \
                else EMPTY_ROOT
            st = self._open_shadow(root)
            self.storage_tries[addr] = st
        return st

    def _apply(self, gen: FlatGeneration) -> None:
        """Fold one generation's diffs into the shadow tries and verify
        the root — the background Merkleization."""
        from coreth_tpu import rlp
        for addr in gen.destructs:
            # the pre-destruct storage is dead wholesale (even on
            # destruct+re-create); later slot writes repopulate
            self.storage_tries[addr] = self._open_shadow(EMPTY_ROOT)
        by_contract: Dict[bytes, list] = {}
        for (addr, key) in sorted(gen.storage):
            by_contract.setdefault(addr, []).append(key)
        for addr, keys in by_contract.items():
            st = self._storage_trie(addr)
            for key in keys:
                v = gen.storage[(addr, key)]
                if v == 0:
                    st.delete(key)
                else:
                    st.update(key, rlp.encode(
                        v.to_bytes(32, "big").lstrip(b"\x00")))
        for addr in sorted(gen.accounts):
            v = gen.accounts[addr]
            if v is DELETED:
                self.trie.delete(addr)
                self.storage_tries.pop(addr, None)
                continue
            balance, nonce, root, code_hash, multicoin = v
            st = self.storage_tries.get(addr)
            if st is not None and st.hash() != root:
                raise ExporterError(
                    f"shadow storage root diverged for "
                    f"{addr.hex()} at block {gen.number}")
            self.trie.update(addr, StateAccount(
                nonce=nonce, balance=balance, root=root,
                code_hash=code_hash, is_multi_coin=multicoin).rlp())
        got = self.trie.hash()
        if got != gen.root:
            raise ExporterError(
                f"shadow state root diverged at block {gen.number}: "
                f"{got.hex()} != {gen.root.hex()}")

    def _durable(self, gen: FlatGeneration) -> None:
        """The write-ordered durability step (retryable: every write
        is an idempotent put)."""
        written = self.flat.write_gen_entries(self.kv, gen)
        with self._mu:
            self.entries_written += written
        faults.fire(PT_TORN)
        if gen.checkpoint:
            # nodes first — the record-implies-closure invariant
            self._commit_shadow(self.trie)
            for st in self.storage_tries.values():
                self._commit_shadow(st)
            node_db = self.db.node_db
            if hasattr(node_db, "flush"):
                node_db.flush()
            self.kv.flush()
            faults.fire(PT_CRASH_GAP)
            schema.write_flat_meta(self.kv, gen.number, gen.root)
            schema.write_replay_checkpoint(
                self.kv, gen.number, gen.block_hash, gen.root,
                gen.header.encode(), worker=self.worker)
            self.kv.flush()
            with self._mu:
                self.records += 1
            if self.on_record is not None:
                self.on_record(gen)

    def _export(self, gen: FlatGeneration) -> None:
        # flow id = block number: the block's trace arrow continues
        # from the execute thread onto this worker's timeline row
        with obs.span("flat/export", flow=gen.number,
                      checkpoint=bool(gen.checkpoint)):
            self._apply(gen)
            for attempt in range(self.DURABLE_RETRIES):
                try:
                    self._durable(gen)
                    break
                except faults.FaultInjected:
                    if attempt == self.DURABLE_RETRIES - 1:
                        raise
                    continue
            self.flat.mark_exported(gen)
            with self._mu:
                self.exports += 1

    # ------------------------------------------------------------ report
    def snapshot(self) -> dict:
        return {
            "backend": self._backend,
            "exports": self.exports,
            "records": self.records,
            "stale_skips": self.stale_skips,
            "entries_written": self.entries_written,
            "export_ms": self.export_ns // 1_000_000,
            "failed": self.error is not None,
        }
