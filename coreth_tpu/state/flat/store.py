"""Flat state store: O(1) reads, generational diffs, rollback.

The in-memory shape is two dicts — ``accounts[addr]`` and
``storage[addr][slot_key]`` — so a cold read is a hash lookup instead
of a Merkle-trie walk (the reference's ``core/state/snapshot/`` role).
Keys are RAW addresses/slot keys in memory: every producer (the commit
pipeline's deduped window effects, the host fallback's StateDB diff)
and every consumer (engine cold reads, device table fills, StateDB
resolution) already speaks raw keys, so no keccak is ever paid on the
read path.  The PERSISTED base is hash-keyed (``fa ++ keccak(addr)`` /
``fs ++ keccak(addr) ++ slot``, rawdb/schema.py) with the address
preimage in the value — the hashing happens on the background export
thread, never on the execute thread.

Three value classes per key:

- a **generation diff** — authoritative, written by a commit unit
  (one flushed window, or one host-fallback block) with an undo entry
  captured at apply time;
- a **cold-read fill** — a read-through cache entry recorded when a
  consumer fell through to the trie; safe to store in the live dicts
  because a fill can only happen for a key NO generation since base
  has written (otherwise the read would have hit), so its value is
  base-era and survives any rollback;
- ``DELETED`` — known-absent (an account the trie does not contain),
  so existence checks are O(1) too.

Generations are the rollback and export unit.  ``apply_generation``
captures per-key undo; ``rollback_last`` pops the newest generation
and restores the pre-block flat view (the engine separately reopens
its tries at the generation's ``prev_root``).  The background exporter
(exporter.py) drains sealed generations in order; a generation from a
quarantined block is applied with ``hold=True`` and the exporter stops
in front of it until a later commit accepts the chain past it (or the
stream drains) — so rollback never races a durable export.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu.rawdb import schema
from coreth_tpu.types import StateAccount

# known-absent marker (an account the trie provably lacks); also the
# generation-diff value for an account a block deletes (EIP-158 /
# SELFDESTRUCT).  Distinct from None, which means "flat does not know".
DELETED = "flat-deleted"

# undo-log marker: the key did not exist in the flat view before the
# generation wrote it (rollback removes it again)
_ABSENT = "flat-absent"

# account tuples are (balance, nonce, storage_root, code_hash,
# is_multi_coin) — the StateAccount fields in a shape cheap to build
# from the commit pipeline's staged state without an RLP round trip
AccountTuple = Tuple[int, int, bytes, bytes, bool]


class FlatError(Exception):
    pass


class FlatGeneration:
    """One commit unit's flat-state delta plus its undo log.

    kind: "window" (a flushed commit-pipeline window), "fallback"
    (a strict host-path block), "quarantine" (a tolerantly-applied
    poison block — the rollback target), or "checkpoint" (an empty
    marker generation that asks the exporter to write a durable
    checkpoint record at the current tip).
    """

    __slots__ = (
        "number", "block_hash", "root", "header", "prev_root",
        "prev_header", "accounts", "storage", "destructs",
        "undo_accounts", "undo_storage", "undo_destructs", "kind",
        "checkpoint", "hold", "exported", "rolled_back",
    )

    def __init__(self, number: int, block_hash: bytes, root: bytes,
                 header, prev_root: Optional[bytes],
                 prev_header, accounts: Dict[bytes, object],
                 storage: Dict[Tuple[bytes, bytes], int],
                 destructs, kind: str, checkpoint: bool, hold: bool):
        self.number = number
        self.block_hash = block_hash
        self.root = root
        self.header = header
        self.prev_root = prev_root
        self.prev_header = prev_header
        self.accounts = accounts
        self.storage = storage
        self.destructs = tuple(destructs)
        self.undo_accounts: Dict[bytes, object] = {}
        self.undo_storage: Dict[Tuple[bytes, bytes], object] = {}
        # addr -> the storage sub-dict popped by a destruct/delete
        # (None when the account had no tracked storage)
        self.undo_destructs: Dict[bytes, Optional[dict]] = {}
        self.kind = kind
        self.checkpoint = checkpoint
        self.hold = hold
        self.exported = False
        self.rolled_back = False


class FlatStore:
    """The live flat view + the generation log (single writer: the
    engine's execute thread; the export thread only reads sealed
    generations and flips their ``exported`` flag)."""

    # without an exporter attached, generations older than this are
    # pruned (their diff/undo payloads dropped) — the live dicts keep
    # the values, only rollback depth is bounded
    KEEP = 4

    def __init__(self):
        self.accounts: Dict[bytes, object] = {}
        self.storage: Dict[bytes, Dict[bytes, int]] = {}
        self.gens: List[FlatGeneration] = []
        # (number, block_hash, root, header) of the last REAL sealed
        # generation — the tip a checkpoint marker stamps
        self.tip: Optional[tuple] = None
        self.base_number: Optional[int] = None  # persisted-base stamp
        self._exporter_attached = False
        # most recent exported generation (payloads dropped): the
        # flat/stale_generation fault hands it back to model a queue
        # double-delivery
        self._last_exported: Optional[FlatGeneration] = None
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # keccak(addr) memo for the hash-keyed persisted form; only the
        # export thread populates it
        self._ah: Dict[bytes, bytes] = {}
        # ---- counters (bench flat_state section + serve report)
        self.account_hits = 0
        self.account_misses = 0
        self.storage_hits = 0
        self.storage_misses = 0
        self.fills = 0
        self.generations = 0
        self.rollbacks = 0
        self.loaded_entries = 0

    # ------------------------------------------------------------- reads
    def account(self, addr: bytes):
        """AccountTuple | DELETED | None (= flat does not know)."""
        v = self.accounts.get(addr)
        if v is None:
            self.account_misses += 1
        else:
            self.account_hits += 1
        return v

    def storage_value(self, addr: bytes, key: bytes) -> Optional[int]:
        """Committed slot value (0 = known-zero) or None (= unknown)."""
        sub = self.storage.get(addr)
        v = sub.get(key) if sub is not None else None
        if v is None:
            self.storage_misses += 1
        else:
            self.storage_hits += 1
        return v

    # ------------------------------------------------- read-through fills
    def fill_account(self, addr: bytes, value) -> None:
        """Record a trie-derived value for a key flat did not know.
        Only ever inserted when absent: a concurrent generation write
        must not be clobbered by a slower trie read."""
        if addr not in self.accounts:
            self.accounts[addr] = value
            self.fills += 1

    def fill_storage(self, addr: bytes, key: bytes, value: int) -> None:
        sub = self.storage.setdefault(addr, {})
        if key not in sub:
            sub[key] = value
            self.fills += 1

    # -------------------------------------------------------- generations
    def apply_generation(self, *, number: int, block_hash: bytes,
                         root: bytes, header,
                         prev_root: Optional[bytes] = None,
                         prev_header=None,
                         accounts: Optional[Dict[bytes, object]] = None,
                         storage: Optional[
                             Dict[Tuple[bytes, bytes], int]] = None,
                         destructs=(), kind: str = "window",
                         checkpoint: bool = False,
                         hold: bool = False) -> FlatGeneration:
        """Apply one commit unit's diff to the live view, capturing
        undo, and seal it as a generation.  ``destructs`` lists
        accounts destroyed during the block (their whole tracked
        storage dies, even if the account was re-created)."""
        gen = FlatGeneration(number, block_hash, root, header,
                             prev_root, prev_header,
                             dict(accounts or {}), dict(storage or {}),
                             destructs, kind, checkpoint, hold)
        for addr in gen.destructs:
            gen.undo_destructs[addr] = self.storage.pop(addr, None)
        for addr, v in gen.accounts.items():
            gen.undo_accounts[addr] = self.accounts.get(addr, _ABSENT)
            self.accounts[addr] = v
            if v is DELETED and addr not in gen.undo_destructs:
                gen.undo_destructs[addr] = self.storage.pop(addr, None)
        for (addr, key), val in gen.storage.items():
            sub = self.storage.setdefault(addr, {})
            gen.undo_storage[(addr, key)] = sub.get(key, _ABSENT)
            sub[key] = val
        with self._cv:
            if kind != "checkpoint":
                # the chain moved past any held (quarantined)
                # generation: the quarantine was accepted, release it
                # to the exporter
                for g in self.gens:
                    g.hold = False
                self.tip = (number, block_hash, root, header)
            self.gens.append(gen)
            self.generations += 1
            self._prune_locked()
            self._cv.notify_all()
        return gen

    def mark_checkpoint(self) -> Optional[FlatGeneration]:
        """Stamp a checkpoint at the current tip: an EMPTY marker
        generation the exporter turns into a durable record.  O(1) on
        the execute thread — this is the whole 'stamp cost'.  None
        when nothing was ever sealed."""
        if self.tip is None:
            return None
        number, block_hash, root, header = self.tip
        return self.apply_generation(
            number=number, block_hash=block_hash, root=root,
            header=header, kind="checkpoint", checkpoint=True)

    def rollback_last(self) -> FlatGeneration:
        """Pop the newest generation and restore the flat view to its
        ``prev_root`` state.  Refuses if the generation was already
        exported (it is durable — a rollback past it would need a
        checkpoint rewind, which reorg semantics do not require: the
        exporter holds in front of quarantined generations)."""
        with self._cv:
            if not self.gens:
                raise FlatError("rollback: no generations")
            gen = self.gens[-1]
            if gen.exported:
                raise FlatError(
                    f"rollback: generation {gen.number} already "
                    "exported (durable)")
            self.gens.pop()
        for (addr, key), prev in gen.undo_storage.items():
            sub = self.storage.get(addr)
            if sub is None:
                continue
            if prev is _ABSENT:
                sub.pop(key, None)
            else:
                sub[key] = prev
        for addr, prev in gen.undo_accounts.items():
            if prev is _ABSENT:
                self.accounts.pop(addr, None)
            else:
                self.accounts[addr] = prev
        for addr, sub in gen.undo_destructs.items():
            if sub is not None:
                self.storage[addr] = sub
            elif addr in self.storage and not self.storage[addr]:
                del self.storage[addr]
        gen.rolled_back = True
        with self._cv:
            # the tip is the previous real generation (if still known)
            self.tip = None
            for g in reversed(self.gens):
                if g.kind != "checkpoint":
                    self.tip = (g.number, g.block_hash, g.root,
                                g.header)
                    break
            self.rollbacks += 1
            self._cv.notify_all()
        return gen

    def last_generation(self) -> Optional[FlatGeneration]:
        with self._lock:
            return self.gens[-1] if self.gens else None

    # ------------------------------------------------------- export queue
    def attach_exporter(self) -> None:
        with self._lock:
            self._exporter_attached = True

    def next_for_export(self, timeout: float) -> Optional[FlatGeneration]:
        """Oldest unexported, unheld generation (export order = apply
        order), or None after ``timeout``.  The armed
        ``flat/stale_generation`` fault hands back an ALREADY-exported
        generation instead — the queue-races-rollback shape the
        exporter must detect (by its ``exported`` flag) and skip."""
        from coreth_tpu import faults
        from coreth_tpu.state.flat.exporter import PT_STALE
        deadline_wait = timeout
        with self._cv:
            while True:
                nxt = None
                for g in self.gens:
                    if g.exported:
                        continue
                    if g.hold:
                        break
                    nxt = g
                    break
                if nxt is not None:
                    if self._last_exported is not None \
                            and faults.check(PT_STALE) is not None:
                        return self._last_exported
                    return nxt
                if not self._cv.wait(deadline_wait):  # noqa: DET001 — export-thread queue wait, not consensus data
                    return None

    def mark_exported(self, gen: FlatGeneration) -> None:
        with self._cv:
            gen.exported = True
            # drop payloads; the live dicts carry the values
            gen.accounts = {}
            gen.storage = {}
            gen.undo_accounts = {}
            gen.undo_storage = {}
            gen.undo_destructs = {}
            self._last_exported = gen
            self._prune_locked()
            self._cv.notify_all()

    def mark_preexisting_exported(self) -> None:
        """Generations sealed BEFORE an exporter attached are covered
        by its seed commit (the caller persists the engine tries once,
        synchronously, at attach time) — mark them exported so the
        worker starts from the seed root, not from diffs whose base
        nodes were never durable."""
        with self._cv:
            for g in self.gens:
                if not g.exported:
                    g.exported = True
                    g.accounts = {}
                    g.storage = {}
                    g.undo_accounts = {}
                    g.undo_storage = {}
                    g.undo_destructs = {}
            self._prune_locked()
            self._cv.notify_all()

    def drained(self) -> bool:
        """True when the exporter has nothing LEFT it may process: a
        held (quarantined) generation — and everything stacked on it —
        deliberately stays unexported until the chain accepts past it,
        so it does not count against a drain (the final checkpoint
        then covers exactly the pre-quarantine prefix, which is what
        reorg semantics finalize)."""
        with self._lock:
            for g in self.gens:
                if g.hold:
                    return True
                if not g.exported:
                    return False
            return True

    def _prune_locked(self) -> None:
        """Bound the generation log: exported generations leave from
        the front; without an exporter, old generations beyond KEEP
        drop their payloads (rollback depth is bounded either way —
        the newest generation always survives)."""
        while len(self.gens) > 1 and self.gens[0].exported:
            self.gens.pop(0)
        if not self._exporter_attached:
            while len(self.gens) > self.KEEP:
                self.gens.pop(0)

    # -------------------------------------------------------- persistence
    def _addr_hash(self, addr: bytes) -> bytes:
        h = self._ah.get(addr)
        if h is None:
            h = keccak256(addr)
            self._ah[addr] = h
        return h

    def write_gen_entries(self, kv, gen: FlatGeneration) -> int:
        """Persist one generation's diff under the hash-keyed schema
        (export-thread only — this is where the keccaks happen).
        Every value is stamped with the generation's block number, so
        a reload after a crash can skip entries newer than the
        checkpoint record it resumes from.  Destructed (or deleted)
        accounts additionally land a STORAGE BARRIER: their persisted
        slot entries cannot be enumerated for deletion (keccak keys),
        so the barrier invalidates everything stamped below it —
        without it a destruct+re-create would resurrect stale slot
        values on reload."""
        n = 0
        barriers: Dict[bytes, None] = dict.fromkeys(gen.destructs)
        for addr in sorted(gen.accounts):
            v = gen.accounts[addr]
            if v is DELETED:
                barriers[addr] = None
            schema.write_flat_account(
                kv, self._addr_hash(addr), gen.number, addr,
                None if v is DELETED else v)
            n += 1
        for addr in sorted(barriers):
            schema.write_flat_barrier(kv, self._addr_hash(addr),
                                      gen.number)
            n += 1
        for (addr, key) in sorted(gen.storage):
            schema.write_flat_storage(
                kv, self._addr_hash(addr), key, gen.number, addr,
                gen.storage[(addr, key)])
            n += 1
        return n

    def load(self, kv, trusted_number: int) -> int:
        """Rebuild the persisted base from ``kv``, trusting only
        entries stamped at or below ``trusted_number`` (the checkpoint
        record's block — anything newer may have been exported ahead
        of the record the caller is resuming from).  Storage barriers
        (a destruct at generation N) drop slot entries stamped BELOW
        their generation; a barrier stamped past ``trusted_number``
        poisons the account's persisted storage entirely — whether the
        destruct belongs to the resumed timeline is unknowable, so the
        slots fall through to the trie.  Returns the entry count
        loaded."""
        barriers: Dict[bytes, int] = {}
        for raw_key, raw_val in kv.items():
            b = schema.parse_flat_barrier(raw_key, raw_val)
            if b is not None:
                barriers[b[0]] = b[1]
        n = 0
        for raw_key, raw_val in kv.items():
            acct = schema.parse_flat_account(raw_key, raw_val)
            if acct is not None:
                number, addr, tup = acct
                if number <= trusted_number:
                    self.accounts[addr] = DELETED if tup is None else tup
                    n += 1
                continue
            slot = schema.parse_flat_storage(raw_key, raw_val)
            if slot is not None:
                number, addr, key, value = slot
                if number > trusted_number:
                    continue
                bar = barriers.get(raw_key[2:2 + 32])
                if bar is not None and (bar > trusted_number
                                        or number < bar):
                    continue  # destructed under (or past) the barrier
                self.storage.setdefault(addr, {})[key] = value
                n += 1
        # a loaded DELETED account must not shadow resurrected storage:
        # entries above arrive in kv order, so re-drop storage of
        # accounts whose newest trusted record is DELETED
        for addr, v in self.accounts.items():
            if v is DELETED:
                self.storage.pop(addr, None)
        self.base_number = trusted_number
        self.loaded_entries = n
        return n

    # ------------------------------------------------------------ reports
    def snapshot(self) -> dict:
        return {
            "account_hits": self.account_hits,
            "account_misses": self.account_misses,
            "storage_hits": self.storage_hits,
            "storage_misses": self.storage_misses,
            "fills": self.fills,
            "generations": self.generations,
            "rollbacks": self.rollbacks,
            "loaded_entries": self.loaded_entries,
            "live_accounts": len(self.accounts),
            "live_storage": sum(len(s) for s in self.storage.values()),
        }


class FlatStateView:
    """StateDB-facing adapter (statedb.py consults it duck-typed, so
    ``state`` never imports upward into this package): account and
    slot reads flat-first, with read-through fills.  ``check`` arms
    the caller-side differential oracle (CORETH_FLAT_CHECK) — the
    StateDB re-derives every flat hit from its trie and raises on
    divergence."""

    DELETED = DELETED

    def __init__(self, flat: FlatStore, check: bool = False):
        self.flat = flat
        self.check = check

    def account_state(self, addr: bytes):
        """StateAccount | DELETED | None (= unknown, use the trie)."""
        v = self.flat.account(addr)
        if v is None or v is DELETED:
            return v
        return StateAccount(nonce=v[1], balance=v[0], root=v[2],
                            code_hash=v[3], is_multi_coin=v[4])

    def storage_value(self, addr: bytes, key: bytes) -> Optional[int]:
        return self.flat.storage_value(addr, key)

    def fill_account(self, addr: bytes, account) -> None:
        """account: a StateAccount (present) or None (absent)."""
        if account is None:
            self.flat.fill_account(addr, DELETED)
        else:
            self.flat.fill_account(
                addr, (account.balance, account.nonce, account.root,
                       account.code_hash, account.is_multi_coin))

    def fill_storage(self, addr: bytes, key: bytes, value: int) -> None:
        self.flat.fill_storage(addr, key, value)


def flat_diff_from_statedb(statedb):
    """One host-path block's (accounts, storage, destructs) delta in
    FLAT key space (raw addresses / raw slot keys) from a
    finalised+hashed StateDB — the fallback/quarantine generation
    feed.  Mirrors state.snapshot.diff_from_statedb, which produces
    the hash-keyed snapshot-tree form."""
    accounts: Dict[bytes, object] = {}
    storage: Dict[Tuple[bytes, bytes], int] = {}
    for addr in sorted(statedb._mutated):
        obj = statedb._objects.get(addr)
        if obj is None or obj.deleted or obj.suicided:
            accounts[addr] = DELETED
            continue
        a = obj.account
        accounts[addr] = (a.balance, a.nonce, a.root, a.code_hash,
                          a.is_multi_coin)
        for key, value in obj.written_storage.items():
            storage[(addr, key)] = int.from_bytes(value, "big")
    destructs = sorted(statedb._destructed)
    return accounts, storage, destructs
