"""World state: journaled StateDB over trie-backed storage.

Semantic twin of reference ``core/state/`` (statedb.go, state_object.go,
journal.go).  The flat-read acceleration role of core/state/snapshot/
is played by ``state/flat`` (the asynchronous flat-state layer: O(1)
raw-keyed reads, generational diffs, background checkpoint export) and
by the blockHash-keyed snapshot tree in ``state/snapshot.py`` on the
chain path; the TPU replay engine (coreth_tpu.replay) additionally
mirrors hot state into device arrays.
"""

from coreth_tpu.state.database import Database  # noqa: F401
from coreth_tpu.state.statedb import StateDB  # noqa: F401
from coreth_tpu.state.statedb import normalize_coin_id, normalize_state_key  # noqa: F401
