"""World state: journaled StateDB over trie-backed storage.

Semantic twin of reference ``core/state/`` (statedb.go, state_object.go,
journal.go).  The flat-read acceleration role of core/state/snapshot/ is
played by the Database's account/storage caches; the TPU replay engine
(coreth_tpu.replay) additionally mirrors hot state into device arrays.
"""

from coreth_tpu.state.database import Database  # noqa: F401
from coreth_tpu.state.statedb import StateDB  # noqa: F401
from coreth_tpu.state.statedb import normalize_coin_id, normalize_state_key  # noqa: F401
