"""Offline state pruning.

Twin of reference core/state/pruner/ (pruner.go + bloom.go, driven by
eth/backend.go:404 handleOfflinePruning): with the node stopped, walk
the live state under the pinned root — the account trie, every storage
trie it references, and every code blob — into a live set, then drop
every other trie node from the durable store.  The live-set membership
structure here is an exact set rather than the reference's bloom
filter (no false-positive retention; the trade is memory, fine at
these scales).
"""

from __future__ import annotations

from typing import Set, Tuple

from coreth_tpu.mpt import EMPTY_ROOT
from coreth_tpu.mpt.iterator import leaves
from coreth_tpu.mpt.trie import (
    BRANCH, EXT, HASHREF, LEAF, Trie,
)
from coreth_tpu.rawdb.kv import KVStore
from coreth_tpu.rawdb.state_manager import PersistentNodeDict
from coreth_tpu.types import StateAccount
from coreth_tpu.types.account import EMPTY_CODE_HASH, EMPTY_ROOT_HASH

NODE_PREFIX = PersistentNodeDict.PREFIX
CODE_PREFIX = b"c"


def _collect_nodes(trie: Trie, live: Set[bytes]) -> None:
    """Hashes of every node reachable under the trie's root."""
    def walk(node):
        node = trie._resolve(node)
        if node is None:
            return
        encoded, ref = trie._encode_node(node, None)
        if isinstance(ref, bytes) and len(ref) == 32:
            live.add(ref)
        kind = node[0]
        if kind == EXT:
            walk(node[2])
        elif kind == BRANCH:
            for c in node[1]:
                if c is not None:
                    walk(c)

    walk(trie.root)


def prune(kv: KVStore, state_root: bytes) -> Tuple[int, int]:
    """Drop every trie node and code blob not reachable from
    `state_root`; returns (kept, removed) counts.  Run offline — the
    chain must not be writing the store concurrently."""
    nodes = PersistentNodeDict(kv)
    live_nodes: Set[bytes] = set()
    live_code: Set[bytes] = set()

    account_trie = Trie(root_hash=state_root, db=nodes)
    _collect_nodes(account_trie, live_nodes)
    for _key, raw in leaves(account_trie):
        acct = StateAccount.from_rlp(raw)
        if acct.root not in (EMPTY_ROOT, EMPTY_ROOT_HASH):
            st = Trie(root_hash=acct.root, db=nodes)
            _collect_nodes(st, live_nodes)
        if acct.code_hash != EMPTY_CODE_HASH:
            live_code.add(acct.code_hash)

    kept = 0
    removed = 0
    for key, _v in list(kv.items()):
        if key[:1] == NODE_PREFIX and len(key) == 33:
            if key[1:] in live_nodes:
                kept += 1
            else:
                kv.delete(key)
                removed += 1
        elif key[:1] == CODE_PREFIX and len(key) == 33:
            if key[1:] in live_code:
                kept += 1
            else:
                kv.delete(key)
                removed += 1
    kv.flush()
    if hasattr(kv, "compact"):
        kv.compact()
    return kept, removed
