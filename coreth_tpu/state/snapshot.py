"""Snapshot flat-state layer.

Twin of reference core/state/snapshot/ (snapshot.go:186 Tree, :211 New,
:326 Update, :400 Flatten; difflayer.go; generate.go): a flat
hash-keyed view of the world state — O(1) account and storage reads
that bypass trie traversal — maintained as a disk layer plus one
in-memory diff layer per processed block.  Layers are keyed by BLOCK
hash (the coreth-specific departure from geth's root keying, needed
because competing siblings can share state roots), and a block's diff
is flattened toward the disk layer when consensus accepts it.

Keys are keccak(address) / keccak(slot) exactly as the secure tries
store them, so the generator can seed a snapshot straight from a trie
and the StateDB read path can consult the snapshot before the trie.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from coreth_tpu.crypto import keccak256

# a deleted account/slot in a diff layer
DELETED = b""


class SnapshotError(Exception):
    pass


class DiskLayer:
    """The base flat state (disklayer.go role).  Storage is two-level
    (addr_hash -> slot_hash -> value) so destructing an account is one
    pop, not a scan of every slot on disk.

    While a background rebuild runs (generate.go role), ``gen_marker``
    holds the hashed key the generator has reached: reads at or above
    it fall through to the state trie (``_fallback``), so a node that
    lost its snapshot serves correct state immediately and gets O(1)
    reads progressively."""

    def __init__(self, root: bytes):
        self.root = root
        self.accounts: Dict[bytes, bytes] = {}   # keccak(addr) -> RLP
        self.storage: Dict[bytes, Dict[bytes, bytes]] = {}
        self.gen_marker: Optional[bytes] = None  # None = complete
        self._fallback = None                    # (node_db, state_root)
        # keys written by flatten() while the generator runs: the
        # generator must not clobber them with older trie values.
        # Three granularities (a single account-level set would make
        # _apply_generated skip un-flattened storage slots entirely,
        # turning later reads into authoritative zeros — the round-5
        # state-root-divergence bug):
        # - _gen_overrides: account RLPs written by flatten; the
        #   generator skips the account RLP but still merges trie
        #   storage slots that are not individually overridden;
        # - _gen_slot_overrides: (addr_hash, slot_hash) pairs written
        #   (or deleted) by flatten; only those slots are skipped;
        # - _gen_storage_blocked: destructed / deleted accounts — the
        #   pre-destruct trie storage is dead wholesale, so the
        #   generator must not merge ANY of it (re-created content
        #   arrives via flatten and the slot overrides).
        self._gen_overrides: set = set()
        self._gen_slot_overrides: set = set()
        self._gen_storage_blocked: set = set()

    def _covered(self, addr_hash: bytes) -> bool:
        return self.gen_marker is None or addr_hash < self.gen_marker \
            or addr_hash in self._gen_overrides \
            or addr_hash in self._gen_storage_blocked

    def _slot_covered(self, addr_hash: bytes, slot_hash: bytes) -> bool:
        return self.gen_marker is None or addr_hash < self.gen_marker \
            or addr_hash in self._gen_storage_blocked \
            or (addr_hash, slot_hash) in self._gen_slot_overrides

    def _trie_account(self, addr_hash: bytes) -> Optional[bytes]:
        from coreth_tpu.mpt.trie import Trie
        node_db, root = self._fallback
        return Trie(root_hash=root, db=node_db).get(addr_hash)

    def account(self, addr_hash: bytes) -> Optional[bytes]:
        if not self._covered(addr_hash):
            return self._trie_account(addr_hash)
        return self.accounts.get(addr_hash)

    def storage_slot(self, addr_hash: bytes,
                     slot_hash: bytes) -> Optional[bytes]:
        # slot-granular coverage: an account whose RLP was flattened
        # mid-generation may still have most of its storage only in
        # the rebuild trie — a slot neither generated nor individually
        # overridden must fall through (its trie value is still
        # current: any change would have come through flatten and
        # landed an override)
        if not self._slot_covered(addr_hash, slot_hash):
            from coreth_tpu.mpt.trie import Trie
            from coreth_tpu.types import StateAccount
            raw = self._trie_account(addr_hash)
            if raw is None:
                return None
            acct = StateAccount.from_rlp(raw)
            node_db, _ = self._fallback
            return Trie(root_hash=acct.root, db=node_db).get(slot_hash)
        sub = self.storage.get(addr_hash)
        return sub.get(slot_hash) if sub is not None else None


class DiffLayer:
    """One block's state delta over its parent (difflayer.go)."""

    def __init__(self, parent, block_hash: bytes, root: bytes,
                 accounts: Dict[bytes, bytes],
                 storage: Dict[Tuple[bytes, bytes], bytes],
                 destructs=None):
        self.parent = parent
        self.block_hash = block_hash
        self.root = root
        self.accounts = accounts
        self.storage = storage
        # accounts destroyed during the block — including ones later
        # re-created in the same block (geth's separate destructs set):
        # nothing below this layer survives for them
        self.destructs = set(destructs or ())

    # reads walk the diff chain down to the disk layer
    def account(self, addr_hash: bytes) -> Optional[bytes]:
        layer = self
        while isinstance(layer, DiffLayer):
            if addr_hash in layer.accounts:
                v = layer.accounts[addr_hash]
                return None if v == DELETED else v
            if addr_hash in layer.destructs:
                return None
            layer = layer.parent
        return layer.account(addr_hash)

    def storage_slot(self, addr_hash: bytes,
                     slot_hash: bytes) -> Optional[bytes]:
        layer = self
        key = (addr_hash, slot_hash)
        while isinstance(layer, DiffLayer):
            if key in layer.storage:
                v = layer.storage[key]
                return None if v == DELETED else v
            if addr_hash in layer.destructs \
                    or (addr_hash in layer.accounts
                        and layer.accounts[addr_hash] == DELETED):
                return None  # destructed: nothing below survives
            layer = layer.parent
        return layer.storage_slot(addr_hash, slot_hash)


class Tree:
    """Layer manager keyed by block hash (snapshot.go Tree)."""

    def __init__(self, base_root: bytes,
                 genesis_hash: bytes = b"\x00" * 32):
        self.disk = DiskLayer(base_root)
        self.disk_block = genesis_hash
        self.layers: Dict[bytes, DiffLayer] = {}
        # update() runs on the chain's insert thread while flatten()
        # runs on its acceptor thread (blockchain.go guards the same
        # pair with snapTree's lock)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- lookup
    def snapshot(self, block_hash: bytes):
        """The readable layer for a processed block (or the disk layer
        for the block it represents)."""
        if block_hash == self.disk_block:
            return self.disk
        return self.layers.get(block_hash)

    # ------------------------------------------------------------- update
    def update(self, block_hash: bytes, parent_hash: bytes, root: bytes,
               accounts: Dict[bytes, bytes],
               storage: Dict[Tuple[bytes, bytes], bytes],
               destructs=None) -> None:
        """New diff layer for a processed block (snapshot.go:326);
        values of DELETED mark removals; `destructs` carries accounts
        destroyed during the block even if re-created afterwards."""
        with self._lock:
            parent = self.snapshot(parent_hash)
            if parent is None:
                raise SnapshotError(
                    f"parent snapshot {parent_hash.hex()} missing")
            if block_hash in self.layers:
                raise SnapshotError("duplicate snapshot layer")
            self.layers[block_hash] = DiffLayer(
                parent, block_hash, root, dict(accounts), dict(storage),
                destructs)

    # ------------------------------------------------------------ discard
    def discard(self, block_hash: bytes) -> None:
        """Drop a rejected block's diff layer (snapshot.go Discard).
        Descendant layers keep their parent references and die with
        their own rejections."""
        with self._lock:
            self.layers.pop(block_hash, None)

    # ------------------------------------------------------------ flatten
    def flatten(self, block_hash: bytes) -> None:
        """Consensus accepted `block_hash`: merge its (now unique) diff
        chain into the disk layer and drop rejected siblings
        (snapshot.go:400 Flatten — blockHash-keyed)."""
        with self._lock:
            layer = self.layers.get(block_hash)
            if layer is None:
                raise SnapshotError(f"no layer for {block_hash.hex()}")
            # collect the chain disk..block
            chain: List[DiffLayer] = []
            node = layer
            while isinstance(node, DiffLayer):
                chain.append(node)
                node = node.parent
            generating = self.disk.gen_marker is not None
            for diff in reversed(chain):
                for ah in diff.destructs:
                    self.disk.storage.pop(ah, None)
                    if generating:
                        # the pre-destruct trie storage is dead in its
                        # entirety — block the whole account's fill
                        self.disk._gen_overrides.add(ah)
                        self.disk._gen_storage_blocked.add(ah)
                for ah, v in diff.accounts.items():
                    if generating:
                        # flattened values are NEWER than whatever the
                        # generator would read from the rebuild-root
                        # trie; mark so it skips these account RLPs —
                        # storage stays slot-granular (below) so the
                        # generator still merges un-flattened slots
                        self.disk._gen_overrides.add(ah)
                    if v == DELETED:
                        self.disk.accounts.pop(ah, None)
                        self.disk.storage.pop(ah, None)
                        if generating:
                            self.disk._gen_storage_blocked.add(ah)
                    else:
                        self.disk.accounts[ah] = v
                for (ah, sh), v in diff.storage.items():
                    if generating:
                        self.disk._gen_slot_overrides.add((ah, sh))
                    if v == DELETED:
                        sub = self.disk.storage.get(ah)
                        if sub is not None:
                            sub.pop(sh, None)
                    else:
                        self.disk.storage.setdefault(ah, {})[sh] = v
            self.disk.root = layer.root
            self.disk_block = block_hash
            # drop every layer whose ancestry does not include the
            # accepted block (rejected siblings).  Two passes: classify
            # everything BEFORE re-parenting, because re-parenting a
            # direct child onto the disk layer would cut grandchildren
            # off from the ancestry walk mid-iteration.
            dead = set(d.block_hash for d in chain)
            survivors: Dict[bytes, DiffLayer] = {}
            for bh, l in self.layers.items():
                if bh in dead:
                    continue
                node = l
                descends = False
                while isinstance(node, DiffLayer):
                    if node.block_hash == block_hash:
                        descends = True
                        break
                    node = node.parent
                if descends:
                    survivors[bh] = l
            for l in survivors.values():
                if isinstance(l.parent, DiffLayer) \
                        and l.parent.block_hash == block_hash:
                    l.parent = self.disk
            self.layers = survivors


    # --------------------------------------------------- background gen
    def rebuild(self, db, state_root: bytes, block_hash: bytes,
                batch: int = 256) -> threading.Thread:
        """Rebuild the disk layer from the state trie on a WORKER
        thread (generate.go role): a node that lost its snapshot
        serves immediately — reads above the generation marker fall
        through to the trie — while the flat state fills in key order.
        Diff layers may stack and flatten concurrently; values they
        land are protected from the generator via the override set.
        Returns the worker thread (join it, or wait_generated())."""
        from coreth_tpu.mpt.iterator import leaves
        from coreth_tpu.mpt.trie import Trie
        from coreth_tpu.types import StateAccount
        from coreth_tpu.types.account import EMPTY_ROOT_HASH

        with self._lock:
            disk = DiskLayer(state_root)
            disk.gen_marker = b""          # nothing covered yet
            disk._fallback = (db.node_db, state_root)
            self.disk = disk
            self.disk_block = block_hash
            self.layers = {}

        def worker():
            account_trie = Trie(root_hash=state_root, db=db.node_db)
            pending = []
            for addr_hash, raw in leaves(account_trie):
                pending.append((addr_hash, raw))
                if len(pending) >= batch:
                    self._apply_generated(db, disk, pending)
                    pending = []
            self._apply_generated(db, disk, pending)
            with self._lock:
                disk.gen_marker = None
                disk._fallback = None
                disk._gen_overrides = set()
                disk._gen_slot_overrides = set()
                disk._gen_storage_blocked = set()

        t = threading.Thread(target=worker, daemon=True,
                             name="snapshot-generator")
        t.start()
        self._gen_thread = t
        return t

    def _apply_generated(self, db, disk: DiskLayer, items) -> None:
        from coreth_tpu.mpt.iterator import leaves
        from coreth_tpu.mpt.trie import Trie
        from coreth_tpu.types import StateAccount
        from coreth_tpu.types.account import EMPTY_ROOT_HASH
        if not items:
            return
        with self._lock:
            for addr_hash, raw in items:
                blocked = addr_hash in disk._gen_storage_blocked
                if not blocked and addr_hash not in disk._gen_overrides:
                    disk.accounts[addr_hash] = raw
                if blocked:
                    continue  # destructed: the whole trie copy is dead
                # merge trie storage even when the account RLP was
                # overridden by flatten — only individually overridden
                # slots carry newer data; skipping the whole account
                # would turn the un-flattened slots into authoritative
                # zeros once the marker passes (round-5 advisor bug)
                acct = StateAccount.from_rlp(raw)
                if acct.root != EMPTY_ROOT_HASH:
                    st = Trie(root_hash=acct.root, db=db.node_db)
                    sub = disk.storage.setdefault(addr_hash, {})
                    for slot_hash, v in leaves(st):
                        if (addr_hash, slot_hash) \
                                in disk._gen_slot_overrides:
                            continue  # flatten landed newer data
                        sub[slot_hash] = v
            disk.gen_marker = items[-1][0] + b"\x01"

    def wait_generated(self, timeout: float = 60.0) -> None:  # noqa: DET001 — host-side thread-join wait, not consensus data
        t = getattr(self, "_gen_thread", None)
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise SnapshotError("snapshot generation timed out")


# ----------------------------------------------------------- generation

def generate_from_trie(db, state_root: bytes,
                       genesis_hash: bytes = b"\x00" * 32) -> Tree:
    """Build a snapshot tree from a committed state trie (generate.go
    role, synchronous)."""
    from coreth_tpu.mpt.iterator import leaves, nibbles_to_key
    from coreth_tpu.mpt.trie import Trie
    from coreth_tpu.types import StateAccount
    from coreth_tpu.types.account import EMPTY_ROOT_HASH

    tree = Tree(state_root, genesis_hash)
    account_trie = Trie(root_hash=state_root, db=db.node_db)
    for addr_hash, raw in leaves(account_trie):
        tree.disk.accounts[addr_hash] = raw
        acct = StateAccount.from_rlp(raw)
        if acct.root != EMPTY_ROOT_HASH:
            st = Trie(root_hash=acct.root, db=db.node_db)
            for slot_hash, v in leaves(st):
                tree.disk.storage.setdefault(addr_hash, {})[slot_hash] = v
    return tree


def diff_from_statedb(statedb):
    """Extract a processed block's (accounts, storage, destructs) delta
    in snapshot key space from a finalised+hashed StateDB (the Update
    feed at blockchain.go writeBlockWithState).  Only mutated accounts
    (statedb._mutated) and actually-written slots (written_storage)
    enter the diff — origin_storage also caches pure reads, which must
    not bloat every layer.  destructs carries every account destroyed
    during the block — including destruct+re-create sequences, whose
    pre-destruct storage must be masked."""
    accounts: Dict[bytes, bytes] = {}
    storage: Dict[Tuple[bytes, bytes], bytes] = {}
    destructs = {keccak256(a) for a in getattr(statedb, "_destructed",
                                               ())}
    for addr in statedb._mutated:
        obj = statedb._objects.get(addr)
        ah = keccak256(addr)
        if obj is None or obj.deleted or obj.suicided:
            accounts[ah] = DELETED
            continue
        accounts[ah] = obj.account.rlp()
        for key, value in obj.written_storage.items():
            sh = keccak256(key)
            if value == b"\x00" * 32:
                storage[(ah, sh)] = DELETED
            else:
                from coreth_tpu import rlp
                storage[(ah, sh)] = rlp.encode(value.lstrip(b"\x00"))
    return accounts, storage, destructs
