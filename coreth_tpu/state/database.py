"""State backing store: trie node db + code db + structural trie cache.

Plays the role of reference core/state/database.go (cachingDB) plus the
hashdb node store (trie/triedb/hashdb): committed trie nodes live in
``node_db`` keyed by hash, contract code in ``code_db`` keyed by code
hash, and recently-committed tries are kept structurally (Python node
trees) in ``trie_cache`` so re-opening state at a recent root costs a
copy, not a node-by-node decode.
"""

from __future__ import annotations

from typing import Dict, Optional

from coreth_tpu.mpt import SecureTrie, EMPTY_ROOT
from coreth_tpu.types.account import EMPTY_CODE_HASH


class Database:
    def __init__(self, node_db=None, code_db=None):
        # any mutable mapping works; rawdb.PersistentNodeDict gives the
        # disk-backed variant with deferred flushing
        self.node_db: Dict[bytes, bytes] = \
            node_db if node_db is not None else {}
        self.code_db: Dict[bytes, bytes] = \
            code_db if code_db is not None else {}
        self.trie_cache: Dict[bytes, SecureTrie] = {}
        self.max_cached_tries = 128

    def open_trie(self, root: bytes) -> SecureTrie:
        """Account or storage trie at ``root``; always a private copy."""
        cached = self.trie_cache.get(root)
        if cached is not None:
            return cached.copy()
        return SecureTrie(root_hash=root, db=self.node_db)

    def cache_trie(self, root: bytes, trie: SecureTrie) -> None:
        if len(self.trie_cache) >= self.max_cached_tries:
            # drop the oldest entries (insertion order)
            for key in list(self.trie_cache)[: self.max_cached_tries // 4]:
                del self.trie_cache[key]
        self.trie_cache[root] = trie.copy()

    def contract_code(self, code_hash: bytes) -> bytes:
        if code_hash == EMPTY_CODE_HASH:
            return b""
        return self.code_db.get(code_hash, b"")

    def write_code(self, code_hash: bytes, code: bytes) -> None:
        self.code_db[code_hash] = code
