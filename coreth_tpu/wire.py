"""Linear-codec wire format for atomic transactions.

Follows the avalanchego linearcodec/wrappers layout the reference
registers in plugin/evm/codec.go: a u16 codec version, a u32 type id
for interface values, then struct fields in declaration order —
fixed-width big-endian ints, 32-byte ids raw, variable byte strings
u32-length-prefixed, slices u32-count-prefixed.  Type ids 0/1 =
UnsignedImportTx/UnsignedExportTx (the registration order in
codec.go), 2+ = fx types in secp256k1fx registration order.
"""

from __future__ import annotations

import struct

CODEC_VERSION = 0

TYPE_IMPORT_TX = 0
TYPE_EXPORT_TX = 1
TYPE_SECP_TRANSFER_INPUT = 2
TYPE_SECP_TRANSFER_OUTPUT = 3
TYPE_SECP_CREDENTIAL = 4


class Packer:
    def __init__(self):
        self.buf = bytearray()

    def u8(self, v: int):
        self.buf += struct.pack(">B", v)

    def u16(self, v: int):
        self.buf += struct.pack(">H", v)

    def u32(self, v: int):
        self.buf += struct.pack(">I", v)

    def u64(self, v: int):
        self.buf += struct.pack(">Q", v)

    def fixed(self, b: bytes, n: int):
        if len(b) != n:
            raise ValueError(f"expected {n} bytes, got {len(b)}")
        self.buf += b

    def var_bytes(self, b: bytes):
        self.u32(len(b))
        self.buf += b

    def bytes(self) -> bytes:
        return bytes(self.buf)


class Unpacker:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise ValueError("short buffer")
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def fixed(self, n: int) -> bytes:
        return self._take(n)

    def var_bytes(self) -> bytes:
        return self._take(self.u32())

    def done(self) -> bool:
        return self.off == len(self.data)
