"""Shared-slot swap workload — the BASELINE config[3] contention
fixture (the Uniswap-V2/ring analog of reference
core/bench_test.go:64-75).

A hand-assembled constant-product pool: reserves in storage slots 0/1,
``swap(amountIn)`` reads both, computes ``out = amountIn * r1 /
(r0 + amountIn)`` (MUL + DIV on the device ALU), writes both back, and
emits one log.  Every swap conflicts with every other through the two
shared slots, so a block of swaps is a fully serial OCC chain — the
adversarial case for the optimistic scheduler — while remaining
entirely device-eligible bytecode.
"""

from __future__ import annotations

from coreth_tpu.crypto import keccak256
from coreth_tpu.workloads import erc20

SWAP_SELECTOR = bytes.fromhex("11223344")
SWAP_TOPIC = keccak256(b"Swap(address)")

_b1 = erc20._b1
# extend the shared assembler's opcode table (a copy, not a mutation)
_OPS = dict(erc20._OPS)
_OPS.update({"MUL": 0x02, "DIV": 0x04, "DUP4": 0x83, "DUP5": 0x84,
             "SWAP2": 0x91, "LOG1": 0xA1, "POP": 0x50})


def _assemble(program):
    return erc20._assemble(program, ops=_OPS)


POOL_RUNTIME = _assemble([
    _b1(0x00), "CALLDATALOAD", _b1(0xE0), "SHR",
    "DUP1", ("PUSH", SWAP_SELECTOR), "EQ", ("PUSHL", "swap"), "JUMPI",
    _b1(0x00), _b1(0x00), "REVERT",

    ("LABEL", "swap"),
    _b1(0x04), "CALLDATALOAD",        # [amt]
    _b1(0x00), "SLOAD",               # [amt, r0]
    _b1(0x01), "SLOAD",               # [amt, r0, r1]
    "DUP1", "DUP4", "MUL",            # [amt, r0, r1, amt*r1]
    "DUP3", "DUP5", "ADD",            # [amt, r0, r1, num, r0+amt]
    "SWAP1", "DIV",                   # [amt, r0, r1, out]
    "DUP1", "SWAP2",                  # [amt, r0, out, out, r1]
    "SUB",                            # [amt, r0, out, r1-out]
    _b1(0x01), "SSTORE",              # [amt, r0, out]
    "SWAP1",                          # [amt, out, r0]
    "DUP3", "ADD",                    # [amt, out, r0+amt]
    _b1(0x00), "SSTORE",              # [amt, out]
    _b1(0x00), "MSTORE",              # [amt]         mem[0] = out
    "CALLER", _b1(0x20), _b1(0x00),   # [amt, caller, 32, 0]
    "LOG1",                           # [amt]
    "STOP",
])

POOL_CODE_HASH = keccak256(POOL_RUNTIME)


def swap_calldata(amount_in: int) -> bytes:
    return SWAP_SELECTOR + amount_in.to_bytes(32, "big")


def pool_genesis_account(r0: int, r1: int):
    from coreth_tpu.chain import GenesisAccount
    return GenesisAccount(
        balance=0, code=POOL_RUNTIME, nonce=1,
        storage={(0).to_bytes(32, "big"): r0.to_bytes(32, "big"),
                 (1).to_bytes(32, "big"): r1.to_bytes(32, "big")})


def expected_out(r0: int, r1: int, amount_in: int) -> int:
    return (amount_in * r1) // (r0 + amount_in)
