"""ERC-20 token workload — the BASELINE config[1] fixture.

A hand-assembled minimal token contract (transfer + balanceOf over a
balances mapping at storage slot 0, Transfer event, unchecked classic
semantics).  Hand assembly keeps the execution path — and thus the
gas schedule — small and auditable; the contract is exercised through
the host EVM interpreter (reference semantics: core/vm/instructions.go
SLOAD/SSTORE/LOG3, core/state/state_object.go updateTrie), which is
also how its per-transfer execution gas constant is measured rather
than hand-derived.

Storage layout: balances[addr] at keccak256(pad32(addr) ++ pad32(0)) —
the Solidity mapping rule the reference's state tests rely on.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from coreth_tpu.crypto import keccak256

TRANSFER_SELECTOR = bytes.fromhex("a9059cbb")
BALANCEOF_SELECTOR = bytes.fromhex("70a08231")
# keccak256("Transfer(address,address,uint256)")
TRANSFER_TOPIC = keccak256(b"Transfer(address,address,uint256)")

_OPS = {
    "STOP": 0x00, "ADD": 0x01, "SUB": 0x03, "LT": 0x10, "GT": 0x11,
    "EQ": 0x14, "SHR": 0x1C, "SHA3": 0x20, "CALLER": 0x33,
    "CALLDATALOAD": 0x35, "MSTORE": 0x52, "SLOAD": 0x54, "SSTORE": 0x55,
    "JUMPI": 0x57, "JUMPDEST": 0x5B, "LOG3": 0xA3, "RETURN": 0xF3,
    "REVERT": 0xFD, "DUP1": 0x80, "DUP2": 0x81, "DUP3": 0x82,
    "SWAP1": 0x90,
}


def _assemble(program: List, ops: Dict[str, int] = None) -> bytes:
    """Two-pass assembler: items are opcode names, ("PUSH", bytes),
    ("PUSHL", label) 2-byte label pushes, or ("LABEL", name).
    `ops` overrides the opcode table (workloads/swap.py extends it)."""
    _ops = ops or _OPS
    # pass 1: layout
    offsets: Dict[str, int] = {}
    pc = 0
    for item in program:
        if isinstance(item, str):
            pc += 1
        elif item[0] == "LABEL":
            offsets[item[1]] = pc
            pc += 1                      # JUMPDEST emitted at the label
        elif item[0] == "PUSH":
            pc += 1 + len(item[1])
        elif item[0] == "PUSHL":
            pc += 3                      # PUSH2 + 2-byte offset
        else:
            raise ValueError(item)
    # pass 2: emit
    out = bytearray()
    for item in program:
        if isinstance(item, str):
            out.append(_ops[item])
        elif item[0] == "LABEL":
            out.append(_OPS["JUMPDEST"])
        elif item[0] == "PUSH":
            data = item[1]
            out.append(0x5F + len(data))     # PUSH1..PUSH32
            out += data
        elif item[0] == "PUSHL":
            out.append(0x61)                 # PUSH2
            out += offsets[item[1]].to_bytes(2, "big")
    return bytes(out)


def _b1(v: int) -> Tuple[str, bytes]:
    return ("PUSH", bytes([v]))


TOKEN_RUNTIME = _assemble([
    # dispatcher: selector = calldataload(0) >> 224
    _b1(0x00), "CALLDATALOAD", _b1(0xE0), "SHR",
    "DUP1", ("PUSH", TRANSFER_SELECTOR), "EQ", ("PUSHL", "transfer"),
    "JUMPI",
    "DUP1", ("PUSH", BALANCEOF_SELECTOR), "EQ", ("PUSHL", "balanceOf"),
    "JUMPI",
    _b1(0x00), _b1(0x00), "REVERT",

    # transfer(address to, uint256 amt)
    ("LABEL", "transfer"),
    _b1(0x24), "CALLDATALOAD",                       # [amt]
    "CALLER", _b1(0x00), "MSTORE",
    _b1(0x00), _b1(0x20), "MSTORE",
    _b1(0x40), _b1(0x00), "SHA3",                    # [amt, fromKey]
    "DUP1", "SLOAD",                                 # [amt, fK, fromBal]
    "DUP3", "DUP2", "LT",                            # fromBal < amt ?
    ("PUSHL", "revert"), "JUMPI",                    # [amt, fK, fromBal]
    "DUP3", "SWAP1", "SUB",                          # [amt, fK, fromBal-amt]
    "SWAP1", "SSTORE",                               # [amt]
    _b1(0x04), "CALLDATALOAD",                       # [amt, to]
    _b1(0x00), "MSTORE",                             # [amt] mem0 = to
    _b1(0x40), _b1(0x00), "SHA3",                    # [amt, toKey]
    "DUP1", "SLOAD",                                 # [amt, toKey, toBal]
    "DUP3", "ADD",                                   # [amt, toKey, toBal+amt]
    "SWAP1", "SSTORE",                               # [amt]
    # emit Transfer(caller, to, amt)
    "DUP1", _b1(0x00), "MSTORE",
    _b1(0x04), "CALLDATALOAD",                       # [amt, to]
    "CALLER",                                        # [amt, to, caller]
    ("PUSH", TRANSFER_TOPIC),                        # [amt, to, from, sig]
    _b1(0x20), _b1(0x00),                            # [.., size, offset]
    "LOG3",                                          # [amt]
    _b1(0x01), _b1(0x00), "MSTORE",
    _b1(0x20), _b1(0x00), "RETURN",

    ("LABEL", "revert"),
    _b1(0x00), _b1(0x00), "REVERT",

    # balanceOf(address)
    ("LABEL", "balanceOf"),
    _b1(0x04), "CALLDATALOAD", _b1(0x00), "MSTORE",
    _b1(0x00), _b1(0x20), "MSTORE",
    _b1(0x40), _b1(0x00), "SHA3", "SLOAD",
    _b1(0x00), "MSTORE",
    _b1(0x20), _b1(0x00), "RETURN",
])

TOKEN_CODE_HASH = keccak256(TOKEN_RUNTIME)


from functools import lru_cache


@lru_cache(maxsize=1 << 17)
def balance_slot(addr: bytes) -> bytes:
    """Storage slot key of balances[addr] (mapping slot 0).  Memoized:
    the replay classifier derives two slot keys per token tx and the
    sender/recipient population recurs across blocks, so the keccak
    runs once per address instead of once per tx."""
    return keccak256(b"\x00" * 12 + addr + b"\x00" * 32)


def transfer_calldata(to: bytes, amount: int) -> bytes:
    return (TRANSFER_SELECTOR + b"\x00" * 12 + to
            + amount.to_bytes(32, "big"))


def parse_transfer_calldata(data: bytes):
    """(to, amount) if data is a well-formed transfer call, else None."""
    if len(data) != 68 or data[:4] != TRANSFER_SELECTOR:
        return None
    if any(data[4:16]):
        return None
    return data[16:36], int.from_bytes(data[36:68], "big")


def token_genesis_account(balances: Dict[bytes, int]):
    """GenesisAccount for the token with pre-funded balances."""
    from coreth_tpu.chain import GenesisAccount
    storage = {balance_slot(addr): v.to_bytes(32, "big")
               for addr, v in balances.items()}
    return GenesisAccount(balance=0, code=TOKEN_RUNTIME, nonce=1,
                          storage=storage)


def intrinsic_gas(data: bytes, rules) -> int:
    """Intrinsic tx gas for a plain call (state_transition.go:79)."""
    from coreth_tpu.processor.state_transition import intrinsic_gas as ig
    return ig(data, [], False, rules)


_EXEC_GAS_CACHE: Dict[tuple, int] = {}


def measure_transfer_exec_gas(config, number: int, time: int,
                              variant: str = "reset") -> int:
    """Execution gas of one transfer() call, measured by running the
    host interpreter once on a scratch state — self-calibrating against
    the exact jump-table/gas rules instead of a hand-derived constant.

    Variants (the only gas classes a successful non-self transfer can
    hit post-AP1, where refunds are disabled so zeroing the from-slot
    costs the same as a partial spend):
      - "reset": both slots nonzero before, partial amount (SSTORE
        nonzero->nonzero on both slots)
      - "set":   to-slot zero before (SSTORE zero->nonzero, EIP-2929
        SSTORE_SET on the credit side)
      - "noop":  amount == 0 (both SSTOREs write the current value)
    """
    # key on fork-schedule identity, not id(config): id() values can be
    # reused after garbage collection and gas depends only on the rules
    rules = config.rules(number, time)
    key = (config.chain_id, variant) + tuple(
        getattr(rules, f) for f in sorted(vars(rules))
        if f.startswith("is_"))
    cached = _EXEC_GAS_CACHE.get(key)
    if cached is not None:
        return cached
    from coreth_tpu.evm.evm import EVM, BlockContext, TxContext, Config
    from coreth_tpu.state import Database, StateDB
    from coreth_tpu.mpt import EMPTY_ROOT

    sender = b"\x11" * 20
    recip = b"\x22" * 20
    token = b"\x33" * 20
    db = Database()
    statedb = StateDB(EMPTY_ROOT, db)
    statedb.set_code(token, TOKEN_RUNTIME)
    statedb.set_state(token, balance_slot(sender),
                      (10**20).to_bytes(32, "big"))
    if variant != "set":
        statedb.set_state(token, balance_slot(recip),
                          (1).to_bytes(32, "big"))
    statedb.add_balance(sender, 10**18)
    # commit + reopen so SSTORE sees real committed "original" values
    # (EIP-2200 original-value gas depends on them; a fresh object's
    # origins all read zero and would miscost the reset paths by 2800)
    pre_root = statedb.commit(False)
    statedb = StateDB(pre_root, db)
    block_ctx = BlockContext(coinbase=b"\x00" * 20, number=number,
                             time=time, gas_limit=8_000_000)
    evm = EVM(block_ctx, TxContext(origin=sender, gas_price=0), statedb,
              config, Config())
    statedb.prepare(rules, sender, block_ctx.coinbase, token,
                    list(rules.active_precompiles), [])
    gas_limit = 200_000
    amount = 0 if variant == "noop" else 1000
    ret, gas_left, err = evm.call(sender, token,
                                  transfer_calldata(recip, amount),
                                  gas_limit, 0)
    if err is not None:
        raise RuntimeError(f"token gas probe failed: {err}")
    _EXEC_GAS_CACHE[key] = gas_limit - gas_left
    return _EXEC_GAS_CACHE[key]
