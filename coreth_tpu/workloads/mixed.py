"""Mixed Avalanche-semantics segment — the BASELINE config[4] fixture.

A historical-segment-shaped chain under the AP5 rule set: periodic
atomic ExtData blocks (ImportTx carrying AVAX for the fee burn plus a
non-AVAX asset for multicoin credits), nativeAssetCall multicoin
transfers (reference core/vm/contracts_stateful_native_asset.go:75),
and plain transfer spam in between.  Deterministic: the shared-memory
hub can be reseeded identically for every replay (UTXO seeds derive
from block indices).
"""

from __future__ import annotations

from typing import List, Tuple

from coreth_tpu.atomic import (
    AtomicBackend, ChainContext, EVMOutput, Memory, TransferableInput,
    TransferableOutput, Tx, UnsignedImportTx, UTXO, make_callbacks,
    short_id,
)
from coreth_tpu.atomic.shared_memory import Element, Requests
from coreth_tpu.chain import Genesis, GenesisAccount, generate_chain
from coreth_tpu.consensus.engine import DummyEngine
from coreth_tpu.crypto.secp256k1 import (
    _g_mul, _to_affine, priv_to_address,
)
from coreth_tpu.state import Database
from coreth_tpu.types import DynamicFeeTx, sign_tx

GWEI = 10**9
CTX = ChainContext()
ASSET = b"\x5b" * 32
ASSET_RECIPIENT = b"\x45" * 20
IMPORT_EVERY = 8            # block i % 8 == 0 -> atomic ExtData block
NAC_EVERY = 8               # block i % 8 == 1 -> nativeAssetCall block


def _short_addr(priv: int) -> bytes:
    return short_id(_to_affine(_g_mul(priv)))


def _seed(memory: Memory, asset_id: bytes, amount: int, owner: int,
          tx_id: bytes) -> UTXO:
    out = TransferableOutput(asset_id=asset_id, amount=amount,
                            addrs=[_short_addr(owner)])
    utxo = UTXO(tx_id=tx_id, output_index=0, out=out)
    sm_x = memory.new_shared_memory(CTX.x_chain_id)
    sm_x.apply({CTX.chain_id: Requests(put_requests=[
        Element(utxo.input_id(), utxo.encode(), out.addrs)])})
    return utxo


def seed_memory(n_blocks: int, import_key: int) -> Tuple[Memory, list]:
    """Fresh hub with one (AVAX, asset) UTXO pair per import block."""
    memory = Memory()
    utxos = []
    for i in range(0, n_blocks, IMPORT_EVERY):
        avax_u = _seed(memory, CTX.avax_asset_id, 60_000_000,
                       import_key, b"\x21" + i.to_bytes(4, "big") * 7
                       + b"\x21" * 3)
        asset_u = _seed(memory, ASSET, 1_000_000, import_key,
                        b"\x42" + i.to_bytes(4, "big") * 7 + b"\x42" * 3)
        utxos.append((i, avax_u, asset_u))
    return memory, utxos


def _import_tx(avax_u: UTXO, asset_u: UTXO, to: bytes,
               key: int) -> Tx:
    unsigned = UnsignedImportTx(
        network_id=CTX.network_id, blockchain_id=CTX.chain_id,
        source_chain=CTX.x_chain_id,
        imported_inputs=[
            TransferableInput(tx_id=avax_u.tx_id,
                              output_index=avax_u.output_index,
                              asset_id=CTX.avax_asset_id,
                              amount=avax_u.out.amount,
                              sig_indices=[0]),
            TransferableInput(tx_id=asset_u.tx_id,
                              output_index=asset_u.output_index,
                              asset_id=ASSET,
                              amount=asset_u.out.amount,
                              sig_indices=[0])],
        outs=[EVMOutput(address=to, amount=50_000_000,
                        asset_id=CTX.avax_asset_id),
              EVMOutput(address=to, amount=1_000_000,
                        asset_id=ASSET)])
    tx = Tx(unsigned)
    tx.sign([[key], [key]])
    return tx


def build_mixed_chain(config, n_blocks: int, txs_per_block: int,
                      keys: List[int]):
    """Returns (genesis, blocks).  keys[0] is the importer (becomes a
    multicoin account -> its blocks ride the host path); transfer spam
    comes from keys[1:]."""
    from coreth_tpu.evm.precompiles import NATIVE_ASSET_CALL_ADDR
    addrs = [priv_to_address(k) for k in keys]
    alloc = {a: GenesisAccount(balance=10**24) for a in addrs}
    genesis = Genesis(config=config, gas_limit=8_000_000, alloc=alloc)
    db = Database()
    memory, utxos = seed_memory(n_blocks, keys[0])
    backend = AtomicBackend(CTX, memory.new_shared_memory(CTX.chain_id))
    pending: list = []
    cb = make_callbacks(backend, config,
                        pending_atomic_txs=lambda: pending)
    engine = DummyEngine(cb=cb)
    engine.set_config(config)
    gblock = genesis.to_block(db)
    nonces = [0] * len(keys)

    def tx_(k, to, data=b"", gas=21_000, value=0):
        t = sign_tx(DynamicFeeTx(
            chain_id_=config.chain_id, nonce=nonces[k],
            gas_tip_cap_=GWEI, gas_fee_cap_=2000 * GWEI, gas=gas,
            to=to, value=value, data=data), keys[k], config.chain_id)
        nonces[k] += 1
        return t

    def gen(i, bg):
        pending.clear()
        for bi, avax_u, asset_u in utxos:
            if bi == i:
                pending.append(_import_tx(avax_u, asset_u, addrs[0],
                                          keys[0]))
        if i % NAC_EVERY == 1 and i > 1:
            data = (ASSET_RECIPIENT + ASSET
                    + (100 + i).to_bytes(32, "big"))
            bg.add_tx(tx_(0, NATIVE_ASSET_CALL_ADDR, data=data,
                          gas=200_000))
        else:
            for j in range(txs_per_block):
                k = 1 + (i * txs_per_block + j) % (len(keys) - 1)
                to = b"\xe1" + (i * 1000 + j).to_bytes(4, "big") * 4 \
                    + b"\xe1" * 3
                bg.add_tx(tx_(k, to, value=1000 + j))

    blocks, _ = generate_chain(config, gblock, db, n_blocks, gen,
                               gap=10, engine=engine)
    return genesis, blocks


def replay_engine(genesis, n_blocks: int, import_key: int, **kw):
    """ReplayEngine wired with atomic callbacks over a freshly
    reseeded shared-memory hub."""
    from coreth_tpu.replay import ReplayEngine
    memory, _ = seed_memory(n_blocks, import_key)
    db = Database()
    gblock = genesis.to_block(db)
    backend = AtomicBackend(CTX, memory.new_shared_memory(CTX.chain_id))
    cb = make_callbacks(backend, genesis.config,
                        pending_atomic_txs=lambda: [])
    return ReplayEngine(genesis.config, db, gblock.root,
                        parent_header=gblock.header,
                        engine=DummyEngine(cb=cb), **kw), gblock


def host_chain(genesis, n_blocks: int, import_key: int):
    """Python host BlockChain wired the same way (the py baseline)."""
    from coreth_tpu.chain import BlockChain
    memory, _ = seed_memory(n_blocks, import_key)
    db = Database()
    backend = AtomicBackend(CTX, memory.new_shared_memory(CTX.chain_id))
    cb = make_callbacks(backend, genesis.config,
                        pending_atomic_txs=lambda: [])
    engine = DummyEngine(cb=cb)
    return BlockChain(genesis, db=db, engine=engine)
