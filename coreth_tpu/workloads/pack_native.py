"""Packers for the compiled C++ replay baselines (native/baseline.cc,
native/evm.cc).

Python packs the wire data once (prep, excluded from timed regions —
which favors the baselines, BASELINE.md); the C++ side then replays
sequentially with bit-identical root validation per block.
"""

from __future__ import annotations

from typing import List, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu.state.statedb import normalize_state_key
from coreth_tpu.types import Block, LatestSigner


def pack_evm_replay(genesis, blocks: List[Block]) -> Tuple:
    """Args for crypto.native.evm_replay over a contract-call chain."""
    signer = LatestSigner(genesis.config.chain_id)
    txs = bytearray()
    offs = [0]
    env = bytearray()
    n = 0
    for b in blocks:
        for tx in b.transactions:
            r, s, recid = tx.inner.raw_signature()
            price = tx.gas_price if b.base_fee is None else min(
                tx.gas_fee_cap, b.base_fee + tx.gas_tip_cap)
            required = tx.gas * tx.gas_fee_cap + tx.value
            txs += signer.sig_hash(tx)
            txs += r.to_bytes(32, "big") + s.to_bytes(32, "big") \
                + bytes([recid])
            txs += tx.to
            txs += tx.value.to_bytes(32, "big")
            txs += tx.gas.to_bytes(8, "big")
            txs += price.to_bytes(32, "big")
            txs += required.to_bytes(32, "big")
            txs += tx.nonce.to_bytes(8, "big")
            txs += len(tx.data).to_bytes(4, "little") + tx.data
            n += 1
        offs.append(n)
        env += b.root
        env += b.header.coinbase
        env += b.time.to_bytes(8, "big")
        env += b.number.to_bytes(8, "big")
        env += b.header.gas_limit.to_bytes(8, "big")
        env += (b.base_fee or 0).to_bytes(32, "big")
        env += b.header.gas_used.to_bytes(8, "big")
    accounts = bytearray()
    contracts = bytearray()
    n_accounts = 0
    n_contracts = 0
    for addr, acct in genesis.alloc.items():
        code = getattr(acct, "code", b"") or b""
        if code:
            contracts += addr + keccak256(code)
            contracts += acct.balance.to_bytes(32, "big")
            contracts += acct.nonce.to_bytes(8, "big")
            contracts += len(code).to_bytes(4, "little") + code
            storage = getattr(acct, "storage", None) or {}
            contracts += len(storage).to_bytes(4, "little")
            for key, val in storage.items():
                contracts += normalize_state_key(key)
                contracts += (val if isinstance(val, bytes)
                              else val.to_bytes(32, "big")
                              ).rjust(32, b"\x00")
            n_contracts += 1
        else:
            accounts += addr + acct.balance.to_bytes(32, "big") \
                + acct.nonce.to_bytes(8, "big")
            n_accounts += 1
    return (bytes(txs), offs, bytes(env), bytes(accounts), n_accounts,
            bytes(contracts), n_contracts, genesis.config.chain_id)
