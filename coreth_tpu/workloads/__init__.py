from coreth_tpu.workloads.erc20 import (  # noqa: F401
    TOKEN_RUNTIME, TOKEN_CODE_HASH, TRANSFER_SELECTOR, TRANSFER_TOPIC,
    balance_slot, transfer_calldata, parse_transfer_calldata,
    token_genesis_account, measure_transfer_exec_gas, intrinsic_gas,
)
from coreth_tpu.workloads.hot_contract import (  # noqa: F401
    HOT_CONTRACT, HOT_RUNTIME, build_hot_chain, hot_genesis_alloc,
    hot_tx_gen, zipf_sampler,
)
