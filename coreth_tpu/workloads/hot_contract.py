"""Single-hot-contract workload — the FAFO heavy-traffic shape.

ONE ERC-20-shaped contract (the hand-assembled workloads/erc20 token
runtime, so the census coverage assertion and the device/native opcode
sets already pin it) receives 100% of transactions, with realistic
Zipf-skewed sender and recipient populations: a handful of heavy
senders/recipients (the DEX-pool / stablecoin head) over a long tail
of one-off users.  This is the shape that serialized the PR-8 sharded
mesh — every lane bucketed to the one contract's shard — and the
acceptance workload for ISSUE 14's key-range placement: its multichip
curve must stay flat.

Everything here is deterministic (a fixed-seed 64-bit LCG drives the
Zipf draws), so two builds of the same shape produce byte-identical
chains and the cross-width root equivalence tests can compare replays.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Callable, List

from coreth_tpu.workloads.erc20 import TOKEN_RUNTIME, transfer_calldata

# the one hot contract's address (token runtime from workloads/erc20 —
# "ERC-20-shaped": transfer() over a balances mapping at slot 0)
HOT_CONTRACT = b"\x79" * 20
HOT_RUNTIME = TOKEN_RUNTIME

_M64 = (1 << 64) - 1


def _lcg(seed: int) -> Callable[[], int]:
    """Deterministic 64-bit LCG (Knuth MMIX constants): the workload
    must not consult `random` — chain bytes are compared across
    processes and mesh widths."""
    state = (seed ^ 0x9E3779B97F4A7C15) & _M64 or 1

    def nxt() -> int:
        nonlocal state
        state = (state * 6364136223846793005
                 + 1442695040888963407) & _M64
        return state >> 11

    return nxt


def zipf_sampler(n: int, alpha: float, seed: int) -> Callable[[], int]:
    """Sampler over ranks [0, n) with P(i) ~ 1/(i+1)^alpha — the
    classic Zipf head/tail skew (alpha ~1.1 for real token-transfer
    traffic).  Deterministic: CDF inversion over a fixed-seed LCG."""
    weights: List[float] = []
    acc = 0.0
    for i in range(n):
        acc += 1.0 / float(i + 1) ** alpha
        weights.append(acc)
    total = weights[-1]
    rnd = _lcg(seed)

    def draw() -> int:
        u = (rnd() / float(1 << 53)) * total
        return min(n - 1, bisect_right(weights, u))

    return draw


def recipient_pool(addrs, extra: int) -> List[bytes]:
    """Recipient population: the funded holder set plus `extra`
    synthetic one-off addresses (fresh balance slots — the SSTORE-set
    side of the gas ladder)."""
    pool = list(addrs)
    for i in range(extra):
        pool.append(b"\x9a" + i.to_bytes(4, "big") * 4 + b"\x9a" * 3)
    return pool


def hot_genesis_alloc(addrs) -> dict:
    """Genesis alloc for the hot workload: gas-funded senders, all
    token balance pre-minted to them on the ONE hot contract."""
    from coreth_tpu.chain import GenesisAccount
    from coreth_tpu.workloads.erc20 import token_genesis_account
    alloc = {a: GenesisAccount(balance=10**27) for a in addrs}
    alloc[HOT_CONTRACT] = token_genesis_account(
        {a: 10**24 for a in addrs})
    return alloc


def hot_tx_gen(keys, addrs, txs_per_block: int, nonces,
               *, chain_id: int, alpha: float = 1.1,
               seed: int = 20260804, extra_recipients: int = 0,
               gas: int = 200_000):
    """A ``gen(i, bg)`` callback for generate_chain: every tx is a
    transfer() into HOT_CONTRACT, senders and recipients drawn from
    independent Zipf distributions (heavy head, long tail)."""
    from coreth_tpu.types import DynamicFeeTx, sign_tx
    gwei = 10**9
    pool = recipient_pool(addrs, extra_recipients
                          or max(16, 2 * len(addrs)))
    senders = zipf_sampler(len(keys), alpha, seed)
    recips = zipf_sampler(len(pool), alpha, seed ^ 0x5BD1E995)

    def gen(i, bg):
        for j in range(txs_per_block):
            k = senders()
            to = pool[recips()]
            bg.add_tx(sign_tx(DynamicFeeTx(
                chain_id_=chain_id, nonce=nonces[k],
                gas_tip_cap_=gwei, gas_fee_cap_=2000 * gwei, gas=gas,
                to=HOT_CONTRACT, value=0,
                data=transfer_calldata(to, 1 + (i * 31 + j) % 97),
            ), keys[k], chain_id))
            nonces[k] += 1

    return gen


def hot_genesis(config, n_keys: int, *, key_base: int = 0xA11CE0,
                gas_limit: int = 30_000_000):
    """(genesis, keys, addrs) for the hot workload — the ONE place the
    key derivation lives, so the bench's cache-reuse path and the
    chain builder below cannot drift apart."""
    from coreth_tpu.chain import Genesis
    from coreth_tpu.crypto.secp256k1 import priv_to_address
    keys = [key_base + i for i in range(n_keys)]
    addrs = [priv_to_address(k) for k in keys]
    genesis = Genesis(config=config, gas_limit=gas_limit,
                      alloc=hot_genesis_alloc(addrs))
    return genesis, keys, addrs


def build_hot_chain(config, n_blocks: int, txs_per_block: int,
                    n_keys: int = 64, *, alpha: float = 1.1,
                    seed: int = 20260804, gas_limit: int = 30_000_000,
                    key_base: int = 0xA11CE0):
    """Build the single-hot-contract chain (genesis, blocks) — shared
    by the bench ``hot_contract`` section, tools/mesh_scaling.py's
    hot mode, and the tier-1 scaling smoke."""
    from coreth_tpu.chain import generate_chain
    from coreth_tpu.state import Database
    genesis, keys, addrs = hot_genesis(config, n_keys,
                                       key_base=key_base,
                                       gas_limit=gas_limit)
    db = Database()
    gblock = genesis.to_block(db)
    nonces = [0] * n_keys
    gen = hot_tx_gen(keys, addrs, txs_per_block, nonces,
                     chain_id=config.chain_id, alpha=alpha, seed=seed)
    blocks, _ = generate_chain(config, gblock, db, n_blocks, gen,
                               gap=10)
    return genesis, blocks
