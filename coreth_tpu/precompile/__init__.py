"""Stateful precompile framework.

Twin of reference ``precompile/`` (contract/, modules/, precompileconfig/,
registry/): user-defined precompiles registered at reserved addresses,
activated/deactivated by chain-config upgrades, with predicate support
(gas + verify hooks consumed by the warp precompile).
"""

from coreth_tpu.precompile.modules import (  # noqa: F401
    Module,
    register_module,
    registered_modules,
    reserved_address,
)
