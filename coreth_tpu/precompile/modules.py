"""Stateful-precompile module registry.

Twin of reference precompile/modules/registerer.go: modules register at
reserved addresses (0x01/0x02/0x03 || 18*0x00 || xx) and are iterated in
deterministic (address) order — the order is consensus-relevant because
ApplyUpgrades writes state (state_processor.go:182-186).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

_RESERVED_PREFIXES = (b"\x01", b"\x02", b"\x03")
_RESERVED_BODY = b"\x00" * 18


def reserved_address(addr: bytes) -> bool:
    """modules/registerer.go:37 ReservedAddress."""
    return addr[:1] in _RESERVED_PREFIXES \
        and addr[1:19] == _RESERVED_BODY


@dataclass
class Module:
    address: bytes
    config_key: str
    contract: object  # Precompile with run_stateful
    # called by ApplyUpgrades; default = no state changes
    apply_upgrade: Callable = lambda *a, **k: None
    # activation timestamp (None = registered but inactive); modules
    # become visible through ChainConfig.rules() once active
    timestamp: Optional[int] = 0
    # optional precompileconfig.Predicater (predicate_gas/verify_predicate)
    predicater: object = None


def unregister_module(address: bytes) -> None:
    """Test hook: drop a registration (module registries in the
    reference are import-time-global too; tests need cleanup)."""
    _registry.pop(address, None)


_registry: Dict[bytes, Module] = {}


def register_module(module: Module) -> None:
    if not reserved_address(module.address):
        raise ValueError(
            f"address {module.address.hex()} not in a reserved range")
    for existing in _registry.values():
        if existing.config_key == module.config_key:
            raise ValueError(f"config key {module.config_key} already used")
    if module.address in _registry:
        raise ValueError(f"address {module.address.hex()} already used")
    _registry[module.address] = module


def registered_modules() -> List[Module]:
    """Sorted by address — deterministic iteration
    (registerer.go sortedness contract)."""
    return [m for _, m in sorted(_registry.items())]


def get_module(addr: bytes) -> Optional[Module]:
    return _registry.get(addr)
