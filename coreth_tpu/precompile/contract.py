"""Stateful precompile contract framework.

Twin of reference precompile/contract/ (contract.go
statefulPrecompileFunction + newStatefulPrecompileWithFunctionSelectors,
interfaces.go AccessibleState): a stateful precompile is a map from
4-byte ABI selectors to gas-charged functions that see the EVM
(statedb, block context, caller) — the mechanism every precompile
module (warp included) plugs into the interpreter through
(evm.precompile(), evm.go:78).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu import vmerrs


def selector(signature: str) -> bytes:
    """4-byte ABI selector from a function signature string."""
    return keccak256(signature.encode())[:4]


@dataclass
class PrecompileFunction:
    """One selector-dispatched entry point (contract.go
    statefulPrecompileFunction)."""
    sel: bytes
    execute: Callable  # (accessible_state, caller, addr, input, gas,
    #                     read_only) -> (ret, remaining_gas)


class StatefulPrecompiledContract:
    """Selector-dispatching stateful precompile (contract.go:57)."""

    stateful = True

    def __init__(self, functions: Dict[bytes, Callable],
                 fallback: Optional[Callable] = None):
        self.functions = functions
        self.fallback = fallback

    def run_stateful(self, evm, caller: bytes, addr: bytes,
                     input_: bytes, gas: int, read_only: bool
                     ) -> Tuple[bytes, int]:
        if len(input_) < 4:
            if self.fallback is not None:
                return self.fallback(evm, caller, addr, input_, gas,
                                     read_only)
            raise vmerrs.ErrExecutionReverted()
        fn = self.functions.get(input_[:4])
        if fn is None:
            raise vmerrs.ErrExecutionReverted()
        return fn(evm, caller, addr, input_[4:], gas, read_only)


def deduct_gas(gas: int, cost: int) -> int:
    """contract.go DeductGas."""
    if gas < cost:
        raise vmerrs.ErrOutOfGas()
    return gas - cost


# ------------------------------------------------------- ABI mini-codec

def abi_word(v) -> bytes:
    if isinstance(v, bytes):
        return v.rjust(32, b"\x00")
    return int(v).to_bytes(32, "big")


def abi_pack_bytes(payload: bytes) -> bytes:
    """Dynamic `bytes` tail encoding: length word + padded data."""
    padded = payload + b"\x00" * ((32 - len(payload) % 32) % 32)
    return abi_word(len(payload)) + padded
