"""Metrics registry: counters, gauges, meters, histograms, timers.

Twin of reference metrics/ (the go-metrics fork: registry.go +
metrics.go Enabled gate + prometheus/ gatherer): components register
named instruments in a hierarchy-by-name registry; the Prometheus
exposition renders the whole registry as text for scraping (the
endpoint AvalancheGo aggregates, vm.go:674 initializeMetrics).
"""

from coreth_tpu.metrics.registry import (
    Counter, Gauge, Histogram, Meter, Registry, Timer, default_registry,
    get_or_register,
)
from coreth_tpu.metrics.prometheus import render_prometheus

__all__ = [
    "Counter", "Gauge", "Histogram", "Meter", "Registry", "Timer",
    "default_registry", "get_or_register", "render_prometheus",
]
