"""The instrument types + registry (metrics/registry.go role).

`Enabled` gates cost the way the reference's metrics.Enabled /
EnabledExpensive do: when disabled, instruments become no-ops so hot
paths never pay for bookkeeping they do not report.
"""

from __future__ import annotations

import math
import threading
import time as _time
from typing import Callable, Dict, List, Optional

ENABLED = True


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        if not ENABLED:
            return
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self.value}


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def update(self, v: float) -> None:
        if ENABLED:
            self.value = v

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Meter:
    """Rate-of-events meter (count + mean rate since start)."""
    __slots__ = ("count", "start", "_lock")

    def __init__(self, clock=_time.monotonic):
        self.count = 0
        self.start = clock()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        if not ENABLED:
            return
        with self._lock:
            self.count += n

    def rate_mean(self, clock=_time.monotonic) -> float:
        dt = clock() - self.start
        # first-scrape guard: dt can be ~0 (a scrape right after
        # registration, or a coarse clock returning the same tick) and
        # count/dt would explode into a bogus rate — report 0 until a
        # meaningful interval has elapsed
        if dt < 1e-6:
            return 0.0
        return self.count / dt

    def snapshot(self) -> dict:
        return {"type": "meter", "count": self.count,
                "rate_mean": self.rate_mean()}


class Histogram:
    """Reservoir-free histogram: count/sum/min/max + fixed quantile
    estimation over a bounded ring of recent samples."""

    def __init__(self, window: int = 1028):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._ring: List[float] = []
        self._window = window
        self._lock = threading.Lock()

    def update(self, v: float) -> None:
        if not ENABLED:
            return
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._ring) >= self._window:
                self._ring[self.count % self._window] = v
            else:
                self._ring.append(v)

    def replace_from(self, other: "Histogram") -> None:
        """Adopt another histogram's state wholesale — the publish
        primitive for accumulating privately and exposing atomically
        in a registered instrument (window adopted too, so the ring
        invariant holds)."""
        with self._lock:
            self.count = other.count
            self.sum = other.sum
            self.min = other.min
            self.max = other.max
            self._window = other._window
            self._ring = list(other._ring)

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self._ring:
                return 0.0
            s = sorted(self._ring)
            return s[min(len(s) - 1, int(math.ceil(q * len(s))) - 1)]

    def snapshot(self) -> dict:
        return {"type": "histogram", "count": self.count,
                "sum": self.sum, "min": self.min or 0.0,
                "max": self.max or 0.0,
                "p50": self.quantile(0.5), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class Timer(Histogram):
    """Histogram over durations with a context-manager clock."""

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = _time.monotonic()
                return self

            def __exit__(self, *exc):
                timer.update(_time.monotonic() - self.t0)
                return False

        return _Ctx()

    def snapshot(self) -> dict:
        out = super().snapshot()
        out["type"] = "timer"
        return out


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        # optional one-line descriptions registered alongside a metric;
        # the Prometheus exposition renders them as # HELP lines
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    def register(self, name: str, metric,
                 description: Optional[str] = None) -> object:
        with self._lock:
            if name in self._metrics:
                raise ValueError(f"metric {name!r} already registered")
            self._metrics[name] = metric
            if description:
                self._help[name] = description
        return metric

    def get(self, name: str):
        return self._metrics.get(name)

    def description(self, name: str) -> Optional[str]:
        return self._help.get(name)

    def get_or_register(self, name: str, factory: Callable,
                        description: Optional[str] = None):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            if description and name not in self._help:
                self._help[name] = description
            return m

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)
            self._help.pop(name, None)

    def each(self):
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self) -> Dict[str, dict]:
        return {name: m.snapshot() for name, m in self.each()}


default_registry = Registry()


def get_or_register(name: str, factory: Callable,
                    registry: Optional[Registry] = None,
                    description: Optional[str] = None):
    return (registry or default_registry).get_or_register(
        name, factory, description)
