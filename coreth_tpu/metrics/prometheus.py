"""Prometheus text exposition of a metrics registry.

Twin of reference metrics/prometheus/ (the gatherer AvalancheGo scrapes
through its own endpoint): metric names sanitize '/' and '.' into '_',
histograms/timers expose count/sum and quantile gauges.
"""

from __future__ import annotations

from typing import Optional

from coreth_tpu.metrics.registry import Registry, default_registry


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    return s if not s[:1].isdigit() else "_" + s


def render_prometheus(registry: Optional[Registry] = None) -> str:
    reg = registry or default_registry
    lines = []
    for name, metric in reg.each():
        snap = metric.snapshot()
        base = _sanitize(name)
        kind = snap.pop("type")
        desc = reg.description(name)
        if desc:
            # HELP precedes TYPE for the metric family's primary name
            # (meters expose under <base>_total)
            helped = f"{base}_total" if kind == "meter" else base
            lines.append(f"# HELP {helped} {desc}")
        if kind == "counter":
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{base} {snap['count']}")
        elif kind == "gauge":
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {snap['value']}")
        elif kind == "meter":
            lines.append(f"# TYPE {base}_total counter")
            lines.append(f"{base}_total {snap['count']}")
            lines.append(f"# TYPE {base}_rate_mean gauge")
            lines.append(f"{base}_rate_mean {snap['rate_mean']}")
        else:  # histogram / timer
            lines.append(f"# TYPE {base} summary")
            for q in ("p50", "p95", "p99"):
                quant = q[1:] if q != "p50" else "50"
                lines.append(
                    f'{base}{{quantile="0.{quant}"}} {snap[q]}')
            lines.append(f"{base}_sum {snap['sum']}")
            lines.append(f"{base}_count {snap['count']}")
    return "\n".join(lines) + "\n"
