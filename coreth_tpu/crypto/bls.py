"""BLS12-381 signatures: sign / verify / aggregate.

Role parity with the reference's supranational/blst dependency
(SURVEY.md section 2.7), which warp uses for validator signatures
(warp/backend.go:136 signing, aggregator quorum verification).  This is
the min-pk scheme blst implements: secret keys are scalars, public
keys live in G1 (48-byte compressed), signatures in G2 (96-byte
compressed); aggregation is point addition on either side.

The pairing is the optimal-ate over the Fq12 tower computed with
affine Miller-loop arithmetic (the py_ecc-style formulation: clarity
over speed — this is host-side control-plane crypto, not the TPU hot
path).  Hash-to-curve is the RFC 9380 SSWU suite
(BLS12381G2_XMD:SHA-256_SSWU_RO_ with blst's proof-of-possession DST,
crypto/h2c.py): expand_message_xmd, hash_to_field, simplified SWU onto
the 3-isogenous curve, the degree-3 isogeny back to E2 (coefficients
validated on-curve at import), and cofactor clearing — replacing the
earlier try-and-increment map (round-4 verdict #6).  The pipeline
reproduces the RFC 9380 Appendix J.10.1 known-answer vectors
byte-for-byte (tests/test_crypto), so signatures are wire-compatible
with blst.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

# ---------------------------------------------------------------- params

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000  # the BLS parameter (negative)

G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2X = (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
       0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E)
G2Y = (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
       0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE)

H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551  # noqa: E501 — G2 cofactor effective multiple


# ------------------------------------------------------------- Fq tower

def _inv(a: int, m: int = P) -> int:
    return pow(a, m - 2, m)


class Fq2(tuple):
    """Fq[u] / (u^2 + 1)."""

    def __new__(cls, c0: int, c1: int):
        return super().__new__(cls, (c0 % P, c1 % P))

    def __add__(self, o):
        return Fq2(self[0] + o[0], self[1] + o[1])

    def __sub__(self, o):
        return Fq2(self[0] - o[0], self[1] - o[1])

    def __neg__(self):
        return Fq2(-self[0], -self[1])

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq2(self[0] * o, self[1] * o)
        a0, a1 = self
        b0, b1 = o
        t0 = a0 * b0
        t1 = a1 * b1
        return Fq2(t0 - t1, (a0 + a1) * (b0 + b1) - t0 - t1)

    __rmul__ = __mul__

    def sq(self):
        a0, a1 = self
        return Fq2((a0 + a1) * (a0 - a1), 2 * a0 * a1)

    def inv(self):
        a0, a1 = self
        d = _inv(a0 * a0 + a1 * a1)
        return Fq2(a0 * d, -a1 * d)

    def conj(self):
        return Fq2(self[0], -self[1])

    def is_zero(self):
        return self[0] == 0 and self[1] == 0

    def sqrt(self) -> Optional["Fq2"]:
        """Square root in Fq2 (complex method), or None."""
        a0, a1 = self
        if a1 == 0:
            s = pow(a0, (P + 1) // 4, P)
            if s * s % P == a0:
                return Fq2(s, 0)
            # a0 is a non-residue: sqrt = u * sqrt(-a0)
            s = pow((-a0) % P, (P + 1) // 4, P)
            if s * s % P == (-a0) % P:
                return Fq2(0, s)
            return None
        # norm = a0^2 + a1^2 must be a residue
        n = (a0 * a0 + a1 * a1) % P
        d = pow(n, (P + 1) // 4, P)
        if d * d % P != n:
            return None
        two_inv = _inv(2)
        x0 = (a0 + d) * two_inv % P
        s0 = pow(x0, (P + 1) // 4, P)
        if s0 * s0 % P != x0:
            x0 = (a0 - d) * two_inv % P
            s0 = pow(x0, (P + 1) // 4, P)
            if s0 * s0 % P != x0:
                return None
        s1 = a1 * _inv(2 * s0) % P
        cand = Fq2(s0, s1)
        return cand if cand.sq() == self else None


FQ2_ONE = Fq2(1, 0)
FQ2_ZERO = Fq2(0, 0)

# Fq12 as polynomials over Fq modulo w^12 - 2w^6 + 2 — the py_ecc
# formulation (w^6 = w^6; the modulus encodes w^6 = u + 1 with u^2=-1
# flattened to a single extension, avoiding the explicit tower).
FQ12_MODULUS = [2, 0, 0, 0, 0, 0, -2, 0, 0, 0, 0, 0]  # + w^12


class Fq12:
    __slots__ = ("c",)

    def __init__(self, coeffs: Sequence[int]):
        self.c = [x % P for x in coeffs]

    @classmethod
    def one(cls):
        return cls([1] + [0] * 11)

    @classmethod
    def zero(cls):
        return cls([0] * 12)

    def __eq__(self, o):
        return self.c == o.c

    def __add__(self, o):
        return Fq12([a + b for a, b in zip(self.c, o.c)])

    def __sub__(self, o):
        return Fq12([a - b for a, b in zip(self.c, o.c)])

    def __neg__(self):
        return Fq12([-a for a in self.c])

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq12([a * o for a in self.c])
        b = [0] * 23
        for i, ai in enumerate(self.c):
            if ai:
                for j, bj in enumerate(o.c):
                    b[i + j] += ai * bj
        # reduce by w^12 = 2w^6 - 2
        for i in range(22, 11, -1):
            t = b[i]
            if t:
                b[i] = 0
                b[i - 6] += 2 * t
                b[i - 12] -= 2 * t
        return Fq12(b[:12])

    __rmul__ = __mul__

    def inv(self):
        """Extended euclid over Fq[w] mod the fixed modulus (py_ecc)."""
        lm, hm = [1] + [0] * 12, [0] * 13
        low = self.c + [0]
        high = FQ12_MODULUS + [1]

        def deg(p):
            d = len(p) - 1
            while d and p[d] % P == 0:
                d -= 1
            return d

        def poly_rounded_div(a, b):
            dega, degb = deg(a), deg(b)
            temp = [x for x in a]
            o = [0] * len(a)
            for i in range(dega - degb, -1, -1):
                q = temp[degb + i] * _inv(b[degb]) % P
                o[i] += q
                for c in range(degb + 1):
                    temp[c + i] -= o[i] * b[c]
            return [x % P for x in o[:deg(o) + 1]]

        while deg(low):
            r = poly_rounded_div(high, low)
            r += [0] * (13 - len(r))
            nm = [x for x in hm]
            new = [x for x in high]
            for i in range(13):
                for j in range(13 - i):
                    nm[i + j] -= lm[i] * r[j]
                    new[i + j] -= low[i] * r[j]
            nm = [x % P for x in nm]
            new = [x % P for x in new]
            lm, low, hm, high = nm, new, lm, low
        d = _inv(low[0])
        return Fq12([x * d % P for x in lm[:12]])

    def pow(self, e: int):
        result = Fq12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base * base
            e >>= 1
        return result


# Fq2 -> Fq12 embedding: u maps to w^6 - 1 (since w^6 = u + 1)
def fq2_to_fq12(a: Fq2) -> Fq12:
    c = [0] * 12
    c[0] = (a[0] - a[1]) % P
    c[6] = a[1]
    return Fq12(c)


# ----------------------------------------------------------- the curves

# E1: y^2 = x^3 + 4 over Fq; E2: y^2 = x^3 + 4(u+1) over Fq2
B1 = 4
B2 = Fq2(4, 4)

G1 = (G1X, G1Y)
G2 = (Fq2(*G2X), Fq2(*G2Y))


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B1) % P == 0


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y.sq() - x.sq() * x == B2


def _ec_add(p1, p2, fadd, fsub, fmul, fsq, finv, is_eq, neg_y):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if is_eq(x1, x2):
        if is_eq(y1, y2):
            # double
            lam = fmul(fmul(fsq(x1), 3), finv(fmul(y1, 2)))
        else:
            return None
    else:
        lam = fmul(fsub(y2, y1), finv(fsub(x2, x1)))
    x3 = fsub(fsub(fsq(lam), x1), x2)
    y3 = fsub(fmul(lam, fsub(x1, x3)), y1)
    return (x3, y3)


def g1_add(p1, p2):
    return _ec_add(
        p1, p2,
        lambda a, b: (a + b) % P, lambda a, b: (a - b) % P,
        lambda a, b: a * b % P, lambda a: a * a % P, _inv,
        lambda a, b: a == b, lambda y: (-y) % P)


def g1_neg(p1):
    return None if p1 is None else (p1[0], (-p1[1]) % P)


def g1_mul(pt, k: int):
    return _g1_mul_raw(pt, k % R)


def g2_add(p1, p2):
    return _ec_add(
        p1, p2,
        lambda a, b: a + b, lambda a, b: a - b,
        lambda a, b: (a * b) if isinstance(b, Fq2) else a * b,
        lambda a: a.sq(), lambda a: a.inv(),
        lambda a, b: a == b, lambda y: -y)


def g2_neg(pt):
    return None if pt is None else (pt[0], -pt[1])


def g2_mul(pt, k: int):
    acc = None
    while k:
        if k & 1:
            acc = g2_add(acc, pt)
        pt = g2_add(pt, pt)
        k >>= 1
    return acc


# ----------------------------------------------------------- the pairing

def _fq12_point_add(p1, p2):
    return _ec_add(
        p1, p2,
        lambda a, b: a + b, lambda a, b: a - b,
        lambda a, b: a * b, lambda a: a * a, lambda a: a.inv(),
        lambda a, b: a == b, lambda y: -y)


def _fq12_point_mul(pt, k):
    acc = None
    while k:
        if k & 1:
            acc = _fq12_point_add(acc, pt)
        pt = _fq12_point_add(pt, pt)
        k >>= 1
    return acc


def _twist(pt):
    """E2 -> E(Fq12) untwist (py_ecc twist): (x, y) ->
    (x' / w^2, y' / w^3) with the u -> w^6-1 embedding."""
    if pt is None:
        return None
    x, y = pt
    xc = [(x[0] - x[1]) % P, x[1]]
    yc = [(y[0] - y[1]) % P, y[1]]
    nx = Fq12([xc[0]] + [0] * 5 + [xc[1]] + [0] * 5)
    ny = Fq12([yc[0]] + [0] * 5 + [yc[1]] + [0] * 5)
    w = Fq12([0, 1] + [0] * 10)
    w2, w3 = w * w, w * w * w
    return (nx * w2.inv(), ny * w3.inv())


def _g1_to_fq12(pt):
    if pt is None:
        return None
    return (Fq12([pt[0]] + [0] * 11), Fq12([pt[1]] + [0] * 11))


def _linefunc(p1, p2, t):
    """Evaluate the line through p1, p2 at t (all over Fq12)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if not x1 == x2:
        m = (y2 - y1) * (x2 - x1).inv()
        return m * (xt - x1) - (yt - y1)
    if y1 == y2:
        m = (x1 * x1) * 3 * (y1 * 2).inv()
        return m * (xt - x1) - (yt - y1)
    return xt - x1


ATE_LOOP_COUNT = 15132376222941642752  # |x|, the BLS parameter
LOG_ATE = 62


def miller_loop(q, p) -> Fq12:
    """f_{T,Q}(P) for the ate pairing (py_ecc formulation)."""
    if q is None or p is None:
        return Fq12.one()
    r_pt = q
    f = Fq12.one()
    for i in range(LOG_ATE, -1, -1):
        f = f * f * _linefunc(r_pt, r_pt, p)
        r_pt = _fq12_point_add(r_pt, r_pt)
        if ATE_LOOP_COUNT & (2 ** i):
            f = f * _linefunc(r_pt, q, p)
            r_pt = _fq12_point_add(r_pt, q)
    return f.pow((P ** 12 - 1) // R)


def pairing(q_g2, p_g1) -> Fq12:
    """e(P, Q) with P in G1, Q in G2."""
    if p_g1 is None or q_g2 is None:
        return Fq12.one()
    return miller_loop(_twist(q_g2), _g1_to_fq12(p_g1))


# ------------------------------------------------------- subgroup checks

def _g1_mul_raw(pt, k: int):
    """Scalar mul WITHOUT reducing k mod R (g1_mul reduces, which would
    make a subgroup check k=R trivially pass)."""
    acc = None
    while k:
        if k & 1:
            acc = g1_add(acc, pt)
        pt = g1_add(pt, pt)
        k >>= 1
    return acc


def g1_in_subgroup(pt) -> bool:
    """Prime-order subgroup membership: [r]P == O.  E1(Fq) has order
    h1*r with cofactor h1 = 0x396c8c005555e1568c00aaab0000aaab, so an
    on-curve point can still lie outside G1."""
    return pt is None or _g1_mul_raw(pt, R) is None


def g2_in_subgroup(pt) -> bool:
    """[r]Q == O on E2 (the E2 cofactor is ~2^381, so the check is
    essential for untrusted 96-byte inputs)."""
    return pt is None or g2_mul(pt, R) is None


# ------------------------------------------------------------- encoding

def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + b"\x00" * 47
    x, y = pt
    flag = 0x80 | (0x20 if y > (P - 1) // 2 else 0)
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flag
    return bytes(raw)


@lru_cache(maxsize=4096)
def g1_decompress(data: bytes):
    """Decompress + subgroup-check, memoized: validator pubkeys recur
    on every warp verification, and the [r]P membership check is the
    expensive part — the cache makes it once-per-key.  Safe because the
    returned point is an immutable tuple of ints."""
    if len(data) != 48:
        raise ValueError("bad G1 encoding length")
    if data[0] & 0x40:
        return None  # infinity
    y_flag = bool(data[0] & 0x20)
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    y2 = (pow(x, 3, P) + B1) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("x not on curve")
    if (y > (P - 1) // 2) != y_flag:
        y = P - y
    pt = (x, y)
    # blst enforces subgroup membership on deserialization; accepting
    # points outside G1 enables small-subgroup/malleability attacks on
    # warp pubkeys (advisor finding, round 3)
    if not g1_in_subgroup(pt):
        raise ValueError("point not in the r-order subgroup")
    return pt


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + b"\x00" * 95
    x, y = pt
    # sign from the lexicographically-largest test on (c1, c0)
    neg = -y
    bigger = (y[1], y[0]) > (neg[1], neg[0])
    flag = 0x80 | (0x20 if bigger else 0)
    raw = bytearray(x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big"))
    raw[0] |= flag
    return bytes(raw)


@lru_cache(maxsize=4096)
def g2_decompress(data: bytes):
    if len(data) != 96:
        raise ValueError("bad G2 encoding length")
    if data[0] & 0x40:
        return None
    y_flag = bool(data[0] & 0x20)
    c1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    c0 = int.from_bytes(data[48:], "big")
    x = Fq2(c0, c1)
    y = (x.sq() * x + B2).sqrt()
    if y is None:
        raise ValueError("x not on curve")
    neg = -y
    if ((y[1], y[0]) > (neg[1], neg[0])) != y_flag:
        y = neg
    pt = (x, y)
    if not g2_in_subgroup(pt):
        raise ValueError("point not in the r-order subgroup")
    return pt


# -------------------------------------------------------- hash to curve

# blst's min-pk proof-of-possession ciphersuite tag (crypto/h2c.py)
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def hash_to_g2(msg: bytes, dst: bytes = DST):
    """RFC 9380 hash_to_curve for G2 (SSWU; see crypto/h2c.py)."""
    from coreth_tpu.crypto import h2c
    return h2c.hash_to_g2(msg, dst)


# ------------------------------------------------------------- the API

class BLSError(Exception):
    pass


def secret_from_bytes(ikm: bytes) -> int:
    """Deterministic keygen from seed material."""
    h = hashlib.sha512(b"coreth-tpu-bls-keygen" + ikm).digest()
    sk = int.from_bytes(h, "big") % R
    return sk or 1


def public_key(sk: int) -> bytes:
    return g1_compress(g1_mul(G1, sk))


def sign(sk: int, msg: bytes) -> bytes:
    return g2_compress(g2_mul(hash_to_g2(msg), sk))


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    try:
        pk_pt = g1_decompress(pk)
        sig_pt = g2_decompress(sig)
    except ValueError:
        return False
    if pk_pt is None or sig_pt is None:
        return False
    h = hash_to_g2(msg)
    # e(pk, H(m)) == e(g1, sig)  <=>  e(-pk, H(m)) * e(g1, sig) == 1
    lhs = pairing(h, g1_neg(pk_pt))
    rhs = pairing(sig_pt, G1)
    return lhs * rhs == Fq12.one()


def aggregate_signatures(sigs: List[bytes]) -> bytes:
    acc = None
    for s in sigs:
        acc = g2_add(acc, g2_decompress(s))
    return g2_compress(acc)


def aggregate_public_keys(pks: List[bytes]) -> bytes:
    acc = None
    for p in pks:
        acc = g1_add(acc, g1_decompress(p))
    return g1_compress(acc)


def verify_aggregate(pks: List[bytes], msg: bytes, sig: bytes) -> bool:
    """Same-message aggregate verify (the warp quorum check)."""
    if not pks:
        return False
    return verify(aggregate_public_keys(pks), msg, sig)
