"""Keccak-256 — host reference implementation.

Ethereum uses the *original* Keccak padding (delimited suffix 0x01), not the
NIST SHA-3 suffix (0x06), so :mod:`hashlib`'s sha3_256 cannot be used.

This is the correctness anchor for the whole framework: trie hashing
(reference trie/hasher.go:195 hashData), tx/receipt roots (reference
core/types/hashing.go:97 DeriveSha), CREATE2 addresses, secure-trie key
hashing, and the SHA3 opcode all bottom out here (or in the batched device
kernel in coreth_tpu.ops.keccak, which is cross-checked against this).

Structure follows the Keccak team's public-domain CompactFIPS202 Python
(round constants derived by LFSR rather than hard-coded, eliminating a class
of transcription bugs).  A C++ native fast path lives in native/keccak.cc and
is preferred automatically when built (see coreth_tpu.crypto.native).
"""

from __future__ import annotations

_MASK = (1 << 64) - 1


def _rol(v: int, n: int) -> int:
    n &= 63
    return ((v << n) | (v >> (64 - n))) & _MASK


def keccak_f1600(lanes):
    """Permute a 5x5 list-of-lists of 64-bit lanes; returns the new state
    (the input list must not be reused afterwards)."""
    R = 1
    for _round in range(24):
        # theta
        C = [lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3] ^ lanes[x][4]
             for x in range(5)]
        D = [C[(x + 4) % 5] ^ _rol(C[(x + 1) % 5], 1) for x in range(5)]
        lanes = [[lanes[x][y] ^ D[x] for y in range(5)] for x in range(5)]
        # rho and pi
        x, y = 1, 0
        current = lanes[x][y]
        for t in range(24):
            x, y = y, (2 * x + 3 * y) % 5
            current, lanes[x][y] = lanes[x][y], _rol(current, (t + 1) * (t + 2) // 2)
        # chi
        for y in range(5):
            T = [lanes[x][y] for x in range(5)]
            for x in range(5):
                lanes[x][y] = T[x] ^ ((~T[(x + 1) % 5]) & T[(x + 2) % 5] & _MASK)
        # iota
        for j in range(7):
            R = ((R << 1) ^ ((R >> 7) * 0x71)) % 256
            if R & 2:
                lanes[0][0] ^= 1 << ((1 << j) - 1)
    return lanes


def _keccak(rate_bytes: int, suffix: int, data: bytes, out_len: int) -> bytes:
    lanes = [[0] * 5 for _ in range(5)]

    def absorb_block(block: bytes) -> None:
        for i in range(rate_bytes // 8):
            lane = int.from_bytes(block[8 * i:8 * i + 8], "little")
            lanes[i % 5][i // 5] ^= lane

    # absorb full blocks
    off = 0
    n = len(data)
    while n - off >= rate_bytes:
        absorb_block(data[off:off + rate_bytes])
        lanes = keccak_f1600(lanes)
        off += rate_bytes
    # pad10*1 with the keccak suffix
    block = bytearray(data[off:])
    block.append(suffix)
    block.extend(b"\x00" * (rate_bytes - len(block)))
    block[-1] ^= 0x80
    absorb_block(bytes(block))
    lanes = keccak_f1600(lanes)
    # squeeze (out_len <= rate for all our uses)
    out = bytearray()
    for i in range(rate_bytes // 8):
        out.extend(lanes[i % 5][i // 5].to_bytes(8, "little"))
        if len(out) >= out_len:
            break
    return bytes(out[:out_len])


def keccak256_py(data: bytes) -> bytes:
    """Pure-python keccak-256 (rate 136, suffix 0x01)."""
    return _keccak(136, 0x01, data, 32)


# Native fast path is installed lazily by coreth_tpu.crypto.native; default to
# the pure-python implementation so the module works with no build step.
_impl = keccak256_py


def keccak256(data: bytes) -> bytes:
    return _impl(data)


def set_impl(fn) -> None:
    global _impl
    _impl = fn


EMPTY_KECCAK = bytes.fromhex(
    "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")


def keccak256_many(msgs) -> list:
    """Digests for a batch of equal-length messages in ONE native call
    (coreth_keccak256_batch) when the C++ runtime is loaded, else the
    per-message path.  The premap predictor hashes every predicted
    (source-word || slot) pair of a window through this, so prediction
    costs one ctypes crossing per window instead of one keccak call per
    candidate key."""
    msgs = list(msgs)
    if not msgs:
        return []
    from coreth_tpu.crypto import native
    if native.load() is not None and len(msgs) > 1:
        stride = max(len(m) for m in msgs)
        blob = b"".join(m.ljust(stride, b"\x00") for m in msgs)
        out = native.keccak256_batch(blob, [len(m) for m in msgs],
                                     stride)
        return [out[32 * i:32 * i + 32] for i in range(len(msgs))]
    return [keccak256(m) for m in msgs]
