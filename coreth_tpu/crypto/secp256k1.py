"""secp256k1 ECDSA — host reference implementation.

Role parity with the reference's cgo libsecp256k1 binding (geth
crypto/secp256k1, used by types.Sender for every transaction and by the
ecrecover precompile, reference core/vm/contracts.go:60).  The pure-Python
code here is the correctness anchor; a C++ native fast path (native/
secp256k1.cc, batched recovery) is installed by coreth_tpu.crypto.native.

Signing is RFC6979-deterministic (same scheme libsecp256k1 uses), with
Ethereum's low-s normalization (EIP-2) and 0/1 recovery ids.
"""

from __future__ import annotations

import hashlib
import hmac

from coreth_tpu.crypto.keccak import keccak256

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
B = 7

# ---------------------------------------------------------------------------
# Jacobian point arithmetic (None = point at infinity)


def _jac_double(pt):
    if pt is None:
        return None
    x, y, z = pt
    if y == 0:
        return None
    ysq = (y * y) % P
    s = (4 * x * ysq) % P
    m = (3 * x * x) % P  # a = 0 for secp256k1
    nx = (m * m - 2 * s) % P
    ny = (m * (s - nx) - 8 * ysq * ysq) % P
    nz = (2 * y * z) % P
    return (nx, ny, nz)


def _jac_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1sq = (z1 * z1) % P
    z2sq = (z2 * z2) % P
    u1 = (x1 * z2sq) % P
    u2 = (x2 * z1sq) % P
    s1 = (y1 * z2sq * z2) % P
    s2 = (y2 * z1sq * z1) % P
    if u1 == u2:
        if s1 != s2:
            return None
        return _jac_double(p1)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    hsq = (h * h) % P
    hcu = (hsq * h) % P
    v = (u1 * hsq) % P
    nx = (r * r - hcu - 2 * v) % P
    ny = (r * (v - nx) - s1 * hcu) % P
    nz = (h * z1 * z2) % P
    return (nx, ny, nz)


def _jac_mul(pt, k: int):
    k %= N
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = _jac_add(acc, add)
        add = _jac_double(add)
        k >>= 1
    return acc


# Fixed-base acceleration for G: 8-bit windows of precomputed multiples,
# built lazily on first signature (32 windows x 255 points).  Signing and
# pubkey derivation drop from ~256 doublings to ~32 additions.
_G_WINDOWS = None


def _g_windows():
    global _G_WINDOWS
    if _G_WINDOWS is None:
        windows = []
        base = (Gx, Gy, 1)
        for _ in range(32):
            row = [None] * 256
            acc = None
            for j in range(1, 256):
                acc = _jac_add(acc, base)
                row[j] = acc
            windows.append(row)
            # base <<= 8
            for _ in range(8):
                base = _jac_double(base)
        _G_WINDOWS = windows
    return _G_WINDOWS


def _g_mul(k: int):
    """k*G via the fixed-base window table."""
    k %= N
    windows = _g_windows()
    acc = None
    i = 0
    while k:
        byte = k & 0xFF
        if byte:
            acc = _jac_add(acc, windows[i][byte])
        k >>= 8
        i += 1
    return acc


def _to_affine(pt):
    if pt is None:
        return None
    x, y, z = pt
    zinv = pow(z, P - 2, P)
    zinv2 = (zinv * zinv) % P
    return ((x * zinv2) % P, (y * zinv2 * zinv) % P)


def _on_curve(x: int, y: int) -> bool:
    return (y * y - (x * x * x + B)) % P == 0


# ---------------------------------------------------------------------------
# RFC6979 deterministic nonce (SHA-256)


def _rfc6979_k(priv: int, msg_hash: bytes) -> int:
    x = priv.to_bytes(32, "big")
    h1 = (int.from_bytes(msg_hash, "big") % N).to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


# ---------------------------------------------------------------------------
# Public API


def pubkey(priv: int) -> tuple[int, int]:
    pt = _to_affine(_g_mul(priv))
    assert pt is not None
    return pt


def pubkey_to_address(pub: tuple[int, int]) -> bytes:
    raw = pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
    return keccak256(raw)[12:]


def priv_to_address(priv: int) -> bytes:
    return pubkey_to_address(pubkey(priv))


def sign(msg_hash: bytes, priv: int) -> tuple[int, int, int]:
    """Sign a 32-byte hash.  Returns (r, s, recid) with low-s and recid in {0,1}."""
    z = int.from_bytes(msg_hash, "big")
    while True:
        k = _rfc6979_k(priv, msg_hash)
        R = _to_affine(_g_mul(k))
        assert R is not None
        r = R[0] % N
        if r == 0:
            continue
        s = (pow(k, N - 2, N) * ((z + r * priv) % N)) % N
        if s == 0:
            continue
        recid = (R[1] & 1) | (2 if R[0] >= N else 0)
        if s > N // 2:  # EIP-2 low-s
            s = N - s
            recid ^= 1
        return r, s, recid


def recover_pubkey(msg_hash: bytes, r: int, s: int, recid: int) -> tuple[int, int]:
    """Recover the signer's public key.  Raises ValueError on invalid input.

    Matches libsecp256k1 ecdsa_recover semantics (reference
    crypto.SigToPub / the ecrecover precompile): requires 0 < r,s < N.
    """
    if not (0 < r < N and 0 < s < N and 0 <= recid <= 3):
        raise ValueError("invalid signature values")
    x = r + N if recid & 2 else r
    if x >= P:
        raise ValueError("r out of field range")
    ysq = (pow(x, 3, P) + B) % P
    y = pow(ysq, (P + 1) // 4, P)
    if (y * y) % P != ysq:
        raise ValueError("r is not an x coordinate on the curve")
    if (y & 1) != (recid & 1):
        y = P - y
    z = int.from_bytes(msg_hash, "big")
    rinv = pow(r, N - 2, N)
    u1 = (-z * rinv) % N
    u2 = (s * rinv) % N
    Q = _jac_add(_g_mul(u1), _jac_mul((x, y, 1), u2))
    pt = _to_affine(Q)
    if pt is None:
        raise ValueError("recovered point at infinity")
    return pt


def recover_address_py(msg_hash: bytes, r: int, s: int, recid: int) -> bytes:
    return pubkey_to_address(recover_pubkey(msg_hash, r, s, recid))


# Native fast path is installed by coreth_tpu.crypto.native when built.
_recover_impl = recover_address_py


def recover_address(msg_hash: bytes, r: int, s: int, recid: int) -> bytes:
    return _recover_impl(msg_hash, r, s, recid)


def set_recover_impl(fn) -> None:
    global _recover_impl
    _recover_impl = fn
