"""Host cryptography for coreth-tpu.

Pure-Python reference implementations with a C++ native fast path (built from
native/, loaded via ctypes).  Device-batched variants live in coreth_tpu.ops.
"""

from coreth_tpu.crypto.keccak import (
    keccak256, keccak256_many, keccak256_py, EMPTY_KECCAK,
)

# Try to activate the native fast path; harmless if the library isn't built.
try:  # pragma: no cover - exercised when native lib present
    from coreth_tpu.crypto import native as _native
    _native.install()
except Exception:  # noqa: BLE001 - any failure leaves the pure-py path active
    pass

__all__ = ["keccak256", "keccak256_many", "keccak256_py",
           "EMPTY_KECCAK"]
