"""RFC 9380 hash-to-curve for BLS12-381 G2 (SSWU, XMD:SHA-256).

The suite blst implements for Avalanche warp signatures
(BLS12381G2_XMD:SHA-256_SSWU_RO_, reference warp/backend.go:136 via
supranational/blst): expand_message_xmd (§5.3.1), hash_to_field with
m=2 / L=64 / count=2 (§5.2), the simplified SWU map onto the
3-isogenous curve E' (§6.6.2: A' = 240*I, B' = 1012*(1+I),
Z = -(2+I)), the degree-3 isogeny back to E2 (Appendix E.3), and
cofactor clearing by the effective G2 cofactor.

Validation: (a) the isogeny coefficients are cross-checked at import
by mapping random E' points and asserting the images satisfy E2's
curve equation y^2 = x^3 + 4(1+I); (b) the full pipeline reproduces
the RFC 9380 Appendix J.10.1 known-answer vectors byte-for-byte
(tests/test_crypto.test_rfc9380_known_answer_vectors), which pins
wire compatibility with every conforming implementation, blst
included.
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

from coreth_tpu.crypto import bls as _b

P = _b.P
Fq2 = _b.Fq2

# E' (the 3-isogenous SSWU target): y^2 = x^3 + A'x + B'  (RFC 8.8.2)
A_ISO = Fq2(0, 240)
B_ISO = Fq2(1012, 1012)
Z_SSWU = Fq2(P - 2, P - 1)          # -(2 + I)

# Degree-3 isogeny E' -> E2 coefficients (RFC 9380 Appendix E.3).
# Layout: x = x_num(x')/x_den(x'), y = y' * y_num(x')/y_den(x') with
# coefficient lists ordered from degree 0 upward; x_den and y_den are
# monic (leading 1 implicit in the lists below).
_K = 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6
_L = 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A
_M = 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D

X_NUM = [
    Fq2(_K, _K),
    Fq2(0, _L),
    Fq2(_L + 4, _M),                   # ...c71e, ...e38d
    Fq2(0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0),  # noqa: E501
]
X_DEN = [
    Fq2(0, P - 72),                    # ...aa63
    Fq2(12, P - 12),                   # ...aa9f
    Fq2(1, 0),                         # monic x^2
]
Y_NUM = [
    Fq2(0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,  # noqa: E501
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),  # noqa: E501
    Fq2(0, _K - 24),                   # ...a97be
    Fq2(_L + 2, _M + 2),               # ...c71c, ...e38f
    Fq2(0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0),  # noqa: E501
]
Y_DEN = [
    Fq2(P - 432, P - 432),             # ...a8fb
    Fq2(0, P - 216),                   # ...a9d3
    Fq2(18, P - 18),                   # ...aa99
    Fq2(1, 0),                         # monic x^3
]


def expand_message_xmd(msg: bytes, dst: bytes,
                       len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256 (b=32, s=64)."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + 31) // 32
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * 64
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(
        z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [bi]
    for i in range(2, ell + 1):
        x = bytes(a ^ b for a, b in zip(b0, bi))
        bi = hashlib.sha256(x + i.to_bytes(1, "big")
                            + dst_prime).digest()
        out.append(bi)
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, dst: bytes,
                      count: int = 2) -> List[Fq2]:
    """RFC 9380 §5.2: m=2, L=64 for BLS12-381."""
    L = 64
    blob = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        cs = []
        for j in range(2):
            off = L * (j + i * 2)
            cs.append(int.from_bytes(blob[off:off + L], "big") % P)
        out.append(Fq2(cs[0], cs[1]))
    return out


def _sgn0(x: Fq2) -> int:
    """RFC 9380 §4.1 sgn0 for m=2."""
    sign_0 = x[0] % 2
    zero_0 = x[0] == 0
    sign_1 = x[1] % 2
    return sign_0 | (1 if (zero_0 and sign_1) else 0)


def _g_iso(x: Fq2) -> Fq2:
    return x.sq() * x + A_ISO * x + B_ISO


def sswu(u: Fq2) -> Tuple[Fq2, Fq2]:
    """Simplified SWU onto E' (RFC 9380 §6.6.2)."""
    u2 = u.sq()
    tv1 = Z_SSWU * u2
    tv2 = tv1.sq() + tv1                   # Z^2 u^4 + Z u^2
    if tv2.is_zero():
        x1 = B_ISO * (Z_SSWU * A_ISO).inv()
    else:
        x1 = NEG_B_OVER_A * (FQ2_ONE + tv2.inv())
    gx1 = _g_iso(x1)
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x = tv1 * x1
        gx2 = _g_iso(x)
        y = gx2.sqrt()
        assert y is not None  # exactly one of gx1/gx2 is square
    if _sgn0(u) != _sgn0(y):
        y = -y
    return x, y


FQ2_ONE = Fq2(1, 0)
NEG_B_OVER_A = -(B_ISO * A_ISO.inv())


def _horner(coeffs: List[Fq2], x: Fq2) -> Fq2:
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = acc * x + c
    return acc


def iso3(pt: Tuple[Fq2, Fq2]) -> Tuple[Fq2, Fq2]:
    """The 3-isogeny E' -> E2 (Appendix E.3)."""
    x, y = pt
    xn = _horner(X_NUM, x)
    xd = _horner(X_DEN, x)
    yn = _horner(Y_NUM, x)
    yd = _horner(Y_DEN, x)
    return xn * xd.inv(), y * yn * yd.inv()


def map_to_curve_g2(u: Fq2) -> Tuple[Fq2, Fq2]:
    return iso3(sswu(u))


# AvalancheGo/blst ciphersuite tags (min-pk, proof-of-possession
# scheme): signatures hash with the SIG tag, possession proofs with POP
DST_SIG = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def hash_to_g2(msg: bytes, dst: bytes = DST_SIG):
    """Full hash_to_curve: two field elements, two SSWU maps, point
    add on E2, clear cofactor (§3 hash_to_curve)."""
    u0, u1 = hash_to_field_fq2(msg, dst)
    q0 = map_to_curve_g2(u0)
    q1 = map_to_curve_g2(u1)
    r = _b.g2_add(q0, q1)
    return _b.g2_mul(r, _b.H_EFF_G2)


def _selfcheck(n: int = 4, seed: bytes = b"h2c-import-check") -> None:
    """Map n deterministic pseudo-random field elements and assert the
    SSWU output lies on E' and the isogeny image lies on E2 — a wrong
    curve/isogeny constant fails here with probability ~1."""
    for i in range(n):
        blob = hashlib.sha512(seed + bytes([i])).digest()
        u = Fq2(int.from_bytes(blob[:32], "big") % P,
                int.from_bytes(blob[32:], "big") % P)
        xp, yp = sswu(u)
        assert yp.sq() == _g_iso(xp), "SSWU point off E'"
        x, y = iso3((xp, yp))
        assert y.sq() == x.sq() * x + _b.B2, "isogeny image off E2"


_selfcheck()
