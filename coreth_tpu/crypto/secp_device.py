"""Device-batched ECDSA recovery — host orchestration.

Splits recovery the TPU-native way (SURVEY.md section 2.7: "batched
ECDSA-recover kernel"; reference analog core/sender_cacher.go):

  1. host: parse + range-check, and u1/u2 = (-z/r, s/r) mod n via ONE
     Montgomery batch inversion across the whole batch (a few CPython
     modmuls per signature, no per-signature pow)
  2. device, one call (ops/secp.recover_kernel): y = sqrt(x^3+7),
     parity select, the G+R table entry (batched Fermat inversion),
     and the dominant Shamir ladder u1*G + u2*R
  3. host: Jacobian -> affine via one more batch inversion + keccak

Inputs and outputs of the device call are byte-packed (~2.6 MB per 16k
signatures round trip) because the tunnel to the chip costs ~0.2 s per
sync plus ~25-60 MB/s — transfer layout, not FLOPs, is the budget.

ABI mirrors crypto.native.recover_addresses_batch so callers can switch
between the C++ and device paths transparently:
  recover_addresses_device(hashes, rs, ss, recids) -> (addrs20, ok)

Rows the branchless ladder flags as doubling collisions (addend ==
accumulator; statistically negligible, constructible adversarially) are
re-run on the exact host path.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from coreth_tpu.crypto.keccak import keccak256
from coreth_tpu.crypto import secp256k1 as _ref

P = _ref.P
N = _ref.N


def _batch_inv(vals: List[int], mod: int) -> List[int]:
    """Montgomery batch inversion: one pow + 3 muls per element.
    All vals must be nonzero mod `mod`."""
    if not vals:
        return []
    prefix = []
    acc = 1
    for v in vals:
        acc = acc * v % mod
        prefix.append(acc)
    inv = pow(acc, mod - 2, mod)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = inv * (prefix[i - 1] if i else 1) % mod
        inv = inv * (vals[i] % mod) % mod
    return out


def _words_le(values: List[int]) -> np.ndarray:
    """ints -> (B, 8) int32 little-endian 32-bit words."""
    blob = b"".join(v.to_bytes(32, "little") for v in values)
    return np.frombuffer(blob, dtype="<u4").reshape(
        len(values), 8).astype(np.int32)


def _pad_pow2(n: int, floor: int = 64) -> int:
    b = max(n, floor)
    return 1 << (b - 1).bit_length()


# Largest single kernel launch: batches beyond this are chunked so
# padding waste, HBM footprint, and the set of compiled shape variants
# all stay bounded (pow2 buckets 64..16384 — at most 9 executables).
MAX_CHUNK = int(__import__("os").environ.get(
    "CORETH_RECOVER_MAX_CHUNK", str(16384)))


def recover_addresses_device(hashes: bytes, rs: bytes, ss: bytes,
                             recids: bytes) -> Tuple[bytes, bytes]:
    """Batched recovery over packed buffers; returns (addresses, ok)."""
    from coreth_tpu.ops import secp as S

    n = len(recids)
    if n == 0:
        return b"", b""
    if n > MAX_CHUNK:
        addrs = bytearray()
        okb = bytearray()
        for lo in range(0, n, MAX_CHUNK):
            hi = min(lo + MAX_CHUNK, n)
            a, o = recover_addresses_device(
                hashes[32 * lo:32 * hi], rs[32 * lo:32 * hi],
                ss[32 * lo:32 * hi], recids[lo:hi])
            addrs += a
            okb += o
        return bytes(addrs), bytes(okb)
    r_l = [int.from_bytes(rs[32 * i:32 * i + 32], "big") for i in range(n)]
    s_l = [int.from_bytes(ss[32 * i:32 * i + 32], "big") for i in range(n)]
    z_l = [int.from_bytes(hashes[32 * i:32 * i + 32], "big")
           for i in range(n)]

    ok = [True] * n
    xs = [0] * n
    for i in range(n):
        r, s, recid = r_l[i], s_l[i], recids[i]
        if not (0 < r < N and 0 < s < N and recid <= 3):
            ok[i] = False
            continue
        x = r + N if recid & 2 else r
        if x >= P:
            ok[i] = False
            continue
        xs[i] = x

    live = [i for i in range(n) if ok[i]]
    rinv = dict(zip(live, _batch_inv([r_l[i] for i in live], N)))
    u1s = [0] * n
    u2s = [0] * n
    for i in live:
        u1s[i] = (-z_l[i] * rinv[i]) % N
        u2s[i] = (s_l[i] * rinv[i]) % N

    # --- device: sqrt + G+R table + Shamir ladder, one call ------------
    pad = _pad_pow2(n)
    padz = [0] * (pad - n)
    parity = np.frombuffer(recids, dtype=np.uint8).astype(np.int32) & 1
    parity = np.concatenate([parity, np.zeros(pad - n, np.int32)])
    out = np.asarray(S.recover_kernel(
        S.fe_bytes_np(xs + padz), parity,
        _words_le(u1s + padz), _words_le(u2s + padz)))[:n]

    inf = out[:, 99].astype(bool)
    bad = out[:, 100].astype(bool)
    residue = out[:, 101].astype(bool)

    # --- host: to affine (one batch inversion) + keccak ----------------
    zj = {}
    for i in live:
        if residue[i] and not inf[i] and not bad[i]:
            z = int.from_bytes(out[i, 66:99].tobytes(), "little")
            if z:
                zj[i] = z
    fin = sorted(zj)
    zinv = dict(zip(fin, _batch_inv([zj[i] for i in fin], P)))

    addrs = bytearray(20 * n)
    okb = bytearray(n)
    for i in range(n):
        if not ok[i]:
            continue
        if not residue[i]:
            continue                 # x not on curve
        if bad[i]:
            # ladder hit a doubling collision: exact host path
            try:
                addr = _ref.recover_address_py(
                    hashes[32 * i:32 * i + 32], r_l[i], s_l[i], recids[i])
            except ValueError:
                continue
            addrs[20 * i:20 * i + 20] = addr
            okb[i] = 1
            continue
        if i not in zinv:
            continue                 # u1*G + u2*R = infinity: invalid
        xi = int.from_bytes(out[i, 0:33].tobytes(), "little")
        yi = int.from_bytes(out[i, 33:66].tobytes(), "little")
        zi = zinv[i]
        zi2 = zi * zi % P
        ax = xi * zi2 % P
        ay = yi * zi2 % P * zi % P
        pub = ax.to_bytes(32, "big") + ay.to_bytes(32, "big")
        addrs[20 * i:20 * i + 20] = keccak256(pub)[12:]
        okb[i] = 1
    return bytes(addrs), bytes(okb)
