"""Device-batched ECDSA recovery — host orchestration.

Splits recovery the TPU-native way (SURVEY.md section 2.7: "batched
ECDSA-recover kernel"; reference analog core/sender_cacher.go):

  1. host: parse + range-check, and u1/u2 = (-z/r, s/r) mod n via ONE
     Montgomery batch inversion across the whole batch (a few CPython
     modmuls per signature, no per-signature pow)
  2. device, one call (ops/secp.recover_kernel): y = sqrt(x^3+7),
     parity select, the G+R table entry (batched Fermat inversion),
     and the dominant Shamir ladder u1*G + u2*R
  3. host: Jacobian -> affine via one more batch inversion + keccak

Inputs and outputs of the device call are byte-packed (~2.6 MB per 16k
signatures round trip) because the tunnel to the chip costs ~0.2 s per
sync plus ~25-60 MB/s — transfer layout, not FLOPs, is the budget.

ABI mirrors crypto.native.recover_addresses_batch so callers can switch
between the C++ and device paths transparently:
  recover_addresses_device(hashes, rs, ss, recids) -> (addrs20, ok)

Rows the branchless ladder flags as doubling collisions (addend ==
accumulator; statistically negligible, constructible adversarially) are
re-run on the exact host path.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from coreth_tpu.crypto.keccak import keccak256
from coreth_tpu.crypto import secp256k1 as _ref

P = _ref.P
N = _ref.N


def _batch_inv(vals: List[int], mod: int) -> List[int]:
    """Montgomery batch inversion: one pow + 3 muls per element.
    All vals must be nonzero mod `mod`."""
    if not vals:
        return []
    prefix = []
    acc = 1
    for v in vals:
        acc = acc * v % mod
        prefix.append(acc)
    inv = pow(acc, mod - 2, mod)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = inv * (prefix[i - 1] if i else 1) % mod
        inv = inv * (vals[i] % mod) % mod
    return out


def _words_le(values: List[int]) -> np.ndarray:
    """ints -> (B, 8) int32 little-endian 32-bit words."""
    blob = b"".join(v.to_bytes(32, "little") for v in values)
    return np.frombuffer(blob, dtype="<u4").reshape(
        len(values), 8).astype(np.int32)


def _pad_pow2(n: int, floor: int = 64) -> int:
    b = max(n, floor)
    return 1 << (b - 1).bit_length()


# Largest single kernel launch: batches beyond this are chunked so
# padding waste, HBM footprint, and the set of compiled shape variants
# all stay bounded (pow2 buckets 64..4096 — at most 7 executables).
# Measured on the tunneled v5e chip: 2048-chunks are dispatch-bound
# (0.14 ms/sig), 4096 and 8192 both reach 0.083 ms/sig, and a single
# 16384 launch loses to pow2 padding waste (0.11 ms/sig) — so 4096.
MAX_CHUNK = int(__import__("os").environ.get(
    "CORETH_RECOVER_MAX_CHUNK", str(4096)))


def issue_recover(hashes: bytes, rs: bytes, ss: bytes,
                  recids: bytes, kernel=None) -> list:
    """Host prep + async kernel dispatch for a packed signature batch.

    Returns a list of per-chunk contexts; pass to complete_recover to
    block on the device results and finish on host.  The kernel calls
    are dispatched asynchronously (jax), so the caller can do host work
    — or enqueue more device work — while the ladder runs.

    kernel: alternative device entry with recover_kernel's signature —
    the mesh-sharded ladder (parallel/mesh.py sharded_recover) plugs in
    here so multi-chip recovery reuses all of the host prep/finish."""
    n = len(recids)
    ctxs = []
    for lo in range(0, n, MAX_CHUNK):
        hi = min(lo + MAX_CHUNK, n)
        ctxs.append(_issue_chunk(
            hashes[32 * lo:32 * hi], rs[32 * lo:32 * hi],
            ss[32 * lo:32 * hi], recids[lo:hi], kernel))
    return ctxs


def complete_recover(ctxs: list) -> Tuple[bytes, bytes]:
    """Block on issued chunks; returns (addresses, ok) packed bytes."""
    addrs = bytearray()
    okb = bytearray()
    for ctx in ctxs:
        a, o = _complete_chunk(ctx)
        addrs += a
        okb += o
    return bytes(addrs), bytes(okb)


def recover_addresses_device(hashes: bytes, rs: bytes, ss: bytes,
                             recids: bytes) -> Tuple[bytes, bytes]:
    """Batched recovery over packed buffers; returns (addresses, ok)."""
    return complete_recover(issue_recover(hashes, rs, ss, recids))


def _issue_chunk(hashes: bytes, rs: bytes, ss: bytes, recids: bytes,
                 kernel=None):
    from coreth_tpu.ops import secp as S

    n = len(recids)
    if n == 0:
        return None
    # host prep in C++ when available (range checks + the u1/u2 batch
    # inversion — Python bigint math would sit on the critical path),
    # pure-python fallback otherwise
    from coreth_tpu.crypto import native
    prep = native.recover_prep(hashes, rs, ss, recids) \
        if native.load() is not None else None
    if prep is not None:
        xs_le, u1_le, u2_le, okb = prep
        ok = [bool(b) for b in okb]
        pad = _pad_pow2(n)
        x_arr = np.zeros((pad, 33), dtype=np.uint8)
        x_arr[:n] = np.frombuffer(xs_le, dtype=np.uint8).reshape(n, 33)
        u1_arr = np.zeros((pad, 8), dtype=np.int32)
        u2_arr = np.zeros((pad, 8), dtype=np.int32)
        u1_arr[:n] = np.frombuffer(u1_le, dtype="<u4").reshape(
            n, 8).astype(np.int32)
        u2_arr[:n] = np.frombuffer(u2_le, dtype="<u4").reshape(
            n, 8).astype(np.int32)
    else:
        r_l = [int.from_bytes(rs[32 * i:32 * i + 32], "big")
               for i in range(n)]
        s_l = [int.from_bytes(ss[32 * i:32 * i + 32], "big")
               for i in range(n)]
        z_l = [int.from_bytes(hashes[32 * i:32 * i + 32], "big")
               for i in range(n)]
        ok = [True] * n
        xs = [0] * n
        for i in range(n):
            r, s, recid = r_l[i], s_l[i], recids[i]
            if not (0 < r < N and 0 < s < N and recid <= 3):
                ok[i] = False
                continue
            x = r + N if recid & 2 else r
            if x >= P:
                ok[i] = False
                continue
            xs[i] = x
        live = [i for i in range(n) if ok[i]]
        rinv = dict(zip(live, _batch_inv([r_l[i] for i in live], N)))
        u1s = [0] * n
        u2s = [0] * n
        for i in live:
            u1s[i] = (-z_l[i] * rinv[i]) % N
            u2s[i] = (s_l[i] * rinv[i]) % N
        pad = _pad_pow2(n)
        padz = [0] * (pad - n)
        x_arr = S.fe_bytes_np(xs + padz)
        u1_arr = _words_le(u1s + padz)
        u2_arr = _words_le(u2s + padz)

    # --- device: sqrt + G+R table + Shamir ladder, async dispatch ------
    parity = np.frombuffer(recids, dtype=np.uint8).astype(np.int32) & 1
    parity = np.concatenate([parity, np.zeros(pad - n, np.int32)])
    dev_out = (kernel or S.recover_kernel)(x_arr, parity, u1_arr, u2_arr)
    return dict(n=n, dev_out=dev_out, ok=ok, hashes=hashes, rs=rs, ss=ss,
                recids=recids)


def _redo_collision(hashes, rs, ss, recids, i, addrs, okb):
    """Ladder doubling-collision row: exact host re-run (rare)."""
    try:
        addr = _ref.recover_address_py(
            hashes[32 * i:32 * i + 32],
            int.from_bytes(rs[32 * i:32 * i + 32], "big"),
            int.from_bytes(ss[32 * i:32 * i + 32], "big"), recids[i])
    except ValueError:
        return
    addrs[20 * i:20 * i + 20] = addr
    okb[i] = 1


def _complete_chunk(ctx) -> Tuple[bytes, bytes]:
    if ctx is None:
        return b"", b""
    n = ctx["n"]
    ok = ctx["ok"]
    hashes, rs, ss = ctx["hashes"], ctx["rs"], ctx["ss"]
    recids = ctx["recids"]
    out = np.asarray(ctx["dev_out"])[:n]

    from coreth_tpu.crypto import native
    if native.load() is not None:
        # C++ finish: batched Z inversion + affine + keccak
        rows = out.tobytes()
        addrs_b, okb_b = native.recover_finish(rows, n, bytes(ok))
        addrs = bytearray(addrs_b)
        okb = bytearray(okb_b)
        for i in range(n):
            if okb[i] == 2:
                okb[i] = 0
                _redo_collision(hashes, rs, ss, recids, i, addrs, okb)
        return bytes(addrs), bytes(okb)

    inf = out[:, 99].astype(bool)
    bad = out[:, 100].astype(bool)
    residue = out[:, 101].astype(bool)

    # --- host: to affine (one batch inversion) + keccak ----------------
    zj = {}
    for i in range(n):
        if ok[i] and residue[i] and not inf[i] and not bad[i]:
            z = int.from_bytes(out[i, 66:99].tobytes(), "little")
            if z:
                zj[i] = z
    fin = sorted(zj)
    zinv = dict(zip(fin, _batch_inv([zj[i] for i in fin], P)))

    addrs = bytearray(20 * n)
    okb = bytearray(n)
    for i in range(n):
        if not ok[i]:
            continue
        if not residue[i]:
            continue                 # x not on curve
        if bad[i]:
            _redo_collision(hashes, rs, ss, recids, i, addrs, okb)
            continue
        if i not in zinv:
            continue                 # u1*G + u2*R = infinity: invalid
        xi = int.from_bytes(out[i, 0:33].tobytes(), "little")
        yi = int.from_bytes(out[i, 33:66].tobytes(), "little")
        zi = zinv[i]
        zi2 = zi * zi % P
        ax = xi * zi2 % P
        ay = yi * zi2 % P * zi % P
        pub = ax.to_bytes(32, "big") + ay.to_bytes(32, "big")
        addrs[20 * i:20 * i + 20] = keccak256(pub)[12:]
        okb[i] = 1
    return bytes(addrs), bytes(okb)
