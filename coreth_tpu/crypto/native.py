"""ctypes bridge to the C++ host runtime (native/libcoreth_native.so).

The native library supplies the fast paths that the reference gets from
asm/cgo dependencies (SURVEY.md section 2.7): keccak-256 and batched
secp256k1 recovery.  Built lazily with ``make -C native`` on first import if
g++ is available; every caller keeps working on the pure-Python path when
the build is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcoreth_native.so")

_lib = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:  # noqa: BLE001 — any build failure leaves the pure-py path active
        return False


def _stale() -> bool:
    """True when any C++ source is newer than the built library."""
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        for fn in os.listdir(_NATIVE_DIR):
            if fn.endswith(".cc") or fn == "Makefile":
                if os.path.getmtime(
                        os.path.join(_NATIVE_DIR, fn)) > lib_mtime:
                    return True
    except OSError:
        return False
    return False


def load():
    """Load the native library, or return None.

    Builds when the .so is missing, and REBUILDS when any source file
    is newer than it (a prebuilt library must not mask source edits).
    If the rebuild fails (no C++ toolchain), the existing prebuilt .so
    still loads — callers probe per-symbol (hasattr) for ABI surfaces
    newer than the prebuilt, so features degrade one by one instead of
    all-or-nothing."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        if not _build():
            return None
    elif _stale():
        _build()  # best effort: fall back to the prebuilt on failure
    lib = ctypes.CDLL(_LIB_PATH)
    lib.coreth_keccak256.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.coreth_keccak256.restype = None
    lib.coreth_ecrecover.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_char_p]
    lib.coreth_ecrecover.restype = ctypes.c_int
    lib.coreth_ecrecover_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p]
    lib.coreth_ecrecover_batch.restype = None
    lib.coreth_recover_prep.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p]
    lib.coreth_recover_prep.restype = None
    lib.coreth_recover_finish.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p]
    lib.coreth_recover_finish.restype = None
    lib.coreth_baseline_replay.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_double)]
    lib.coreth_baseline_replay.restype = ctypes.c_int
    lib.coreth_receipt_root.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p]
    lib.coreth_receipt_root.restype = None
    lib.coreth_evm_replay.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_double)]
    lib.coreth_evm_replay.restype = ctypes.c_int
    _lib = lib
    return _lib


def _require() -> ctypes.CDLL:
    lib = load()
    if lib is None:
        raise RuntimeError(
            "coreth native library unavailable (build failed or g++ missing); "
            "use the pure-python entry points in coreth_tpu.crypto")
    return lib


def keccak256_native(data: bytes) -> bytes:
    out = ctypes.create_string_buffer(32)
    _require().coreth_keccak256(data, len(data), out)
    return out.raw


def recover_address_native(msg_hash: bytes, r: int, s: int, recid: int) -> bytes:
    out = ctypes.create_string_buffer(20)
    ok = _require().coreth_ecrecover(
        msg_hash, r.to_bytes(32, "big"), s.to_bytes(32, "big"), recid, out)
    if not ok:
        raise ValueError("invalid signature values")
    return out.raw


def recover_addresses_batch(hashes: bytes, rs: bytes, ss: bytes,
                            recids: bytes):
    """Batched recovery over packed buffers.  Returns (addresses, ok) bytes."""
    n = len(recids)
    out = ctypes.create_string_buffer(20 * n)
    ok = ctypes.create_string_buffer(n)
    _require().coreth_ecrecover_batch(hashes, rs, ss, recids, n, out, ok)
    return out.raw, ok.raw


def install() -> bool:
    """Activate native fast paths on the pure-python entry points."""
    if load() is None:
        return False
    from coreth_tpu.crypto import keccak as _k
    from coreth_tpu.crypto import secp256k1 as _s
    _k.set_impl(keccak256_native)
    _s.set_recover_impl(recover_address_native)
    return True


def baseline_replay(tx_records: bytes, block_offsets, roots: bytes,
                    coinbases: bytes, accounts: bytes, n_accounts: int):
    """Run the compiled sequential transfer processor (native/baseline.cc
    — the Go-proxy baseline; see BASELINE.md).  Returns (rc, phases)
    where rc==0 means every block's state root matched and phases is
    [t_sender, t_exec, t_trie] seconds."""
    lib = _require()
    n_blocks = len(block_offsets) - 1
    off = (ctypes.c_uint64 * len(block_offsets))(*block_offsets)
    phases = (ctypes.c_double * 3)()
    rc = lib.coreth_baseline_replay(
        tx_records, off, n_blocks, roots, coinbases, accounts,
        n_accounts, phases)
    return rc, list(phases)


def evm_replay(tx_records: bytes, block_offsets, block_env: bytes,
               accounts: bytes, n_accounts: int, contracts: bytes,
               n_contracts: int, chain_id: int):
    """Run the compiled sequential EVM processor (native/evm.cc — the
    contract-workload baseline; see BASELINE.md round 5).  Returns
    (rc, phases); rc==0 means every block's state root matched."""
    lib = _require()
    n_blocks = len(block_offsets) - 1
    off = (ctypes.c_uint64 * len(block_offsets))(*block_offsets)
    phases = (ctypes.c_double * 3)()
    rc = lib.coreth_evm_replay(
        tx_records, off, n_blocks, block_env, accounts, n_accounts,
        contracts, n_contracts, chain_id, phases)
    return rc, list(phases)


def receipt_root(cum_gas, tx_types: bytes, has_log: bytes,
                 log_blob: bytes):
    """Receipt-trie root + header bloom for a device-path block in one
    C++ call (DeriveSha/StackTrie + CreateBloom role — reference
    core/types/hashing.go:97, bloom9.go).  Receipts are status-1 with 0
    or 1 Transfer-shaped log (addr20 ++ 3*topic32 ++ data32 = 148B).
    Returns (root32, bloom256)."""
    lib = _require()
    n = len(tx_types)
    cg = (ctypes.c_uint64 * n)(*cum_gas)
    root = ctypes.create_string_buffer(32)
    bloom = ctypes.create_string_buffer(256)
    lib.coreth_receipt_root(cg, tx_types, has_log, log_blob, n, root,
                            bloom)
    return root.raw, bloom.raw


def recover_prep(hashes: bytes, rs: bytes, ss: bytes, recids: bytes):
    """C++ host prep for the device recovery kernel: range checks, x
    coordinate, and u1/u2 scalars via one Montgomery batch inversion.
    Returns (xs_le33, u1_le32, u2_le32, ok) packed bytes."""
    lib = _require()
    n = len(recids)
    xs = ctypes.create_string_buffer(33 * n)
    u1 = ctypes.create_string_buffer(32 * n)
    u2 = ctypes.create_string_buffer(32 * n)
    ok = ctypes.create_string_buffer(n)
    lib.coreth_recover_prep(hashes, rs, ss, recids, n, xs, u1, u2, ok)
    return xs.raw, u1.raw, u2.raw, ok.raw


def recover_finish(rows: bytes, n: int, ok_in: bytes):
    """C++ finish for the device recovery kernel: batched Jacobian->
    affine conversion + keccak address derivation.  Returns (addrs, ok)
    where ok[i]==2 marks ladder-collision rows for host re-run."""
    lib = _require()
    out = ctypes.create_string_buffer(20 * n)
    ok = ctypes.create_string_buffer(n)
    lib.coreth_recover_finish(rows, n, ok_in, out, ok)
    return out.raw, ok.raw
