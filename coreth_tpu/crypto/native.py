"""ctypes bridge to the C++ host runtime (native/libcoreth_native.so).

The native library supplies the fast paths that the reference gets from
asm/cgo dependencies (SURVEY.md section 2.7): keccak-256 and batched
secp256k1 recovery.  Built lazily with ``make -C native`` on first import if
g++ is available; every caller keeps working on the pure-Python path when
the build is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcoreth_native.so")

_lib = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:  # noqa: BLE001
        return False


def load():
    """Load (building if necessary) the native library, or return None."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.coreth_keccak256.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.coreth_keccak256.restype = None
    lib.coreth_ecrecover.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_char_p]
    lib.coreth_ecrecover.restype = ctypes.c_int
    lib.coreth_ecrecover_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p]
    lib.coreth_ecrecover_batch.restype = None
    _lib = lib
    return _lib


def _require() -> ctypes.CDLL:
    lib = load()
    if lib is None:
        raise RuntimeError(
            "coreth native library unavailable (build failed or g++ missing); "
            "use the pure-python entry points in coreth_tpu.crypto")
    return lib


def keccak256_native(data: bytes) -> bytes:
    out = ctypes.create_string_buffer(32)
    _require().coreth_keccak256(data, len(data), out)
    return out.raw


def recover_address_native(msg_hash: bytes, r: int, s: int, recid: int) -> bytes:
    out = ctypes.create_string_buffer(20)
    ok = _require().coreth_ecrecover(
        msg_hash, r.to_bytes(32, "big"), s.to_bytes(32, "big"), recid, out)
    if not ok:
        raise ValueError("invalid signature values")
    return out.raw


def recover_addresses_batch(hashes: bytes, rs: bytes, ss: bytes,
                            recids: bytes):
    """Batched recovery over packed buffers.  Returns (addresses, ok) bytes."""
    n = len(recids)
    out = ctypes.create_string_buffer(20 * n)
    ok = ctypes.create_string_buffer(n)
    _require().coreth_ecrecover_batch(hashes, rs, ss, recids, n, out, ok)
    return out.raw, ok.raw


def install() -> bool:
    """Activate native fast paths on the pure-python entry points."""
    if load() is None:
        return False
    from coreth_tpu.crypto import keccak as _k
    from coreth_tpu.crypto import secp256k1 as _s
    _k.set_impl(keccak256_native)
    _s.set_recover_impl(recover_address_native)
    return True
