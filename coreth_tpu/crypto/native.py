"""ctypes bridge to the C++ host runtime (native/libcoreth_native.so).

The native library supplies the fast paths that the reference gets from
asm/cgo dependencies (SURVEY.md section 2.7): keccak-256 and batched
secp256k1 recovery.  Built lazily via ``coreth_tpu.nativebuild`` on
first load if g++ is available; every caller keeps working on the
pure-Python path when the build is unavailable.

``CORETH_NATIVE_SANITIZE=1`` loads the sanitizer-hardened build
(``libcoreth_native_asan.so``, ``make sanitize``) instead: same ABI,
but every heap overflow / use-after-free / UB at the boundary aborts
the process.  The ASan runtime must be preloaded for that to work —
drive it through a subprocess with ``nativebuild.asan_env()`` (see
tests/test_sanitize.py); the tier-1 sanitizer suite does exactly this.

``CORETH_NATIVE_TSAN=1`` likewise loads the ThreadSanitizer build
(``libcoreth_native_tsan.so``, ``make sanitize-thread``): data races
where GIL-releasing native calls overlap across threads are reported
instead of silently corrupting.  Drive it through a subprocess with
``nativebuild.tsan_env()`` (see tests/test_tsan.py).
"""

from __future__ import annotations

import ctypes
import os

from coreth_tpu import nativebuild

_lib = None


def load():
    """Load the native library, or return None.

    Builds when the .so is missing, and REBUILDS when any source file
    is newer than it (a prebuilt library must not mask source edits).
    If the rebuild fails (no C++ toolchain), the existing prebuilt .so
    still loads — callers probe per-symbol (hasattr) for ABI surfaces
    newer than the prebuilt, so features degrade one by one instead of
    all-or-nothing.  The ``CORETH_NATIVE_SANITIZE`` /
    ``CORETH_NATIVE_TSAN`` selection is read once, at first load (the
    handle is cached for the process)."""
    global _lib
    if _lib is not None:
        return _lib
    sanitize = os.environ.get("CORETH_NATIVE_SANITIZE", "") == "1"
    tsan = not sanitize \
        and os.environ.get("CORETH_NATIVE_TSAN", "") == "1"
    path = nativebuild.ensure_built(sanitize=sanitize, tsan=tsan)
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.coreth_keccak256.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.coreth_keccak256.restype = None
    lib.coreth_ecrecover.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_char_p]
    lib.coreth_ecrecover.restype = ctypes.c_int
    lib.coreth_ecrecover_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p]
    lib.coreth_ecrecover_batch.restype = None
    lib.coreth_recover_prep.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p]
    lib.coreth_recover_prep.restype = None
    lib.coreth_recover_finish.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p]
    lib.coreth_recover_finish.restype = None
    lib.coreth_baseline_replay.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_double)]
    lib.coreth_baseline_replay.restype = ctypes.c_int
    lib.coreth_receipt_root.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p]
    lib.coreth_receipt_root.restype = None
    lib.coreth_evm_replay.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_double)]
    lib.coreth_evm_replay.restype = ctypes.c_int
    lib.coreth_keccak256_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p]
    lib.coreth_keccak256_batch.restype = None
    lib.coreth_test_fe_mul.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.coreth_test_fe_mul.restype = None
    # test-only symbol compiled ONLY into the sanitized build (`make
    # sanitize`) — proves the ASan trap actually fires
    if hasattr(lib, "coreth_sanitize_smoke"):
        lib.coreth_sanitize_smoke.argtypes = [ctypes.c_int64]
        lib.coreth_sanitize_smoke.restype = ctypes.c_int
    # test-only symbol compiled ONLY into the tsan build (`make
    # sanitize-thread`) — proves the TSan trap actually fires
    if hasattr(lib, "coreth_tsan_smoke"):
        lib.coreth_tsan_smoke.argtypes = [ctypes.c_int]
        lib.coreth_tsan_smoke.restype = ctypes.c_int
    _lib = lib
    return _lib


def _require() -> ctypes.CDLL:
    lib = load()
    if lib is None:
        raise RuntimeError(
            "coreth native library unavailable (build failed or g++ missing); "
            "use the pure-python entry points in coreth_tpu.crypto")
    return lib


def keccak256_native(data: bytes) -> bytes:
    out = ctypes.create_string_buffer(32)
    _require().coreth_keccak256(data, len(data), out)
    return out.raw


def recover_address_native(msg_hash: bytes, r: int, s: int, recid: int) -> bytes:
    out = ctypes.create_string_buffer(20)
    ok = _require().coreth_ecrecover(
        msg_hash, r.to_bytes(32, "big"), s.to_bytes(32, "big"), recid, out)
    if not ok:
        raise ValueError("invalid signature values")
    return out.raw


def recover_addresses_batch(hashes: bytes, rs: bytes, ss: bytes,
                            recids: bytes):
    """Batched recovery over packed buffers.  Returns (addresses, ok) bytes."""
    n = len(recids)
    out = ctypes.create_string_buffer(20 * n)
    ok = ctypes.create_string_buffer(n)
    _require().coreth_ecrecover_batch(hashes, rs, ss, recids, n, out, ok)
    return out.raw, ok.raw


def install() -> bool:
    """Activate native fast paths on the pure-python entry points."""
    if load() is None:
        return False
    from coreth_tpu.crypto import keccak as _k
    from coreth_tpu.crypto import secp256k1 as _s
    _k.set_impl(keccak256_native)
    _s.set_recover_impl(recover_address_native)
    return True


def baseline_replay(tx_records: bytes, block_offsets, roots: bytes,
                    coinbases: bytes, accounts: bytes, n_accounts: int):
    """Run the compiled sequential transfer processor (native/baseline.cc
    — the Go-proxy baseline; see BASELINE.md).  Returns (rc, phases)
    where rc==0 means every block's state root matched and phases is
    [t_sender, t_exec, t_trie] seconds.

    The decoder is bounds-checked, not trusted: the wrapper validates
    the fixed-stride blobs against the counts it passes, and the C
    side validates the offsets against the explicit tx-blob length
    (rc 5 = malformed; fuzzed under ASan in tests/test_sanitize.py)."""
    lib = _require()
    if not block_offsets:
        raise ValueError("block_offsets must hold at least [0]")
    n_blocks = len(block_offsets) - 1
    if len(roots) != 32 * n_blocks:
        raise ValueError(f"roots blob {len(roots)}B != 32*{n_blocks}")
    if len(coinbases) != 20 * n_blocks:
        raise ValueError(
            f"coinbases blob {len(coinbases)}B != 20*{n_blocks}")
    if len(accounts) != 60 * n_accounts:
        raise ValueError(
            f"accounts blob {len(accounts)}B != 60*{n_accounts}")
    if any(o < 0 for o in block_offsets):
        raise ValueError("negative block offset")
    off = (ctypes.c_uint64 * len(block_offsets))(*block_offsets)
    phases = (ctypes.c_double * 3)()
    rc = lib.coreth_baseline_replay(
        tx_records, len(tx_records), off, n_blocks, roots, coinbases,
        accounts, n_accounts, phases)
    return rc, list(phases)


def evm_replay(tx_records: bytes, block_offsets, block_env: bytes,
               accounts: bytes, n_accounts: int, contracts: bytes,
               n_contracts: int, chain_id: int):
    """Run the compiled sequential EVM processor (native/evm.cc — the
    contract-workload baseline; see BASELINE.md round 5).  Returns
    (rc, phases); rc==0 means every block's state root matched.

    Like baseline_replay, the packed-blob decode is bounds-checked:
    fixed-stride blobs validate here, and the variable-length tx and
    contract records (dlen/clen/nslots prefixes) validate in C against
    the explicit blob lengths (rc -10 = malformed)."""
    lib = _require()
    if not block_offsets:
        raise ValueError("block_offsets must hold at least [0]")
    n_blocks = len(block_offsets) - 1
    if len(block_env) != 116 * n_blocks:
        raise ValueError(
            f"block_env blob {len(block_env)}B != 116*{n_blocks}")
    if len(accounts) != 60 * n_accounts:
        raise ValueError(
            f"accounts blob {len(accounts)}B != 60*{n_accounts}")
    if any(o < 0 for o in block_offsets):
        raise ValueError("negative block offset")
    off = (ctypes.c_uint64 * len(block_offsets))(*block_offsets)
    phases = (ctypes.c_double * 3)()
    rc = lib.coreth_evm_replay(
        tx_records, len(tx_records), off, n_blocks, block_env,
        accounts, n_accounts, contracts, len(contracts), n_contracts,
        chain_id, phases)
    return rc, list(phases)


def receipt_root(cum_gas, tx_types: bytes, has_log: bytes,
                 log_blob: bytes):
    """Receipt-trie root + header bloom for a device-path block in one
    C++ call (DeriveSha/StackTrie + CreateBloom role — reference
    core/types/hashing.go:97, bloom9.go).  Receipts are status-1 with 0
    or 1 Transfer-shaped log (addr20 ++ 3*topic32 ++ data32 = 148B).
    Returns (root32, bloom256)."""
    lib = _require()
    n = len(tx_types)
    cg = (ctypes.c_uint64 * n)(*cum_gas)
    root = ctypes.create_string_buffer(32)
    bloom = ctypes.create_string_buffer(256)
    lib.coreth_receipt_root(cg, tx_types, has_log, log_blob, n, root,
                            bloom)
    return root.raw, bloom.raw


def recover_prep(hashes: bytes, rs: bytes, ss: bytes, recids: bytes):
    """C++ host prep for the device recovery kernel: range checks, x
    coordinate, and u1/u2 scalars via one Montgomery batch inversion.
    Returns (xs_le33, u1_le32, u2_le32, ok) packed bytes."""
    lib = _require()
    n = len(recids)
    xs = ctypes.create_string_buffer(33 * n)
    u1 = ctypes.create_string_buffer(32 * n)
    u2 = ctypes.create_string_buffer(32 * n)
    ok = ctypes.create_string_buffer(n)
    lib.coreth_recover_prep(hashes, rs, ss, recids, n, xs, u1, u2, ok)
    return xs.raw, u1.raw, u2.raw, ok.raw


def keccak256_batch(data: bytes, lens, stride: int) -> bytes:
    """Batched fixed-stride keccak-256: item i occupies
    ``data[i*stride : i*stride + lens[i]]``.  Returns the packed
    32-byte digests."""
    n = len(lens)
    arr = (ctypes.c_uint64 * n)(*lens)
    out = ctypes.create_string_buffer(32 * n)
    _require().coreth_keccak256_batch(data, arr, stride, n, out)
    return out.raw


def sanitize_smoke_available() -> bool:
    """True when the loaded library carries the test-only sanitizer
    smoke helper (i.e. it is the ``make sanitize`` build)."""
    lib = load()
    return lib is not None and hasattr(lib, "coreth_sanitize_smoke")


def sanitize_smoke(idx: int) -> int:
    """Drive the deliberately-bugged test-only helper: reads
    ``buf[idx]`` of an 8-byte heap allocation.  ``idx >= 8`` is a heap
    overflow the sanitized build must trap (abort), which is exactly
    what tests/test_sanitize.py proves in a subprocess."""
    return _require().coreth_sanitize_smoke(idx)


def tsan_smoke_available() -> bool:
    """True when the loaded library carries the test-only race smoke
    helper (i.e. it is the ``make sanitize-thread`` build)."""
    lib = load()
    return lib is not None and hasattr(lib, "coreth_tsan_smoke")


def tsan_smoke(racy: int) -> int:
    """Drive the deliberately-racy test-only helper: two threads
    hammer one counter, unsynchronized when ``racy`` is truthy (the
    TSan build must report a data race — with ``halt_on_error=1``
    the process dies with TSAN_OPTIONS' exitcode) and mutex-guarded
    otherwise (must stay silent).  tests/test_tsan.py proves both
    halves in subprocesses."""
    return _require().coreth_tsan_smoke(1 if racy else 0)


def recover_finish(rows: bytes, n: int, ok_in: bytes):
    """C++ finish for the device recovery kernel: batched Jacobian->
    affine conversion + keccak address derivation.  Returns (addrs, ok)
    where ok[i]==2 marks ladder-collision rows for host re-run."""
    lib = _require()
    out = ctypes.create_string_buffer(20 * n)
    ok = ctypes.create_string_buffer(n)
    lib.coreth_recover_finish(rows, n, ok_in, out, ok)
    return out.raw, ok.raw
