"""EVM error taxonomy (twin of reference vmerrs/vmerrs.go)."""


class VMError(Exception):
    """Base: consumes all remaining gas unless stated otherwise."""


class ErrOutOfGas(VMError):
    pass


class ErrCodeStoreOutOfGas(VMError):
    pass


class ErrDepth(VMError):
    pass


class ErrInsufficientBalance(VMError):
    pass


class ErrContractAddressCollision(VMError):
    pass


class ErrExecutionReverted(VMError):
    """REVERT opcode: remaining gas is returned to the caller."""


class ErrMaxCodeSizeExceeded(VMError):
    pass


class ErrMaxInitCodeSizeExceeded(VMError):
    pass


class ErrInvalidJump(VMError):
    pass


class ErrWriteProtection(VMError):
    pass


class ErrReturnDataOutOfBounds(VMError):
    pass


class ErrGasUintOverflow(VMError):
    pass


class ErrInvalidCode(VMError):
    """EIP-3541: new code starting with 0xEF."""


class ErrNonceUintOverflow(VMError):
    pass


class ErrAddrProhibited(VMError):
    """Avalanche: calls to the blackhole address are forbidden."""


class ErrInvalidCoinID(VMError):
    pass


class ErrStackUnderflow(VMError):
    pass


class ErrStackOverflow(VMError):
    pass


class ErrInvalidOpCode(VMError):
    pass


class ErrToAddrProhibited6(VMError):
    """ApricotPhase6: prohibited to-addresses for native asset call."""
