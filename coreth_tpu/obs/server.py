"""Zero-dependency live telemetry endpoint (stdlib ``http.server``).

A long streaming run used to be a black box until ``run()`` returned;
this server makes it inspectable WHILE it runs:

- ``/metrics`` — the metrics registry's Prometheus text exposition
  (the existing ``render_prometheus``), scrapeable by anything that
  speaks the format;
- ``/trace``   — the active span tracer's ring as Chrome trace-event /
  Perfetto JSON (load it straight into ui.perfetto.dev);
- ``/report``  — the live report dict the owner registered (the
  streaming pipeline's in-flight ``StreamReport``).

Opt-in: ``CORETH_TELEMETRY_PORT=<port>`` (``0`` picks an ephemeral
port); the streaming pipeline starts one around ``run()`` and stops it
in the same ``finally`` that closes the checkpoint exporter, so an
error path cannot leak the listener thread.  Binds 127.0.0.1 only —
this is an operator diagnostic, not a public surface.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from coreth_tpu.metrics import render_prometheus
from coreth_tpu.obs import trace as _trace


class TelemetryServer:
    """One HTTP listener serving /metrics, /trace, and /report."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None,
                 report: Optional[Callable[[], dict]] = None):
        self.registry = registry
        self.report = report
        self._host = host
        self._want_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    # ------------------------------------------------------------ routes
    def _route(self, path: str):
        path = path.split("?", 1)[0]
        if path == "/metrics":
            return (render_prometheus(self.registry),
                    "text/plain; version=0.0.4")
        if path == "/trace":
            t = _trace.TRACER
            doc = t.export() if t is not None else {"traceEvents": []}
            # default=str for the same reason as write_out: span args
            # are an open **kwargs surface
            return json.dumps(doc, default=str), "application/json"
        if path == "/report":
            rep = self.report() if self.report is not None else {}
            # default=str: report dicts may carry bytes-ish oddities
            # from future fields; the endpoint must render regardless
            return json.dumps(rep, default=str), "application/json"
        raise KeyError(path)

    # --------------------------------------------------------- lifecycle
    def start(self) -> int:
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: no per-scrape spam
                pass

            def do_GET(self):
                try:
                    body, ctype = outer._route(self.path)
                except KeyError:
                    self.send_error(404)
                    return
                except Exception as exc:  # noqa: BLE001 — a render bug must 500 the scrape, never kill the listener thread
                    self.send_error(500, str(exc))
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((self._host, self._want_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-telemetry",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


def maybe_start_from_env(registry=None,
                         report: Optional[Callable[[], dict]] = None
                         ) -> Optional[TelemetryServer]:
    """Start a TelemetryServer iff CORETH_TELEMETRY_PORT is set (0 =
    ephemeral); returns it started, or None when the knob is absent."""
    raw = os.environ.get("CORETH_TELEMETRY_PORT")
    if raw is None or raw == "":
        return None
    srv = TelemetryServer(port=int(raw), registry=registry,
                          report=report)
    try:
        srv.start()
    except OSError as exc:
        # a bind failure (EADDRINUSE: two pipelines sharing one fixed
        # port — use 0/ephemeral for that) must degrade to "no
        # endpoint", never kill the stream before its first block
        import sys
        print(f"coreth obs: telemetry endpoint disabled "
              f"(bind {raw}: {exc})", file=sys.stderr)
        return None
    return srv
