"""Observability: end-to-end span tracing, Perfetto export, telemetry.

Level-0 leaf beside ``metrics``/``faults`` in layers.toml: every layer
from the serve pipeline down to the device dispatch seams threads its
timing evidence through it, so it imports nothing of the tree above
(metrics and faults are same-level peers).

- ``obs.trace`` — the span tracer: ``span()``/``instant()`` with ONE
  module-global None check when disabled (CORETH_TRACE=0, the default),
  per-block :class:`BlockTrace` contexts whose stage intervals become
  ``StreamReport.stage_breakdown``, a bounded ring, and Chrome
  trace-event / Perfetto JSON export (CORETH_TRACE_OUT).
- ``obs.server`` — the zero-dependency live telemetry endpoint
  (CORETH_TELEMETRY_PORT): /metrics, /trace, /report.
- ``obs.recorder`` — the divergence flight recorder
  (CORETH_FORENSICS=1): a per-block witness ring that freezes into
  content-addressed, offline-replayable bundles when an oracle trips,
  a block quarantines, or a backend hard-demotes
  (tools/replay_bundle.py is the matching bisection CLI).
"""

from coreth_tpu.obs.trace import (
    PT_EXPORT_FAIL, BlockTrace, EventRing, SpanTracer,
    StageAccumulator, arm_from_env, block_begin, enabled, install,
    instant, jax_span, span, tracer, uninstall, write_out,
)
from coreth_tpu.obs import recorder  # noqa: F401 — re-export the forensics module (and its obs/bundle_fail declaration) under the obs namespace

__all__ = [
    "PT_EXPORT_FAIL", "BlockTrace", "EventRing", "SpanTracer",
    "StageAccumulator", "arm_from_env", "block_begin", "enabled",
    "install", "instant", "jax_span", "span", "recorder", "tracer",
    "uninstall", "write_out",
]
