"""Divergence forensics: the flight recorder + replayable bundles.

The correctness story of this stack is differential — bit-identical
roots across backends, armed oracles at every seam (hostexec check,
trie check, flat check, spec-vs-generic) — but when one of them fired
mid-stream the evidence used to evaporate with the process: a counter
bumped, a scope hard-demoted, a block parked in quarantine, and nothing
left to debug offline.  This module is the black box that survives:

1. **Witness ring.**  When armed (``CORETH_FORENSICS=1``; one
   module-global ``is None`` check per site otherwise — the
   metrics/faults/trace pattern) every dispatched block lands a ring
   entry: the block object (wire bytes serialized lazily on the drain
   thread), its parent header, which backend took it, and a light
   touched-set sketch.  Blocks that run the exact host path
   additionally attach a **full witness**: the touched pre-state slice
   (account tuples + storage pre-values harvested from the StateDB's
   committed-read cache + contract code), per-tx receipts, the
   computed root, and any recorded mismatch reasons — everything
   ``tools/replay_bundle.py`` needs to re-execute the block with no
   chain and no DB.
2. **Triggers.**  Divergence/quarantine/demotion seams call
   :func:`note_trigger` with a declared trigger id
   (:func:`declare_trigger` — the faults-registry pattern, so the
   completeness gate in tests/test_forensics.py can assert every
   declared seam is actually routed through the recorder).  A trigger
   freezes the ring into a **bundle** the moment a full witness for
   its block exists (triggers noted mid-block wait for the witness the
   host path is about to record); leftovers freeze as context-only
   bundles at :func:`flush_pending`.
3. **Bundles.**  Frozen snapshots serialize on a background drain
   thread — never on the hot path — into a content-addressed directory
   (``bundle-<sha256[:16]>`` under ``CORETH_FORENSICS_DIR``): a JSON
   manifest plus raw blobs (block wire bytes, parent header RLP,
   contract code), written into a temp dir and atomically renamed, so
   a crash or an injected failure (``obs/bundle_fail``) can never
   leave a half-written bundle behind.  Writes/failures/ring occupancy
   mirror into the metrics registry (``forensics/*``) and each bundle
   lands a ``forensics/bundle`` instant in the span tracer.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import shutil
import threading
import time
from typing import Dict, List, Optional

from coreth_tpu import faults
from coreth_tpu.obs import trace as _trace

# the bundle write fails mid-drain: the stream must finish on the
# right root, the failure is COUNTED (bundle_failures), and no
# half-written directory survives (atomic rename)
PT_BUNDLE_FAIL = faults.declare(
    "obs/bundle_fail",
    "bundle write fails mid-drain (counted, no half-written dir)")

# ------------------------------------------------------------- triggers
#
# Every divergence/quarantine/demotion seam that routes evidence into
# the recorder declares itself here; tests/test_forensics.py gates
# declared == covered-and-wired, so a new oracle cannot land without
# forensics coverage.

_TRIGGERS: Dict[str, str] = {}


def declare_trigger(name: str, doc: str) -> str:
    _TRIGGERS[name] = doc
    return name


def declared_triggers() -> Dict[str, str]:
    return dict(_TRIGGERS)


TR_HOSTEXEC = declare_trigger(
    "hostexec/oracle_divergence",
    "armed CORETH_HOST_EXEC_CHECK oracle: native engine disagrees with "
    "the interpreter (evm/hostexec/bridge.py)")
TR_FLAT = declare_trigger(
    "flat/oracle_divergence",
    "armed CORETH_FLAT_CHECK oracle: flat store disagrees with the "
    "trie (replay/engine.py + state/statedb.py)")
TR_TRIE = declare_trigger(
    "trie/oracle_divergence",
    "armed CORETH_TRIE_CHECK oracle: native trie disagrees with the "
    "python twin at a window fold (replay/commit.py)")
TR_ROOT = declare_trigger(
    "commit/root_mismatch",
    "window fold landed on a root different from the last staged "
    "header's (replay/commit.py; covers the sharded window path too — "
    "per-block device validation failures re-run on the host and "
    "surface through engine/fallback_mismatch)")
TR_FALLBACK = declare_trigger(
    "engine/fallback_mismatch",
    "strict host-path replay mismatch: gas/receipt-root/state-root "
    "disagree with the header (replay/engine.py _fallback)")
TR_QUARANTINE = declare_trigger(
    "serve/quarantine",
    "poison block failed every backend and was tolerantly applied "
    "(replay/engine.py quarantine_block)")
TR_DEMOTE = declare_trigger(
    "supervisor/hard_demote",
    "a backend was hard-demoted for being WRONG, not slow "
    "(replay/supervisor.py strike(hard=True))")
TR_BOUNDARY = declare_trigger(
    "cluster/boundary_mismatch",
    "cluster aggregator rejected this worker's boundary root and "
    "demanded its evidence before re-assigning the lane "
    "(serve/cluster/worker.py _send_bundles)")


# THE module global every instrumentation site checks (None = off)
RECORDER: Optional["FlightRecorder"] = None


class _Entry:
    """One ring slot: a dispatched block + whatever evidence exists."""

    __slots__ = ("number", "block", "parent", "backend", "touched",
                 "witness", "results")

    def __init__(self, number, block, parent, backend, touched):
        self.number = number
        self.block = block          # Block object; encoded on drain
        self.parent = parent        # parent Header object or None
        self.backend = backend
        self.touched = touched      # light dispatch-time sketch
        self.witness = None         # full pre-state slice (host path)
        self.results = None         # receipts/root/reasons (host path)


class FlightRecorder:
    """Bounded per-block witness ring + trigger-frozen bundle writer."""

    def __init__(self, out_dir: Optional[str] = None,
                 ring: int = 32, max_bundles: int = 8):
        self.dir = out_dir or os.environ.get(
            "CORETH_FORENSICS_DIR", ".coreth_forensics")
        self.ring_size = ring
        self.max_bundles = max_bundles
        self._lock = threading.Lock()
        self._ring: List[_Entry] = []
        self._pending: List[dict] = []   # triggers awaiting a witness
        # engine-supplied replay context (chain config scalars) +
        # backend/env fingerprint, both merged in by the engines
        self.config: Dict[str, object] = {}
        self.fingerprint: Dict[str, object] = _env_fingerprint()
        # counters (mirrored to metrics via publish())
        self.bundle_writes = 0
        self.bundle_failures = 0
        self.bundle_dedup = 0   # identical evidence already on disk
        self.triggers = 0
        self.write_ms = 0.0
        self.bundles: List[dict] = []   # {"path","number","kind"}
        self._q: "queue.Queue" = queue.Queue()
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- capture
    def _entry_for(self, number: int) -> Optional[_Entry]:
        for e in reversed(self._ring):
            if e.number == number:
                return e
        return None

    def record_dispatch(self, block, parent, backend: str,
                        touched: Optional[dict] = None) -> None:
        """A block entered an execution backend: land (or refresh) its
        ring entry.  Cheap — object references only; serialization is
        the drain thread's job."""
        with self._lock:
            e = self._entry_for(block.number)
            if e is None:
                e = _Entry(block.number, block, parent, backend, touched)
                self._ring.append(e)
                if len(self._ring) > self.ring_size:
                    self._ring.pop(0)
            else:
                e.block = block
                e.backend = backend
                if parent is not None:
                    e.parent = parent
                if touched is not None:
                    e.touched = touched

    def record_witness(self, block, parent, prestate: dict,
                       results: dict) -> None:
        """The host path finished (or died on) a block: attach the full
        witness — the replayable pre-state slice + results — and freeze
        any trigger that was waiting for it."""
        with self._lock:
            e = self._entry_for(block.number)
            if e is None:
                e = _Entry(block.number, block, parent, "host", None)
                self._ring.append(e)
                if len(self._ring) > self.ring_size:
                    self._ring.pop(0)
            e.block = block
            if parent is not None:
                e.parent = parent
            e.witness = prestate
            e.results = results
            due = [t for t in self._pending
                   if t.get("number") in (None, block.number)]
            if not due:
                return
            self._pending = [t for t in self._pending if t not in due]
            self._patch_witness(e, due)
        self._freeze(due)

    def note_trigger(self, kind: str, reason: str,
                     number: Optional[int] = None,
                     tx_index: Optional[int] = None,
                     contract: Optional[bytes] = None,
                     key: Optional[bytes] = None,
                     got=None, want=None,
                     pre_value: Optional[bytes] = None) -> None:
        """A divergence/quarantine/demotion seam fired.  Freeze a
        bundle now if the trigger block's full witness already exists;
        otherwise hold it pending — the host path that surfaces every
        per-block trigger records the witness moments later (leftovers
        freeze context-only at flush_pending()).

        ``pre_value`` is the authoritative (trie-side) 32-byte
        pre-value of ``(contract, key)`` when the seam knows it: an
        oracle trip aborts the read BEFORE it lands in the StateDB's
        committed-read cache, so without this the one key the trigger
        is ABOUT would be missing from the harvested witness."""
        trig = {"kind": kind, "reason": reason, "number": number,
                "tx_index": tx_index,
                "contract": contract.hex() if contract else None,
                "key": key.hex() if key else None,
                "got": repr(got) if got is not None else None,
                "want": repr(want) if want is not None else None,
                # raw-bytes fields (stripped at serialization) feed
                # the witness patch in _patch_witness
                "_contract_raw": contract, "_key_raw": key,
                "_pre_raw": pre_value}
        self.triggers += 1
        with self._lock:
            e = self._entry_for(number) if number is not None else None
            have = e is not None and e.witness is not None
            if have:
                self._patch_witness(e, [trig])
            if not have:
                self._pending.append(trig)
                return
        self._freeze([trig])

    @staticmethod
    def _patch_witness(e: _Entry, triggers: List[dict]) -> None:
        """Backfill each trigger's authoritative pre-value into the
        witness slice if the harvest missed the key (caller holds the
        lock; the witness dict is entry-owned)."""
        w = e.witness
        if not w:
            return
        storage = w.get("storage")
        if storage is None:
            return
        for t in triggers:
            c, k, pv = (t.get("_contract_raw"), t.get("_key_raw"),
                        t.get("_pre_raw"))
            if c is not None and k is not None and pv is not None:
                storage.setdefault((c, k), pv)

    def flush_pending(self) -> None:
        """Freeze any triggers still waiting for a witness (the crash/
        propagate paths where no host retry ever ran) as context-only
        bundles, so the evidence outlives the process anyway."""
        with self._lock:
            due, self._pending = self._pending, []
        if due:
            self._freeze(due)

    # ------------------------------------------------------------ freeze
    @staticmethod
    def _copy_entry(e: _Entry) -> _Entry:
        """A frozen copy of one ring slot: the blocks/headers are
        immutable, but witness/results/touched are REPLACED by a later
        record_witness (the quarantine re-run of a strict failure) and
        PATCHED in place by a later trigger — the bundle must pin the
        state at trigger time, not whatever the retry leaves behind."""
        c = _Entry(e.number, e.block, e.parent, e.backend,
                   dict(e.touched) if e.touched is not None else None)
        if e.witness is not None:
            w = dict(e.witness)
            for fld in ("accounts", "storage", "code"):
                if isinstance(w.get(fld), dict):
                    w[fld] = dict(w[fld])
            c.witness = w
        if e.results is not None:
            c.results = dict(e.results)
        return c

    def _freeze(self, triggers: List[dict]) -> None:
        """Snapshot the ring + triggers and hand the bundle to the
        drain thread (per-entry field copies; the blob/JSON
        serialization itself happens on the drain thread)."""
        if self.bundle_writes + self.bundle_failures \
                + self._q.qsize() >= self.max_bundles:
            return
        with self._lock:
            snap = {
                "triggers": list(triggers),
                "entries": [self._copy_entry(e) for e in self._ring],
                "config": dict(self.config),
                "fingerprint": dict(self.fingerprint),
            }
        self._ensure_thread()
        self._q.put(snap)

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._drain_loop, name="forensics-drain",
                daemon=True)
            self._thread.start()

    def _drain_loop(self) -> None:
        while True:
            snap = self._q.get()
            if snap is None:
                self._q.task_done()
                return
            try:
                self._write_bundle(snap)
            finally:
                self._q.task_done()

    def drain(self, timeout_s: float = 30.0) -> None:
        """Block until every queued bundle is written (or the timeout
        lapses) — called at pipeline publish / uninstall, never from
        the execute path."""
        deadline = time.monotonic() + timeout_s
        while not self._q.empty() or self._q.unfinished_tasks:
            if time.monotonic() >= deadline:
                return
            time.sleep(0.005)

    def close(self) -> None:
        """Drain, then stop the drain thread (the None sentinel) —
        a long-lived process installing recorders repeatedly must not
        accumulate parked daemon threads pinning old rings."""
        self.drain()
        t = self._thread
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout=5)
        self._thread = None

    # ------------------------------------------------------------- write
    def _write_bundle(self, snap: dict) -> Optional[str]:
        t0 = time.monotonic()
        tmp = None
        try:
            faults.fire(PT_BUNDLE_FAIL)
            manifest, blobs = _serialize(snap)
            body = json.dumps(manifest, sort_keys=True, indent=1)
            digest = hashlib.sha256(body.encode()).hexdigest()[:16]
            final = os.path.join(self.dir, f"bundle-{digest}")
            trig = snap["triggers"][0]
            if os.path.isdir(final):
                # identical evidence already on disk: no second write,
                # but the trigger still SURFACES (a second run hitting
                # the same poison block must report its bundle path,
                # not "no evidence")
                with self._lock:
                    self.bundle_dedup += 1
                    self.bundles.append({"path": final,
                                         "number": trig.get("number"),
                                         "kind": trig["kind"]})
                return final
            with self._lock:
                self._seq += 1
                seq = self._seq
            tmp = os.path.join(self.dir,
                               f".tmp-{os.getpid()}-{seq}")
            os.makedirs(os.path.join(tmp, "blobs"))
            for name, data in blobs.items():
                with open(os.path.join(tmp, "blobs", name), "wb") as f:
                    f.write(data)
            with open(os.path.join(tmp, "manifest.json"), "w",
                      encoding="utf-8") as f:
                f.write(body)
            os.replace(tmp, final)   # the atomic publish
            tmp = None
            with self._lock:
                self.bundle_writes += 1
                self.write_ms += (time.monotonic() - t0) * 1000.0
                self.bundles.append({"path": final,
                                     "number": trig.get("number"),
                                     "kind": trig["kind"]})
            _trace.instant("forensics/bundle", path=final,
                           kind=trig["kind"])
            return final
        except (faults.FaultInjected, OSError, TypeError,
                ValueError) as exc:
            # counted, never raised: forensics must not take down the
            # stream it is documenting; the atomic-rename protocol
            # means a failure here leaves no partial directory
            if tmp is not None:
                shutil.rmtree(tmp, ignore_errors=True)
            with self._lock:
                self.bundle_failures += 1
                self.last_error = repr(exc)
            return None

    last_error: Optional[str] = None

    # --------------------------------------------------------- reporting
    def bundles_for(self, number: int,
                    kind: Optional[str] = None) -> List[str]:
        return [b["path"] for b in self.bundles
                if b["number"] == number
                and (kind is None or b["kind"] == kind)]

    def snapshot(self) -> dict:
        return {
            "dir": self.dir,
            "ring_blocks": len(self._ring),
            "triggers": self.triggers,
            "bundle_writes": self.bundle_writes,
            "bundle_failures": self.bundle_failures,
            "bundle_dedup": self.bundle_dedup,
            "write_ms": round(self.write_ms, 3),
            "bundles": [dict(b) for b in self.bundles],
        }

    def publish(self, registry=None) -> None:
        from coreth_tpu.metrics import Gauge, get_or_register
        for name in ("bundle_writes", "bundle_failures", "triggers"):
            get_or_register(f"forensics/{name}", Gauge,
                            registry).update(getattr(self, name))
        get_or_register("forensics/ring_blocks", Gauge,
                        registry).update(len(self._ring))


# --------------------------------------------------------- serialization

_ENV_KNOBS = (
    "CORETH_TRIE", "CORETH_TRIE_CHECK", "CORETH_FLAT",
    "CORETH_FLAT_CHECK", "CORETH_HOST_EXEC", "CORETH_HOST_EXEC_CHECK",
    "CORETH_MACHINE", "CORETH_DEVICE_OCC", "CORETH_SPECIALIZE",
    "CORETH_EXCHANGE", "CORETH_KEYRANGE", "CORETH_KEYRANGE_THRESHOLD",
    "CORETH_PREMAP_PREDICT", "CORETH_PREMAP_NEST", "CORETH_PREMAP_ARR",
    "CORETH_SERIAL_SHORTCIRCUIT", "CORETH_NO_TOKEN_FASTPATH",
    "CORETH_MACHINE_WINDOW",
)


def _env_fingerprint() -> Dict[str, object]:
    """Backend/env fingerprint: every knob that selects an execution
    or commitment backend, plus the resolved trie backend — what the
    offline replayer needs to reconstruct the live run's routing."""
    # the RESOLVED backends (trie backend, shard count, flat/check
    # arming) merge in from the engine via merge_fingerprint — this
    # level-0 module records only env + process identity itself
    return {
        "env": {k: os.environ[k] for k in _ENV_KNOBS
                if k in os.environ},
        "pid": os.getpid(),
    }


def _hx(b: bytes) -> str:
    return b.hex()


def _serialize(snap: dict):
    """Snapshot (object refs) -> (manifest dict, blob name -> bytes).
    Runs on the drain thread only."""
    blobs: Dict[str, bytes] = {}
    blocks = []
    for e in snap["entries"]:
        wire = e.block.encode()
        bname = f"block-{e.number}.bin"
        blobs[bname] = wire
        row = {
            "number": e.number,
            "hash": _hx(e.block.hash()),
            "backend": e.backend,
            "block_blob": bname,
            "block_sha256": hashlib.sha256(wire).hexdigest(),
        }
        if e.parent is not None:
            pname = f"parent-{e.number}.bin"
            blobs[pname] = e.parent.encode()
            row["parent_header_blob"] = pname
        if e.touched is not None:
            row["touched"] = e.touched
        if e.witness is not None:
            w = e.witness
            accounts = {}
            for addr, acct in w.get("accounts", {}).items():
                accounts[_hx(addr)] = None if acct is None else {
                    "balance": acct[0], "nonce": acct[1],
                    "root": _hx(acct[2]), "code_hash": _hx(acct[3]),
                    "multicoin": bool(acct[4])}
            storage: Dict[str, Dict[str, str]] = {}
            for (c, k), v in w.get("storage", {}).items():
                storage.setdefault(_hx(c), {})[_hx(k)] = \
                    _hx(v) if isinstance(v, bytes) \
                    else _hx(int(v).to_bytes(32, "big"))
            code_list = []
            for ch, code in w.get("code", {}).items():
                cname = f"code-{_hx(ch)[:16]}.bin"
                blobs[cname] = code
                code_list.append({"code_hash": _hx(ch), "blob": cname})
            row["witness"] = {
                "accounts": accounts, "storage": storage,
                "code": code_list,
                "complete": bool(w.get("complete", True)),
                "failed_tx_index": w.get("failed_tx_index"),
            }
        if e.results is not None:
            r = dict(e.results)
            for fld in ("computed_root", "header_root"):
                if isinstance(r.get(fld), bytes):
                    r[fld] = _hx(r[fld])
            row["results"] = r
        blocks.append(row)
    manifest = {
        "version": 1,
        "triggers": [{k: v for k, v in t.items()
                      if not k.startswith("_")}
                     for t in snap["triggers"]],
        "fingerprint": snap["fingerprint"],
        "config": snap["config"],
        "blocks": blocks,
    }
    return manifest, blobs


# ------------------------------------------------------------ module API

def enabled() -> bool:
    return RECORDER is not None


def recorder() -> Optional[FlightRecorder]:
    return RECORDER


def install(out_dir: Optional[str] = None, ring: Optional[int] = None,
            max_bundles: Optional[int] = None) -> FlightRecorder:
    global RECORDER
    if RECORDER is not None:
        # replacing an active recorder must not strand its parked
        # drain thread (and the ring it pins) — same teardown as
        # uninstall(), evidence flushed first
        uninstall()
    rec = FlightRecorder(
        out_dir=out_dir,
        ring=ring or int(os.environ.get("CORETH_FORENSICS_RING",
                                        "32") or "32"),
        max_bundles=max_bundles or int(os.environ.get(
            "CORETH_FORENSICS_MAX", "8") or "8"))
    os.makedirs(rec.dir, exist_ok=True)
    RECORDER = rec
    return rec


def uninstall() -> Optional[FlightRecorder]:
    """Remove the global recorder; pending triggers freeze and queued
    bundles drain first, so no evidence is dropped at teardown."""
    global RECORDER
    rec = RECORDER
    if rec is not None:
        rec.flush_pending()
        rec.close()
    RECORDER = None
    return rec


def arm_from_env() -> Optional[FlightRecorder]:
    """Install a recorder if CORETH_FORENSICS=1 and none is active yet
    (idempotent — engine and pipeline constructors both call this,
    mirroring faults/obs.arm_from_env)."""
    if RECORDER is not None:
        return RECORDER
    if not bool(int(os.environ.get("CORETH_FORENSICS", "0") or "0")):
        return None
    return install()


def note_config(config) -> None:
    """Engine hand-off of the chain config's JSON-able scalars (fork
    blocks/times + chain id) — what the offline replayer rebuilds its
    ChainConfig from."""
    rec = RECORDER
    if rec is None:
        return
    rec.config = {k: v for k, v in vars(config).items()
                  if isinstance(v, (int, bool)) or v is None}


def merge_fingerprint(extra: dict) -> None:
    rec = RECORDER
    if rec is None:
        return
    rec.fingerprint.update(extra)


def record_dispatch(block, parent, backend: str,
                    touched: Optional[dict] = None) -> None:
    rec = RECORDER
    if rec is None:
        return
    rec.record_dispatch(block, parent, backend, touched)


def record_witness(block, parent, prestate: dict, results: dict) -> None:
    rec = RECORDER
    if rec is None:
        return
    rec.record_witness(block, parent, prestate, results)


def note_trigger(kind: str, reason: str, **ctx) -> None:
    rec = RECORDER
    if rec is None:
        return
    rec.note_trigger(kind, reason, **ctx)


def flush_pending() -> None:
    rec = RECORDER
    if rec is None:
        return
    rec.flush_pending()
