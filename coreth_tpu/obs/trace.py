"""End-to-end span tracing: per-block latency attribution + Perfetto.

The stack runs six concurrent actors (feed, prefetch, execute, device
dispatch, commit, flat exporter) plus a supervisor that silently
reroutes work between backends; this module is the shared evidence
layer that says WHERE a block's enqueue->committed time went.

Design constraints, in order (the faults-registry / metrics.ENABLED
mold):

1. **Disabled costs ~nothing.**  ``TRACER`` is a module global that is
   ``None`` by default; every instrumentation site goes through
   :func:`span` / :func:`instant` / :func:`block_begin`, which return
   after ONE module-global ``is None`` check — no ring is allocated,
   no event is recorded, no contextvar is touched.  ``CORETH_TRACE=1``
   installs the tracer (:func:`arm_from_env`, called idempotently by
   the pipeline and engine constructors, like ``faults.arm_from_env``).
2. **Bounded.**  Events land in a ring (``CORETH_TRACE_RING``, default
   64k events); a long-running stream overwrites its oldest events
   instead of growing, and ``dropped`` counts the evictions.
3. **Exportable.**  :meth:`SpanTracer.export` renders the ring as
   Chrome trace-event / Perfetto JSON: one row per thread (metadata
   ``thread_name`` events), complete ``X`` spans, ``i`` instants, and
   ``s``/``t``/``f`` flow arrows that follow a block (flow id = block
   number) across the feed, prefetch, execute, and flat-exporter
   threads.  ``CORETH_TRACE_OUT=path`` writes the export at pipeline
   shutdown (:func:`write_out`); a write failure — the
   ``obs/export_fail`` injection point, or a real I/O error — is
   counted, never raised: the trace is diagnostics, losing it must not
   take the pipeline down.
4. **Attributable.**  A :class:`BlockTrace` rides each block from feed
   enqueue to commit; its named stage intervals sum EXACTLY to the
   block's enqueue->committed latency, and the tracer aggregates them
   into ``stage_breakdown()`` — the per-stage share surface
   ``StreamReport.stage_breakdown`` and the bench ``tracing`` section
   publish.

``CORETH_TRACE_JAX=1`` additionally brackets device dispatches with
``jax.profiler.TraceAnnotation`` (:func:`jax_span`) so XLA activity
lines up under the same timeline when a jax profile is captured.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from coreth_tpu import faults

# the trace-file write fails mid-export: the pipeline must finish
# unharmed and the failure must be COUNTED (SpanTracer.export_failures)
PT_EXPORT_FAIL = faults.declare(
    "obs/export_fail",
    "trace-file write fails mid-export (pipeline unharmed, counted)")

# THE module global every instrumentation site checks (None = off)
TRACER: Optional["SpanTracer"] = None

# current flow id (block number) for span/instant inheritance: set by
# a span opened with an explicit flow=, read by everything nested under
# it on the same thread — contextvars give per-thread isolation without
# threading the id through every call signature
_FLOW: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "coreth_trace_flow", default=None)

# Stable per-thread trace ids.  threading.get_ident() is the raw
# pthread handle, which the OS RECYCLES the moment a thread exits — a
# fast backlog feed thread can die before the prefetch thread is even
# created, handing both the same ident and merging their timeline rows
# (observed: the prefetch row labeled "serve-feed").  A monotonic
# counter bound to a threading.local never repeats, so every thread
# lifetime gets its own row.
_TID_LOCAL = threading.local()
_TID_COUNTER = itertools.count(1)


def _tid() -> int:
    t = getattr(_TID_LOCAL, "tid", None)
    if t is None:
        t = next(_TID_COUNTER)
        _TID_LOCAL.tid = t
    return t


class _NullSpan:
    """Shared no-op context manager the disabled path hands out."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One recorded span: a complete ``X`` event emitted at exit, with
    flow inheritance through the contextvar while it is open."""

    __slots__ = ("_t", "name", "_flow", "_args", "_t0", "_tok")

    def __init__(self, tracer: "SpanTracer", name: str,
                 flow: Optional[int], args: dict):
        self._t = tracer
        self.name = name
        self._flow = flow
        self._args = args
        self._tok = None

    def __enter__(self):
        t = self._t
        self._t0 = t._now_us()
        if self._flow is None:
            self._flow = _FLOW.get()
        else:
            self._tok = _FLOW.set(self._flow)
        if self._flow is not None:
            t._bind_flow(self._flow, self._t0)
        return self

    def __exit__(self, *exc):
        t = self._t
        tid = _tid()
        t._note_thread(tid)
        ev = {"ph": "X", "name": self.name, "ts": self._t0,
              "dur": t._now_us() - self._t0, "tid": tid}
        if self._flow is not None:
            args = dict(self._args) if self._args else {}
            args["flow"] = self._flow
            ev["args"] = args
        elif self._args:
            ev["args"] = self._args
        t._emit(ev)
        if self._tok is not None:
            _FLOW.reset(self._tok)
            self._tok = None
        return False


class StageAccumulator:
    """Thread-safe per-consumer sink for block stage attribution.

    Each consumer (a StreamingPipeline run) owns ONE of these, so two
    pipelines sharing the process-global tracer — a builder+replica
    pair, or back-to-back bench reps armed via CORETH_TRACE=1 — never
    blend each other's blocks into one breakdown.  The tracer embeds a
    default instance for consumers that don't pass their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stage_s: Dict[str, float] = {}
        self._latency_s = 0.0
        self._blocks = 0

    def add_block(self, stages: Dict[str, float],
                  total_s: float) -> None:
        """Fold one committed block's stage intervals (seconds; their
        sum equals the block's enqueue->committed latency)."""
        with self._lock:
            self._blocks += 1
            self._latency_s += total_s
            acc = self._stage_s
            for k, v in stages.items():
                acc[k] = acc.get(k, 0.0) + v

    def breakdown(self) -> dict:
        """Per-stage SHARE of total enqueue->committed time across
        every block folded so far (shares sum to ~1.0 by construction;
        ``_blocks``/``_latency_s`` carry the denominators)."""
        with self._lock:
            total = self._latency_s
            if total <= 0 or not self._blocks:
                return {}
            out = {k: round(v / total, 4)
                   for k, v in sorted(self._stage_s.items())}
            out["_blocks"] = self._blocks
            out["_latency_s"] = round(total, 3)
        return out


class SpanTracer:
    """Thread-safe span/instant recorder over a bounded ring."""

    def __init__(self, ring: int = 65536, clock=time.monotonic,
                 jax_annotations: Optional[bool] = None):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self.ring_size = ring
        self._ring: deque = deque(maxlen=ring)
        self.dropped = 0           # events evicted from the full ring
        self.export_failures = 0   # write_out failures (counted, eaten)
        self._thread_names: Dict[int, str] = {}
        if jax_annotations is None:
            jax_annotations = bool(int(
                os.environ.get("CORETH_TRACE_JAX", "0") or "0"))
        self.jax = jax_annotations
        # default attribution sink (BlockTrace folds here unless its
        # owner passed a per-consumer StageAccumulator)
        self.attribution = StageAccumulator()

    # ------------------------------------------------------------ recording
    def _now_us(self) -> int:
        return int((self._clock() - self._t0) * 1e6)

    def _note_thread(self, tid: int) -> None:
        # unlocked fast path for the steady state; the insert itself
        # must hold the lock because export() iterates/prunes this
        # dict under it (an unlocked insert racing that iteration is
        # a RuntimeError out of a live /trace scrape)
        if tid in self._thread_names:
            return
        with self._lock:
            self._thread_names[tid] = threading.current_thread().name

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if len(self._ring) == self.ring_size:
                self.dropped += 1
            self._ring.append(ev)

    def _bind_flow(self, flow: int, ts: int) -> None:
        """One flow-arrow binding at (ts, this thread).  Every binding
        records as ``t``; export() derives ``s``/``f`` from the ring's
        surviving content (first/last binding per id), so pairing needs
        NO cross-run state and survives both ring eviction of a flow's
        head and block numbers recurring across pipeline runs."""
        tid = _tid()
        self._note_thread(tid)
        with self._lock:
            if len(self._ring) == self.ring_size:
                self.dropped += 1
            self._ring.append({"ph": "t", "name": "block", "id": flow,
                               "ts": ts, "tid": tid})

    def span(self, name: str, flow: Optional[int] = None,
             **args) -> _Span:
        return _Span(self, name, flow, args)

    def instant(self, name: str, flow: Optional[int] = None,
                **args) -> None:
        ts = self._now_us()
        tid = _tid()
        self._note_thread(tid)
        if flow is None:
            flow = _FLOW.get()
        if flow is not None:
            self._bind_flow(flow, ts)
        ev = {"ph": "i", "s": "t", "name": name, "ts": ts, "tid": tid}
        if args:
            ev["args"] = args
        self._emit(ev)

    # ------------------------------------------------- stage attribution
    def add_block(self, stages: Dict[str, float],
                  total_s: float) -> None:
        """Fold into the tracer's default attribution sink."""
        self.attribution.add_block(stages, total_s)

    def stage_breakdown(self) -> dict:
        """The default sink's breakdown (per-consumer sinks — the
        pipeline's — report through their own StageAccumulator)."""
        return self.attribution.breakdown()

    # --------------------------------------------------------------- export
    def export(self) -> dict:
        """The ring as a Chrome trace-event / Perfetto JSON document:
        thread_name metadata rows first, then the events with pid/cat
        stamped.  Flow phases derive from the SURVIVING ring content —
        per id, the first binding becomes ``s`` and the last the
        terminating ``f`` — so arrows pair up even when the ring
        evicted a flow's head or a block number recurred across runs.
        Only the shallow snapshot happens under the recording lock
        (per-event copies outside it: a 64k-ring scrape must not stall
        every instrumented thread)."""
        pid = os.getpid()
        with self._lock:
            snap = list(self._ring)
            # prune names whose threads have no surviving events: a
            # long-lived env-armed tracer spawns fresh pipeline threads
            # (fresh tids — the counter never reuses) every run, and
            # without pruning the name map and every export's metadata
            # rows would grow without bound.  Safe: a still-live thread
            # re-notes its name on its next event.
            live = {e["tid"] for e in snap}
            for tid in [t for t in self._thread_names
                        if t not in live]:
                del self._thread_names[tid]
            names = dict(self._thread_names)
        evs = [dict(e) for e in snap]
        first_bind: Dict[int, int] = {}
        last_bind: Dict[int, int] = {}
        for i, e in enumerate(evs):
            if e["ph"] == "t":
                first_bind.setdefault(e["id"], i)
                last_bind[e["id"]] = i
        out = []
        for tid, nm in sorted(names.items()):
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "ts": 0, "cat": "__metadata",
                        "args": {"name": nm}})
        for i, e in enumerate(evs):
            e["pid"] = pid
            e.setdefault("cat", "coreth")
            if e["ph"] == "t":
                fid = e["id"]
                if first_bind[fid] == i:
                    e["ph"] = "s"
                elif last_bind[fid] == i:
                    e["ph"] = "f"
                    e["bp"] = "e"
            out.append(e)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def write_out(self, path: Optional[str] = None) -> Optional[str]:
        """Write the export to ``path`` (default ``CORETH_TRACE_OUT``);
        returns the path written, or None (not configured / failed —
        failures are counted in ``export_failures``, never raised)."""
        path = path or os.environ.get("CORETH_TRACE_OUT")
        if not path:
            return None
        try:
            faults.fire(PT_EXPORT_FAIL)
            # default=str: the open **kwargs span API means one
            # refactor could pass a non-JSON primitive (a numpy int,
            # say) — degrade it to its repr instead of losing the file
            data = json.dumps(self.export(), default=str)
            with open(path, "w", encoding="utf-8") as f:
                f.write(data)
            return path
        except (faults.FaultInjected, OSError, TypeError, ValueError):
            # counted, never raised: this runs in the pipeline's
            # shutdown finally — a failed diagnostic write must not
            # turn a successful stream into a crashed run
            self.export_failures += 1
            return None


class BlockTrace:
    """Per-block trace context: rides one block from feed enqueue to
    commit (carried on the pipeline's queue items), emitting flow-bound
    instants on each thread it crosses and accumulating the stage
    intervals whose sum IS the block's enqueue->committed latency.

    Stages (consecutive, clamped non-negative, summing exactly to the
    total): ``queue_feed`` (enqueue -> prefetch pickup), ``prefetch``
    (per-block share of the chunk warm), ``queue_exec`` (prefetched ->
    execute-stage pickup), ``commit`` (per-block share of the window's
    trie-fold flush), and ``execute`` (the remainder: classify, device
    dispatch/validation, host fallback)."""

    __slots__ = ("_t", "_sink", "number", "t_enqueue", "t_prefetch",
                 "prefetch_s", "t_exec")

    def __init__(self, tracer: SpanTracer, number: int,
                 t_enqueue: Optional[float] = None,
                 sink: Optional[StageAccumulator] = None):
        self._t = tracer
        # attribution sink: the owner's per-consumer accumulator, so
        # concurrent/sequential pipelines sharing the global tracer
        # never blend breakdowns (default: the tracer's own)
        self._sink = sink if sink is not None else tracer.attribution
        self.number = number
        self.t_enqueue = tracer._clock() if t_enqueue is None \
            else t_enqueue
        self.t_prefetch: Optional[float] = None
        self.prefetch_s = 0.0
        self.t_exec: Optional[float] = None
        tracer.instant("block/enqueue", flow=number, number=number)

    def prefetched(self, t_start: float, share_s: float) -> None:
        self.t_prefetch = t_start
        self.prefetch_s = share_s
        self._t.instant("block/prefetched", flow=self.number)

    def exec_start(self) -> None:
        self.t_exec = self._t._clock()
        self._t.instant("block/exec_start", flow=self.number)

    def finish(self, t_commit: float, commit_s: float = 0.0) -> None:
        total = max(t_commit - self.t_enqueue, 0.0)
        t_pf = self.t_prefetch if self.t_prefetch is not None \
            else self.t_enqueue
        queue_feed = min(max(t_pf - self.t_enqueue, 0.0), total)
        prefetch = min(self.prefetch_s, total - queue_feed)
        t_ex = self.t_exec if self.t_exec is not None else t_pf
        queue_exec = min(max(t_ex - t_pf - prefetch, 0.0),
                         total - queue_feed - prefetch)
        commit = min(max(commit_s, 0.0),
                     total - queue_feed - prefetch - queue_exec)
        execute = total - queue_feed - prefetch - queue_exec - commit
        self._sink.add_block(
            {"queue_feed": queue_feed, "prefetch": prefetch,
             "queue_exec": queue_exec, "execute": execute,
             "commit": commit}, total)
        self._t.instant("block/committed", flow=self.number)


class EventRing:
    """Small ALWAYS-ON ordered event ring (the evm/device/shard.py
    dispatch-ordering trace).  Appends cost one bounded-deque push when
    tracing is off — the exact semantics the dispatch-ordering test in
    tests/test_shard_replay.py pins — and mirror into the active tracer
    as instant events when it is on, so the Perfetto timeline shows the
    same dispatch/fetch ordering the test asserts."""

    __slots__ = ("name", "_dq")

    def __init__(self, name: str, maxlen: int = 512):
        self.name = name
        self._dq: deque = deque(maxlen=maxlen)

    def append(self, entry: str) -> None:
        self._dq.append(entry)
        t = TRACER
        if t is not None:
            t.instant(f"{self.name}/{entry}")

    def clear(self) -> None:
        self._dq.clear()

    def __iter__(self):
        return iter(self._dq)

    def __len__(self) -> int:
        return len(self._dq)

    def __contains__(self, entry) -> bool:
        return entry in self._dq


# ------------------------------------------------------------- module API

def enabled() -> bool:
    return TRACER is not None


def tracer() -> Optional[SpanTracer]:
    """The active tracer (None when tracing is off) — the accessor for
    callers that hold ``obs`` rather than this module (the re-exported
    ``TRACER`` name would snapshot the binding at import)."""
    return TRACER


def span(name: str, **kw):
    """A recorded span, or the shared no-op when tracing is off (the
    one-module-global-None-check contract every site relies on)."""
    t = TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **kw)


def instant(name: str, **kw) -> None:
    t = TRACER
    if t is None:
        return
    t.instant(name, **kw)


def block_begin(number: int, t_enqueue: Optional[float] = None,
                sink: Optional[StageAccumulator] = None
                ) -> Optional[BlockTrace]:
    """A BlockTrace riding block ``number`` (None when tracing is off
    — callers carry the None and skip their marks).  ``sink`` is the
    owner's per-consumer StageAccumulator."""
    t = TRACER
    if t is None:
        return None
    return BlockTrace(t, number, t_enqueue, sink)


def jax_span(name: str):
    """``jax.profiler.TraceAnnotation`` bracketing a device dispatch
    when CORETH_TRACE_JAX=1 and tracing is on (so XLA activity lines up
    under the same timeline in a captured jax profile); the shared
    no-op otherwise."""
    t = TRACER
    if t is None or not t.jax:
        return _NULL_SPAN
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — annotation is advisory; a jax without the profiler API must not break tracing
        return _NULL_SPAN


def install(tracer: Optional[SpanTracer] = None,
            ring: Optional[int] = None) -> SpanTracer:
    """Install (and return) the global tracer.  Tests and the bench use
    this directly; production opts in through CORETH_TRACE=1."""
    global TRACER
    if tracer is None:
        tracer = SpanTracer(ring=ring) if ring else SpanTracer()
    TRACER = tracer
    return tracer


def uninstall() -> Optional[SpanTracer]:
    """Remove and return the global tracer (instrumentation sites go
    back to the one-None-check no-op)."""
    global TRACER
    t = TRACER
    TRACER = None
    return t


def arm_from_env() -> Optional[SpanTracer]:
    """Install a tracer if CORETH_TRACE=1 and none is active yet
    (idempotent — the pipeline and engine constructors both call this,
    whoever runs first wins, mirroring faults.arm_from_env)."""
    if TRACER is not None:
        return TRACER
    if not bool(int(os.environ.get("CORETH_TRACE", "0") or "0")):
        return None
    ring = int(os.environ.get("CORETH_TRACE_RING", "65536") or "65536")
    return install(ring=ring)


def write_out(path: Optional[str] = None) -> Optional[str]:
    """Write the active tracer's export to CORETH_TRACE_OUT (or
    ``path``); no-op when tracing is off or no path is configured."""
    t = TRACER
    if t is None:
        return None
    return t.write_out(path)
