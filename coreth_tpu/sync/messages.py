"""Sync wire messages (plugin/evm/message twin).

LeafsRequest/Response carry verified key ranges (message/
leafs_request.go); CodeRequest fetches contract bytecode by hash;
BlockRequest fetches ancestor block bodies.  Encoding rides the same
linear-codec packer the atomic txs use.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from coreth_tpu.wire import Packer, Unpacker


# LeafsRequest node types (message/leafs_request.go NodeType)
STATE_TRIE_NODE = 0
ATOMIC_TRIE_NODE = 1


@dataclass
class LeafsRequest:
    """Range request against one trie (leafs_request.go:30)."""
    root: bytes = b"\x00" * 32
    account: bytes = b""           # set for storage-trie requests
    start: bytes = b""             # first key (inclusive), raw trie key
    limit: int = 1024
    node_type: int = STATE_TRIE_NODE

    def encode(self) -> bytes:
        p = Packer()
        p.u8(0)
        p.fixed(self.root, 32)
        p.var_bytes(self.account)
        p.var_bytes(self.start)
        p.u32(self.limit)
        p.u8(self.node_type)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "LeafsRequest":
        u = Unpacker(data)
        assert u.u8() == 0
        return cls(u.fixed(32), u.var_bytes(), u.var_bytes(), u.u32(),
                   u.u8())


@dataclass
class LeafsResponse:
    keys: List[bytes] = field(default_factory=list)
    vals: List[bytes] = field(default_factory=list)
    more: bool = False
    proof: List[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        p = Packer()
        p.u8(1)
        p.u32(len(self.keys))
        for k, v in zip(self.keys, self.vals):
            p.var_bytes(k)
            p.var_bytes(v)
        p.u8(1 if self.more else 0)
        p.u32(len(self.proof))
        for n in self.proof:
            p.var_bytes(n)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "LeafsResponse":
        u = Unpacker(data)
        assert u.u8() == 1
        n = u.u32()
        keys, vals = [], []
        for _ in range(n):
            keys.append(u.var_bytes())
            vals.append(u.var_bytes())
        more = bool(u.u8())
        proof = [u.var_bytes() for _ in range(u.u32())]
        return cls(keys, vals, more, proof)


@dataclass
class CodeRequest:
    hashes: List[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        p = Packer()
        p.u8(2)
        p.u32(len(self.hashes))
        for h in self.hashes:
            p.fixed(h, 32)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "CodeRequest":
        u = Unpacker(data)
        assert u.u8() == 2
        return cls([u.fixed(32) for _ in range(u.u32())])


@dataclass
class CodeResponse:
    codes: List[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        p = Packer()
        p.u8(3)
        p.u32(len(self.codes))
        for c in self.codes:
            p.var_bytes(c)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "CodeResponse":
        u = Unpacker(data)
        assert u.u8() == 3
        return cls([u.var_bytes() for _ in range(u.u32())])


@dataclass
class BlockRequest:
    """Fetch `parents` ancestors ending at `block_hash`
    (message/block_request.go)."""
    block_hash: bytes = b"\x00" * 32
    height: int = 0
    parents: int = 1

    def encode(self) -> bytes:
        p = Packer()
        p.u8(4)
        p.fixed(self.block_hash, 32)
        p.u64(self.height)
        p.u16(self.parents)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BlockRequest":
        u = Unpacker(data)
        assert u.u8() == 4
        return cls(u.fixed(32), u.u64(), u.u16())


@dataclass
class BlockResponse:
    blocks: List[bytes] = field(default_factory=list)  # wire bodies

    def encode(self) -> bytes:
        p = Packer()
        p.u8(5)
        p.u32(len(self.blocks))
        for b in self.blocks:
            p.var_bytes(b)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BlockResponse":
        u = Unpacker(data)
        assert u.u8() == 5
        return cls([u.var_bytes() for _ in range(u.u32())])


@dataclass
class SignatureRequest:
    """Warp signature request (message/signature_request.go): exactly
    one of message_id (sign a stored warp message) or block_hash (sign
    an accepted block hash) is set."""
    message_id: bytes = b""
    block_hash: bytes = b""

    def encode(self) -> bytes:
        p = Packer()
        p.u8(6)
        p.var_bytes(self.message_id)
        p.var_bytes(self.block_hash)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "SignatureRequest":
        u = Unpacker(data)
        assert u.u8() == 6
        return cls(u.var_bytes(), u.var_bytes())


@dataclass
class SignatureResponse:
    """96-byte BLS signature; empty = this node cannot sign the
    request (message/signature_request.go SignatureResponse)."""
    signature: bytes = b""

    def encode(self) -> bytes:
        p = Packer()
        p.u8(7)
        p.var_bytes(self.signature)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "SignatureResponse":
        u = Unpacker(data)
        assert u.u8() == 7
        return cls(u.var_bytes())


@dataclass
class EthCallRequest:
    """Cross-chain eth_call (message/eth_call_request.go): another
    chain's VM evaluates a read against this chain's tip state."""
    to: bytes = b"\x00" * 20
    data: bytes = b""

    def encode(self) -> bytes:
        p = Packer()
        p.u8(8)
        p.fixed(self.to, 20)
        p.var_bytes(self.data)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "EthCallRequest":
        u = Unpacker(data)
        assert u.u8() == 8
        return cls(u.fixed(20), u.var_bytes())


@dataclass
class EthCallResponse:
    result: bytes = b""
    error: str = ""

    def encode(self) -> bytes:
        p = Packer()
        p.u8(9)
        p.var_bytes(self.result)
        p.var_bytes(self.error.encode())
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "EthCallResponse":
        u = Unpacker(data)
        assert u.u8() == 9
        return cls(u.var_bytes(), u.var_bytes().decode())


def decode_message(data: bytes):
    kind = data[0]
    return {0: LeafsRequest, 1: LeafsResponse, 2: CodeRequest,
            3: CodeResponse, 4: BlockRequest, 5: BlockResponse,
            6: SignatureRequest, 7: SignatureResponse,
            8: EthCallRequest,
            9: EthCallResponse}[kind].decode(data)
