"""Server side of state sync.

Twin of reference sync/handlers/ (leafs_request.go:76 OnLeafsRequest —
walk the requested trie from `start`, cap the page, attach edge range
proofs; block_request.go — serve ancestor bodies; code requests by
hash).  Serves straight from a chain's Database/rawdb stores.
"""

from __future__ import annotations

from typing import List, Optional

from coreth_tpu.mpt.iterator import leaves
from coreth_tpu.mpt.proof import prove
from coreth_tpu.mpt.trie import Trie
from coreth_tpu.sync.messages import (
    BlockRequest, BlockResponse, CodeRequest, CodeResponse, LeafsRequest,
    LeafsResponse, decode_message,
)

MAX_LEAFS = 1024


class SyncHandler:
    """Answers sync requests for one chain (network_handler.go role)."""

    def __init__(self, db, chain=None, atomic_node_db=None):
        """db: state Database (node_db + code_db); chain: optional
        BlockChain for block requests; atomic_node_db: the atomic
        trie's node store — a dict or a zero-arg callable returning
        one — served for ATOMIC_TRIE_NODE leaf requests
        (leafs_request.go NodeType dispatch).  A callable is resolved
        per request, so a state sync that swaps the backend's trie
        (and its node store) is picked up by later requests."""
        self.db = db
        self.chain = chain
        self.atomic_node_db = atomic_node_db

    # ------------------------------------------------------------- dispatch
    def handle(self, raw: bytes) -> bytes:
        msg = decode_message(raw)
        if isinstance(msg, LeafsRequest):
            return self.on_leafs_request(msg).encode()
        if isinstance(msg, CodeRequest):
            return self.on_code_request(msg).encode()
        if isinstance(msg, BlockRequest):
            return self.on_block_request(msg).encode()
        raise ValueError(f"unexpected message {type(msg).__name__}")

    # -------------------------------------------------------------- leaves
    def on_leafs_request(self, req: LeafsRequest) -> LeafsResponse:
        from coreth_tpu.sync.messages import ATOMIC_TRIE_NODE
        limit = min(req.limit, MAX_LEAFS)
        node_db = self.db.node_db
        if req.node_type == ATOMIC_TRIE_NODE:
            if self.atomic_node_db is None:
                raise ValueError("atomic trie not served here")
            node_db = (self.atomic_node_db()
                       if callable(self.atomic_node_db)
                       else self.atomic_node_db)
        trie = Trie(root_hash=req.root, db=node_db)
        keys: List[bytes] = []
        vals: List[bytes] = []
        more = False
        for k, v in leaves(trie, start=req.start, limit=limit + 1):
            if len(keys) == limit:
                more = True
                break
            keys.append(k)
            vals.append(v)
        proof: List[bytes] = []
        if req.start or more:
            # edge proofs: the start bound and the last served key
            # (leafs_request.go:335 range proofs)
            proof = prove(trie, req.start if req.start
                          else (keys[0] if keys else b"\x00" * 32))
            if keys:
                proof = proof + prove(trie, keys[-1])
        return LeafsResponse(keys, vals, more, proof)

    # ---------------------------------------------------------------- code
    def on_code_request(self, req: CodeRequest) -> CodeResponse:
        return CodeResponse(
            [self.db.code_db.get(h, b"") for h in req.hashes])

    # -------------------------------------------------------------- blocks
    def on_block_request(self, req: BlockRequest) -> BlockResponse:
        out: List[bytes] = []
        if self.chain is None:
            return BlockResponse(out)
        h = req.block_hash
        for _ in range(req.parents):
            block = self.chain.get_block(h)
            if block is None:
                break
            out.append(block.encode())
            if block.number == 0:
                break
            h = block.parent_hash
        return BlockResponse(out)
