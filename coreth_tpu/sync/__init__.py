"""State sync: download a whole world state by verified leaf ranges.

Twin of reference sync/ (client/client.go, statesync/state_syncer.go,
handlers/leafs_request.go) + plugin/evm/message: a syncing node walks
the remote account trie in contiguous ranges, each response carrying
edge Merkle proofs verified locally (mpt/proof.verify_range_proof), and
recursively fetches storage tries (deduped by root) and contract code.
Progress markers make the whole process resumable after a crash.

The transport seam is a callable (request -> response); tests wire two
nodes' handlers together directly, the way the reference fakes its
message channel (syncervm_test.go:621).
"""

from coreth_tpu.sync.messages import (
    BlockRequest, BlockResponse, CodeRequest, CodeResponse, LeafsRequest,
    LeafsResponse,
)
from coreth_tpu.sync.handlers import SyncHandler
from coreth_tpu.sync.client import SyncClient
from coreth_tpu.sync.statesync import StateSyncer

__all__ = [
    "BlockRequest", "BlockResponse", "CodeRequest", "CodeResponse",
    "LeafsRequest", "LeafsResponse", "StateSyncer", "SyncClient",
    "SyncHandler",
]
