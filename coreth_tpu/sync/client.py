"""Client side of state sync: request + verify.

Twin of reference sync/client/client.go (GetLeafs :114,
parseLeafsResponse :132 — every leaf range is verified against the
requested root with edge Merkle proofs before acceptance; GetCode
verifies hashes).  The transport is any callable bytes -> bytes;
retries wrap transient transport failures (:293 get/retry loop).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu.mpt.proof import BadProofError, verify_range_proof
from coreth_tpu.sync.messages import (
    BlockRequest, BlockResponse, CodeRequest, CodeResponse, LeafsRequest,
    LeafsResponse, decode_message,
)

ZERO_KEY = b"\x00" * 32


class SyncClientError(Exception):
    pass


class SyncClient:
    def __init__(self, transport: Callable[[bytes], bytes],
                 retries: int = 3):
        self.transport = transport
        self.retries = retries

    def _call(self, payload: bytes) -> bytes:
        err: Optional[Exception] = None
        for _ in range(self.retries):
            try:
                return self.transport(payload)
            except Exception as e:  # noqa: BLE001 — transport fault
                err = e
        raise SyncClientError(f"transport failed: {err}")

    def get_leafs(self, root: bytes, start: bytes = ZERO_KEY,
                  limit: int = 1024, account: bytes = b"",
                  node_type: int = 0
                  ) -> Tuple[List[bytes], List[bytes], bool]:
        """One verified leaf page: (keys, vals, more).  Raises
        BadProofError when the response fails proof verification —
        an untrusted peer cannot make us accept a wrong range."""
        req = LeafsRequest(root=root, account=account, start=start,
                           limit=limit, node_type=node_type)
        resp = decode_message(self._call(req.encode()))
        if not isinstance(resp, LeafsResponse):
            raise SyncClientError("unexpected response type")
        proof = resp.proof if resp.proof else None
        if proof is None and (start != ZERO_KEY and start != b""):
            raise BadProofError("mid-trie response without proof")
        more = verify_range_proof(root, start if start else ZERO_KEY,
                                  resp.keys, resp.vals, proof)
        if more != resp.more:
            raise BadProofError("response 'more' flag contradicts proof")
        return resp.keys, resp.vals, resp.more

    def get_code(self, hashes: List[bytes]) -> List[bytes]:
        resp = decode_message(self._call(CodeRequest(hashes).encode()))
        if not isinstance(resp, CodeResponse):
            raise SyncClientError("unexpected response type")
        if len(resp.codes) != len(hashes):
            raise SyncClientError("code count mismatch")
        for h, c in zip(hashes, resp.codes):
            if keccak256(c) != h:
                raise SyncClientError(f"code hash mismatch {h.hex()}")
        return resp.codes

    def get_blocks(self, block_hash: bytes, height: int,
                   parents: int) -> List[bytes]:
        resp = decode_message(self._call(
            BlockRequest(block_hash, height, parents).encode()))
        if not isinstance(resp, BlockResponse):
            raise SyncClientError("unexpected response type")
        # hash-chain + body-integrity checks: the block id only covers
        # the header, so the tx root and ext-data hash must also be
        # recomputed from the body (client.go parseBlocks semantics)
        from coreth_tpu.mpt import StackTrie
        from coreth_tpu.types import Block, derive_sha
        from coreth_tpu.types.block import calc_ext_data_hash
        want = block_hash
        for raw in resp.blocks:
            try:
                b = Block.decode(raw)
            except Exception as e:  # noqa: BLE001 — malformed body
                raise SyncClientError(f"undecodable block: {e}") from None
            if b.hash() != want:
                raise SyncClientError("block hash mismatch")
            if derive_sha(b.transactions, StackTrie()) != b.header.tx_hash:
                raise SyncClientError("block tx root mismatch")
            if calc_ext_data_hash(b.ext_data()) != b.header.ext_data_hash:
                raise SyncClientError("block ext-data hash mismatch")
            want = b.parent_hash
        return resp.blocks
