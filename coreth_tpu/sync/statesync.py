"""The state syncer: download a whole world state, resumably.

Twin of reference sync/statesync/state_syncer.go (:37 stateSync, :199
Start) + trie_queue/trie_segments + code_syncer: walk the remote
account trie in verified ranges; every account leaf with a storage
root queues that trie (deduplicated — identical roots sync once,
statesync dedup semantics); code hashes fetch in batches; all leaves
land in a local Database whose recomputed roots must equal the synced
ones bit-for-bit.

Progress markers (rawdb accessors_state_sync.go role) record the next
range start per trie and which tries are done, so a crashed sync
resumes where it stopped instead of starting over.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from coreth_tpu.crypto import keccak256
from coreth_tpu.mpt import EMPTY_ROOT
from coreth_tpu.mpt.trie import Trie
from coreth_tpu.state import Database
from coreth_tpu.sync.client import SyncClient, ZERO_KEY
from coreth_tpu.types import StateAccount
from coreth_tpu.types.account import EMPTY_CODE_HASH, EMPTY_ROOT_HASH

CODE_BATCH = 64


class SyncError(Exception):
    pass


class StateSyncer:
    def __init__(self, client: SyncClient, db: Optional[Database] = None,
                 page: int = 1024, progress: Optional[dict] = None,
                 workers: int = 4, client_factory=None):
        """workers: storage tries download on a thread pool (the
        reference's per-segment leaf-syncer concurrency,
        sync/statesync/trie_segments.go + leaf_syncer.go).
        client_factory: () -> SyncClient giving each worker its own
        request stream (required for transports that are not
        thread-safe, e.g. one socket); with None, workers share
        `client` under a lock — latency still overlaps with local
        trie-building work."""
        import threading
        self.client = client
        self.db = db or Database()
        self.page = page
        self.workers = max(1, workers)
        self.client_factory = client_factory
        self._client_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        # progress markers: {"account_pos": key|b"done",
        #                    "storage": {root_hex: pos|b"done"},
        #                    "codes": set of fetched hex hashes}
        self.progress = progress if progress is not None else {}
        self.progress.setdefault("account_pos", ZERO_KEY)
        self.progress.setdefault("storage", {})
        self.progress.setdefault("codes", set())
        self.stats = {"account_leafs": 0, "storage_leafs": 0,
                      "storage_tries": 0, "codes": 0, "pages": 0}

    # ------------------------------------------------------------ sub-syncs
    def _get_leafs(self, client, root, pos):
        """One verified range request; a shared client serializes via
        the lock, per-worker clients go straight through."""
        if client is not self.client:
            return client.get_leafs(root, start=pos, limit=self.page)
        with self._client_lock:
            return client.get_leafs(root, start=pos, limit=self.page)

    def _sync_trie(self, root: bytes, pos_get, pos_set, client=None):
        """Pull one trie by verified ranges into a local Trie backed by
        the shared node store; returns (trie, leaf_count), committed."""
        client = client or self.client
        # the done-marker is only trusted when the root is actually
        # resident in THIS db — a progress dict paired with a fresh
        # Database (or a crash before commit) re-syncs instead of
        # wedging on a stale marker
        if pos_get() == b"done" and (root == EMPTY_ROOT
                                     or root in self.db.node_db):
            t = Trie(root_hash=root, db=self.db.node_db)
            return t, sum(1 for _ in t.items())
        t = Trie(db=self.db.node_db)
        # partial tries restart from the beginning: page-level resume
        # would need persisted partial nodes; trie-level completion is
        # what the progress markers guarantee
        pos = ZERO_KEY
        count = 0
        while True:
            keys, vals, more = self._get_leafs(client, root, pos)
            with self._stats_lock:
                self.stats["pages"] += 1
            for k, v in zip(keys, vals):
                t.update(k, v)
            count += len(keys)
            if not more:
                break
            pos = _next_key(keys[-1])
            pos_set(pos)
        if t.hash() != root:
            raise SyncError("synced trie root mismatch")
        t.commit()
        pos_set(b"done")
        return t, count

    # --------------------------------------------------------------- start
    def sync(self, state_root: bytes) -> Database:
        """Download the full state under `state_root` (Start :199)."""
        storage_progress: Dict[str, bytes] = self.progress["storage"]
        code_hashes: List[bytes] = []
        storage_roots: List[bytes] = []

        def account_pos_get():
            return self.progress["account_pos"]

        def account_pos_set(v):
            self.progress["account_pos"] = v

        account_trie, _ = self._sync_trie(
            state_root, account_pos_get, account_pos_set)

        # walk synced accounts for storage roots + code hashes
        seen_roots: Set[bytes] = set()
        seen_code: Set[bytes] = set()
        for _k, v in account_trie.items():
            acct = StateAccount.from_rlp(v)
            self.stats["account_leafs"] += 1
            if acct.root not in (EMPTY_ROOT_HASH, EMPTY_ROOT) \
                    and acct.root not in seen_roots:
                seen_roots.add(acct.root)
                storage_roots.append(acct.root)
            if acct.code_hash != EMPTY_CODE_HASH \
                    and acct.code_hash not in seen_code:
                seen_code.add(acct.code_hash)
                code_hashes.append(acct.code_hash)

        # storage tries are independent: download them on a worker
        # pool (trie_segments.go / leaf_syncer.go concurrency).  Each
        # worker gets its own client when a factory is supplied; the
        # node store is the shared Python dict (GIL-atomic writes,
        # disjoint tries commit disjoint node sets + shared subtrees
        # write identical bytes).
        def one(root, client):
            key = root.hex()

            def pos_get(key=key):
                return storage_progress.get(key, ZERO_KEY)

            def pos_set(v, key=key):
                storage_progress[key] = v

            _st, n = self._sync_trie(root, pos_get, pos_set,
                                     client=client)
            with self._stats_lock:
                self.stats["storage_tries"] += 1
                self.stats["storage_leafs"] += n

        nworkers = min(self.workers, max(1, len(storage_roots)))
        if nworkers <= 1 or len(storage_roots) <= 1:
            for root in storage_roots:
                one(root, self.client)
        else:
            from concurrent.futures import ThreadPoolExecutor
            clients = [self.client_factory() if self.client_factory
                       else self.client for _ in range(nworkers)]
            with ThreadPoolExecutor(max_workers=nworkers) as pool:
                futs = [pool.submit(one, root,
                                    clients[i % nworkers])
                        for i, root in enumerate(storage_roots)]
                for f in futs:
                    f.result()  # propagate SyncError

        todo = [h for h in code_hashes
                if h.hex() not in self.progress["codes"]]
        for i in range(0, len(todo), CODE_BATCH):
            batch = todo[i:i + CODE_BATCH]
            for h, code in zip(batch, self.client.get_code(batch)):
                self.db.write_code(h, code)
                self.progress["codes"].add(h.hex())
                self.stats["codes"] += 1
        return self.db


def _next_key(key: bytes) -> bytes:
    """Smallest key strictly greater than `key` (range continuation)."""
    b = bytearray(key)
    for i in range(len(b) - 1, -1, -1):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b)
        b[i] = 0
    return bytes(b) + b"\x01"
