"""C++-backed secure trie — the replay engine's commit-path backend.

The role of the reference's compiled trie machinery (trie/ + hasher.go
run as native Go): account/storage folds walk and rehash the MPT in
C++ (native/baseline.cc trie handle API) instead of Python — measured
~4.5x faster at bench scale, which is the difference between losing
and beating the compiled sequential baseline on the trie phase.

Backend selection (``backend()``): ``CORETH_TRIE=native`` demands the
C++ trie (raises if the library is unavailable), ``CORETH_TRIE=py``
forces the pure-Python ``mpt.trie`` path (with the measured
``mpt.rehash`` device batched-keccak policy); unset picks native when
the library loads.  ``CORETH_NATIVE_TRIE=0`` remains the legacy
kill-switch for the auto default.

Each contract's storage trie is its own native handle — a
per-contract session kept alive across commit windows — and the
window-batched fold-and-root calls (``fold_storage``,
``fold_accounts_root``) commit a whole deduped window in one ctypes
crossing per trie.  Interface mirrors the python SecureTrie surface
the engine uses (get/update/delete/hash) plus commit_into(node_db)
which exports the hashed nodes for interop with python tries/StateDBs.
Bit-identical roots are pinned against the python implementation by
tests, and ``CORETH_TRIE_CHECK=1`` (``CheckedSecureTrie``) keeps the
Python trie in the loop as a differential oracle that re-derives every
root.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu.crypto import native as _native
from coreth_tpu.mpt.iterator import nibbles_to_key


def available() -> bool:
    import os
    if os.environ.get("CORETH_NATIVE_TRIE", "1") == "0":
        return False
    return _native.load() is not None


def backend() -> str:
    """The selected trie backend: 'native' or 'py' (CORETH_TRIE)."""
    import os
    env = os.environ.get("CORETH_TRIE", "")
    if env in ("py", "python"):
        return "py"
    if env == "native":
        if _native.load() is None:
            raise RuntimeError(
                "CORETH_TRIE=native but the native library is "
                "unavailable (no toolchain and no prebuilt .so)")
        return "native"
    if env:
        raise ValueError(f"CORETH_TRIE={env!r}: expected 'native' or 'py'")
    return "native" if available() else "py"


def trie_check_armed() -> bool:
    """One parse for CORETH_TRIE_CHECK, shared by every consumer
    (engine commit path, flat exporter): unset, empty, or "0" is off;
    any other value arms the python-twin differential oracle."""
    import os
    return os.environ.get("CORETH_TRIE_CHECK", "").strip() not in ("", "0")


class TrieOracleError(AssertionError):
    """CORETH_TRIE_CHECK divergence: native and Python roots differ."""


class NativeSecureTrie:
    def __init__(self):
        self._lib = _native._require()
        self._ensure_decls(self._lib)
        self.h = self._lib.coreth_trie_new()

    @staticmethod
    def _ensure_decls(lib) -> None:
        if getattr(lib, "_trie_decls", False):
            return
        lib.coreth_trie_new.restype = ctypes.c_void_p
        lib.coreth_trie_new.argtypes = []
        lib.coreth_trie_free.argtypes = [ctypes.c_void_p]
        lib.coreth_trie_free.restype = None
        lib.coreth_trie_update_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64]
        lib.coreth_trie_update_batch.restype = None
        lib.coreth_trie_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32)]
        lib.coreth_trie_get.restype = ctypes.c_int
        lib.coreth_trie_hash.argtypes = [ctypes.c_void_p,
                                         ctypes.c_char_p]
        lib.coreth_trie_hash.restype = None
        lib.coreth_trie_export.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.coreth_trie_export.restype = ctypes.c_uint64
        lib.coreth_trie_fold_accounts.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.coreth_trie_fold_accounts.restype = None
        # window-commit ABI (PR 4); probe per symbol so an older
        # prebuilt .so degrades to the loop fallbacks below
        if hasattr(lib, "coreth_trie_fold_storage"):
            lib.coreth_trie_delete.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p]
            lib.coreth_trie_delete.restype = None
            lib.coreth_trie_fold_storage.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64, ctypes.c_char_p]
            lib.coreth_trie_fold_storage.restype = None
            lib.coreth_trie_fold_accounts_root.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64, ctypes.c_char_p]
            lib.coreth_trie_fold_accounts_root.restype = None
        # ordered (derive_sha) ABI (PR 13); same per-symbol probe
        if hasattr(lib, "coreth_trie_update_ordered"):
            lib.coreth_trie_update_ordered.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64]
            lib.coreth_trie_update_ordered.restype = None
        lib._trie_decls = True

    def __del__(self):
        try:
            if getattr(self, "h", None):
                self._lib.coreth_trie_free(self.h)
                self.h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # ----------------------------------------------------------- secure ops
    def get(self, key: bytes) -> Optional[bytes]:
        return self.get_hashed(keccak256(key))

    def update(self, key: bytes, value: bytes) -> None:
        self.update_hashed(keccak256(key), value)

    def delete(self, key: bytes) -> None:
        self.delete_hashed(keccak256(key))

    def delete_hashed(self, key32: bytes) -> None:
        if hasattr(self._lib, "coreth_trie_delete"):
            self._lib.coreth_trie_delete(self.h, key32)
        else:  # prebuilt-.so degradation: len-0 batch entry deletes
            self.update_hashed(key32, b"")

    # ----------------------------------------------------------- hashed ops
    def get_hashed(self, key32: bytes) -> Optional[bytes]:
        cap = 4096
        out = ctypes.create_string_buffer(cap)
        ln = ctypes.c_uint32()
        ok = self._lib.coreth_trie_get(self.h, key32, out, cap,
                                       ctypes.byref(ln))
        if not ok:
            return None
        if ln.value > cap:
            out = ctypes.create_string_buffer(ln.value)
            self._lib.coreth_trie_get(self.h, key32, out, ln.value,
                                      ctypes.byref(ln))
        return out.raw[:ln.value]

    def update_hashed(self, key32: bytes, value: bytes) -> None:
        lens = (ctypes.c_uint32 * 1)(len(value))
        self._lib.coreth_trie_update_batch(self.h, key32, value, lens, 1)

    def update_batch_hashed(self, keys32: bytes, blob: bytes,
                            lens) -> None:
        n = len(lens)
        arr = (ctypes.c_uint32 * n)(*lens)
        self._lib.coreth_trie_update_batch(self.h, keys32, blob, arr, n)

    # ----------------------------------------------------------------- hash
    def hash(self) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.coreth_trie_hash(self.h, out)
        return out.raw

    def commit_into(self, node_db: Dict[bytes, bytes]) -> bytes:
        """Export every hashed node into `node_db`; returns the root."""
        need = self._lib.coreth_trie_export(self.h, None, 0)
        if need:
            buf = ctypes.create_string_buffer(int(need))
            self._lib.coreth_trie_export(self.h, buf, need)
            raw = buf.raw
            off = 0
            while off < need:
                h = raw[off:off + 32]
                ln = int.from_bytes(raw[off + 32:off + 36], "little")
                node_db[h] = raw[off + 36:off + 36 + ln]
                off += 36 + ln
        return self.hash()

    def fold_accounts(self, keys32: bytes, balances32: bytes,
                      nonces, roots32: bytes, code_hashes32: bytes,
                      mc: bytes, deletes: bytes) -> None:
        """One-call per-block account fold with C++ RLP encoding
        (statedb updateTrie + IntermediateRoot hot loop)."""
        n = len(deletes)
        arr = (ctypes.c_uint64 * n)(*nonces)
        self._lib.coreth_trie_fold_accounts(
            self.h, keys32, balances32, arr, roots32, code_hashes32,
            mc, deletes, n)

    # --------------------------------------------- window fold-and-root
    def fold_storage(self, keys32: bytes, vals32: bytes,
                     n: int) -> bytes:
        """Fold a deduped window of storage writes (pre-hashed keys,
        raw 32-byte BE values, zero => delete) and return the new
        storage root — ONE ctypes crossing per contract per window."""
        if hasattr(self._lib, "coreth_trie_fold_storage"):
            out = ctypes.create_string_buffer(32)
            self._lib.coreth_trie_fold_storage(self.h, keys32, vals32,
                                               n, out)
            return out.raw
        # prebuilt-.so degradation: batched update (len 0 deletes)
        from coreth_tpu import rlp
        lens: List[int] = []
        blob = bytearray()
        for i in range(n):
            v = vals32[32 * i:32 * i + 32].lstrip(b"\x00")
            if not v:
                lens.append(0)
                continue
            enc = rlp.encode(v)
            lens.append(len(enc))
            blob += enc
        self.update_batch_hashed(keys32, bytes(blob), lens)
        return self.hash()

    def fold_accounts_root(self, keys32: bytes, balances32: bytes,
                           nonces, roots32: bytes,
                           code_hashes32: bytes, mc: bytes,
                           deletes: bytes) -> bytes:
        """Account fold + rehash in one crossing; returns the root."""
        n = len(deletes)
        if hasattr(self._lib, "coreth_trie_fold_accounts_root"):
            arr = (ctypes.c_uint64 * n)(*nonces)
            out = ctypes.create_string_buffer(32)
            self._lib.coreth_trie_fold_accounts_root(
                self.h, keys32, balances32, arr, roots32,
                code_hashes32, mc, deletes, n, out)
            return out.raw
        self.fold_accounts(keys32, balances32, nonces, roots32,
                           code_hashes32, mc, deletes)
        return self.hash()

    # ------------------------------------------------------------- seeding
    @classmethod
    def from_python_trie(cls, trie) -> "NativeSecureTrie":
        """Seed from a python Trie/SecureTrie (keys in the store are
        already keccak-hashed; items() yields their nibbles)."""
        out = cls()
        for nibs, value in trie.items():
            out.update_hashed(nibbles_to_key(nibs), value)
        return out


class NativeOrderedTrie:
    """derive_sha hasher over the C++ trie handle: the same streaming
    ``update``/``hash`` surface as ``mpt.StackTrie``, but updates
    buffer host-side and fold in ONE ctypes crossing at ``hash()`` —
    the variable-length rlp(index) keys of tx/receipt tries go through
    ``coreth_trie_update_ordered`` (the py stacktrie walk was ~15% of
    the erc20-machine replay wall; native fold is the difference per
    the commit-pipeline measurements).  Roots are self-checking at
    every call site: derive_sha results compare against the block
    header, so a divergence fails the replay loudly."""

    def __init__(self):
        self._lib = _native._require()
        NativeSecureTrie._ensure_decls(self._lib)
        self.h = self._lib.coreth_trie_new()
        self._keys: List[bytes] = []
        self._vals: List[bytes] = []

    def __del__(self):
        try:
            if getattr(self, "h", None):
                self._lib.coreth_trie_free(self.h)
                self.h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def update(self, key: bytes, value: bytes) -> None:
        if len(key) > 16:
            # the C++ ordered fold walks at most 16 key bytes (rlp(u64
            # index) caps at 9) — a longer key (e.g. a 32-byte hashed
            # account key) would be silently truncated into collisions
            raise ValueError(
                "NativeOrderedTrie keys cap at 16 bytes (rlp tx/receipt"
                f" index); got {len(key)} — use SecureTrie for hashed"
                " keys")
        self._keys.append(key)
        self._vals.append(value)

    def hash(self) -> bytes:
        n = len(self._keys)
        if n:
            kl = (ctypes.c_uint32 * n)(*map(len, self._keys))
            vl = (ctypes.c_uint32 * n)(*map(len, self._vals))
            self._lib.coreth_trie_update_ordered(
                self.h, b"".join(self._keys), kl,
                b"".join(self._vals), vl, n)
            self._keys.clear()
            self._vals.clear()
        out = ctypes.create_string_buffer(32)
        self._lib.coreth_trie_hash(self.h, out)
        return out.raw


def ordered_available() -> bool:
    """Whether the loaded library exports the ordered-insert ABI (a
    prebuilt .so from before PR 13 degrades to the py stacktrie)."""
    if not available():
        return False
    return hasattr(_native.load(), "coreth_trie_update_ordered")


def derive_hasher():
    """The derive_sha hasher for the selected backend: a fresh
    ``NativeOrderedTrie`` under ``CORETH_TRIE=native`` (or the auto
    default), ``mpt.StackTrie`` under ``py`` — callers on the replay
    hot path pick the backend with this instead of hard-coding the
    python stacktrie."""
    if backend() == "native" and ordered_available():
        return NativeOrderedTrie()
    from coreth_tpu.mpt.stacktrie import StackTrie
    return StackTrie()


class CheckedSecureTrie:
    """CORETH_TRIE_CHECK=1 differential oracle.

    Wraps a native trie and its Python ``SecureTrie`` twin: every
    mutation (including the window-batched folds) applies to BOTH, and
    every root derivation re-derives the root on the Python trie and
    raises ``TrieOracleError`` on the first divergence.  Debug/test
    mode — the twin costs the full Python fold this pipeline exists to
    avoid.
    """

    def __init__(self, py_trie):
        self.py = py_trie
        self.native = NativeSecureTrie.from_python_trie(py_trie)
        self._check(seed=True)

    # Trie.update on the twin writes by PRE-HASHED key (SecureTrie
    # would re-keccak); imported lazily to keep module import light.
    def _py_update_hashed(self, key32: bytes, value: bytes) -> None:
        from coreth_tpu.mpt.trie import Trie
        Trie.update(self.py, key32, value)

    def _check(self, seed: bool = False) -> bytes:
        n = self.native.hash()
        p = self.py.hash()
        if n != p:
            raise TrieOracleError(
                f"trie oracle divergence{' at seed' if seed else ''}: "
                f"native {n.hex()} != py {p.hex()}")
        return n

    # ------------------------------------------------------ secure ops
    def get(self, key: bytes) -> Optional[bytes]:
        return self.native.get(key)

    def update(self, key: bytes, value: bytes) -> None:
        self.native.update(key, value)
        self.py.update(key, value)

    def delete(self, key: bytes) -> None:
        self.native.delete(key)
        self.py.delete(key)

    def hash(self) -> bytes:
        return self._check()

    def commit_into(self, node_db: Dict[bytes, bytes]) -> bytes:
        root = self.native.commit_into(node_db)
        py_root = self.py.commit()
        if root != py_root:
            raise TrieOracleError(
                f"trie oracle divergence at commit: native "
                f"{root.hex()} != py {py_root.hex()}")
        return root

    # ----------------------------------------------- window fold-and-root
    def fold_storage(self, keys32: bytes, vals32: bytes,
                     n: int) -> bytes:
        from coreth_tpu import rlp
        root = self.native.fold_storage(keys32, vals32, n)
        for i in range(n):
            key32 = keys32[32 * i:32 * i + 32]
            v = vals32[32 * i:32 * i + 32].lstrip(b"\x00")
            self._py_update_hashed(key32, rlp.encode(v) if v else b"")
        py_root = self.py.hash()
        if root != py_root:
            raise TrieOracleError(
                f"storage fold divergence: native {root.hex()} != "
                f"py {py_root.hex()}")
        return root

    def fold_accounts(self, keys32: bytes, balances32: bytes, nonces,
                      roots32: bytes, code_hashes32: bytes, mc: bytes,
                      deletes: bytes) -> None:
        self.fold_accounts_root(keys32, balances32, nonces, roots32,
                                code_hashes32, mc, deletes)

    def fold_accounts_root(self, keys32: bytes, balances32: bytes,
                           nonces, roots32: bytes,
                           code_hashes32: bytes, mc: bytes,
                           deletes: bytes) -> bytes:
        from coreth_tpu.types.account import StateAccount
        root = self.native.fold_accounts_root(
            keys32, balances32, nonces, roots32, code_hashes32, mc,
            deletes)
        for i in range(len(deletes)):
            key32 = keys32[32 * i:32 * i + 32]
            if deletes[i]:
                self._py_update_hashed(key32, b"")
                continue
            self._py_update_hashed(key32, StateAccount(
                nonce=int(nonces[i]),
                balance=int.from_bytes(
                    balances32[32 * i:32 * i + 32], "big"),
                root=roots32[32 * i:32 * i + 32],
                code_hash=code_hashes32[32 * i:32 * i + 32],
                is_multi_coin=bool(mc[i])).rlp())
        py_root = self.py.hash()
        if root != py_root:
            raise TrieOracleError(
                f"account fold divergence: native {root.hex()} != "
                f"py {py_root.hex()}")
        return root
