"""C++-backed secure trie for the replay engine's hot fold.

The role of the reference's compiled trie machinery (trie/ + hasher.go
run as native Go): per-block account/storage folds walk and rehash the
MPT in C++ (native/baseline.cc trie handle API) instead of Python —
measured ~4.5x faster at bench scale, which is the difference between
losing and beating the compiled sequential baseline on the trie phase.

Interface mirrors the python SecureTrie surface the engine uses (get/
update/delete/hash) plus commit_into(node_db) which exports the hashed
nodes for interop with python tries/StateDBs.  Bit-identical roots are
pinned against the python implementation by tests.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

from coreth_tpu.crypto import keccak256
from coreth_tpu.crypto import native as _native
from coreth_tpu.mpt.iterator import nibbles_to_key


def available() -> bool:
    import os
    if os.environ.get("CORETH_NATIVE_TRIE", "1") == "0":
        return False
    return _native.load() is not None


class NativeSecureTrie:
    def __init__(self):
        self._lib = _native._require()
        self._ensure_decls(self._lib)
        self.h = self._lib.coreth_trie_new()

    @staticmethod
    def _ensure_decls(lib) -> None:
        if getattr(lib, "_trie_decls", False):
            return
        lib.coreth_trie_new.restype = ctypes.c_void_p
        lib.coreth_trie_new.argtypes = []
        lib.coreth_trie_free.argtypes = [ctypes.c_void_p]
        lib.coreth_trie_free.restype = None
        lib.coreth_trie_update_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64]
        lib.coreth_trie_update_batch.restype = None
        lib.coreth_trie_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32)]
        lib.coreth_trie_get.restype = ctypes.c_int
        lib.coreth_trie_hash.argtypes = [ctypes.c_void_p,
                                         ctypes.c_char_p]
        lib.coreth_trie_hash.restype = None
        lib.coreth_trie_export.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.coreth_trie_export.restype = ctypes.c_uint64
        lib.coreth_trie_fold_accounts.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.coreth_trie_fold_accounts.restype = None
        lib._trie_decls = True

    def __del__(self):
        try:
            if getattr(self, "h", None):
                self._lib.coreth_trie_free(self.h)
                self.h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # ----------------------------------------------------------- secure ops
    def get(self, key: bytes) -> Optional[bytes]:
        return self.get_hashed(keccak256(key))

    def update(self, key: bytes, value: bytes) -> None:
        self.update_hashed(keccak256(key), value)

    def delete(self, key: bytes) -> None:
        self.update_hashed(keccak256(key), b"")

    # ----------------------------------------------------------- hashed ops
    def get_hashed(self, key32: bytes) -> Optional[bytes]:
        cap = 4096
        out = ctypes.create_string_buffer(cap)
        ln = ctypes.c_uint32()
        ok = self._lib.coreth_trie_get(self.h, key32, out, cap,
                                       ctypes.byref(ln))
        if not ok:
            return None
        if ln.value > cap:
            out = ctypes.create_string_buffer(ln.value)
            self._lib.coreth_trie_get(self.h, key32, out, ln.value,
                                      ctypes.byref(ln))
        return out.raw[:ln.value]

    def update_hashed(self, key32: bytes, value: bytes) -> None:
        lens = (ctypes.c_uint32 * 1)(len(value))
        self._lib.coreth_trie_update_batch(self.h, key32, value, lens, 1)

    def update_batch_hashed(self, keys32: bytes, blob: bytes,
                            lens) -> None:
        n = len(lens)
        arr = (ctypes.c_uint32 * n)(*lens)
        self._lib.coreth_trie_update_batch(self.h, keys32, blob, arr, n)

    # ----------------------------------------------------------------- hash
    def hash(self) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.coreth_trie_hash(self.h, out)
        return out.raw

    def commit_into(self, node_db: Dict[bytes, bytes]) -> bytes:
        """Export every hashed node into `node_db`; returns the root."""
        need = self._lib.coreth_trie_export(self.h, None, 0)
        if need:
            buf = ctypes.create_string_buffer(int(need))
            self._lib.coreth_trie_export(self.h, buf, need)
            raw = buf.raw
            off = 0
            while off < need:
                h = raw[off:off + 32]
                ln = int.from_bytes(raw[off + 32:off + 36], "little")
                node_db[h] = raw[off + 36:off + 36 + ln]
                off += 36 + ln
        return self.hash()

    def fold_accounts(self, keys32: bytes, balances32: bytes,
                      nonces, roots32: bytes, code_hashes32: bytes,
                      mc: bytes, deletes: bytes) -> None:
        """One-call per-block account fold with C++ RLP encoding
        (statedb updateTrie + IntermediateRoot hot loop)."""
        n = len(deletes)
        arr = (ctypes.c_uint64 * n)(*nonces)
        self._lib.coreth_trie_fold_accounts(
            self.h, keys32, balances32, arr, roots32, code_hashes32,
            mc, deletes, n)

    # ------------------------------------------------------------- seeding
    @classmethod
    def from_python_trie(cls, trie) -> "NativeSecureTrie":
        """Seed from a python Trie/SecureTrie (keys in the store are
        already keccak-hashed; items() yields their nibbles)."""
        out = cls()
        for nibs, value in trie.items():
            out.update_hashed(nibbles_to_key(nibs), value)
        return out
