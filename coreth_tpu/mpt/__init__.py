"""Merkle-Patricia trie.

Twin of reference ``trie/`` (trie.go insert/delete/hash/commit,
secure_trie.go keccak-keyed access, stacktrie.go ordered builder) with a
TPU-friendly split: structural edits happen on host, hashing is batched —
:mod:`coreth_tpu.mpt.rehash` collects dirty nodes level-by-level and
hashes whole frontiers with the batched keccak kernel.
"""

from coreth_tpu.mpt.trie import Trie, SecureTrie, EMPTY_ROOT  # noqa: F401
from coreth_tpu.mpt.stacktrie import StackTrie  # noqa: F401
