"""Merkle proofs: single-key proofs and range proofs.

Twin of reference trie/proof.go (Prove :36, VerifyProof :100,
VerifyRangeProof :383).  Range proofs are the state-sync workhorse:
given a root, a contiguous run of (key, value) leaves, and edge proofs
for the boundaries, the verifier rebuilds a skeleton trie from the
proofs, *removes every node inside the claimed range* (so omissions
cannot hide behind hash references), re-inserts the supplied pairs,
and accepts iff the recomputed root matches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu.mpt import EMPTY_ROOT
from coreth_tpu.mpt.trie import (
    BRANCH, EXT, HASHREF, LEAF, MissingNodeError, Trie, _MEMO,
    key_to_nibbles,
)


class BadProofError(Exception):
    pass


# ------------------------------------------------------------------ prove

def prove(trie: Trie, key: bytes) -> List[bytes]:
    """Collect the RLP encodings of every hashed node on the path from
    the root towards `key` (trie/proof.go:36 Prove).  Inline (<32 byte)
    nodes ride embedded in their parents; the root is always included.
    Works for absent keys too (the path to the divergence point)."""
    nibbles = key_to_nibbles(key)
    proof: List[bytes] = []
    node = trie.root
    first = True
    while node is not None:
        node = trie._resolve(node)
        if node is None:
            break
        encoded, _ref = trie._encode_node(node, None)
        if first or len(encoded) >= 32:
            proof.append(encoded)
        first = False
        kind = node[0]
        if kind == LEAF:
            break
        if kind == EXT:
            if nibbles[:len(node[1])] != node[1]:
                break
            nibbles = nibbles[len(node[1]):]
            node = node[2]
            continue
        if not nibbles:
            break
        nxt = node[1][nibbles[0]]
        nibbles = nibbles[1:]
        node = nxt
    return proof


def _proof_db(proof: List[bytes]) -> Dict[bytes, bytes]:
    return {keccak256(p): p for p in proof}


def verify_proof(root: bytes, key: bytes,
                 proof: List[bytes]) -> Optional[bytes]:
    """Value of `key` under `root` given its proof, or None when the
    proof shows absence; raises BadProofError on a broken proof
    (trie/proof.go:100 VerifyProof)."""
    if root == EMPTY_ROOT:
        if proof:
            raise BadProofError("proof for the empty trie")
        return None
    db = _proof_db(proof)
    if root not in db:
        raise BadProofError("proof does not include the root node")
    t = Trie(root_hash=root, db=db)
    try:
        return t.get(key)
    except MissingNodeError as e:
        raise BadProofError(f"incomplete proof: missing {e}") from None


# ------------------------------------------------------------ range proof

def _cmp(a: bytes, b: bytes) -> int:
    return (a > b) - (a < b)


def _invalidate(node):
    node[_MEMO] = None
    return node


def _unset_ge(trie: Trie, node, l: bytes):
    """Remove every key >= l from the subtree (left-edge cleanup)."""
    node = trie._resolve(node)
    if node is None:
        return None
    kind = node[0]
    if kind == BRANCH:
        if not l:
            return None  # every key here is >= the exhausted bound
        for i in range(l[0] + 1, 16):
            node[1][i] = None
        node[1][l[0]] = _unset_ge(trie, node[1][l[0]], l[1:])
        return _invalidate(node)
    p = node[1]
    if kind == EXT:
        if p == l[:len(p)]:
            node[2] = _unset_ge(trie, node[2], l[len(p):])
            if node[2] is None:
                return None
            return _invalidate(node)
        return None if p > l[:len(p)] else node
    # leaf
    return None if _cmp(p, l) >= 0 else node


def _unset_le(trie: Trie, node, r: bytes):
    """Remove every key <= r from the subtree (right-edge cleanup)."""
    node = trie._resolve(node)
    if node is None:
        return None
    kind = node[0]
    if kind == BRANCH:
        if not r:
            # only the (unused in secure tries) branch value can be <= r
            node[2] = b""
            return _invalidate(node)
        for i in range(0, r[0]):
            node[1][i] = None
        node[1][r[0]] = _unset_le(trie, node[1][r[0]], r[1:])
        return _invalidate(node)
    p = node[1]
    if kind == EXT:
        if p == r[:len(p)]:
            node[2] = _unset_le(trie, node[2], r[len(p):])
            if node[2] is None:
                return None
            return _invalidate(node)
        return None if p < r[:len(p)] else node
    # leaf
    return None if _cmp(p, r) <= 0 else node


def _unset_range(trie: Trie, node, l: bytes, r: bytes):
    """Remove every key in the closed range [l, r] (l < r) from the
    skeleton, so only the supplied pairs can reconstitute it."""
    node = trie._resolve(node)
    if node is None:
        return None
    kind = node[0]
    if kind == BRANCH:
        if not l or not r:
            raise BadProofError("boundary key shorter than trie depth")
        li, ri = l[0], r[0]
        if li == ri:
            node[1][li] = _unset_range(trie, node[1][li], l[1:], r[1:])
            return _invalidate(node)
        for i in range(li + 1, ri):
            node[1][i] = None
        node[1][li] = _unset_ge(trie, node[1][li], l[1:])
        node[1][ri] = _unset_le(trie, node[1][ri], r[1:])
        return _invalidate(node)
    p = node[1]
    lp, rp = l[:len(p)], r[:len(p)]
    if kind == EXT:
        if p == lp and p == rp:
            node[2] = _unset_range(trie, node[2], l[len(p):], r[len(p):])
            if node[2] is None:
                return None
            return _invalidate(node)
        if p == lp:            # subtree max < r: only left bound binds
            node[2] = _unset_ge(trie, node[2], l[len(p):])
            if node[2] is None:
                return None
            return _invalidate(node)
        if p == rp:            # subtree min > l: only right bound binds
            node[2] = _unset_le(trie, node[2], r[len(p):])
            if node[2] is None:
                return None
            return _invalidate(node)
        return None if lp < p < rp else node
    # leaf: inside the closed range -> removed (pairs re-add it)
    return None if _cmp(p, l) >= 0 and _cmp(p, r) <= 0 else node


def _has_right_element(trie: Trie, nibbles: bytes) -> bool:
    """Any key strictly greater than `nibbles` under the skeleton?
    (proof.go hasRightElement)"""
    node = trie.root
    while node is not None:
        node = trie._resolve(node)
        if node is None:
            return False
        kind = node[0]
        if kind == LEAF:
            return _cmp(node[1], nibbles) > 0
        if kind == EXT:
            p = node[1]
            if p == nibbles[:len(p)]:
                nibbles = nibbles[len(p):]
                node = node[2]
                continue
            return p > nibbles[:len(p)]
        if not nibbles:
            return any(c is not None for c in node[1])
        for i in range(nibbles[0] + 1, 16):
            if node[1][i] is not None:
                return True
        node = node[1][nibbles[0]]
        nibbles = nibbles[1:]
    return False


def verify_range_proof(root: bytes, first_key: bytes, keys: List[bytes],
                       values: List[bytes],
                       proof: Optional[List[bytes]]) -> bool:
    """VerifyRangeProof (trie/proof.go:383).

    keys must be monotonically increasing raw trie keys (already
    keccak-hashed for secure tries), all >= first_key.  Returns True
    when more elements exist to the right of the range; raises
    BadProofError when the proof does not check out.

    proof=None asserts the pairs are the WHOLE trie.
    """
    if len(keys) != len(values):
        raise BadProofError("key/value count mismatch")
    for i in range(1, len(keys)):
        if keys[i - 1] >= keys[i]:
            raise BadProofError("keys out of order")
    if keys and keys[0] < first_key:
        raise BadProofError("range starts before first_key")

    if proof is None:
        # no-proof mode: the pairs claim to be the entire trie
        t = Trie()
        for k, v in zip(keys, values):
            t.update(k, v)
        if t.hash() != root:
            raise BadProofError("full-range root mismatch")
        return False

    db = _proof_db(proof)
    if root not in db:
        raise BadProofError("proof does not include the root node")
    t = Trie(root_hash=root, db=db)
    first_nibs = key_to_nibbles(first_key)

    try:
        if not keys:
            # absence proof: firstKey resolves to nothing and nothing
            # exists to its right
            if t.get(first_key) is not None:
                raise BadProofError("empty range but first_key exists")
            if _has_right_element(t, first_nibs):
                raise BadProofError(
                    "empty range but elements exist past first_key")
            return False
        last_nibs = key_to_nibbles(keys[-1])
        more = _has_right_element(t, last_nibs)
        t.root = _unset_range(t, t.root, first_nibs, last_nibs)
        for k, v in zip(keys, values):
            t.update(k, v)
        if t.hash() != root:
            raise BadProofError("range root mismatch")
        return more
    except MissingNodeError as e:
        raise BadProofError(f"incomplete proof: missing {e}") from None
