"""Level-synchronous batched trie rehash on device.

The reference parallelizes trie hashing with fork-join goroutines per
fullNode (trie/hasher.go:57 newHasher(parallel)).  The TPU-native design
replaces recursion with level batches: collect every dirty (unmemoized)
node, process depths bottom-up, RLP-encode each level on host (cheap —
child refs are ready), and hash the whole level in ONE batched
keccak-f[1600] device call (coreth_tpu.ops.keccak).  Memos are filled in
place, so the host ``Trie.hash()``/``commit()`` afterwards is O(1).

Below ``min_batch`` dirty nodes the host (native C++) keccak wins on
dispatch latency and is used instead — callers can always call this; it
degrades gracefully.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from coreth_tpu import rlp
from coreth_tpu.crypto import keccak256
from coreth_tpu.mpt.trie import (
    BRANCH, EXT, HASHREF, LEAF, _MEMO, EMPTY_ROOT, Trie, hex_prefix,
)

_device_hasher = None


def _get_device_hasher():
    global _device_hasher
    if _device_hasher is None:
        from coreth_tpu.ops import keccak as K

        def hasher(msgs: List[bytes]) -> List[bytes]:
            blocks, nblocks = K.pack_blocks(msgs)
            words = K.keccak256_blocks(blocks, nblocks)
            return K.digest_words_to_bytes(np.asarray(words))[:len(msgs)]
        _device_hasher = hasher
    return _device_hasher


def collect_dirty(trie: Trie):
    """(node, depth) for every resident node lacking a memo, via
    iterative DFS.  Children of memoized nodes are skipped — their
    hashes are already final."""
    out = []
    if trie.root is None or trie.root[0] == HASHREF:
        return out
    stack = [(trie.root, 0)]
    while stack:
        node, depth = stack.pop()
        if node is None or node[0] == HASHREF:
            continue
        if node[_MEMO] is not None:
            continue
        out.append((node, depth))
        kind = node[0]
        if kind == EXT:
            stack.append((node[2], depth + 1))
        elif kind == BRANCH:
            for c in node[1]:
                stack.append((c, depth + 1))
    return out


def _child_ref(node):
    """Parent-embedded reference of an already-processed child."""
    if node[0] == HASHREF:
        return node[1]
    encoded, ref = node[_MEMO]
    return ref


def _encode(node) -> bytes:
    kind = node[0]
    if kind == LEAF:
        return rlp.encode([hex_prefix(node[1], True), node[2]])
    if kind == EXT:
        return rlp.encode([hex_prefix(node[1], False), _child_ref(node[2])])
    items = [_child_ref(c) if c is not None else b"" for c in node[1]]
    items.append(node[2])
    return rlp.encode(items)


# Default threshold — measured, not guessed (tools/rehash_crossover.py
# on the tunneled v5e chip, 2026-07-30):
#
#    dirty    host_s  device_s
#      256    0.0031    0.5475
#     1024    0.0163    0.5677
#     4096    0.0672    0.7982
#    16384    0.5273    1.5778
#    65536    2.1271    4.6740
#   262144    9.8625   17.2645
#
# The host C++ keccak path wins at EVERY measured size on this
# transport (per-level serialization + tunnel transfers dominate the
# device path), so the default effectively disables device rehash;
# locally-attached chips should re-measure and set
# CORETH_REHASH_MIN_BATCH accordingly.
import os as _os
DEFAULT_MIN_BATCH = int(_os.environ.get("CORETH_REHASH_MIN_BATCH",
                                        "1000000"))


def device_rehash(trie: Trie, min_batch: int = DEFAULT_MIN_BATCH,
                  hasher=None) -> bytes:
    """Fill memos for all dirty nodes using batched device keccak,
    then return the root hash.

    Bit-identical to ``trie.hash()`` — asserted by tests — but the hash
    work runs as one device call per trie level.
    """
    dirty = collect_dirty(trie)
    if not dirty:
        return trie.hash()
    if len(dirty) < min_batch:
        return trie.hash()  # host native keccak path
    hasher = hasher or _get_device_hasher()
    max_depth = max(d for _, d in dirty)
    by_depth: List[List] = [[] for _ in range(max_depth + 1)]
    for node, d in dirty:
        by_depth[d].append(node)
    for depth in range(max_depth, -1, -1):
        level = by_depth[depth]
        if not level:
            continue
        encodings = [_encode(n) for n in level]
        # small encodings inline (no hash); big ones batch to device
        to_hash = [(i, e) for i, e in enumerate(encodings) if len(e) >= 32]
        if len(to_hash) >= min_batch:
            digests = hasher([e for _, e in to_hash])
        else:
            digests = [keccak256(e) for _, e in to_hash]
        hash_map = {i: dg for (i, _), dg in zip(to_hash, digests)}
        for i, (node, encoded) in enumerate(zip(level, encodings)):
            if i in hash_map:
                node[_MEMO] = (encoded, hash_map[i])
            else:
                node[_MEMO] = (encoded, rlp.decode(encoded))
    return trie.hash()
