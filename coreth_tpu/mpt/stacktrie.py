"""StackTrie — streaming ordered-insert trie builder (hash-and-drop).

Behavioral twin of reference trie/stacktrie.go (544 LoC): keys MUST
arrive in strictly increasing nibble order; whenever an insert diverges
left of the in-progress path, the completed left sibling subtree is
immediately collapsed to its 32-byte reference and dropped.  Memory
stays O(depth) and every node is RLP-encoded and hashed exactly once —
unlike the general mpt.Trie, which keeps the whole structure resident.

Used for tx/receipt roots (types/hashing.py derive_sha, reference
core/types/hashing.go:97) and for state-sync range rebuilds (reference
sync/statesync rebuilding leaf ranges through a StackTrie).

Node model (mutable lists):
  ["L", nibbles, value]     in-progress leaf
  ["E", nibbles, child]     in-progress extension
  ["B", [child x 16]]       in-progress branch (no branch values: all
                            caller key sets are prefix-free — RLP index
                            keys and fixed-width hashed keys)
  ["H", ref]                collapsed subtree: 32-byte hash, or the
                            decoded RLP structure when len(rlp) < 32
"""

from __future__ import annotations

from coreth_tpu import rlp
from coreth_tpu.crypto import keccak256
from coreth_tpu.mpt.trie import (
    EMPTY_ROOT, _common_prefix_len, hex_prefix, key_to_nibbles,
)


class StackTrie:
    __slots__ = ("_root",)

    def __init__(self):
        self._root = None

    def reset(self) -> None:
        self._root = None

    # ------------------------------------------------------------- insert
    def update(self, key: bytes, value: bytes) -> None:
        """Insert; keys must arrive in strictly increasing order and be
        prefix-free (no key may be a prefix of another) — both hold for
        the RLP-encoded-index keys derive_sha feeds it."""
        if not value:
            raise ValueError("stacktrie does not support empty values")
        self._root = self._insert(self._root, key_to_nibbles(key), value)

    def _insert(self, n, key, value):
        if n is None:
            return ["L", key, value]
        if not key:
            raise ValueError(
                "key is a prefix of an existing key (prefix-free input "
                "required)")
        kind = n[0]
        if kind == "H":
            raise ValueError("key out of order: subtree already hashed")
        if kind == "B":
            idx = key[0]
            last = max(i for i in range(16) if n[1][i] is not None)
            if idx == last:
                n[1][idx] = self._insert(n[1][idx], key[1:], value)
            elif idx > last:
                n[1][last] = ["H", self._collapse(n[1][last])]
                n[1][idx] = ["L", key[1:], value]
            else:
                raise ValueError("key out of order")
            return n
        if kind == "E":
            cp = _common_prefix_len(n[1], key)
            if cp == len(n[1]):
                n[2] = self._insert(n[2], key[cp:], value)
                return n
            return self._split(n[1], key, cp, value, ext_child=n[2])
        # LEAF
        cp = _common_prefix_len(n[1], key)
        if cp == len(n[1]) and cp == len(key):
            raise ValueError("duplicate key")
        return self._split(n[1], key, cp, value, leaf_value=n[2])

    def _split(self, old_nibs, key, cp, value, ext_child=None,
               leaf_value=None):
        """Divergence at depth cp: collapse the completed old subtree
        into a branch slot, start a new leaf to its right."""
        if cp >= len(old_nibs) or cp >= len(key):
            raise ValueError(
                "key is a prefix of an existing key (prefix-free input "
                "required)")
        old_idx = old_nibs[cp]
        new_idx = key[cp]
        if new_idx <= old_idx:
            raise ValueError("key out of order")
        if ext_child is not None:
            old_sub = (ext_child if cp == len(old_nibs) - 1
                       else ["E", old_nibs[cp + 1:], ext_child])
        else:
            old_sub = ["L", old_nibs[cp + 1:], leaf_value]
        children = [None] * 16
        children[old_idx] = ["H", self._collapse(old_sub)]
        children[new_idx] = ["L", key[cp + 1:], value]
        branch = ["B", children]
        if cp > 0:
            return ["E", key[:cp], branch]
        return branch

    # --------------------------------------------------------------- hash
    def _encode(self, n) -> bytes:
        kind = n[0]
        if kind == "L":
            return rlp.encode([hex_prefix(n[1], True), n[2]])
        if kind == "E":
            return rlp.encode([hex_prefix(n[1], False),
                               self._collapse(n[2])])
        items = [self._collapse(c) if c is not None else b""
                 for c in n[1]]
        items.append(b"")
        return rlp.encode(items)

    def _collapse(self, n):
        """Parent-embedded reference: hash if the encoding is >= 32
        bytes, else the decoded structure inline."""
        if n[0] == "H":
            return n[1]
        enc = self._encode(n)
        return keccak256(enc) if len(enc) >= 32 else rlp.decode(enc)

    def hash(self) -> bytes:
        if self._root is None:
            return EMPTY_ROOT
        return keccak256(self._encode(self._root))
