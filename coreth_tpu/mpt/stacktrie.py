"""StackTrie — ordered-insert trie builder.

Role twin of reference trie/stacktrie.go (used for tx/receipt roots via
DeriveSha, core/types/hashing.go:97, and for state-sync range rebuilds).
This implementation reuses the structural engine from :mod:`mpt.trie`; the
streaming early-hash optimization (hash-and-drop finished subtries) is a
follow-up — correctness and the API contract come first.
"""

from __future__ import annotations

from coreth_tpu.mpt.trie import Trie


class StackTrie:
    def __init__(self):
        self._trie = Trie()

    def update(self, key: bytes, value: bytes) -> None:
        self._trie.update(key, value)

    def hash(self) -> bytes:
        return self._trie.hash()

    def reset(self) -> None:
        self._trie = Trie()
