"""Merkle-Patricia trie — host structural engine.

Semantics per the Ethereum yellow-paper trie spec (reference trie/trie.go:
insert :308, delete :413, Hash :573; hasher.go:69 collapse rules):

- leaf:      [hex-prefix(nibbles, t=1), value]
- extension: [hex-prefix(nibbles, t=0), child-ref]
- branch:    [c0..c15, value]
- a node's reference inside its parent is its RLP if len(rlp) < 32,
  else keccak256(rlp); the root hash is always keccak256(rlp(root)).

The in-memory representation is plain Python lists (mutable, cheap to
edit); hashing walks bottom-up and can hand whole levels to the batched
device keccak (mpt/rehash.py).  ``SecureTrie`` applies keccak to keys
(reference trie/secure_trie.go).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from coreth_tpu import rlp
from coreth_tpu.crypto import keccak256

EMPTY_ROOT = keccak256(rlp.encode(b""))

# Node model (mutable lists so edits are in place):
#   ["L", nibbles(bytes), value(bytes)]              leaf
#   ["E", nibbles(bytes), child]                     extension
#   ["B", [child x 16], value(bytes)]                branch
#   ["H", digest(bytes32)]                           hash reference (db-backed)
#   None                                             empty

LEAF, EXT, BRANCH, HASHREF = "L", "E", "B", "H"


def hex_prefix(nibbles: bytes, is_leaf: bool) -> bytes:
    """Hex-prefix encoding (yellow paper appendix C)."""
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2:
        out = bytearray([(flag + 1) << 4 | nibbles[0]])
        rest = nibbles[1:]
    else:
        out = bytearray([flag << 4])
        rest = nibbles
    for i in range(0, len(rest), 2):
        out.append(rest[i] << 4 | rest[i + 1])
    return bytes(out)


def decode_hex_prefix(data: bytes) -> Tuple[bytes, bool]:
    flag = data[0] >> 4
    is_leaf = flag >= 2
    nibbles = bytearray()
    if flag & 1:
        nibbles.append(data[0] & 0x0F)
    for b in data[1:]:
        nibbles.append(b >> 4)
        nibbles.append(b & 0x0F)
    return bytes(nibbles), is_leaf


def key_to_nibbles(key: bytes) -> bytes:
    out = bytearray()
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return bytes(out)


def _common_prefix_len(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class MissingNodeError(Exception):
    """A hash reference was dereferenced but absent from the node store."""


class Trie:
    """In-memory MPT over an optional {hash: node-rlp} backing store."""

    def __init__(self, root_hash: bytes = EMPTY_ROOT,
                 db: Optional[Dict[bytes, bytes]] = None):
        self.db = db if db is not None else {}
        if root_hash == EMPTY_ROOT:
            self.root = None
        else:
            self.root = [HASHREF, root_hash]
        self._hash_cache: Optional[bytes] = None

    # ------------------------------------------------------------------ get
    def get(self, key: bytes) -> Optional[bytes]:
        return self._get(self.root, key_to_nibbles(key))

    def _resolve(self, node):
        if node is not None and node[0] == HASHREF:
            data = self.db.get(node[1])
            if data is None:
                raise MissingNodeError(node[1].hex())
            return self._decode_node(rlp.decode(data))
        return node

    def _decode_node(self, items):
        """RLP structure -> node model.  Child byte-strings of 32 bytes are
        hash refs; nested lists are inlined nodes."""
        if isinstance(items, list) and len(items) == 2:
            nibbles, is_leaf = decode_hex_prefix(items[0])
            if is_leaf:
                return [LEAF, nibbles, items[1]]
            return [EXT, nibbles, self._decode_ref(items[1])]
        if isinstance(items, list) and len(items) == 17:
            children = [self._decode_ref(c) if c else None
                        for c in items[:16]]
            return [BRANCH, children, items[16]]
        raise ValueError("malformed trie node")

    def _decode_ref(self, item):
        if isinstance(item, list):
            return self._decode_node(item)
        if item == b"":
            return None
        if len(item) == 32:
            return [HASHREF, item]
        raise ValueError("malformed node reference")

    def _get(self, node, nibbles: bytes) -> Optional[bytes]:
        while True:
            if node is None:
                return None
            node = self._resolve(node)
            if node is None:
                return None
            kind = node[0]
            if kind == LEAF:
                return node[2] if node[1] == nibbles else None
            if kind == EXT:
                if nibbles[:len(node[1])] != node[1]:
                    return None
                nibbles = nibbles[len(node[1]):]
                node = node[2]
                continue
            # branch
            if not nibbles:
                return node[2] or None
            nxt = node[1][nibbles[0]]
            nibbles = nibbles[1:]
            node = nxt

    # --------------------------------------------------------------- update
    def update(self, key: bytes, value: bytes) -> None:
        self._hash_cache = None
        nibbles = key_to_nibbles(key)
        if value:
            self.root = self._insert(self.root, nibbles, value)
        else:
            self.root = self._delete(self.root, nibbles)

    def delete(self, key: bytes) -> None:
        self.update(key, b"")

    def _insert(self, node, nibbles: bytes, value: bytes):
        if node is None:
            return [LEAF, nibbles, value]
        node = self._resolve(node)
        if node is None:
            return [LEAF, nibbles, value]
        kind = node[0]
        if kind == LEAF:
            existing = node[1]
            if existing == nibbles:
                node[2] = value
                return node
            cp = _common_prefix_len(existing, nibbles)
            branch = [BRANCH, [None] * 16, b""]
            # split both under a fresh branch at the divergence point
            for nb, val in ((existing, node[2]), (nibbles, value)):
                rest = nb[cp:]
                if not rest:
                    branch[2] = val
                else:
                    branch[1][rest[0]] = [LEAF, rest[1:], val]
            if cp:
                return [EXT, nibbles[:cp], branch]
            return branch
        if kind == EXT:
            prefix = node[1]
            cp = _common_prefix_len(prefix, nibbles)
            if cp == len(prefix):
                node[2] = self._insert(node[2], nibbles[cp:], value)
                return node
            # split the extension
            branch = [BRANCH, [None] * 16, b""]
            # remainder of the old extension path
            old_rest = prefix[cp:]
            child = node[2] if len(old_rest) == 1 else [EXT, old_rest[1:], node[2]]
            branch[1][old_rest[0]] = child
            new_rest = nibbles[cp:]
            if not new_rest:
                branch[2] = value
            else:
                branch[1][new_rest[0]] = [LEAF, new_rest[1:], value]
            if cp:
                return [EXT, nibbles[:cp], branch]
            return branch
        # branch
        if not nibbles:
            node[2] = value
            return node
        idx = nibbles[0]
        node[1][idx] = self._insert(node[1][idx], nibbles[1:], value)
        return node

    # --------------------------------------------------------------- delete
    def _delete(self, node, nibbles: bytes):
        if node is None:
            return None
        node = self._resolve(node)
        if node is None:
            return None
        kind = node[0]
        if kind == LEAF:
            return None if node[1] == nibbles else node
        if kind == EXT:
            prefix = node[1]
            if nibbles[:len(prefix)] != prefix:
                return node
            child = self._delete(node[2], nibbles[len(prefix):])
            if child is None:
                return None
            child = self._resolve(child)
            # merge chains: ext+ext, ext+leaf
            if child[0] == EXT:
                return [EXT, prefix + child[1], child[2]]
            if child[0] == LEAF:
                return [LEAF, prefix + child[1], child[2]]
            node[2] = child
            return node
        # branch
        if not nibbles:
            if not node[2]:
                return node
            node[2] = b""
        else:
            idx = nibbles[0]
            node[1][idx] = self._delete(node[1][idx], nibbles[1:])
        # collapse if <= 1 child remains
        live = [(i, c) for i, c in enumerate(node[1]) if c is not None]
        if node[2]:
            if live:
                return node
            return [LEAF, b"", node[2]]
        if len(live) > 1:
            return node
        if not live:
            return None
        idx, child = live[0]
        child = self._resolve(child)
        if child[0] == LEAF:
            return [LEAF, bytes([idx]) + child[1], child[2]]
        if child[0] == EXT:
            return [EXT, bytes([idx]) + child[1], child[2]]
        return [EXT, bytes([idx]), child]

    # ----------------------------------------------------------------- hash
    def _encode_node(self, node, acc: Optional[List[Tuple[bytes, bytes]]]):
        """Node -> RLP bytes; children collapsed to refs.

        acc, when given, collects (hash, rlp) for every node that hashes
        (the commit set).
        """
        kind = node[0]
        if kind == LEAF:
            return rlp.encode([hex_prefix(node[1], True), node[2]])
        if kind == EXT:
            return rlp.encode([hex_prefix(node[1], False),
                               self._ref(node[2], acc)])
        if kind == BRANCH:
            items = [self._ref(c, acc) if c is not None else b""
                     for c in node[1]]
            items.append(node[2])
            return rlp.encode(items)
        raise AssertionError("unreachable")

    def _ref(self, node, acc):
        if node[0] == HASHREF:
            return node[1]
        encoded = self._encode_node(node, acc)
        if len(encoded) < 32:
            # inlined: strip the outer list encoding by decoding again —
            # parent embeds the structure, not a byte string
            return rlp.decode(encoded)
        h = keccak256(encoded)
        if acc is not None:
            acc.append((h, encoded))
        return h

    def hash(self) -> bytes:
        """Root hash (reference trie.go:573 Hash)."""
        if self._hash_cache is not None:
            return self._hash_cache
        if self.root is None:
            self._hash_cache = EMPTY_ROOT
            return EMPTY_ROOT
        if self.root[0] == HASHREF:
            return self.root[1]
        encoded = self._encode_node(self.root, None)
        self._hash_cache = keccak256(encoded)
        return self._hash_cache

    def commit(self) -> bytes:
        """Hash and persist all nodes into the backing store.

        Returns the root hash (reference trie.go:585 Commit +
        committer.go).  The in-memory tree stays resident (it is the
        clean cache); callers that want a pure hash use :meth:`hash`.
        """
        if self.root is None:
            return EMPTY_ROOT
        if self.root[0] == HASHREF:
            return self.root[1]
        acc: List[Tuple[bytes, bytes]] = []
        encoded = self._encode_node(self.root, acc)
        root_hash = keccak256(encoded)
        self.db[root_hash] = encoded
        for h, data in acc:
            self.db[h] = data
        self._hash_cache = root_hash
        return root_hash

    def copy(self) -> "Trie":
        t = Trie(db=self.db)
        t.root = _deep_copy(self.root)
        t._hash_cache = self._hash_cache
        return t

    # ------------------------------------------------------------- iterate
    def items(self):
        """Yield (key_nibbles, value) in lexicographic key order."""
        yield from self._iter(self.root, b"")

    def _iter(self, node, prefix: bytes):
        if node is None:
            return
        node = self._resolve(node)
        if node is None:
            return
        kind = node[0]
        if kind == LEAF:
            yield prefix + node[1], node[2]
        elif kind == EXT:
            yield from self._iter(node[2], prefix + node[1])
        else:
            if node[2]:
                yield prefix, node[2]
            for i, c in enumerate(node[1]):
                if c is not None:
                    yield from self._iter(c, prefix + bytes([i]))


def _deep_copy(node):
    if node is None:
        return None
    kind = node[0]
    if kind == LEAF:
        return [LEAF, node[1], node[2]]
    if kind == EXT:
        return [EXT, node[1], _deep_copy(node[2])]
    if kind == BRANCH:
        return [BRANCH, [_deep_copy(c) for c in node[1]], node[2]]
    return [HASHREF, node[1]]


class SecureTrie(Trie):
    """Trie with keccak256-hashed keys (reference trie/secure_trie.go).

    Keeps the preimage map so callers can enumerate plain keys.
    """

    def __init__(self, root_hash: bytes = EMPTY_ROOT,
                 db: Optional[Dict[bytes, bytes]] = None):
        super().__init__(root_hash, db)
        self.preimages: Dict[bytes, bytes] = {}

    def get(self, key: bytes) -> Optional[bytes]:
        return super().get(keccak256(key))

    def update(self, key: bytes, value: bytes) -> None:
        hk = keccak256(key)
        self.preimages[hk] = key
        super().update(hk, value)

    def delete(self, key: bytes) -> None:
        self.update(key, b"")

    def copy(self) -> "SecureTrie":
        t = SecureTrie(db=self.db)
        t.root = _deep_copy(self.root)
        t._hash_cache = self._hash_cache
        t.preimages = dict(self.preimages)
        return t
