"""Merkle-Patricia trie — host structural engine with incremental hashing.

Semantics per the Ethereum yellow-paper trie spec (reference trie/trie.go:
insert :308, delete :413, Hash :573; hasher.go:69 collapse rules):

- leaf:      [hex-prefix(nibbles, t=1), value]
- extension: [hex-prefix(nibbles, t=0), child-ref]
- branch:    [c0..c15, value]
- a node's reference inside its parent is its RLP if len(rlp) < 32,
  else keccak256(rlp); the root hash is always keccak256(rlp(root)).

Every node carries a memo slot caching (encoded-rlp, parent-ref); edits
clear memos along the touched path only, so re-hashing after a block
touches O(dirty * depth) nodes — the host analog of the reference's
cached trie nodes (trie/triedb/hashdb), and the contract that lets
mpt/rehash.py hand whole dirty frontiers to the batched device keccak.

``SecureTrie`` applies keccak to keys (reference trie/secure_trie.go).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from coreth_tpu import rlp
from coreth_tpu.crypto import keccak256

EMPTY_ROOT = keccak256(rlp.encode(b""))

# Node model (mutable lists so edits are in place); last slot is the memo:
#   [LEAF,   nibbles(bytes), value(bytes),      memo]
#   [EXT,    nibbles(bytes), child,             memo]
#   [BRANCH, [child x 16],   value(bytes),      memo]
#   [HASHREF, digest(bytes32)]                  (db-backed reference)
# memo = (encoded_rlp: bytes, ref) where ref is the 32-byte hash if
# len(encoded) >= 32 else the decoded RLP structure to inline in parents.

LEAF, EXT, BRANCH, HASHREF = "L", "E", "B", "H"
_MEMO = 3  # memo slot index for L/E/B nodes


def hex_prefix(nibbles: bytes, is_leaf: bool) -> bytes:
    """Hex-prefix encoding (yellow paper appendix C)."""
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2:
        out = bytearray([(flag + 1) << 4 | nibbles[0]])
        rest = nibbles[1:]
    else:
        out = bytearray([flag << 4])
        rest = nibbles
    for i in range(0, len(rest), 2):
        out.append(rest[i] << 4 | rest[i + 1])
    return bytes(out)


def decode_hex_prefix(data: bytes) -> Tuple[bytes, bool]:
    flag = data[0] >> 4
    is_leaf = flag >= 2
    nibbles = bytearray()
    if flag & 1:
        nibbles.append(data[0] & 0x0F)
    for b in data[1:]:
        nibbles.append(b >> 4)
        nibbles.append(b & 0x0F)
    return bytes(nibbles), is_leaf


def key_to_nibbles(key: bytes) -> bytes:
    out = bytearray()
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return bytes(out)


def _common_prefix_len(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class MissingNodeError(Exception):
    """A hash reference was dereferenced but absent from the node store."""


def _leaf(nibbles, value):
    return [LEAF, nibbles, value, None]


def _ext(nibbles, child):
    return [EXT, nibbles, child, None]


def _branch(children, value):
    return [BRANCH, children, value, None]


class Trie:
    """In-memory MPT over an optional {hash: node-rlp} backing store."""

    def __init__(self, root_hash: bytes = EMPTY_ROOT,
                 db: Optional[Dict[bytes, bytes]] = None):
        self.db = db if db is not None else {}
        if root_hash == EMPTY_ROOT:
            # corethlint: shared Trie instances are thread-confined — concurrent users (exporter shadow tries, trie-prefetch, snapshot workers) each build their own Trie over a shared read-only node db
            self.root = None
        else:
            self.root = [HASHREF, root_hash]

    # ------------------------------------------------------------------ get
    def get(self, key: bytes) -> Optional[bytes]:
        return self._get(self.root, key_to_nibbles(key))

    def _resolve(self, node):
        if node is not None and node[0] == HASHREF:
            data = self.db.get(node[1])
            if data is None:
                raise MissingNodeError(node[1].hex())
            return self._decode_node(rlp.decode(data))
        return node

    def _resolve_in_place(self, parent, slot):
        """Resolve a HASHREF child and replace it in the parent so the
        decode cost is paid once."""
        node = parent[slot]
        if node is not None and node[0] == HASHREF:
            node = self._resolve(node)
            parent[slot] = node
        return node

    def _decode_node(self, items):
        """RLP structure -> node model.  Child byte-strings of 32 bytes are
        hash refs; nested lists are inlined nodes."""
        if isinstance(items, list) and len(items) == 2:
            nibbles, is_leaf = decode_hex_prefix(items[0])
            if is_leaf:
                return _leaf(nibbles, items[1])
            return _ext(nibbles, self._decode_ref(items[1]))
        if isinstance(items, list) and len(items) == 17:
            children = [self._decode_ref(c) if c else None
                        for c in items[:16]]
            return _branch(children, items[16])
        raise ValueError("malformed trie node")

    def _decode_ref(self, item):
        if isinstance(item, list):
            return self._decode_node(item)
        if item == b"":
            return None
        if len(item) == 32:
            return [HASHREF, item]
        raise ValueError("malformed node reference")

    def _get(self, node, nibbles: bytes) -> Optional[bytes]:
        while True:
            if node is None:
                return None
            node = self._resolve(node)
            if node is None:
                return None
            kind = node[0]
            if kind == LEAF:
                return node[2] if node[1] == nibbles else None
            if kind == EXT:
                if nibbles[:len(node[1])] != node[1]:
                    return None
                nibbles = nibbles[len(node[1]):]
                node = node[2]
                continue
            # branch
            if not nibbles:
                return node[2] or None
            nxt = node[1][nibbles[0]]
            nibbles = nibbles[1:]
            node = nxt

    # --------------------------------------------------------------- update
    def update(self, key: bytes, value: bytes) -> None:
        nibbles = key_to_nibbles(key)
        if value:
            self.root = self._insert(self.root, nibbles, value)
        else:
            self.root = self._delete(self.root, nibbles)

    def delete(self, key: bytes) -> None:
        self.update(key, b"")

    def _insert(self, node, nibbles: bytes, value: bytes):
        if node is None:
            return _leaf(nibbles, value)
        node = self._resolve(node)
        if node is None:
            return _leaf(nibbles, value)
        kind = node[0]
        if kind == LEAF:
            existing = node[1]
            if existing == nibbles:
                node[2] = value
                node[_MEMO] = None
                return node
            cp = _common_prefix_len(existing, nibbles)
            branch = _branch([None] * 16, b"")
            for nb, val in ((existing, node[2]), (nibbles, value)):
                rest = nb[cp:]
                if not rest:
                    branch[2] = val
                else:
                    branch[1][rest[0]] = _leaf(rest[1:], val)
            if cp:
                return _ext(nibbles[:cp], branch)
            return branch
        if kind == EXT:
            prefix = node[1]
            cp = _common_prefix_len(prefix, nibbles)
            if cp == len(prefix):
                node[2] = self._insert(node[2], nibbles[cp:], value)
                node[_MEMO] = None
                return node
            branch = _branch([None] * 16, b"")
            old_rest = prefix[cp:]
            child = node[2] if len(old_rest) == 1 \
                else _ext(old_rest[1:], node[2])
            branch[1][old_rest[0]] = child
            new_rest = nibbles[cp:]
            if not new_rest:
                branch[2] = value
            else:
                branch[1][new_rest[0]] = _leaf(new_rest[1:], value)
            if cp:
                return _ext(nibbles[:cp], branch)
            return branch
        # branch
        if not nibbles:
            node[2] = value
            node[_MEMO] = None
            return node
        idx = nibbles[0]
        node[1][idx] = self._insert(node[1][idx], nibbles[1:], value)
        node[_MEMO] = None
        return node

    # --------------------------------------------------------------- delete
    def _delete(self, node, nibbles: bytes):
        if node is None:
            return None
        node = self._resolve(node)
        if node is None:
            return None
        kind = node[0]
        if kind == LEAF:
            return None if node[1] == nibbles else node
        if kind == EXT:
            prefix = node[1]
            if nibbles[:len(prefix)] != prefix:
                return node
            child = self._delete(node[2], nibbles[len(prefix):])
            if child is None:
                return None
            child = self._resolve(child)
            if child[0] == EXT:
                return _ext(prefix + child[1], child[2])
            if child[0] == LEAF:
                return _leaf(prefix + child[1], child[2])
            node[2] = child
            node[_MEMO] = None
            return node
        # branch
        if not nibbles:
            if not node[2]:
                return node
            node[2] = b""
        else:
            idx = nibbles[0]
            node[1][idx] = self._delete(node[1][idx], nibbles[1:])
        node[_MEMO] = None
        live = [(i, c) for i, c in enumerate(node[1]) if c is not None]
        if node[2]:
            if live:
                return node
            return _leaf(b"", node[2])
        if len(live) > 1:
            return node
        if not live:
            return None
        idx, child = live[0]
        child = self._resolve_in_place(node[1], idx)
        if child[0] == LEAF:
            return _leaf(bytes([idx]) + child[1], child[2])
        if child[0] == EXT:
            return _ext(bytes([idx]) + child[1], child[2])
        return _ext(bytes([idx]), child)

    # ----------------------------------------------------------------- hash
    def _encode_node(self, node, acc):
        """Node -> (rlp bytes, parent-ref), memoized.

        acc, when given, collects (hash, rlp) for every hashed node (the
        commit set) — including memoized subtrees on their first commit.
        """
        memo = node[_MEMO]
        if memo is not None:
            if acc is not None:
                self._collect_committed(node, acc)
            return memo
        kind = node[0]
        if kind == LEAF:
            encoded = rlp.encode([hex_prefix(node[1], True), node[2]])
        elif kind == EXT:
            encoded = rlp.encode([hex_prefix(node[1], False),
                                  self._ref(node[2], acc)])
        else:
            items = [self._ref(c, acc) if c is not None else b""
                     for c in node[1]]
            items.append(node[2])
            encoded = rlp.encode(items)
        if len(encoded) < 32:
            ref = rlp.decode(encoded)
        else:
            ref = keccak256(encoded)
            if acc is not None:
                acc.append((ref, encoded))
        node[_MEMO] = (encoded, ref)
        return node[_MEMO]

    def _collect_committed(self, node, acc):
        """Emit (hash, rlp) pairs for a memoized subtree (first commit
        after a hash() pass)."""
        stack = [node]
        while stack:
            n = stack.pop()
            if n is None or n[0] == HASHREF:
                continue
            memo = n[_MEMO]
            if memo is None:
                continue
            encoded, ref = memo
            if isinstance(ref, bytes) and len(ref) == 32:
                if ref in self.db:
                    continue  # subtree already persisted
                acc.append((ref, encoded))
            if n[0] == EXT:
                stack.append(n[2])
            elif n[0] == BRANCH:
                stack.extend(n[1])

    def _ref(self, node, acc):
        if node[0] == HASHREF:
            return node[1]
        return self._encode_node(node, acc)[1]

    def hash(self) -> bytes:
        """Root hash (reference trie.go:573 Hash)."""
        if self.root is None:
            return EMPTY_ROOT
        if self.root[0] == HASHREF:
            return self.root[1]
        encoded, ref = self._encode_node(self.root, None)
        if isinstance(ref, bytes) and len(ref) == 32:
            return ref
        return keccak256(encoded)

    def commit(self) -> bytes:
        """Hash and persist all nodes into the backing store.

        Returns the root hash (reference trie.go:585 Commit +
        committer.go).  The in-memory tree stays resident (it is the
        clean cache).
        """
        if self.root is None:
            return EMPTY_ROOT
        if self.root[0] == HASHREF:
            return self.root[1]
        acc: List[Tuple[bytes, bytes]] = []
        encoded, ref = self._encode_node(self.root, acc)
        root_hash = ref if isinstance(ref, bytes) and len(ref) == 32 \
            else keccak256(encoded)
        self.db[root_hash] = encoded
        for h, data in acc:
            self.db[h] = data
        return root_hash

    def copy(self) -> "Trie":
        t = Trie(db=self.db)
        t.root = _deep_copy(self.root)
        return t

    # ------------------------------------------------------------- iterate
    def items(self):
        """Yield (key_nibbles, value) in lexicographic key order."""
        yield from self._iter(self.root, b"")

    def _iter(self, node, prefix: bytes):
        if node is None:
            return
        node = self._resolve(node)
        if node is None:
            return
        kind = node[0]
        if kind == LEAF:
            yield prefix + node[1], node[2]
        elif kind == EXT:
            yield from self._iter(node[2], prefix + node[1])
        else:
            if node[2]:
                yield prefix, node[2]
            for i, c in enumerate(node[1]):
                if c is not None:
                    yield from self._iter(c, prefix + bytes([i]))


def _deep_copy(node):
    if node is None:
        return None
    kind = node[0]
    if kind == LEAF:
        return [LEAF, node[1], node[2], node[_MEMO]]
    if kind == EXT:
        return [EXT, node[1], _deep_copy(node[2]), node[_MEMO]]
    if kind == BRANCH:
        return [BRANCH, [_deep_copy(c) for c in node[1]], node[2],
                node[_MEMO]]
    return [HASHREF, node[1]]


class SecureTrie(Trie):
    """Trie with keccak256-hashed keys (reference trie/secure_trie.go).

    Keeps the preimage map so callers can enumerate plain keys.
    """

    def __init__(self, root_hash: bytes = EMPTY_ROOT,
                 db: Optional[Dict[bytes, bytes]] = None):
        super().__init__(root_hash, db)
        self.preimages: Dict[bytes, bytes] = {}

    def get(self, key: bytes) -> Optional[bytes]:
        return super().get(keccak256(key))

    def update(self, key: bytes, value: bytes) -> None:
        hk = keccak256(key)
        self.preimages[hk] = key
        super().update(hk, value)

    def delete(self, key: bytes) -> None:
        self.update(key, b"")

    def copy(self) -> "SecureTrie":
        t = SecureTrie(db=self.db)
        t.root = _deep_copy(self.root)
        t.preimages = dict(self.preimages)
        return t
