"""Trie iterators.

Twin of reference trie/iterator.go: a depth-first NodeIterator over
the resolved structure (yielding path, node kind, hash-or-None, and
leaf values), plus range-bounded leaf iteration used by the sync
handlers to answer LeafsRequests.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

from coreth_tpu.mpt.trie import (
    BRANCH, EXT, LEAF, Trie, key_to_nibbles,
)


def nodes(trie: Trie) -> Iterator[Tuple[bytes, str, Optional[bytes]]]:
    """DFS over (path_nibbles, kind, hash) for every resolved node;
    hash is None for inline (<32 byte) nodes."""
    def walk(node, prefix: bytes):
        node = trie._resolve(node)
        if node is None:
            return
        encoded, ref = trie._encode_node(node, None)
        h = ref if isinstance(ref, bytes) and len(ref) == 32 else None
        kind = node[0]
        yield prefix, kind, h
        if kind == EXT:
            yield from walk(node[2], prefix + node[1])
        elif kind == BRANCH:
            for i, c in enumerate(node[1]):
                if c is not None:
                    yield from walk(c, prefix + bytes([i]))

    yield from walk(trie.root, b"")


def nibbles_to_key(nibbles: bytes) -> bytes:
    """Inverse of key_to_nibbles for even-length nibble paths."""
    if len(nibbles) % 2:
        raise ValueError("odd nibble path has no byte key")
    return bytes((nibbles[i] << 4) | nibbles[i + 1]
                 for i in range(0, len(nibbles), 2))


def leaves(trie: Trie, start: bytes = b"",
           limit: Optional[int] = None) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) pairs in key order, beginning at `start`
    (inclusive) — the shape sync/handlers/leafs_request.go walks.

    Seeks directly to `start` (subtrees entirely below it are never
    visited), so serving a page costs O(page + depth), not O(trie)."""
    start_nibs = key_to_nibbles(start) if start else b""
    count = 0

    def walk(node, prefix: bytes):
        nonlocal count
        if limit is not None and count >= limit:
            return
        node = trie._resolve(node)
        if node is None:
            return
        kind = node[0]
        if kind == LEAF:
            full = prefix + node[1]
            if full >= start_nibs:
                yield nibbles_to_key(full), node[2]
                count += 1
            return
        if kind == EXT:
            sub = prefix + node[1]
            # skip subtrees whose maximal key is still below start
            if sub >= start_nibs[:len(sub)]:
                yield from walk(node[2], sub)
            return
        for i, c in enumerate(node[1]):
            if c is None:
                continue
            sub = prefix + bytes([i])
            if sub < start_nibs[:len(sub)]:
                continue  # entirely left of the start bound
            yield from walk(c, sub)
            if limit is not None and count >= limit:
                return

    yield from walk(trie.root, b"")
