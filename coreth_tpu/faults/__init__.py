"""Deterministic fault injection for the serve/replay stack.

A :class:`FaultPlan` arms named injection points that production code
threads through its failure seams (device dispatch, the native
boundary, the commit pipeline, the serve feed, sender recovery).
Unarmed — the production state — every point is ONE module-global
``None`` check; armed, the plan decides per hit (seeded, so a plan
replays identically) whether the point fires and what it does: raise a
:class:`FaultInjected`, SIGKILL the process (crash-consistency tests),
stall, or hand a site-interpreted spec back to the caller (drop a
block, corrupt a header).

``CORETH_FAULT_PLAN`` arms a plan from the environment (inline JSON or
``@/path/to/plan.json``) — the seam the SIGKILL-resume subprocess
tests and the bench fault section use.
"""

from coreth_tpu.faults.registry import (
    FaultInjected, FaultPlan, FaultSpec, arm, arm_from_env, armed,
    check, declare, declared, disarm, fire, fired,
)

__all__ = [
    "FaultInjected", "FaultPlan", "FaultSpec", "arm", "arm_from_env",
    "armed", "check", "declare", "declared", "disarm", "fire", "fired",
]
