"""The fault-injection registry: declared points, armed plans, firing.

Design constraints (in order):

1. **Production cost ~zero.**  ``check()``/``fire()`` return after one
   module-global ``is None`` comparison when no plan is armed.  No
   dict lookup, no lock, no allocation.
2. **Deterministic.**  A plan owns a seeded ``random.Random``; its
   per-point hit counters and probability draws replay identically for
   the same plan + same call sequence, so a failing fault scenario is
   a reproducible test, not a flake.
3. **Declared ≠ armed.**  Every injection point is ``declare()``d at
   import time by the module that hosts it; ``declared()`` enumerates
   them so the completeness test (tests/test_faults.py) can assert
   every point is exercised by at least one armed scenario — a new
   point cannot land untested.
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Optional


class FaultInjected(Exception):
    """Raised by an armed ``action="raise"`` point.  ``transient``
    mirrors the spec: retry-with-backoff is appropriate; persistent
    faults should strike toward demotion instead."""

    def __init__(self, point: str, transient: bool = False):
        super().__init__(f"injected fault at {point}"
                         + (" (transient)" if transient else ""))
        self.point = point
        self.transient = transient


@dataclass
class FaultSpec:
    """One point's arming.

    action: "raise" (FaultInjected), "sigkill" (os.kill SIGKILL —
      crash-consistency tests), "stall" (sleep ``delay`` then proceed),
      or any site-interpreted verb ("drop", "mutate", ...) the call
      site handles via ``check()``.
    after: skip the first N eligible hits (fire mid-run, not at start).
    times: fire at most N times (None = every hit).
    prob: per-hit firing probability, drawn from the plan's seeded RNG.
    transient: carried onto FaultInjected (retryable vs strike).
    delay: seconds, for action="stall".
    """

    action: str = "raise"
    after: int = 0
    times: Optional[int] = None
    prob: float = 1.0
    transient: bool = False
    delay: float = 0.0


class FaultPlan:
    """Armed point -> spec map with deterministic per-point state."""

    def __init__(self, points: Dict[str, object], seed: int = 0):
        self.points: Dict[str, FaultSpec] = {}
        for name, spec in points.items():
            if isinstance(spec, dict):
                spec = FaultSpec(**spec)
            self.points[name] = spec
        self.seed = seed
        self._rng = random.Random(seed)
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        # plans are consulted from several pipeline threads (feed,
        # prefetch, execute); the counters must not tear
        self._lock = threading.Lock()

    def hit(self, point: str) -> Optional[FaultSpec]:
        """One eligible pass through ``point``; the spec iff it fires."""
        spec = self.points.get(point)
        if spec is None:
            return None
        with self._lock:
            n = self._hits.get(point, 0)
            self._hits[point] = n + 1
            if n < spec.after:
                return None
            if spec.times is not None \
                    and self._fired.get(point, 0) >= spec.times:
                return None
            if spec.prob < 1.0 and self._rng.random() >= spec.prob:
                return None
            self._fired[point] = self._fired.get(point, 0) + 1
        return spec

    def fired(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fired)


# ------------------------------------------------------------------ registry

_DECLARED: Dict[str, str] = {}
_PLAN: Optional[FaultPlan] = None


def declare(name: str, doc: str) -> str:
    """Register an injection point (call at import of the hosting
    module).  Returns ``name`` so sites can bind it to a constant."""
    _DECLARED[name] = doc
    return name


def declared() -> Dict[str, str]:
    """Every declared point -> its one-line doc."""
    return dict(_DECLARED)


def arm(plan: FaultPlan) -> None:
    global _PLAN
    _PLAN = plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


@contextmanager
def armed(plan: FaultPlan):
    """Scoped arming for tests; restores the previous plan on exit."""
    global _PLAN
    prev = _PLAN
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = prev


def arm_from_env() -> Optional[FaultPlan]:
    """Arm CORETH_FAULT_PLAN if set and nothing is armed yet (inline
    JSON, or ``@path`` to a JSON file).  Idempotent — pipeline and
    engine constructors both call this, whoever runs first wins."""
    global _PLAN
    if _PLAN is not None:
        return _PLAN
    raw = os.environ.get("CORETH_FAULT_PLAN")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:], "r", encoding="utf-8") as f:
            raw = f.read()
    obj = json.loads(raw)
    seed = int(obj.pop("seed", 0)) if isinstance(obj, dict) else 0
    points = obj.get("points", obj)
    _PLAN = FaultPlan(points, seed=seed)
    return _PLAN


def check(point: str) -> Optional[FaultSpec]:
    """Armed spec for one eligible pass, else None.  The seam for
    sites that interpret the action themselves (drop/mutate/...)."""
    plan = _PLAN
    if plan is None:  # the production path: one comparison
        return None
    return plan.hit(point)


def fire(point: str) -> Optional[FaultSpec]:
    """check() + execute the built-in actions: raise FaultInjected,
    SIGKILL the process, or stall.  Site-interpreted specs are
    returned for the caller."""
    spec = check(point)
    if spec is None:
        return None
    if spec.action == "raise":
        raise FaultInjected(point, transient=spec.transient)
    if spec.action == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    if spec.action == "stall":
        time.sleep(spec.delay)
    return spec


def fired(point: Optional[str] = None):
    """Fired counts of the armed plan ({} / 0 when disarmed)."""
    plan = _PLAN
    if plan is None:
        return 0 if point is not None else {}
    counts = plan.fired()
    if point is not None:
        return counts.get(point, 0)
    return counts
