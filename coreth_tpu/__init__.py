"""coreth-tpu: a TPU-native execution engine with the capabilities of coreth
(the Avalanche C-Chain VM, /root/reference).

Architecture (tpu-first, not a port):

- ``crypto``     host cryptography: keccak-256, secp256k1 ECDSA recover
                 (pure-Python reference + C++ native fast path via ctypes).
- ``ops``        device kernels: batched keccak-f[1600] on uint32 lanes
                 (jnp + Pallas), 256-bit limb arithmetic, bloom filters.
- ``rlp``        RLP codec (reference: geth rlp, used throughout coreth).
- ``types``      transactions / headers / receipts / logs with the Avalanche
                 extras (ExtDataHash, BlockGasCost, ExtDataGasUsed — see
                 reference core/types/block.go + block_ext.go).
- ``mpt``        Merkle-Patricia trie with level-synchronous batched rehash
                 (reference: trie/).
- ``state``      journaled world state + device-resident flat state
                 (reference: core/state/).
- ``evm``        the EVM as a jitted, vmapped step machine
                 (reference: core/vm/).
- ``processor``  state-transition + block-processing rules, bit-identical to
                 reference core/state_transition.go + core/state_processor.go.
- ``consensus``  dummy-engine twin: header gas verification + Avalanche
                 dynamic fee algorithm (reference: consensus/dummy/).
- ``chain``      chain orchestration, genesis, chain-maker fixtures
                 (reference: core/blockchain.go, core/chain_makers.go).
- ``replay``     the north-star batched block-replay engine: dependency
                 scheduling + lockstep vmapped execution.
- ``parallel``   jax.sharding meshes, shard_map replay sharding, ICI
                 collectives for the Merkle frontier reduction.
"""

__version__ = "0.1.0"
