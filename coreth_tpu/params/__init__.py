"""Chain configuration, fork schedule, and protocol gas constants.

Semantic twin of reference ``params/`` (config.go:474, protocol_params.go,
avalanche_params.go).  The constants are protocol facts — they must match
the Ethereum/Avalanche specification bit-for-bit; everything else (the
Python shape of the config object, the Rules resolution) is our own design.
"""

from coreth_tpu.params.protocol import *  # noqa: F401,F403
from coreth_tpu.params.config import (  # noqa: F401
    ChainConfig,
    Rules,
    TEST_CHAIN_CONFIG,
    TEST_LAUNCH_CONFIG,
    TEST_APRICOT_PHASE1_CONFIG,
    TEST_APRICOT_PHASE2_CONFIG,
    TEST_APRICOT_PHASE3_CONFIG,
    TEST_APRICOT_PHASE4_CONFIG,
    TEST_APRICOT_PHASE5_CONFIG,
    TEST_BANFF_CONFIG,
    TEST_CORTINA_CONFIG,
    TEST_DURANGO_CONFIG,
)
