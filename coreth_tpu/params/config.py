"""Chain configuration + fork schedule.

Semantic twin of reference params/config.go:474-1100.  Ethereum forks
activate by block number; Avalanche upgrades (ApricotPhase1..Durango)
activate by block timestamp.  ``Rules`` is the flattened per-block view the
EVM / processor consult (reference params/config.go:1027).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ChainConfig:
    chain_id: int = 43111
    # Ethereum block-number forks (all active from genesis on Avalanche nets)
    homestead_block: Optional[int] = 0
    eip150_block: Optional[int] = 0
    eip155_block: Optional[int] = 0
    eip158_block: Optional[int] = 0
    byzantium_block: Optional[int] = 0
    constantinople_block: Optional[int] = 0
    petersburg_block: Optional[int] = 0
    istanbul_block: Optional[int] = 0
    muir_glacier_block: Optional[int] = 0
    # per-config stateful-precompile activation overrides, keyed by
    # Module.config_key (None entry = disabled for this config)
    precompile_upgrades: Optional[dict] = None
    # Avalanche timestamp upgrades (None = never active)
    apricot_phase1_time: Optional[int] = None
    apricot_phase2_time: Optional[int] = None
    apricot_phase3_time: Optional[int] = None
    apricot_phase4_time: Optional[int] = None
    apricot_phase5_time: Optional[int] = None
    apricot_phase_pre6_time: Optional[int] = None
    apricot_phase6_time: Optional[int] = None
    apricot_phase_post6_time: Optional[int] = None
    banff_time: Optional[int] = None
    cortina_time: Optional[int] = None
    durango_time: Optional[int] = None
    cancun_time: Optional[int] = None

    # --- block-number forks ------------------------------------------------
    def is_homestead(self, num: int) -> bool:
        return _active_block(self.homestead_block, num)

    def is_eip150(self, num: int) -> bool:
        return _active_block(self.eip150_block, num)

    def is_eip155(self, num: int) -> bool:
        return _active_block(self.eip155_block, num)

    def is_eip158(self, num: int) -> bool:
        return _active_block(self.eip158_block, num)

    def is_byzantium(self, num: int) -> bool:
        return _active_block(self.byzantium_block, num)

    def is_constantinople(self, num: int) -> bool:
        return _active_block(self.constantinople_block, num)

    def is_petersburg(self, num: int) -> bool:
        return _active_block(self.petersburg_block, num)

    def is_istanbul(self, num: int) -> bool:
        return _active_block(self.istanbul_block, num)

    # --- timestamp upgrades ------------------------------------------------
    def is_apricot_phase1(self, time: int) -> bool:
        return _active_time(self.apricot_phase1_time, time)

    def is_apricot_phase2(self, time: int) -> bool:
        return _active_time(self.apricot_phase2_time, time)

    def is_apricot_phase3(self, time: int) -> bool:
        return _active_time(self.apricot_phase3_time, time)

    def is_apricot_phase4(self, time: int) -> bool:
        return _active_time(self.apricot_phase4_time, time)

    def is_apricot_phase5(self, time: int) -> bool:
        return _active_time(self.apricot_phase5_time, time)

    def is_apricot_phase_pre6(self, time: int) -> bool:
        return _active_time(self.apricot_phase_pre6_time, time)

    def is_apricot_phase6(self, time: int) -> bool:
        return _active_time(self.apricot_phase6_time, time)

    def is_apricot_phase_post6(self, time: int) -> bool:
        return _active_time(self.apricot_phase_post6_time, time)

    def is_banff(self, time: int) -> bool:
        return _active_time(self.banff_time, time)

    def is_cortina(self, time: int) -> bool:
        return _active_time(self.cortina_time, time)

    def is_durango(self, time: int) -> bool:
        return _active_time(self.durango_time, time)

    def is_cancun(self, num: int, time: int) -> bool:
        return _active_time(self.cancun_time, time)

    def precompile_activation_time(self, module):
        """Per-config activation override by config_key (the reference
        resolves activation from the chain config's upgrade schedule,
        config.go getActivePrecompileConfig) — falls back to the
        module's registry default."""
        overrides = self.precompile_upgrades or {}
        return overrides.get(module.config_key, module.timestamp)

    def precompile_active(self, module, timestamp: int) -> bool:
        at = self.precompile_activation_time(module)
        return at is not None and timestamp >= at

    def rules(self, num: int, timestamp: int) -> "Rules":
        """Flattened rule set for a block (reference config.go:1027-1100).

        Registered stateful-precompile modules active at `timestamp`
        populate active_precompiles/predicaters (config.go Rules
        ActivePrecompiles — here fed by the module registry)."""
        from coreth_tpu.precompile.modules import registered_modules
        active = {}
        predicaters = {}
        for m in registered_modules():
            if not self.precompile_active(m, timestamp):
                continue
            active[m.address] = m.contract
            if m.predicater is not None:
                predicaters[m.address] = m.predicater
        return Rules(
            active_precompiles=active,
            predicaters=predicaters,
            chain_id=self.chain_id,
            is_homestead=self.is_homestead(num),
            is_eip150=self.is_eip150(num),
            is_eip155=self.is_eip155(num),
            is_eip158=self.is_eip158(num),
            is_byzantium=self.is_byzantium(num),
            is_constantinople=self.is_constantinople(num),
            is_petersburg=self.is_petersburg(num),
            is_istanbul=self.is_istanbul(num),
            is_apricot_phase1=self.is_apricot_phase1(timestamp),
            is_apricot_phase2=self.is_apricot_phase2(timestamp),
            is_apricot_phase3=self.is_apricot_phase3(timestamp),
            is_apricot_phase4=self.is_apricot_phase4(timestamp),
            is_apricot_phase5=self.is_apricot_phase5(timestamp),
            is_apricot_phase_pre6=self.is_apricot_phase_pre6(timestamp),
            is_apricot_phase6=self.is_apricot_phase6(timestamp),
            is_apricot_phase_post6=self.is_apricot_phase_post6(timestamp),
            is_banff=self.is_banff(timestamp),
            is_cortina=self.is_cortina(timestamp),
            is_durango=self.is_durango(timestamp),
            is_cancun=self.is_cancun(num, timestamp),
        )


@dataclass
class Rules:
    chain_id: int = 43111
    is_homestead: bool = False
    is_eip150: bool = False
    is_eip155: bool = False
    is_eip158: bool = False
    is_byzantium: bool = False
    is_constantinople: bool = False
    is_petersburg: bool = False
    is_istanbul: bool = False
    is_apricot_phase1: bool = False
    is_apricot_phase2: bool = False
    is_apricot_phase3: bool = False
    is_apricot_phase4: bool = False
    is_apricot_phase5: bool = False
    is_apricot_phase_pre6: bool = False
    is_apricot_phase6: bool = False
    is_apricot_phase_post6: bool = False
    is_banff: bool = False
    is_cortina: bool = False
    is_durango: bool = False
    is_cancun: bool = False
    # address -> stateful precompile module (filled by precompile registry)
    active_precompiles: dict = field(default_factory=dict)
    predicaters: dict = field(default_factory=dict)

    # EIP-1559-style semantics arrive with ApricotPhase3 on Avalanche
    @property
    def is_london(self) -> bool:
        return self.is_apricot_phase3

    # EIP-2929/2930 semantics arrive with ApricotPhase2
    @property
    def is_berlin(self) -> bool:
        return self.is_apricot_phase2

    # EIP-3529 refund reduction + EIP-3541 arrive with ApricotPhase3
    @property
    def is_eip3529(self) -> bool:
        return self.is_apricot_phase3


def _active_block(fork: Optional[int], num: int) -> bool:
    return fork is not None and fork <= num


def _active_time(fork: Optional[int], time: int) -> bool:
    return fork is not None and fork <= time


def _phases(n: int, chain_id: int = 43111, **extra) -> ChainConfig:
    """Config with apricot phases 1..n active from genesis."""
    names = ["apricot_phase1_time", "apricot_phase2_time",
             "apricot_phase3_time", "apricot_phase4_time",
             "apricot_phase5_time", "apricot_phase_pre6_time",
             "apricot_phase6_time", "apricot_phase_post6_time",
             "banff_time", "cortina_time", "durango_time"]
    kw = {k: 0 for k in names[:n]}
    kw.update(extra)
    return ChainConfig(chain_id=chain_id, **kw)


# Test configurations mirroring reference params/config.go:74-240
TEST_LAUNCH_CONFIG = _phases(0)
TEST_APRICOT_PHASE1_CONFIG = _phases(1)
TEST_APRICOT_PHASE2_CONFIG = _phases(2)
TEST_APRICOT_PHASE3_CONFIG = _phases(3)
TEST_APRICOT_PHASE4_CONFIG = _phases(4)
TEST_APRICOT_PHASE5_CONFIG = _phases(5)
TEST_BANFF_CONFIG = _phases(9)
TEST_CORTINA_CONFIG = _phases(10)
TEST_DURANGO_CONFIG = _phases(11)
# The "everything on" config used by most tests (reference TestChainConfig)
TEST_CHAIN_CONFIG = _phases(11, chain_id=43111)
