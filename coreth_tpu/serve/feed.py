"""Block sources for the streaming pipeline.

Two concrete feeds cover the two halves of "heavy traffic":

- :class:`ChainFeed` drains a pre-built chain at a target rate — the
  replay analog of a peer streaming accepted blocks, with a rate knob
  so benches can measure latency under a *sustained* arrival rate
  instead of an instantaneous backlog;
- :class:`MempoolFeed` assembles blocks live from the existing
  txpool/miner machinery: callers pump signed transactions in, the
  miner's ``commitNewWork`` packs them against the builder chain's
  head, and each produced block is accepted there before it is served
  — so the stream the pipeline replays is exactly what a validator
  would have built under load.

Feeds are pull-based: the pipeline's feed stage calls
:meth:`BlockFeed.next_block` with a timeout; ``None`` means "nothing
available yet" (a stalled feed — the pipeline keeps draining its
in-flight work instead of blocking), :class:`FeedExhausted` ends the
stream.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from coreth_tpu import obs
from coreth_tpu.types import Block


class FeedExhausted(Exception):
    """The feed has no more blocks and never will."""


class BlockFeed:
    """Abstract block source (pull-based; see module docstring)."""

    def next_block(self, timeout: float) -> Optional[Block]:
        """Next block, or None if none became available within
        ``timeout`` seconds.  Raises FeedExhausted at end of stream."""
        raise NotImplementedError

    def close(self) -> None:
        """Release feed resources (idempotent)."""


class ChainFeed(BlockFeed):
    """Pre-built chain drained at a target rate.

    ``rate`` is blocks/second; None releases blocks as fast as the
    consumer pulls them (backlog mode — measures pipeline capacity).
    With a rate, block i is withheld until ``start + i/rate``, so the
    enqueue->committed latency histogram measures service latency at
    that arrival rate, not queue-drain throughput.
    """

    def __init__(self, blocks: List[Block], rate: Optional[float] = None,
                 clock=time.monotonic, sleep=time.sleep):
        self.blocks = blocks
        self.rate = rate
        self._clock = clock
        self._sleep = sleep
        self._i = 0
        self._t0: Optional[float] = None

    def next_block(self, timeout: float) -> Optional[Block]:
        if self._i >= len(self.blocks):
            raise FeedExhausted
        if self.rate:
            if self._t0 is None:
                self._t0 = self._clock()
            ready_at = self._t0 + self._i / self.rate
            now = self._clock()
            if now < ready_at:
                wait = min(timeout, ready_at - now)
                if wait > 0:
                    self._sleep(wait)
                if self._clock() < ready_at:
                    obs.instant("feed/paced_stall", block=self._i)
                    return None  # still pacing: report a stall
        b = self.blocks[self._i]
        self._i += 1
        return b


class MempoolFeed(BlockFeed):
    """Blocks assembled live from the txpool under sustained load.

    ``chain``/``txpool``/``miner`` are the existing machinery
    (chain.BlockChain, txpool.TxPool, miner.Miner) wired to the same
    builder-side state; ``tx_source(pool) -> bool`` is called before
    each block to pump more signed transactions into the pool and
    returns False once the load generator is exhausted.  Each produced
    block is inserted AND accepted on the builder chain (so the pool's
    reset sees the new head), then served to the pipeline — whose
    replica engine must reproduce the builder's state roots
    bit-identically.
    """

    def __init__(self, chain, txpool, miner,
                 tx_source: Optional[Callable[[object], bool]] = None):
        self.chain = chain
        self.txpool = txpool
        self.miner = miner
        self.tx_source = tx_source
        self._source_done = tx_source is None
        self.built = 0

    def next_block(self, timeout: float) -> Optional[Block]:
        if not self._source_done:
            if not self.tx_source(self.txpool):
                self._source_done = True
        pending, _queued = self.txpool.stats()
        if pending == 0:
            if self._source_done:
                raise FeedExhausted
            # load generator lagging: honor the poll timeout so the
            # feed thread doesn't busy-spin against an empty pool
            time.sleep(timeout)
            return None
        with obs.span("feed/build_block"):
            block = self.miner.generate_block()
        if not block.transactions:
            # nothing executable made it in (all pending underpriced
            # against the new base fee, say) — a stall, not the end
            if self._source_done:
                raise FeedExhausted
            time.sleep(timeout)
            return None
        self.chain.insert_block(block)
        self.chain.accept(block.hash())
        self.txpool.reset()
        self.built += 1
        return block

    def close(self) -> None:
        self.chain.close()
