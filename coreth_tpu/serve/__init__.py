"""Streaming block-ingestion service (the "heavy traffic" half of the
north star).

The batch engine proves throughput: hand ``ReplayEngine.replay`` a
pre-built chain, get roots back.  This package proves *service*: blocks
arrive continuously from a :class:`~coreth_tpu.serve.feed.BlockFeed`
(a paced pre-built chain, or blocks assembled live from the
txpool/miner machinery), flow through a bounded-queue pipeline —

    feed -> prefetch -> execute -> commit

— with explicit backpressure between stages (a stalled stage degrades
latency measurably instead of deadlocking or buffering unboundedly),
and every block's enqueue->committed latency lands in p50/p99/max
histograms next to the sustained txs/s over the run (the FAFO
observation: sustained-rate measurement, not one-shot throughput, is
the honest metric once Merkleization is off the critical path).

Execution reuses the engine's existing machinery unchanged — transfer
windows with cross-window speculation, fused machine-OCC runs, the
exact host fallback, and the window-batched commit pipeline — so a
streamed chain produces bit-identical state roots to batch replay
(pinned by tests/test_serve.py across both trie backends).
"""

from coreth_tpu.serve.feed import (
    BlockFeed, ChainFeed, FeedExhausted, MempoolFeed,
)
from coreth_tpu.serve.pipeline import StreamingPipeline, StreamReport

__all__ = [
    "BlockFeed", "ChainFeed", "FeedExhausted", "MempoolFeed",
    "StreamingPipeline", "StreamReport",
]
