"""Lane stores: partitioning and seed bootstrap for the cluster.

A worker can only execute its contiguous block range if it owns the
chain state at the range's start — and the recovery protocol
(coordinator re-assigning a failed range) requires that state to be a
*resumable checkpoint record*, not an in-memory engine.  So every
lane, including lane 0, starts life the same way: ``resume_engine``
from a lane-scoped ``ReplayCheckpoint/<lane>`` record in its own
disk-backed store.

``bootstrap_stores`` produces those stores with ONE sequential pass:
a disk-backed engine replays the chain, and at each lane boundary it
flushes the commit pipeline, persists the trie nodes, writes the
lane's scoped record (the PR-10 write order: nodes durable before the
record), and snapshots the append-only KV log into the lane's
directory.  Total cost = one full replay + one file copy per lane —
the warm-start path a real serving cluster gets from state sync
(ROADMAP direction 5); the bench times only the parallel phase.

The boundary roots fall out for free: lane ``i``'s seed root IS the
root lane ``i-1`` must finish on — the aggregator's verification
chain — and the headers pin them independently (``generate_chain``
executed every block, so ``blocks[start-1].header.root`` is the
single-engine truth).
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass
from typing import List, Optional, Tuple


@dataclass
class LaneSeed:
    """One lane's assignment coordinates: the store seeded at block
    ``start`` (its scoped checkpoint record included), covering the
    half-open block-number range ``(start, end]``."""

    lane: str
    start: int
    end: int
    root: bytes
    db_dir: str


def partition_ranges(n_blocks: int, n_lanes: int
                     ) -> List[Tuple[int, int]]:
    """Contiguous ``(start, end]`` block-number ranges covering
    ``1..n_blocks``; earlier lanes absorb the remainder so sizes
    differ by at most one."""
    if n_lanes <= 0:
        raise ValueError("need at least one lane")
    n_lanes = min(n_lanes, n_blocks)
    base, extra = divmod(n_blocks, n_lanes)
    ranges = []
    start = 0
    for i in range(n_lanes):
        end = start + base + (1 if i < extra else 0)
        ranges.append((start, end))
        start = end
    return ranges


def open_store(db_dir: str, create: bool = False):
    """(kv, db) over ``db_dir``'s append-only chain.db — the
    disk-backed Database shape every checkpoint/resume test uses
    (FileDB + PersistentNodeDict/PersistentCodeDict)."""
    from coreth_tpu.rawdb.kv import FileDB
    from coreth_tpu.rawdb.state_manager import (
        PersistentCodeDict, PersistentNodeDict,
    )
    from coreth_tpu.state import Database
    if create:
        os.makedirs(db_dir, exist_ok=True)
    kv = FileDB(os.path.join(db_dir, "chain.db"))
    db = Database(node_db=PersistentNodeDict(kv),
                  code_db=PersistentCodeDict(kv))
    return kv, db


def write_seed_record(engine, kv, lane: str) -> bytes:
    """Persist the engine's current committed state as ``lane``'s
    resumable record (nodes -> kv -> record, the crash-consistency
    write order).  Returns the recorded root."""
    from coreth_tpu.rawdb import schema
    engine.commit_pipe.flush()
    root = engine.commit()
    node_db = engine.db.node_db
    if hasattr(node_db, "flush"):
        node_db.flush()
    kv.flush()
    header = engine.parent_header
    schema.write_replay_checkpoint(
        kv, header.number, header.hash(), root, header.encode(),
        worker=lane)
    kv.flush()
    return root


def bootstrap_stores(config, genesis, blocks, ranges, base_dir: str,
                     lane_prefix: str = "lane",
                     engine_kw: Optional[dict] = None) -> List[LaneSeed]:
    """Seed one store per range with a single sequential replay (see
    module docstring).  ``blocks[j]`` must carry block number ``j+1``
    (the generate_chain invariant every harness chain satisfies)."""
    from coreth_tpu.replay import ReplayEngine
    engine_kw = engine_kw or {}
    seed_dir = os.path.join(base_dir, "_bootstrap")
    kv, db = open_store(seed_dir, create=True)
    seeds: List[LaneSeed] = []
    try:
        gblock = genesis.to_block(db)
        eng = ReplayEngine(config, db, gblock.root,
                           parent_header=gblock.header, **engine_kw)
        done = 0
        for i, (start, end) in enumerate(ranges):
            if start > done:
                eng.replay(blocks[done:start])
                done = start
            lane = f"{lane_prefix}{i}"
            root = write_seed_record(eng, kv, lane)
            want = gblock.header.root if start == 0 \
                else blocks[start - 1].header.root
            assert root == want, (
                f"bootstrap root diverged at block {start}: "
                f"{root.hex()} != {want.hex()}")
            lane_dir = os.path.join(base_dir, lane)
            os.makedirs(lane_dir, exist_ok=True)
            shutil.copyfile(os.path.join(seed_dir, "chain.db"),
                            os.path.join(lane_dir, "chain.db"))
            seeds.append(LaneSeed(lane=lane, start=start, end=end,
                                  root=root, db_dir=lane_dir))
    finally:
        kv.close()
    return seeds
