"""Cluster worker: one StreamingPipeline lane per assignment.

``python -m coreth_tpu.serve.cluster.worker --connect HOST:PORT
--worker ID`` dials the coordinator, says hello, and then serves
assignments until drained: each ``assign`` names a lane (its
contiguous block range), the lane's seeded store, and the shared
chain file.  The worker resumes an engine from the lane's scoped
``ReplayCheckpoint/<lane>`` record — the SAME path a replacement
worker takes after a crash, so recovery is not a special case — runs
the existing streaming pipeline over the remaining blocks, and
reports the boundary root plus its full ``StreamReport`` row and
metrics snapshot for the coordinator to federate.

While the pipeline runs, a heartbeat thread emits liveness +
progress, and promotes every newly durable checkpoint record into a
``checkpoint_advance`` message — the coordinator's recovery horizon.

Fault points (coreth_tpu/faults):

- ``cluster/heartbeat_loss``: the heartbeat tick consults ``check()``
  and DROPS the send when armed — the network-partition shape; the
  worker stays alive and productive while the coordinator's timeout
  policy decides its fate.
- ``cluster/boundary_mismatch``: corrupts the REPORTED boundary root
  (state on disk stays correct) — the lying-worker shape the
  aggregator must catch by verification, not trust.
"""

from __future__ import annotations

import argparse
import os
import socket
import threading
import time  # noqa: DET003 — control-plane cadence/wall-clock only, never consensus data
from typing import Optional

from coreth_tpu import faults, obs
from coreth_tpu import rlp
from coreth_tpu.obs import recorder as _forensics
from coreth_tpu.serve.cluster import protocol
from coreth_tpu.serve.cluster.bootstrap import open_store

PT_HEARTBEAT_LOSS = faults.declare(
    "cluster/heartbeat_loss",
    "worker heartbeats dropped while the worker stays alive "
    "(network-partition shape; serve/cluster/worker.py tick)")
PT_BOUNDARY_MISMATCH = faults.declare(
    "cluster/boundary_mismatch",
    "worker reports a corrupted boundary root while its store stays "
    "correct (serve/cluster/worker.py boundary report)")

# chain-config vocabulary for assignment messages (a config object
# cannot travel as JSON); extend as workloads need them
def _config(name: str):
    from coreth_tpu import params
    table = {
        "test": params.TEST_CHAIN_CONFIG,
        "ap5": params.TEST_APRICOT_PHASE5_CONFIG,
    }
    if name not in table:
        raise protocol.ProtocolError(f"unknown chain config {name!r}")
    return table[name]


def _jsonable(obj):
    """Bytes-free copy for the control protocol (roots/hashes -> hex);
    drops values JSON cannot carry."""
    if isinstance(obj, (bytes, bytearray)):
        return obj.hex()
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


class HeartbeatSender:
    """Periodic heartbeat + checkpoint-advance emitter.

    Injectable ``clock``/``send`` keep the drop fault and the
    coordinator's timeout detection unit-testable without sockets or
    sleeps (tests/test_cluster.py).  ``progress`` returns the live
    (committed_blocks, txs) pair; ``record`` the newest durable
    checkpoint number (None while none landed).
    """

    def __init__(self, send, worker: str, lane: str, period: float,
                 progress=None, record=None,
                 clock=time.monotonic):
        self.send = send
        self.worker = worker
        self.lane = lane
        self.period = period
        self.progress = progress or (lambda: (0, 0))
        self.record = record or (lambda: None)
        self.clock = clock
        self.sent = 0
        self.dropped = 0
        self.last_record: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self) -> bool:
        """One heartbeat cycle; False when the armed loss fault ate
        the send (the worker is alive — the wire is not)."""
        advanced = self.record()
        if faults.check(PT_HEARTBEAT_LOSS) is not None:
            self.dropped += 1  # corethlint: shared tick() has one caller at a time — the loop thread in production, the test body in units; never both
            return False
        committed, txs = self.progress()
        self.send({"verb": "heartbeat", "worker": self.worker,
                   "lane": self.lane, "committed": committed,
                   "txs": txs})
        if advanced is not None and advanced != self.last_record:
            self.last_record = advanced  # corethlint: shared single tick() caller (see dropped above)
            self.send({"verb": "checkpoint_advance",
                       "worker": self.worker, "lane": self.lane,
                       "number": advanced})
        self.sent += 1  # corethlint: shared single tick() caller (see dropped above)
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.tick()
            except OSError:
                return  # coordinator gone; the main loop will notice

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="cluster-heartbeat", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class ClusterWorker:
    """The worker-side protocol loop over one coordinator socket."""

    def __init__(self, sock: socket.socket, worker_id: str):
        self.sock = sock
        self.worker_id = worker_id
        self.buf = bytearray()
        # the heartbeat thread and the main loop both write the one
        # socket; frames must not interleave
        self._send_mu = threading.Lock()
        self.heartbeat_s = float(os.environ.get(
            "CORETH_CLUSTER_HEARTBEAT_S", "0.5"))

    def send(self, msg: dict) -> None:
        with self._send_mu:
            protocol.send_msg(self.sock, msg)

    # ------------------------------------------------------------- serve
    def run(self) -> None:
        self.send({"verb": "hello", "worker": self.worker_id,
                   "pid": os.getpid()})
        while True:
            msg = protocol.recv_msg(self.sock, self.buf)
            if msg is None:
                return
            verb = msg["verb"]
            if verb == "assign":
                try:
                    self._serve_range(msg)
                except Exception as exc:  # noqa: BLE001 — the coordinator owns the failure policy; a dying worker must say why before the socket drops
                    self.send({"verb": "error",
                               "worker": self.worker_id,
                               "lane": msg.get("lane"),
                               "reason": f"{type(exc).__name__}: {exc}"})
                    raise
            elif verb == "drain":
                if msg.get("bundle"):
                    self._send_bundles(msg)
                return
            else:
                raise protocol.ProtocolError(
                    f"coordinator sent worker-only verb {verb!r}")

    def _serve_range(self, msg: dict) -> None:
        from coreth_tpu.replay.checkpoint import resume_engine
        from coreth_tpu.serve import ChainFeed, StreamingPipeline
        from coreth_tpu.types import Block
        lane, start, end = msg["lane"], msg["start"], msg["end"]
        kv, db = open_store(msg["db_dir"])
        try:
            engine_kw = msg.get("engine") or {}
            eng, ckpt = resume_engine(_config(msg.get("config",
                                                      "test")),
                                      db, kv, worker=lane, **engine_kw)
            if eng is None:
                raise RuntimeError(
                    f"lane {lane} store has no seed record")
            wire = rlp.decode(open(msg["chain"], "rb").read())
            # wire[j] is block number j+1; the lane owns (start, end]
            # and the record closes everything through ckpt.number
            rest = [Block.decode(w) for w in wire[ckpt.number:end]]
            rate = msg.get("feed_rate") or None
            pipe = StreamingPipeline(
                eng, ChainFeed(rest, rate=rate), window_wait=0.005,
                checkpoint_every=msg.get("checkpoint_every") or int(
                    os.environ.get("CORETH_CLUSTER_CHECKPOINT", "4")),
                checkpoint_worker=lane)
            hb = HeartbeatSender(
                self.send, self.worker_id, lane, self.heartbeat_s,
                progress=lambda: (pipe._committed_blocks,
                                  pipe.stats.txs),
                record=lambda: (pipe._ckpt.last_number
                                if pipe._ckpt is not None else None))
            hb.start()
            try:
                # flow id = the lane's first block: the assign arrow
                # from the coordinator continues into execution here
                with obs.span("cluster/execute", flow=start + 1,
                              lane=lane, start=start, end=end):
                    rep = pipe.run()
            finally:
                hb.stop()
            root = eng.root
            spec = faults.check(PT_BOUNDARY_MISMATCH)
            if spec is not None:
                # lie about the boundary (state on disk stays right):
                # the aggregator must catch this by verification
                root = bytes(b ^ 0xFF for b in root)
            self.send({"verb": "boundary_root",
                       "worker": self.worker_id, "lane": lane,
                       "root": root.hex(),
                       "resumed_from": ckpt.number,
                       "blocks": rep.blocks,
                       "report": _jsonable(rep.row()),
                       "metrics": _jsonable(
                           pipe._registry.snapshot()
                           if pipe._registry is not None else {})})
        finally:
            kv.close()

    def _send_bundles(self, msg: dict) -> None:
        """The root-mismatch escrow: freeze this worker's forensic
        evidence and hand the bundle paths over before exiting."""
        rec = _forensics.recorder()
        paths = []
        if rec is not None:
            _forensics.note_trigger(
                _forensics.TR_BOUNDARY,
                msg.get("reason", "coordinator demanded bundles"))
            rec.flush_pending()
            rec.drain()
            paths = [b["path"] for b in rec.snapshot()["bundles"]]
        self.send({"verb": "bundle", "worker": self.worker_id,
                   "lane": msg.get("lane"), "paths": paths})


def run_worker(host: str, port: int, worker_id: str) -> None:
    sock = socket.create_connection((host, port))
    try:
        ClusterWorker(sock, worker_id).run()
    finally:
        try:
            sock.close()
        except OSError:
            pass


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--connect", required=True,
                    help="coordinator HOST:PORT")
    ap.add_argument("--worker", required=True, help="worker id")
    args = ap.parse_args(argv)
    host, port = args.connect.rsplit(":", 1)
    run_worker(host, int(port), args.worker)


if __name__ == "__main__":
    main()
