"""Length-prefixed JSON control protocol between coordinator and
workers.

One frame = 4-byte big-endian payload length + a UTF-8 JSON object.
Every message carries a ``verb`` (the dispatch key) and, from workers,
a ``worker`` id.  The codec is split from the socket helpers so the
framing edge cases — truncation, oversized frames, unknown verbs — are
unit-testable on plain bytes (tests/test_cluster.py).

Verbs (the whole vocabulary; anything else is a protocol error):

  coordinator -> worker
    assign    {lane, start, end, db_dir, chain, ...engine/feed knobs}
    drain     {bundle: bool}  — finish up; bundle=True demands the
              worker's forensics bundles first (root-mismatch path)

  worker -> coordinator
    hello     {worker, pid}
    heartbeat {worker, lane, committed, txs}
    checkpoint_advance {worker, lane, number}   — a durable record
    boundary_root {worker, lane, root, resumed_from, report, metrics}
    bundle    {worker, lane, paths}
    error     {worker, reason}

Values that must survive JSON round-trips as bytes (roots, hashes)
travel hex-encoded; the payload stays printable and the frame length
bounds decompression-free parsing.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

_LEN = struct.Struct(">I")

# A control message is coordination metadata (ids, block numbers, hex
# roots, counter snapshots) — far below this.  Anything larger is a
# corrupt or hostile frame and must be rejected before allocation.
MAX_FRAME = 8 << 20

VERBS = frozenset({
    "assign", "drain",
    "hello", "heartbeat", "checkpoint_advance", "boundary_root",
    "bundle", "error",
})


class ProtocolError(Exception):
    """A frame that can never become a valid message (oversized,
    non-JSON, missing/unknown verb, torn mid-frame EOF)."""


def encode_frame(msg: dict) -> bytes:
    """One wire frame for ``msg``; validates the verb on the way out
    so a coordinator bug surfaces at the sender, not as a peer's
    ProtocolError."""
    verb = msg.get("verb")
    if verb not in VERBS:
        raise ProtocolError(f"unknown verb {verb!r}")
    payload = json.dumps(msg, separators=(",", ":")).encode()
    if len(payload) > MAX_FRAME:
        raise ProtocolError(f"frame too large: {len(payload)} bytes")
    return _LEN.pack(len(payload)) + payload


def decode_frame(buf: bytes) -> Tuple[Optional[dict], bytes]:
    """(message, remainder) from the head of ``buf``; (None, buf) while
    the frame is still incomplete (truncation is not an error — more
    bytes may arrive).  Raises ProtocolError for frames that can never
    become valid."""
    if len(buf) < _LEN.size:
        return None, buf
    (n,) = _LEN.unpack_from(buf)
    if n > MAX_FRAME:
        raise ProtocolError(f"frame too large: {n} bytes")
    if len(buf) < _LEN.size + n:
        return None, buf
    raw, rest = buf[_LEN.size:_LEN.size + n], buf[_LEN.size + n:]
    try:
        msg = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise ProtocolError(f"bad frame payload: {exc}") from exc
    if not isinstance(msg, dict) or msg.get("verb") not in VERBS:
        raise ProtocolError(
            f"unknown verb {msg.get('verb') if isinstance(msg, dict) else msg!r}")
    return msg, rest


def send_msg(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode_frame(msg))


def recv_msg(sock: socket.socket, buf: bytearray) -> Optional[dict]:
    """Next message from ``sock``, consuming ``buf`` (the caller-owned
    reassembly buffer) first.  None on clean EOF at a frame boundary;
    ProtocolError on EOF mid-frame (a torn peer)."""
    while True:
        msg, rest = decode_frame(bytes(buf))
        if msg is not None:
            del buf[:len(buf) - len(rest)]
            return msg
        chunk = sock.recv(65536)
        if not chunk:
            if buf:
                raise ProtocolError("EOF mid-frame")
            return None
        buf.extend(chunk)
