"""Cluster coordinator: range assignment, health, and the
root-verifying aggregator.

The coordinator owns the lane table (one contiguous block range per
lane, each with a seeded store from ``bootstrap_stores``), a pool of
worker connections, and the cluster's single source of truth about
progress.  Control flow is deliberately single-threaded: per-socket
reader threads do nothing but push decoded frames into one queue
(the blessed handoff), and the ``run`` loop is the only writer of
cluster state — assignment, health, verification, and recovery are
sequential decisions over an ordered message stream.

Verification is the aggregator's job and is structural, not trusted:
lane ``i``'s reported boundary root must equal lane ``i+1``'s seed
root (``bootstrap_stores`` recorded the whole chain), and the last
lane must land on ``expected_tip``.  A mismatch does NOT immediately
re-assign — the coordinator first demands the offending worker's
forensics bundles (``drain {bundle: true}``), records the bundle
paths as evidence, and only then returns the lane to the pending
pool.  Worker death (process exit, socket EOF) and heartbeat
silence re-assign directly: the lane's scoped checkpoint record
(``ReplayCheckpoint/<lane>`` in the lane's own store) is the recovery
horizon, so the replacement resumes exactly where the victim's last
durable record closed — the PR-10/11 record-implies-closure protocol
doing double duty as a handoff protocol.

Fault points (coreth_tpu/faults):

- ``cluster/worker_crash``: the health pass SIGKILLs the first
  running worker when armed — the injected-kill shape the handoff
  test and the bench recovery probe use.
- ``cluster/reassign_race``: fires between picking a replacement
  worker and sending the assign — the lost-assignment window; the
  coordinator counts it and re-picks on the next pass instead of
  leaving the lane orphaned.
"""

from __future__ import annotations

import os
import queue
import socket
import subprocess
import sys
import threading
import time  # noqa: DET003 — control-plane deadlines/heartbeat ages only, never consensus data
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from coreth_tpu import faults, obs
from coreth_tpu.metrics import Counter, Registry, get_or_register
from coreth_tpu.obs.server import maybe_start_from_env
from coreth_tpu.serve.cluster import protocol
from coreth_tpu.serve.cluster.bootstrap import LaneSeed

PT_WORKER_CRASH = faults.declare(
    "cluster/worker_crash",
    "coordinator health pass SIGKILLs a running worker (injected "
    "worker death; serve/cluster/coordinator.py _health_check)")
PT_REASSIGN_RACE = faults.declare(
    "cluster/reassign_race",
    "fires between picking a replacement worker and sending assign "
    "(lost-assignment window; serve/cluster/coordinator.py "
    "_assign_pending)")

_COUNTERS = (
    "cluster/assigned", "cluster/reassigned", "cluster/worker_crash",
    "cluster/heartbeat_loss", "cluster/boundary_mismatch",
    "cluster/reassign_race", "cluster/checkpoint_advance",
    "cluster/lanes_done",
)


@dataclass
class LaneState:
    """One contiguous block range and everything the aggregator knows
    about it.  ``status`` walks pending -> running -> done, detouring
    through awaiting_bundle on a root mismatch."""

    lane: str
    start: int
    end: int
    db_dir: str
    seed_root: bytes
    status: str = "pending"
    worker: Optional[str] = None
    history: List[str] = field(default_factory=list)
    resumed_from: Optional[int] = None
    last_checkpoint: Optional[int] = None
    last_heartbeat: Optional[float] = None
    committed: int = 0
    txs: int = 0
    root: Optional[bytes] = None
    report: Optional[dict] = None
    metrics: Optional[dict] = None
    failures: int = 0
    bundles: List[str] = field(default_factory=list)
    recovered_t: Optional[float] = None


class WorkerHandle:
    """One worker connection as the coordinator sees it.  ``proc`` is
    the spawned subprocess (None for fakes and externally-launched
    workers); ``closed`` flips when the reader thread sees EOF."""

    def __init__(self, conn=None, proc=None, worker_id: Optional[str] = None):
        self.id = worker_id
        self.conn = conn
        self.proc = proc
        self.lane: Optional[str] = None
        self.closed = False
        self.drained = False

    def send(self, msg: dict) -> None:
        protocol.send_msg(self.conn, msg)

    def alive(self) -> bool:
        if self.closed or self.drained:
            return False
        if self.proc is not None and self.proc.poll() is not None:
            return False
        return True

    def kill(self) -> None:
        if self.proc is not None:
            try:
                self.proc.kill()
            except OSError:
                pass
        self.closed = True


def plan_reassignments(dead_lanes: List[LaneState],
                       idle_workers: List[WorkerHandle]
                       ) -> List[Tuple[LaneState, WorkerHandle]]:
    """Deterministic pairing for a recovery epoch: lanes ordered by
    range start meet workers ordered by id, one lane per worker.
    Leftover lanes wait for the next pass — double-booking a worker
    would serialize on its socket anyway and muddy the lane/worker
    ownership the health pass depends on."""
    lanes = sorted(dead_lanes, key=lambda l: l.start)
    workers = sorted(idle_workers, key=lambda w: w.id or "")
    return list(zip(lanes, workers))


def _default_spawn(worker_id: str, host: str, port: int,
                   extra_env: Optional[dict] = None):
    env = dict(os.environ)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "coreth_tpu.serve.cluster.worker",
         "--connect", f"{host}:{port}", "--worker", worker_id],
        env=env)


class ClusterCoordinator:
    """Assignment + health + aggregation over one lane table.

    ``spawn``/``clock`` are injectable so the timeout and
    re-assignment policies are unit-testable with fake handles and a
    stepped clock (tests/test_cluster.py); production uses subprocess
    workers dialing back over loopback.
    """

    def __init__(self, seeds: List[LaneSeed], chain_path: str,
                 config: str = "test",
                 expected_tip: Optional[bytes] = None,
                 engine_kw: Optional[dict] = None,
                 feed_rate: Optional[float] = None,
                 checkpoint_every: Optional[int] = None,
                 heartbeat_timeout: Optional[float] = None,
                 max_failures: int = 3,
                 spawn: Optional[Callable] = None,
                 worker_env: Optional[Dict[str, dict]] = None,
                 clock=time.monotonic,
                 registry: Optional[Registry] = None):
        ordered = sorted(seeds, key=lambda s: s.start)
        self.lanes: Dict[str, LaneState] = {
            s.lane: LaneState(lane=s.lane, start=s.start, end=s.end,
                              db_dir=s.db_dir, seed_root=s.root)
            for s in ordered}
        # the verification chain: lane i must finish on lane i+1's
        # seed root; the tail is pinned by expected_tip when given
        self._expected: Dict[str, bytes] = {}
        for a, b in zip(ordered, ordered[1:]):
            self._expected[a.lane] = b.root
        if expected_tip is not None:
            self._expected[ordered[-1].lane] = expected_tip
        self.chain_path = chain_path
        self.config = config
        self.engine_kw = engine_kw or {}
        self.feed_rate = feed_rate
        self.checkpoint_every = checkpoint_every
        self.heartbeat_timeout = heartbeat_timeout if heartbeat_timeout \
            is not None else float(os.environ.get(
                "CORETH_CLUSTER_HEARTBEAT_TIMEOUT_S", "5"))
        self.max_failures = max_failures
        self._spawn = spawn or _default_spawn
        self._worker_env = worker_env or {}
        self._clock = clock
        self._registry = registry if registry is not None else Registry()
        self._ctr = {name: get_or_register(name, Counter,
                                           self._registry)
                     for name in _COUNTERS}
        self.workers: Dict[str, WorkerHandle] = {}
        self._procs: Dict[str, object] = {}
        self._msgs: "queue.Queue" = queue.Queue()
        # the run loop is the only state writer; the lock exists for
        # the telemetry report thread reading a consistent view
        self._mu = threading.Lock()
        self.events: List[dict] = []
        self._expect_workers = 0
        self._t0: Optional[float] = None
        self._listener: Optional[socket.socket] = None
        self._telemetry = None
        self.port: Optional[int] = None

    # --------------------------------------------------------- lifecycle
    def start(self, n_workers: Optional[int] = None) -> int:
        """Listen, spawn the worker pool, return the control port.
        Registration completes when each worker's hello arrives in the
        run loop — assignment never races the handshake."""
        n = n_workers if n_workers is not None else int(
            os.environ.get("CORETH_CLUSTER_WORKERS", "2"))
        self._expect_workers = n
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.bind(("127.0.0.1", int(
            os.environ.get("CORETH_CLUSTER_PORT", "0"))))
        self._listener.listen(max(n, 1))
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop,
                         name="cluster-accept", daemon=True).start()
        for i in range(n):
            wid = f"w{i}"
            # "*" env applies to every worker; per-id entries layer on
            # top (the handoff test arms a fault plan in ONE victim)
            env = dict(self._worker_env.get("*", {}))
            env.update(self._worker_env.get(wid, {}))
            proc = self._spawn(wid, "127.0.0.1", self.port,
                               env or None)
            if proc is not None:
                self._procs[wid] = proc
        return self.port

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            handle = WorkerHandle(conn=conn)
            threading.Thread(target=self._reader, args=(handle,),
                             name="cluster-reader",
                             daemon=True).start()

    def _reader(self, handle: WorkerHandle) -> None:
        buf = bytearray()
        try:
            while True:
                msg = protocol.recv_msg(handle.conn, buf)
                if msg is None:
                    break
                self._msgs.put((handle, msg))
        except (protocol.ProtocolError, OSError):
            pass
        self._msgs.put((handle, None))  # EOF sentinel

    # --------------------------------------------------------- main loop
    def run(self, deadline_s: Optional[float] = None) -> dict:
        """Drive the cluster to completion; returns :meth:`summary`.
        Raises TimeoutError past the deadline and RuntimeError when a
        lane burns through ``max_failures`` recoveries."""
        deadline_s = deadline_s if deadline_s is not None else float(
            os.environ.get("CORETH_CLUSTER_DEADLINE_S", "300"))
        self._t0 = self._clock()
        self._telemetry = maybe_start_from_env(
            registry=self._registry, report=self._cluster_report)
        try:
            while not self._done():
                if self._clock() - self._t0 > deadline_s:
                    raise TimeoutError(
                        f"cluster missed deadline {deadline_s}s: "
                        f"{self._status_line()}")
                self._drain_messages()
                self._assign_pending()
                self._health_check()
        finally:
            self._shutdown()
        return self.summary()

    def _done(self) -> bool:
        return all(l.status == "done" for l in self.lanes.values())

    def _status_line(self) -> str:
        return " ".join(f"{l.lane}={l.status}"
                        for l in self.lanes.values())

    # --------------------------------------------------------- messages
    def _drain_messages(self, timeout: float = 0.05) -> None:
        try:
            handle, msg = self._msgs.get(timeout=timeout)
        except queue.Empty:
            return
        while True:
            self._dispatch(handle, msg)
            try:
                handle, msg = self._msgs.get_nowait()
            except queue.Empty:
                return

    def _dispatch(self, handle: WorkerHandle,
                  msg: Optional[dict]) -> None:
        if msg is None:
            handle.closed = True  # EOF; the health pass decides
            return
        verb = msg["verb"]
        if verb == "hello":
            wid = msg["worker"]
            handle.id = wid
            handle.proc = self._procs.get(wid, handle.proc)
            with self._mu:
                self.workers[wid] = handle
            return
        lane = self.lanes.get(msg.get("lane") or "")
        if verb == "heartbeat" and lane is not None:
            with self._mu:
                lane.last_heartbeat = self._clock()
                lane.committed = msg.get("committed", 0)
                lane.txs = msg.get("txs", 0)
                if (len(lane.history) > 1 and lane.recovered_t is None
                        and lane.committed > 0):
                    # first post-recovery progress: the bench's
                    # recovery-time probe reads this event
                    lane.recovered_t = self._clock() - self._t0
                    self.events.append({
                        "event": "first_commit_after_recovery",
                        "lane": lane.lane, "t": lane.recovered_t})
        elif verb == "checkpoint_advance" and lane is not None:
            with self._mu:
                lane.last_checkpoint = msg["number"]
            self._ctr["cluster/checkpoint_advance"].inc()
        elif verb == "boundary_root" and lane is not None:
            self._on_boundary(handle, lane, msg)
        elif verb == "bundle" and lane is not None:
            with self._mu:
                lane.bundles.extend(msg.get("paths") or [])
                lane.status = "pending"
                lane.worker = None
            self.events.append({"event": "bundle_received",
                                "lane": lane.lane,
                                "worker": msg.get("worker"),
                                "paths": msg.get("paths") or [],
                                "t": self._now()})
        elif verb == "error":
            self.events.append({"event": "worker_error",
                                "worker": msg.get("worker"),
                                "lane": msg.get("lane"),
                                "reason": msg.get("reason"),
                                "t": self._now()})

    def _on_boundary(self, handle: WorkerHandle, lane: LaneState,
                     msg: dict) -> None:
        root = bytes.fromhex(msg["root"])
        want = self._expected.get(lane.lane)
        with self._mu:
            lane.resumed_from = msg.get("resumed_from")
            lane.report = msg.get("report")
            lane.metrics = msg.get("metrics")
        if want is not None and root != want:
            self._ctr["cluster/boundary_mismatch"].inc()
            self.events.append({"event": "boundary_mismatch",
                                "lane": lane.lane, "worker": handle.id,
                                "got": root.hex(), "want": want.hex(),
                                "t": self._now()})
            with self._mu:
                lane.failures += 1
                lane.status = "awaiting_bundle"
            # evidence before recovery: the worker must surrender its
            # forensics bundles, then drain (it exits; a mismatching
            # worker never gets another lane)
            handle.drained = True
            handle.lane = None
            try:
                handle.send({"verb": "drain", "bundle": True,
                             "lane": lane.lane,
                             "reason": f"boundary mismatch on "
                                       f"{lane.lane}: got "
                                       f"{root.hex()[:16]}.. want "
                                       f"{want.hex()[:16]}.."})
            except OSError:
                # worker already gone; recover without the evidence
                with self._mu:
                    lane.status = "pending"
                    lane.worker = None
            return
        with self._mu:
            lane.root = root
            lane.status = "done"
            lane.worker = None
            # the boundary report is the authoritative final count —
            # a short lane can finish between heartbeat ticks, leaving
            # the heartbeat-fed fields at zero
            lane.committed = msg.get("blocks", lane.committed)
            rep = msg.get("report") or {}
            lane.txs = rep.get("txs", lane.txs)
        if len(lane.history) > 1 and lane.recovered_t is None:
            # a completed lane certainly made its first post-recovery
            # commit; don't let a sub-heartbeat-period remainder hide
            # the event the bench recovery probe reads
            lane.recovered_t = self._now()
            self.events.append({"event": "first_commit_after_recovery",
                                "lane": lane.lane,
                                "t": lane.recovered_t})
        handle.lane = None
        self._ctr["cluster/lanes_done"].inc()

    # --------------------------------------------------------- policies
    def _assign_pending(self) -> None:
        pending = [l for l in self.lanes.values()
                   if l.status == "pending"]
        if not pending:
            return
        if (len(self.workers) < self._expect_workers
                and not any(l.history for l in self.lanes.values())
                and self._now() < self.heartbeat_timeout):
            # hold the FIRST epoch until the whole spawned pool has
            # said hello (bounded by the heartbeat grace): assignment
            # is then a deterministic lanes-by-start x workers-by-id
            # pairing instead of a hello race.  Recovery epochs never
            # wait — a shrunken pool is exactly when re-assignment
            # must go to whoever is left
            return
        hopeless = [l for l in pending
                    if l.failures > self.max_failures]
        if hopeless:
            raise RuntimeError(
                f"lane {hopeless[0].lane} failed "
                f"{hopeless[0].failures} times; halting cluster")
        idle = [w for w in self.workers.values()
                if w.lane is None and w.alive()]
        for lane, worker in plan_reassignments(pending, idle):
            # the lost-assignment window: a crash here must not
            # orphan the lane
            try:
                faults.fire(PT_REASSIGN_RACE)
            except faults.FaultInjected:
                self._ctr["cluster/reassign_race"].inc()
                self.events.append({"event": "reassign_race",
                                    "lane": lane.lane,
                                    "t": self._now()})
                continue  # re-pick next pass
            self._send_assign(lane, worker)

    def _send_assign(self, lane: LaneState,
                     worker: WorkerHandle) -> None:
        with obs.span("cluster/assign", flow=lane.start + 1,
                      lane=lane.lane, worker=worker.id):
            worker.send({
                "verb": "assign", "lane": lane.lane,
                "start": lane.start, "end": lane.end,
                "db_dir": lane.db_dir, "chain": self.chain_path,
                "config": self.config, "engine": self.engine_kw,
                "feed_rate": self.feed_rate,
                "checkpoint_every": self.checkpoint_every,
            })
        with self._mu:
            lane.status = "running"
            lane.worker = worker.id
            lane.history.append(worker.id)
            # the heartbeat grace period starts at assignment, not at
            # the worker's first tick — resume + chain decode take time
            lane.last_heartbeat = self._clock()
        worker.lane = lane.lane
        self._ctr["cluster/assigned"].inc()
        if len(lane.history) > 1:
            self._ctr["cluster/reassigned"].inc()
            self.events.append({"event": "reassigned",
                                "lane": lane.lane,
                                "worker": worker.id,
                                "resume_floor": lane.last_checkpoint,
                                "t": self._now()})

    def _health_check(self) -> None:
        spec = faults.check(PT_WORKER_CRASH)
        if spec is not None:
            running = sorted((w for w in self.workers.values()
                              if w.lane is not None and w.alive()),
                             key=lambda w: w.id or "")
            if running:
                victim = running[0]
                self.events.append({"event": "injected_kill",
                                    "worker": victim.id,
                                    "lane": victim.lane,
                                    "t": self._now()})
                victim.kill()
        now = self._clock()
        for worker in sorted(self.workers.values(),
                             key=lambda w: w.id or ""):
            if worker.lane is None:
                continue
            lane = self.lanes[worker.lane]
            if not worker.alive():
                self._ctr["cluster/worker_crash"].inc()
                self.events.append({"event": "worker_crash",
                                    "worker": worker.id,
                                    "lane": lane.lane,
                                    "resume_floor": lane.last_checkpoint,
                                    "t": self._now()})
                self._recover(lane, worker)
            elif (lane.last_heartbeat is not None
                  and now - lane.last_heartbeat
                  > self.heartbeat_timeout):
                self._ctr["cluster/heartbeat_loss"].inc()
                self.events.append({"event": "heartbeat_loss",
                                    "worker": worker.id,
                                    "lane": lane.lane,
                                    "silent_s": now - lane.last_heartbeat,
                                    "t": self._now()})
                worker.kill()  # fence the silent worker before reassigning its lane
                self._recover(lane, worker)

    def _recover(self, lane: LaneState, worker: WorkerHandle) -> None:
        """Return a dead worker's lane to the pending pool.  The
        lane's scoped checkpoint record in its own store IS the
        handoff state — nothing to copy, the next assignee resumes
        from it."""
        worker.lane = None
        with self._mu:
            self.workers.pop(worker.id, None)
            lane.failures += 1
            lane.status = "pending"
            lane.worker = None

    # --------------------------------------------------------- shutdown
    def _shutdown(self) -> None:
        for worker in list(self.workers.values()):
            if worker.alive():
                try:
                    worker.send({"verb": "drain", "bundle": False})
                except OSError:
                    pass
        for proc in self._procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — best-effort teardown; a wedged worker must not hang the coordinator
                try:
                    proc.kill()
                except OSError:
                    pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for worker in self.workers.values():
            if worker.conn is not None:
                try:
                    worker.conn.close()
                except OSError:
                    pass
        if self._telemetry is not None:
            self._telemetry.stop()

    # --------------------------------------------------------- reporting
    def _now(self) -> float:
        return (self._clock() - self._t0) if self._t0 is not None \
            else 0.0

    def _cluster_report(self) -> dict:  # corethlint: thread telemetry-report
        """Federated /report: the cluster view plus every lane's own
        StreamReport row (stage breakdowns intact)."""
        with self._mu:
            return self.summary()

    def summary(self) -> dict:
        lanes = sorted(self.lanes.values(), key=lambda l: l.start)
        verified = all(
            l.status == "done"
            and (self._expected.get(l.lane) is None
                 or l.root == self._expected[l.lane])
            for l in lanes)
        return {
            "lanes": [{
                "lane": l.lane, "start": l.start, "end": l.end,
                "status": l.status, "worker": l.worker,
                "history": list(l.history),
                "resumed_from": l.resumed_from,
                "last_checkpoint": l.last_checkpoint,
                "committed": l.committed, "txs": l.txs,
                "failures": l.failures,
                "root": l.root.hex() if l.root else None,
                "seed_root": l.seed_root.hex(),
                "bundles": list(l.bundles),
                "report": l.report, "metrics": l.metrics,
            } for l in lanes],
            "verified": verified,
            "final_root": lanes[-1].root.hex()
            if lanes and lanes[-1].root else None,
            "blocks": sum(l.committed for l in lanes),
            "txs": sum(l.txs for l in lanes),
            "events": list(self.events),
            "counters": self._registry.snapshot(),
            "wall_s": self._now(),
        }
