"""corethcluster: multi-process sharded serving over the streaming
pipeline.

A coordinator range-partitions the chain feed across worker
subprocesses (each running the existing ``StreamingPipeline`` over a
contiguous block range from its own seeded store), federates their
``/report``/``/metrics`` into one cluster view, verifies every
boundary root against the successor lane's seed root, and — on worker
death or a root mismatch — re-assigns the failed range to a healthy
worker resuming from the lane's last ``ReplayCheckpoint/<lane>``
record.  See README "Distributed serving".
"""

from coreth_tpu.serve.cluster.bootstrap import (
    LaneSeed, bootstrap_stores, open_store, partition_ranges,
    write_seed_record,
)
from coreth_tpu.serve.cluster.coordinator import (
    ClusterCoordinator, LaneState, WorkerHandle, plan_reassignments,
)
from coreth_tpu.serve.cluster.protocol import (
    MAX_FRAME, ProtocolError, VERBS, decode_frame, encode_frame,
    recv_msg, send_msg,
)

# NOTE: coreth_tpu.serve.cluster.worker is deliberately NOT imported
# here — it is the `python -m` entry point workers run under, and
# importing it from the package __init__ would double-execute it
# through runpy.  Import it directly where needed.

__all__ = [
    "LaneSeed", "bootstrap_stores", "open_store", "partition_ranges",
    "write_seed_record",
    "ClusterCoordinator", "LaneState", "WorkerHandle",
    "plan_reassignments",
    "MAX_FRAME", "ProtocolError", "VERBS", "decode_frame",
    "encode_frame", "recv_msg", "send_msg",
]
