"""Prefetch stage: resolve window N+1's inputs while window N executes.

Two prefetch channels, both measured (the acceptance counter for the
streaming bench is "prefetch-hit or overlap counter > 0"):

- **Sender recovery** (host, GIL-releasing): arriving blocks are
  batched through the engine's packed ECDSA recovery
  (``ReplayEngine.warm_senders`` — native C++ batch or the device
  ladder) on the prefetch thread, so by the time the execute stage
  classifies a block its senders are already cached.  ``sigs`` counts
  signatures recovered here; the pipeline's ``prefetch_hits`` counts
  the txs whose sender the execute stage found pre-cached.  The
  device/mesh-sharded ladder is no longer serve-only: batch replay's
  ``_SenderPipeline`` honors the same ``CORETH_SHARD_RECOVER`` opt-in
  and overlaps a window's recovery with the previous window's
  execution (replay/engine.py).

- **Bytecode** : call-shaped txs touch ``db.contract_code`` for their
  callee's code hash so the machine classifier's first read hits the
  rawdb dict instead of a cold path.  Account/slot resolution itself
  stays on the execute thread — it reads and extends the engine's trie
  and DeviceState mirrors, which the commit stage mutates; the third
  prefetch channel (the *fetch-tensor* download of an issued window)
  therefore lives in the engine: ``_issue_window`` starts the
  device->host copy of the window's fetch tensor asynchronously at
  issue time (``ReplayStats.reads_prefetched``), converting the old
  blocking per-window download into a windowed read that overlaps the
  next window's host work.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List

from coreth_tpu import obs
from coreth_tpu.types import Block


class Prefetcher:
    """Stage worker: warms a chunk of blocks for the execute stage."""

    def __init__(self, engine):
        self.e = engine
        # counters land from the prefetch stage thread while the
        # pipeline report reads them — writes hold _mu
        self._mu = threading.Lock()
        self.sigs = 0
        self.shard_sigs = 0   # recovered via the mesh-sharded ladder
        self.code_touches = 0
        self.busy_s = 0.0

    def warm(self, blocks: List[Block]) -> None:
        t0 = time.monotonic()
        with obs.span("serve/prefetch_warm", blocks=len(blocks)):
            todo = sum(1 for b in blocks for tx in b.transactions
                       if tx.cached_sender() is None)
            if todo:
                if not self._shard_recover(blocks):
                    self.e.warm_senders(blocks)
                with self._mu:
                    self.sigs += todo
            self._touch_code(blocks)
        dt = time.monotonic() - t0
        with self._mu:
            self.busy_s += dt

    def _shard_recover(self, blocks: List[Block]) -> bool:
        """CORETH_SHARD_RECOVER=1 + a dp mesh: recover this chunk's
        senders on the device-sharded ECDSA ladder (parallel/mesh.py
        sharded_recover — the signature batch fans out across shards)
        instead of the native host batch.  Falls back (returns False)
        whenever the mesh path cannot serve the batch, so recovery
        semantics never change — only the engine doing the work.
        Parity with the native path is pinned by tests/test_shard_replay."""
        if not bool(int(os.environ.get("CORETH_SHARD_RECOVER", "0"))):
            return False
        e = self.e
        # _recover_kernel owns the eligibility rule (mesh present,
        # pad-floor divisibility): None means no sharded ladder
        kernel = e._recover_kernel() if hasattr(e, "_recover_kernel") \
            else None
        if kernel is None:
            return False
        t0 = time.monotonic()
        try:
            todo, hashes, rs, ss, recids = e._pack_sigs(blocks)
            if not todo:
                return True
            from coreth_tpu.crypto.secp_device import (
                complete_recover, issue_recover)
            ctxs = issue_recover(hashes, rs, ss, recids, kernel=kernel)
            out, ok = complete_recover(ctxs)
            if out is None:
                return False
            e._apply_recovered(todo, out, ok)
            with self._mu:
                self.shard_sigs += len(todo)
            return True
        except Exception:  # noqa: BLE001 — advisory: host path recovers
            return False
        finally:
            # keep the engine's phase attribution honest: this IS
            # sender-recovery time, same as warm_senders accounts it
            e.stats.t_sender += time.monotonic() - t0

    def _touch_code(self, blocks: List[Block]) -> None:
        """Pull callee bytecode for call-shaped txs into the rawdb read
        path.  Reads only: the engine's account index/trie belong to
        the execute thread, so resolution goes through the already-
        known DeviceState rows and skips anything not yet indexed."""
        e = self.e
        state = e.state
        for b in blocks:
            for tx in b.transactions:
                if tx.to is None or not tx.data:
                    continue
                idx = state.index.get(tx.to)
                if idx is None or not state.has_code[idx]:
                    continue
                try:
                    e.db.contract_code(state.code_hashes[idx])
                    with self._mu:
                        self.code_touches += 1
                except Exception:  # noqa: BLE001 — prefetch is advisory
                    pass
