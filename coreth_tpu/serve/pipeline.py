"""Bounded-queue streaming pipeline: feed -> prefetch -> execute -> commit.

Stage model
-----------

- **feed** (thread): pulls blocks from the :class:`BlockFeed`, stamps
  the enqueue time, and blocks on the bounded feed queue when the
  pipeline is behind — backpressure propagates all the way to the
  source instead of buffering unboundedly.
- **prefetch** (thread): drains the feed queue in window-sized chunks,
  warms them (serve/prefetch.py — batched sender recovery + bytecode
  touches), and blocks on the bounded execute queue.
- **execute** (the ``run()`` caller's thread): the streaming analog of
  ``ReplayEngine.replay`` — classify arriving blocks into transfer
  windows, issue window N+1's device dispatch BEFORE validating window
  N (cross-window speculation survives streaming), route
  unclassifiable runs through ``_machine_run`` (fused OCC windows /
  host fallback), and rewind exactly like batch replay when a window
  fails validation.  Runs on the caller's thread because every engine
  structure it touches (tries, DeviceState mirrors, commit staging) is
  single-owner by design.
- **commit**: the engine's window-batched CommitPipeline, wrapped so
  every ``flush()`` is timed (and can be fault-injected slow in
  tests).  Commit work is interleaved on the execute thread AFTER the
  next window's dispatch is in flight — the host/device overlap the
  batch engine already proves — so a slow commit stage stretches the
  execute stage, the bounded queues fill, and the feed blocks: latency
  degrades measurably, queues stay bounded.

Every block's enqueue->committed latency lands in a
:class:`~coreth_tpu.metrics.Histogram` (p50/p99/max), and the report
carries sustained txs/s over the wall of the run — the SLO surface the
bench's streaming section publishes.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from coreth_tpu import faults, obs
from coreth_tpu.obs import recorder as forensics
from coreth_tpu.metrics import Counter, Gauge, Histogram, Meter, \
    get_or_register
from coreth_tpu.serve.feed import BlockFeed, FeedExhausted
from coreth_tpu.serve.prefetch import Prefetcher
from coreth_tpu.types import Block

# Injection points on the serve boundary (coreth_tpu/faults):
PT_FEED_STALL = faults.declare(
    "serve/feed_stall", "feed delivers nothing for a while (stall)")
PT_FEED_DROP = faults.declare(
    "serve/feed_drop", "feed silently loses a block (sequence gap)")
PT_MALFORMED = faults.declare(
    "serve/malformed_block",
    "a block arrives corrupted (header fields lie about the body)")
PT_CRASH = faults.declare(
    "serve/crash",
    "process dies (SIGKILL) after the Nth committed block")


def _corrupt_block(b: Block) -> Block:
    """The malformed-block injection: a wire-roundtripped copy whose
    receipt_hash lies — execution still succeeds, every backend's
    validation fails, which is exactly the poison-block shape the
    quarantine must absorb without stalling later blocks."""
    bad = Block.decode(b.encode())
    bad.header.receipt_hash = b"\xde\xad\xbe\xef" * 8
    return bad


@dataclass
class _Item:
    block: Block
    t_enqueue: float
    # per-block trace context (obs.BlockTrace; None when tracing off):
    # rides the block through every stage, so the committed report can
    # attribute its enqueue->committed latency stage by stage
    bt: object = None


@dataclass
class StreamReport:
    """One streaming run's SLO surface (bench JSON shape)."""
    blocks: int = 0
    txs: int = 0
    wall_s: float = 0.0
    sustained_txs_s: float = 0.0
    latency_ms: dict = field(default_factory=dict)   # p50/p99/max
    prefetch: dict = field(default_factory=dict)
    queues: dict = field(default_factory=dict)
    stages_s: dict = field(default_factory=dict)
    backpressure: dict = field(default_factory=dict)
    feed_stalls: int = 0
    feed_drops: int = 0
    shutdown: bool = False
    # fault-tolerance surface: blocks applied-but-unverified (poison
    # parked without wedging the queue), the supervisor's ladder
    # counters, checkpoint cadence, armed-plan firing counts, and the
    # reason the stream halted early (None = ran to exhaustion)
    quarantined: List[dict] = field(default_factory=list)
    supervisor: dict = field(default_factory=dict)
    checkpoint: dict = field(default_factory=dict)
    faults: dict = field(default_factory=dict)
    halted: Optional[str] = None
    # flat-state layer surface (state/flat): read hit/miss counters,
    # generation/rollback counts (empty when CORETH_FLAT=0)
    flat: dict = field(default_factory=dict)
    # per-stage SHARE of total enqueue->committed time across every
    # committed block (obs tracer; {} when CORETH_TRACE=0): queue_feed
    # / prefetch / queue_exec / execute / commit sum to ~1.0
    stage_breakdown: dict = field(default_factory=dict)
    # divergence-forensics surface (obs/recorder, CORETH_FORENSICS=1):
    # bundle write/failure counts, ring occupancy, and the written
    # bundle paths; quarantined entries above also gain a "bundle"
    # path.  {} when the recorder is off.
    forensics: dict = field(default_factory=dict)

    def row(self) -> dict:
        return dict(self.__dict__)


class StreamingPipeline:
    """Drive one engine from one feed until exhaustion or shutdown.

    ``depth`` bounds each inter-stage queue in blocks (default 2x the
    engine window): total in-flight work is capped at ~2*depth +
    2*window blocks no matter how far ahead the feed could run.
    ``window_wait`` is how long the execute stage waits to top up a
    partial window before running it — the latency/throughput knob
    (holding blocks hostage for a full window would trade p50 for
    batch efficiency).  ``commit_delay`` injects a per-flush stall
    (fault-injection tests only).
    """

    def __init__(self, engine, feed: BlockFeed,
                 depth: Optional[int] = None,
                 window_wait: float = 0.01,
                 commit_delay: float = 0.0,
                 registry=None,
                 quarantine: bool = True,
                 quarantine_limit: int = 8,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_worker: Optional[str] = None):
        faults.arm_from_env()  # CORETH_FAULT_PLAN (idempotent)
        obs.arm_from_env()     # CORETH_TRACE=1 (idempotent)
        forensics.arm_from_env()  # CORETH_FORENSICS=1 (idempotent)
        self.engine = engine
        self.feed = feed
        self.depth = depth or 2 * engine.window
        self.window_wait = window_wait
        self.commit_delay = commit_delay
        # serving must not wedge: a poison block (fails every backend)
        # is applied tolerantly + parked in the report by default;
        # quarantine=False restores batch replay's strict raise
        self.quarantine = quarantine
        self.quarantine_limit = quarantine_limit
        self._quar_streak = 0
        # crash-consistent checkpoints (replay/checkpoint.py) every N
        # committed blocks; default from CORETH_CHECKPOINT, active
        # only when the engine's Database is disk-backed (rawdb
        # PersistentNodeDict exposes its kv)
        if checkpoint_every is None:
            checkpoint_every = int(os.environ.get("CORETH_CHECKPOINT",
                                                  "0"))
        self._ckpt = None
        ckpt_kv = getattr(engine.db.node_db, "kv", None)
        if checkpoint_every > 0 and ckpt_kv is not None:
            from coreth_tpu.replay.checkpoint import CheckpointManager
            # checkpoint_worker scopes the record key to a cluster
            # lane (serve/cluster): N lanes checkpoint without
            # clobbering, and a replacement worker resumes by lane id
            self._ckpt = CheckpointManager(engine, ckpt_kv,
                                           checkpoint_every,
                                           worker=checkpoint_worker)
        self._expect_number: Optional[int] = None
        self._q_feed: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._q_exec: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._feed_done = threading.Event()
        self._pre_done = threading.Event()
        self._shutdown_called = False
        self.prefetcher = Prefetcher(engine)
        self.stats = StreamReport()
        self._latency = Histogram(window=4096)
        self._tx_meter = Meter()
        self._registry = registry
        # progress-stat lock: feed/prefetch threads and the commit path
        # all mutate the inflight accounting, and the live telemetry
        # report reads it mid-run
        self._mu = threading.Lock()
        self._enqueued = 0
        self._committed_blocks = 0
        self._max_inflight = 0
        self._t_first_enqueue: Optional[float] = None
        self._t_last_commit: Optional[float] = None
        self._feed_blocked_s = 0.0
        self._prefetch_blocked_s = 0.0
        self._t_commit = 0.0
        # commit time already attributed to committed blocks' traces
        # (the delta since the last _mark_committed amortizes over
        # that batch of blocks)
        self._t_commit_attr = 0.0
        self._commit_flushes = 0
        # live telemetry endpoint (obs/server.py): started by run()
        # when CORETH_TELEMETRY_PORT is set, stopped in its finally
        self._telemetry = None
        # THIS run's stage-attribution sink (lazily created when
        # tracing is on): per-pipeline, so concurrent or back-to-back
        # runs sharing the process-global tracer never blend
        self._stages = None
        self._prefetch_hits = 0
        self._errors: List[BaseException] = []
        # quarantined Block objects, parallel to stats.quarantined
        # (rollback_quarantined needs the block itself back)
        self._quarantined_blocks: List[Block] = []

    # ------------------------------------------------------- queue helpers
    def _put(self, q: "queue.Queue", item) -> float:
        """Stop-aware bounded put; returns seconds spent blocked.
        Returns -1 if the pipeline stopped before the item fit (the
        item is dropped — mid-stream shutdown sheds un-entered work)."""
        t0 = time.monotonic()
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return time.monotonic() - t0
            except queue.Full:
                continue
        return -1.0

    # ------------------------------------------------------------ stages
    def _feed_loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    b = self.feed.next_block(timeout=0.05)
                except FeedExhausted:
                    break
                if b is None:
                    self.stats.feed_stalls += 1
                    continue
                # injected feed faults: a stall delays the block, a
                # drop loses it (the execute stage detects the gap),
                # a malformed block arrives corrupted (quarantine)
                if faults.fire(PT_FEED_STALL) is not None:
                    self.stats.feed_stalls += 1
                if faults.check(PT_FEED_DROP) is not None:
                    self.stats.feed_drops += 1
                    get_or_register("serve/feed_drops", Counter,
                                    self._registry).inc()
                    continue
                if faults.check(PT_MALFORMED) is not None:
                    b = _corrupt_block(b)
                it = _Item(block=b, t_enqueue=time.monotonic())
                # trace context rides the block from here to commit,
                # folding into THIS run's stage sink (one-None-check
                # no-op when tracing is off)
                if obs.enabled():
                    with self._mu:
                        if self._stages is None:
                            self._stages = obs.StageAccumulator()
                    it.bt = obs.block_begin(b.number, it.t_enqueue,
                                            sink=self._stages)
                with self._mu:
                    if self._t_first_enqueue is None:
                        self._t_first_enqueue = it.t_enqueue
                # the bounded put IS the backpressure: when the
                # pipeline is behind, the feed parks here and the
                # source (paced chain / mempool builder) stops draining
                blocked = self._put(self._q_feed, it)
                if blocked < 0:
                    break
                with self._mu:
                    self._feed_blocked_s += blocked
                    self._enqueued += 1
                    inflight = self._enqueued - self._committed_blocks
                    if inflight > self._max_inflight:
                        self._max_inflight = inflight
        except BaseException as exc:  # noqa: BLE001 — surfaced by run()
            self._errors.append(exc)
            self._stop.set()
        finally:
            self._feed_done.set()

    def _prefetch_loop(self) -> None:
        window = self.engine.window
        try:
            while True:
                chunk: List[_Item] = []
                try:
                    chunk.append(self._q_feed.get(timeout=0.05))
                except queue.Empty:
                    if self._feed_done.is_set() and self._q_feed.empty():
                        break
                    if self._stop.is_set():
                        break
                    continue
                while len(chunk) < window:
                    try:
                        chunk.append(self._q_feed.get_nowait())
                    except queue.Empty:
                        break
                t_pf = time.monotonic()
                self.prefetcher.warm([c.block for c in chunk])
                if obs.enabled():
                    # chunk warm cost amortizes per block; t_pf marks
                    # the end of each block's feed-queue wait
                    share = (time.monotonic() - t_pf) / len(chunk)
                    for c in chunk:
                        if c.bt is not None:
                            c.bt.prefetched(t_pf, share)
                for c in chunk:
                    blocked = self._put(self._q_exec, c)
                    if blocked < 0:
                        return
                    with self._mu:
                        self._prefetch_blocked_s += blocked
        except BaseException as exc:  # noqa: BLE001 — surfaced by run()
            self._errors.append(exc)
            self._stop.set()
        finally:
            self._pre_done.set()

    # ----------------------------------------------------------- commit
    def _wrap_commit(self):
        """Time (and optionally fault-inject) every commit flush."""
        pipe = self.engine.commit_pipe
        orig = pipe.flush

        def timed_flush():
            t0 = time.monotonic()
            if self.commit_delay:
                time.sleep(self.commit_delay)
            out = orig()
            self._t_commit += time.monotonic() - t0
            self._commit_flushes += 1
            return out

        pipe.flush = timed_flush
        return lambda: setattr(pipe, "flush", orig)

    def _mark_committed(self, items: List[_Item]) -> None:
        now = time.monotonic()
        if items and obs.enabled():
            # the commit-flush time since the last committed batch
            # belongs to exactly these blocks' windows; amortize it
            # per block so each trace's stage sum stays exact
            delta = self._t_commit - self._t_commit_attr
            self._t_commit_attr = self._t_commit
            share = delta / len(items)
            for it in items:
                if it.bt is not None:
                    it.bt.finish(now, commit_s=share)
        for it in items:
            self._latency.update(now - it.t_enqueue)
            self._tx_meter.mark(len(it.block.transactions))
            self.stats.txs += len(it.block.transactions)
            # the SIGKILL seam: an armed plan kills the process after
            # the Nth committed block — mid-stream, past a checkpoint
            # boundary — to prove the resume path (crash-consistency
            # tests; a no-op lookup otherwise)
            faults.fire(PT_CRASH)
        self.stats.blocks += len(items)
        with self._mu:
            self._committed_blocks += len(items)
        if items:
            self._t_last_commit = now
            # any clean commit breaks a quarantine streak — the limit
            # counts CONSECUTIVE quarantined blocks, so _try_quarantine
            # re-increments right after its own call here
            self._quar_streak = 0
            if self._ckpt is not None:
                self._ckpt.on_committed(len(items))

    # ------------------------------------------------- fault handling
    def _halt(self, reason: str) -> None:
        """Stop the stream cleanly with the reason in the report: the
        committed prefix stays durable (and checkpointed), run()
        returns its report instead of wedging or crashing."""
        if self.stats.halted is None:
            self.stats.halted = reason
        self._stop.set()

    def _try_quarantine(self, it: _Item, exc: BaseException) -> bool:
        """A block failed validation on every backend: apply it
        tolerantly (engine.quarantine_block) and park it in the
        report.  False (and a halt) when the block cannot even be
        applied, or when too many consecutive blocks quarantine — the
        chain itself has diverged and blind progress would be noise."""
        if not self.quarantine:
            raise exc
        if self._quar_streak + 1 > self.quarantine_limit:
            self._halt(f"quarantine limit ({self.quarantine_limit}) "
                       f"reached at block {it.block.number}")
            return False
        try:
            reasons = self.engine.quarantine_block(it.block)
        except Exception as sub:  # noqa: BLE001 — the block cannot even be applied (invalid txs): halt with the reason; resume needs operator intervention
            self._halt(f"unservable block {it.block.number}: {sub!r}")
            return False
        streak = self._quar_streak
        self.stats.quarantined.append({
            "number": it.block.number,
            "hash": it.block.hash().hex(),
            "reasons": [str(exc)] + reasons,
        })
        self._quarantined_blocks.append(it.block)
        get_or_register("serve/quarantined", Counter,
                        self._registry).inc()
        self._mark_committed([it])  # resets the streak; restore + bump
        self._quar_streak = streak + 1
        return True

    # ---------------------------------------------------------- execute
    def _next_item(self, idle: bool) -> Optional[_Item]:
        """One item from the execute queue, or None at end-of-stream /
        when a partial window should run instead of waiting longer."""
        deadline = time.monotonic() + (0.25 if idle else self.window_wait)
        while True:
            if self._pre_done.is_set() and self._q_exec.empty():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                it = self._q_exec.get(timeout=min(0.05, remaining))
            except queue.Empty:
                continue
            # continuity gate: a lost block (dropped upstream, a
            # wedged peer) would otherwise surface blocks later as a
            # baffling state-root mismatch — halt HERE with the gap
            # named, the committed prefix durable (checkpoint), and
            # the report saying exactly what to refetch
            num = it.block.number
            if self._expect_number is not None \
                    and num != self._expect_number:
                self._halt(f"sequence gap: got block {num}, "
                           f"expected {self._expect_number}")
                get_or_register("serve/sequence_gaps", Counter,
                                self._registry).inc()
                return None
            self._expect_number = num + 1
            # first sight of the block on the execute stage: senders
            # the prefetch stage already recovered count as hits
            self._prefetch_hits += sum(
                1 for tx in it.block.transactions
                if tx.cached_sender() is not None)
            if it.bt is not None:
                it.bt.exec_start()
            return it

    def _eos(self) -> bool:
        return self._pre_done.is_set() and self._q_exec.empty()

    def _drive(self) -> None:
        """The execute stage — see the module docstring's stage model.
        Mirrors ReplayEngine.replay()'s issue-ahead/retire-behind loop,
        driven by arriving items instead of a fixed block list.

        Fault handling on top of the batch loop: a device BackendFault
        leaves the classified items in the buffer (the supervisor has
        struck/demoted; they re-route down the ladder next iteration),
        and a ReplayError carrying its block — a poison block that
        failed every backend — goes through the quarantine instead of
        killing the stream."""
        from coreth_tpu.replay.engine import ReplayError
        from coreth_tpu.replay.supervisor import BackendFault
        e = self.engine
        buf: List[_Item] = []
        pending = None  # (win, its items) — issued, not yet validated
        while True:
            # top up the working buffer; wait only when idle, and only
            # window_wait when a partial window could run instead
            while len(buf) < e.window:
                it = self._next_item(idle=not buf and pending is None)
                if it is None:
                    break
                buf.append(it)
            if not buf and pending is None:
                if self._eos():
                    break
                continue
            # classify a transfer run off the head of the buffer
            run = []
            k = 0
            t0 = time.monotonic()
            while k < len(buf) and len(run) < e.window:
                batch = e._classify(buf[k].block)
                if batch is None:
                    break
                run.append((buf[k].block, batch))
                k += 1
            e.stats.t_classify += time.monotonic() - t0
            win = None
            if run:
                try:
                    win = e._issue_window(run)
                except BackendFault:
                    # struck (and maybe demoted): the items stay in
                    # the buffer and re-route through the host ladder
                    win = None
            # retire the previous window while the chip runs this one
            if pending is not None:
                p_win, p_items = pending
                pending = None
                try:
                    resume = e._complete_window(
                        p_win, [it.block for it in p_items], 0)
                except ReplayError as exc:
                    blk = getattr(exc, "block", None)
                    if blk is None or not self.quarantine:
                        raise
                    # the engine rewound to the prefix before the
                    # poison block and already retried it on the
                    # exact host path; quarantine it and hand the
                    # window tail (stale speculative base) back
                    j = next((i for i, it in enumerate(p_items)
                              if it.block is blk), None)
                    if j is None:
                        raise
                    self._mark_committed(p_items[:j])
                    if win is not None:
                        e._discard_window(win)
                    if not self._try_quarantine(p_items[j], exc):
                        return
                    buf = p_items[j + 1:] + buf
                    continue
                if resume is not None:
                    # prefix [0, resume) is committed (device blocks +
                    # the host-fallback block); the tail re-enters the
                    # buffer for fresh classification, and the window
                    # speculatively issued above ran on a stale base
                    self._mark_committed(p_items[:resume])
                    if win is not None:
                        e._discard_window(win)
                    buf = p_items[resume:] + buf
                    continue
                self._mark_committed(p_items)
            if win is not None:
                pending = (win, buf[:k])
                buf = buf[k:]
                continue
            if buf:
                # head is not transfer-classifiable and nothing is in
                # flight: machine-OCC run / exact host path, exactly
                # like batch replay's hit_fallback branch
                blocks = [it.block for it in buf]
                try:
                    n = e._machine_run(blocks, 0)
                except ReplayError as exc:
                    blk = getattr(exc, "block", None)
                    if blk is None or not self.quarantine:
                        raise
                    # blocks before the poison one were committed
                    # (the fallback flushes staged work first)
                    j = next((i for i, it in enumerate(buf)
                              if it.block is blk), None)
                    if j is None:
                        raise
                    self._mark_committed(buf[:j])
                    if not self._try_quarantine(buf[j], exc):
                        return
                    buf = buf[j + 1:]
                    continue
                self._mark_committed(buf[:n])
                buf = buf[n:]

    # -------------------------------------------------------------- run
    def run(self) -> StreamReport:
        """Drive the pipeline until the feed exhausts (or shutdown()),
        then drain in-flight work, flush the commit stage, and return
        the SLO report.  The engine ends on the same root batch replay
        would produce for the blocks that were committed."""
        t_start = time.monotonic()
        # live inspection while the stream runs: /metrics (Prometheus),
        # /trace (Perfetto JSON), /report (this run's live report) —
        # opt-in via CORETH_TELEMETRY_PORT (obs/server.py).  The stop
        # lives in the OUTERMOST finally, immediately below the start:
        # no failure after this point may leak the listener thread.
        from coreth_tpu.obs.server import maybe_start_from_env
        self._telemetry = maybe_start_from_env(
            registry=self._registry, report=self._live_report)
        try:
            restore = self._wrap_commit()
            feed_t = threading.Thread(target=self._feed_loop,
                                      name="serve-feed", daemon=True)
            pre_t = threading.Thread(target=self._prefetch_loop,
                                     name="serve-prefetch", daemon=True)
            feed_t.start()
            pre_t.start()
            try:
                try:
                    self._drive()
                finally:
                    self._stop.set()
                    feed_t.join(timeout=10)
                    pre_t.join(timeout=10)
                    # anything still staged belongs to completed blocks
                    self.engine.commit_pipe.flush()
                    restore()
                if self._errors:
                    raise self._errors[0]
                if self._ckpt is not None and self.stats.blocks:
                    # final checkpoint: the whole committed stream is
                    # durable, a restart resumes at the exact tail.  In
                    # background mode write() stamps the tip and DRAINS
                    # the flat exporter — the one synchronous wait, at
                    # shutdown, not per interval.
                    self._ckpt.write()
            finally:
                if self._ckpt is not None:
                    # ALWAYS stop the exporter thread — an error path
                    # that skipped it would leak one polling thread per
                    # failed run
                    self._ckpt.close()
        finally:
            if self._telemetry is not None:
                # same argument for the telemetry listener thread
                self._telemetry.stop()
                self._telemetry = None
            # CORETH_TRACE_OUT: flush the ring to a Perfetto-loadable
            # file (failures counted, never raised — obs/export_fail)
            obs.write_out()
            # forensics: a trigger still waiting for a witness at
            # shutdown (a crash-path oracle trip) freezes as a
            # context-only bundle instead of evaporating
            forensics.flush_pending()
        wall = time.monotonic() - t_start
        self._publish(wall)
        return self.stats

    def _live_report(self) -> dict:  # corethlint: thread telemetry-report — called by the TelemetryServer handler thread while the stream runs
        """The /report payload while the stream runs: the report row
        with the CURRENT latency histogram and stage attribution
        spliced in (the final _publish numbers are richer; this is the
        mid-run view)."""
        row = self.stats.row()
        snap = self._latency.snapshot()
        row["latency_ms"] = {
            "p50": round(1000 * snap["p50"], 3),
            "p99": round(1000 * snap["p99"], 3),
            "max": round(1000 * snap["max"], 3),
        }
        if self._stages is not None:
            row["stage_breakdown"] = self._stages.breakdown()
        rec = forensics.recorder()
        if rec is not None:
            # quarantine forensics, live: counters + bundle paths for
            # already-drained bundles (entries parked mid-run show
            # their replay handle without waiting for the final report)
            row["forensics"] = rec.snapshot()
            for entry in row["quarantined"]:
                paths = rec.bundles_for(entry["number"])
                if paths:
                    entry["bundle"] = paths[-1]
        row["committed_blocks"] = self._committed_blocks
        row["enqueued_blocks"] = self._enqueued
        return row

    def rollback_quarantined(self) -> dict:
        """Reorg primitive: pop the NEWEST quarantined block (its
        tolerantly-applied state transition reverts through the flat
        layer's generational undo log, engine.rollback_block) so a
        corrected block can be streamed in its place.  Call after
        run() returned (the engine is single-owner again).  Returns
        the popped quarantine report entry."""
        if not self._quarantined_blocks:
            raise ValueError("no quarantined block to roll back")
        blk = self._quarantined_blocks[-1]
        self.engine.rollback_block(blk)
        self._quarantined_blocks.pop()
        entry = self.stats.quarantined.pop()
        self.stats.blocks -= 1
        self.stats.txs -= len(blk.transactions)
        with self._mu:
            self._committed_blocks -= 1
        # the replacement block re-enters at the popped number
        self._expect_number = blk.number
        return entry

    def shutdown(self) -> None:
        """Mid-stream stop: the feed stops pulling, in-flight queues
        drain what fits, the pending window validates, staged commits
        flush.  run() returns its report as usual."""
        self._shutdown_called = True
        self._stop.set()

    # ------------------------------------------------------------ report
    def _publish(self, wall: float) -> None:
        s = self.stats
        s.wall_s = round(wall, 3)
        span = None
        if self._t_first_enqueue is not None \
                and self._t_last_commit is not None:
            span = self._t_last_commit - self._t_first_enqueue
        s.sustained_txs_s = round(s.txs / span, 1) if span else 0.0
        snap = self._latency.snapshot()
        s.latency_ms = {
            "p50": round(1000 * snap["p50"], 3),
            "p99": round(1000 * snap["p99"], 3),
            "max": round(1000 * snap["max"], 3),
        }
        s.prefetch = {
            "hits": self._prefetch_hits,
            "sigs": self.prefetcher.sigs,
            "code_touches": self.prefetcher.code_touches,
            "overlap_s": round(self.prefetcher.busy_s, 3),
            "reads_prefetched": self.engine.stats.reads_prefetched,
        }
        s.queues = {
            "depth": self.depth,
            "max_inflight": self._max_inflight,
        }
        s.stages_s = {
            "prefetch": round(self.prefetcher.busy_s, 3),
            "commit": round(self._t_commit, 3),
        }
        s.backpressure = {
            "feed_blocked_s": round(self._feed_blocked_s, 3),
            "prefetch_blocked_s": round(self._prefetch_blocked_s, 3),
            "commit_flushes": self._commit_flushes,
        }
        s.shutdown = self._shutdown_called
        # fault-tolerance surface: ladder counters, checkpoint
        # cadence, and what the armed plan (if any) actually fired
        sup = getattr(self.engine, "supervisor", None)
        if sup is not None:
            s.supervisor = sup.snapshot()
            sup.publish(self._registry)
        if self._ckpt is not None:
            s.checkpoint = self._ckpt.snapshot()
        flat = getattr(self.engine, "flat", None)
        if flat is not None:
            s.flat = flat.snapshot()
        if self._stages is not None:
            # per-stage share of enqueue->committed time (sums to ~1.0
            # across queue_feed/prefetch/queue_exec/execute/commit) —
            # THIS run's sink, not the process-global tracer's
            s.stage_breakdown = self._stages.breakdown()
        rec = forensics.recorder()
        if rec is not None:
            # wait for queued bundle writes, then surface them: the
            # report carries the forensics counters and every
            # quarantined entry gains its bundle path (the offline
            # replay handle for exactly that block)
            rec.drain()
            s.forensics = rec.snapshot()
            rec.publish(self._registry)
            for entry in s.quarantined:
                paths = rec.bundles_for(entry["number"])
                if paths:
                    entry["bundle"] = paths[-1]
        s.faults = faults.fired()
        # SLO surface in the metrics registry (scrapeable next to the
        # engine's replay/* gauges)
        reg = self._registry
        get_or_register("serve/block_latency", Histogram,
                        reg).replace_from(self._latency)
        get_or_register("serve/sustained_txs_s", Gauge,
                        reg).update(s.sustained_txs_s)
        get_or_register("serve/blocks", Gauge, reg).update(s.blocks)
