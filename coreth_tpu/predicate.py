"""Predicate byte packing + per-block results bitset.

Twin of reference predicate/ (predicate_bytes.go:29 PackPredicate —
append the 0xff delimiter then zero-pad to 32-byte alignment so the
bytes survive the access-list storage-key representation;
predicate_results.go:44-84 — the per-tx bitset of FAILED predicates
carried in the block for post-Durango verification).
"""

from __future__ import annotations

from typing import Dict, List

DELIMITER = 0xFF
CHUNK = 32


class PredicateError(Exception):
    pass


def pack_predicate(data: bytes) -> bytes:
    padded = data + bytes([DELIMITER])
    if len(padded) % CHUNK:
        padded += b"\x00" * (CHUNK - len(padded) % CHUNK)
    return padded


def unpack_predicate(packed: bytes) -> bytes:
    if not packed or len(packed) % CHUNK:
        raise PredicateError("predicate bytes not 32-byte aligned")
    trimmed = packed.rstrip(b"\x00")
    if not trimmed or trimmed[-1] != DELIMITER:
        raise PredicateError("predicate delimiter missing")
    return trimmed[:-1]


def slots_to_bytes(slots: List[bytes]) -> bytes:
    """Access-list storage keys -> packed predicate byte stream."""
    return b"".join(slots)


def results_bytes_from_extra(extra: bytes):
    """Extract the predicate-results bytes carried after the 80-byte
    dynamic-fee window in a post-Durango header Extra
    (predicate.GetPredicateResultBytes)."""
    from coreth_tpu.params import protocol as P
    if len(extra) <= P.DYNAMIC_FEE_EXTRA_DATA_SIZE:
        return None
    return extra[P.DYNAMIC_FEE_EXTRA_DATA_SIZE:]


def check_tx_predicates(rules, tx) -> Dict[bytes, bytes]:
    """One tx's per-predicater-address failure bitsets
    (core/predicate_check.go:30 CheckPredicates): group the tx's
    access-list tuples by predicater address in order, verify each
    tuple's packed predicate, set the bit on failure."""
    out: Dict[bytes, bytes] = {}
    if not rules.predicaters:
        return out
    per_addr: Dict[bytes, List[List[bytes]]] = {}
    for addr, keys in (tx.access_list or []):
        if addr in rules.predicaters:
            per_addr.setdefault(addr, []).append(list(keys))
    for addr, tuple_list in per_addr.items():
        predicater = rules.predicaters[addr]
        bits = bytearray((len(tuple_list) + 7) // 8)
        for i, keys in enumerate(tuple_list):
            if not predicater.verify_predicate(slots_to_bytes(keys)):
                bits[i // 8] |= 1 << (i % 8)
        out[addr] = bytes(bits)
    return out


class PredicateResults:
    """txIndex -> per-predicate failure bitset (results.go)."""

    def __init__(self):
        self.results: Dict[int, Dict[bytes, bytes]] = {}

    def set_result(self, tx_index: int, address: bytes,
                   bitset: bytes) -> None:
        self.results.setdefault(tx_index, {})[address] = bitset

    def get_result(self, tx_index: int, address: bytes) -> bytes:
        return self.results.get(tx_index, {}).get(address, b"")

    def encode(self) -> bytes:
        from coreth_tpu.wire import Packer
        p = Packer()
        p.u32(len(self.results))
        for tx_index in sorted(self.results):
            p.u32(tx_index)
            entries = self.results[tx_index]
            p.u32(len(entries))
            for addr in sorted(entries):
                p.fixed(addr, 20)
                p.var_bytes(entries[addr])
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "PredicateResults":
        from coreth_tpu.wire import Unpacker
        u = Unpacker(data)
        out = cls()
        for _ in range(u.u32()):
            tx_index = u.u32()
            for _ in range(u.u32()):
                addr = u.fixed(20)
                out.set_result(tx_index, addr, u.var_bytes())
        return out
