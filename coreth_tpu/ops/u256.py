"""256-bit integer arithmetic on TPU-friendly 16-bit limbs.

Values are represented as int32 arrays of shape (..., 16): limb i holds
bits [16*i, 16*i+16) (little-endian limbs), each in [0, 2^16).  The
16-bit-in-int32 layout gives headroom for segment-sums over up to ~2^14
operands before a single carry renormalization — the pattern the replay
engine uses for per-account debit/credit aggregation (reference analog:
the per-tx sequential big.Int balance updates in core/state_transition.go
buyGas/refundGas, here batched).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

LIMBS = 16
LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1


def pack_np(values) -> np.ndarray:
    """Python ints -> (n, 16) numpy limb array (C-speed via to_bytes)."""
    blob = b"".join(v.to_bytes(32, "little") for v in values)
    return np.frombuffer(blob, dtype=np.uint16).reshape(
        len(values), LIMBS).astype(np.int32)


def from_ints(values, dtype=jnp.int32) -> jnp.ndarray:
    """Python ints -> (n, 16) limb array on device."""
    return jnp.asarray(pack_np(values), dtype=dtype)


def to_ints(arr) -> list:
    """(n, 16) limb array -> Python ints (host-side unpacking)."""
    a = np.asarray(arr, dtype=np.int64)
    if a.size == 0:
        return []
    # combine limbs vectorized: little-endian uint16 limbs -> bytes
    blob = a.astype(np.uint16).tobytes()
    return [int.from_bytes(blob[i * 32:(i + 1) * 32], "little")
            for i in range(a.shape[0])]


def normalize(x: jnp.ndarray) -> jnp.ndarray:
    """Propagate carries so every limb lands in [0, 2^16).

    Sequential 16-step carry chain (unrolled at trace time).  A fixed
    number of PARALLEL passes is NOT enough: each parallel pass moves a
    carry only one limb, so 0xFFFF,0xFFFF,...,+1 ripples the full
    width (a carry chain like 2^256-1 + 1 needs 16 steps).  The
    running-carry form handles any nonnegative limb magnitude (segment
    sums feed limbs up to ~2^30; carry stays < 2^15 + prior, well in
    int32).
    """
    out = []
    carry = jnp.zeros(x.shape[:-1], dtype=x.dtype)
    n = x.shape[-1]
    for i in range(n):
        v = x[..., i] + carry
        out.append(v & LIMB_MASK)
        carry = v >> LIMB_BITS
    return jnp.stack(out, axis=-1)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b mod 2^256, both normalized."""
    return normalize(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod 2^(16*limbs) (caller checks a >= b via gte).

    Works for any limb count (the device ALU reuses it at 17/32 limbs).
    The borrow chain is unrolled at trace time (no lax.scan: scans over
    carries interact badly with shard_map's varying-axis typing, and
    the fixed steps fuse fine)."""
    diff = a - b
    limbs = []
    borrow = jnp.zeros(a.shape[:-1], dtype=jnp.int32)
    for i in range(a.shape[-1]):
        limb = diff[..., i] - borrow
        borrow = (limb < 0).astype(jnp.int32)
        limbs.append(limb + (borrow << LIMB_BITS))
    return jnp.stack(limbs, axis=-1)


def gte(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a >= b elementwise over the last axis (both normalized, any
    limb count).

    Lexicographic compare from the most-significant limb, unrolled at
    trace time (see sub() for why no lax.scan)."""
    decided = jnp.zeros(a.shape[:-1], dtype=bool)
    result = jnp.ones(a.shape[:-1], dtype=bool)  # equal => True
    for i in range(a.shape[-1] - 1, -1, -1):
        a_l, b_l = a[..., i], b[..., i]
        gt = a_l > b_l
        lt = a_l < b_l
        result = jnp.where(~decided & gt, True, result)
        result = jnp.where(~decided & lt, False, result)
        decided = decided | gt | lt
    return result


def mul_small(a: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """a * k for k < 2^15 (per-limb product fits int32 headroom)."""
    return normalize(a * k[..., None])


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)
