"""Batched secp256k1 public-key recovery on device — the TPU analog of
the reference's parallel sender recovery (core/sender_cacher.go, which
spreads cgo libsecp256k1 ecrecover across GOMAXPROCS goroutines).

Design: the expensive part of ECDSA recovery is the double-scalar
multiplication u1*G + u2*R (~thousands of 256-bit field multiplies).
The host (crypto/secp_device.py) does the cheap per-signature scalar
math with CPython bignums; this module runs ONE shared Shamir ladder —
256 iterations of point-double + conditional mixed-add — vmapped over
the whole signature batch with branchless (where-selected) complete
addition.  All field arithmetic is exact 20x13-bit-limb int32 math:
13-bit limbs keep every partial-product column under 2^31, so the
entire kernel is int32 VPU work with no 64-bit emulation.

Field-element representation
  (..., 20) int32, limbs little-endian base 2^13, all limbs in
  [0, 2^13), value < 2^257 (i.e. possibly p..4p above canonical; the
  is-zero tests compare against {0, p, 2p} and the host canonicalizes
  final outputs with one `% p`).

Reduction: p = 2^256 - 2^32 - 977, so
  2^260 = 2^36 + 15632  (mod p)      [folds for the 40-limb product]
  2^256 = 2^32 + 977    (mod p)      [final fold to < 2^257]
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

LIMBS = 20
LB = 13
LM = (1 << LB) - 1


def to_limbs_np(values) -> np.ndarray:
    """Python ints -> (n, 20) int32 13-bit-limb array (numpy-vectorized)."""
    blob = b"".join(int(v).to_bytes(33, "little") for v in values)
    raw = np.frombuffer(blob, dtype=np.uint8).reshape(len(values), 33)
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, :260]
    weights = (1 << np.arange(LB, dtype=np.int32))
    return (bits.reshape(len(values), LIMBS, LB).astype(np.int32)
            * weights).sum(axis=2, dtype=np.int32)


def from_limbs(arr) -> list:
    """(n, 20) limb array -> Python ints, numpy-vectorized: spread the
    13-bit limbs into bits, pack to little-endian bytes, convert.
    Requires limbs in [0, 2^13) (exact representation)."""
    a = np.asarray(arr)
    if a.size == 0:
        return []
    assert ((a >= 0) & (a < (1 << LB))).all()
    bits = ((a[:, :, None] >> np.arange(LB, dtype=np.int32)) & 1)
    flat = bits.reshape(a.shape[0], LIMBS * LB).astype(np.uint8)
    pad = np.zeros((a.shape[0], 264 - LIMBS * LB), dtype=np.uint8)
    packed = np.packbits(np.concatenate([flat, pad], axis=1),
                         axis=1, bitorder="little")
    return [int.from_bytes(packed[i].tobytes(), "little")
            for i in range(a.shape[0])]


def _const_limbs(v: int) -> np.ndarray:
    return to_limbs_np([v])[0]

_P_L = _const_limbs(P)
_2P_L = _const_limbs(2 * P)
_GX_L = _const_limbs(GX)
_GY_L = _const_limbs(GY)
_ONE_L = _const_limbs(1)


def _carry(cols, out_len: int):
    """Exact base-2^13 carry/borrow propagation via lax.scan over limbs.

    cols: (..., L) int32, possibly >13-bit and/or negative entries; the
    represented value must be non-negative and < 2^(13*out_len).
    Returns (..., out_len) limbs all in [0, 2^13)."""
    L = cols.shape[-1]
    if L < out_len:
        cols = jnp.concatenate(
            [cols, jnp.zeros(cols.shape[:-1] + (out_len - L,),
                             dtype=jnp.int32)], axis=-1)
    colsT = jnp.moveaxis(cols[..., :out_len], -1, 0)

    def step(carry, col):
        t = col + carry
        return t >> LB, t & LM

    # unroll matters: an un-unrolled scan lowers to a nested while-loop
    # inside the ladder's fori_loop, costing ~1us per step on TPU
    # (thousands of inner iterations per ladder round -> ~1.7s/batch);
    # unroll=8 keeps the graph compact while fusing the chain into a
    # handful of elementwise ops (measured: same steady-state as full
    # unroll, half the compile time).
    _, limbsT = jax.lax.scan(step, jnp.zeros(cols.shape[:-1],
                                             dtype=jnp.int32), colsT,
                             unroll=8)
    return jnp.moveaxis(limbsT, 0, -1)


def _fold260(w, hi_len: int, out_len: int):
    """w = lo(20) ++ hi(hi_len) limbs; replace hi*2^260 with
    hi*(2^36 + 15632), carry to out_len limbs."""
    lo, hi = w[..., :LIMBS], w[..., LIMBS:]
    width = max(LIMBS, hi_len + 3)
    acc = jnp.zeros(w.shape[:-1] + (width,), dtype=jnp.int32)
    acc = acc.at[..., :LIMBS].add(lo)
    acc = acc.at[..., :hi_len].add(hi * 15632)
    acc = acc.at[..., 2:hi_len + 2].add(hi * 1024)   # 2^36 = 2^(13*2+10)
    return _carry(acc, out_len)


def _fold256(w):
    """20-limb value < 2^260 -> congruent value < 2^257."""
    hi4 = w[..., 19] >> 9                            # bits 256..259
    acc = w.at[..., 19].set(w[..., 19] & 511)
    acc = acc.at[..., 0].add(hi4 * 977)
    acc = acc.at[..., 2].add(hi4 * 64)               # 2^32 = 2^(13*2+6)
    return _carry(acc, LIMBS)


def fe_mul(a, b):
    """(a * b) mod-ish p: output value < 2^257, congruent to a*b."""
    cols = jnp.zeros(a.shape[:-1] + (2 * LIMBS - 1,), dtype=jnp.int32)
    for i in range(LIMBS):
        cols = cols.at[..., i:i + LIMBS].add(a[..., i:i + 1] * b)
    w = _carry(cols, 41)                 # value < 2^514
    w = _fold260(w, 21, 25)              # < 2^311
    w = _fold260(w, 5, 21)               # < 2^261
    w = _fold260(w, 1, LIMBS)            # < 2^260
    return _fold256(w)                   # < 2^257


def fe_sq(a):
    return fe_mul(a, a)


def fe_add(a, b):
    w = _carry(a + b, 21)                # < 2^258
    return _fold256(_fold260(w, 1, LIMBS))


_4P_L = _const_limbs(4 * P)


def fe_sub(a, b):
    """(a - b) mod-ish p: a, b values < 2^257 -> output < 2^257.

    Adds 4p so the total stays positive; the borrow chain rides the
    same exact carry scan (arithmetic shifts propagate negatives)."""
    cols = a + jnp.asarray(_4P_L) - b    # value in (0, 2^257 + 4p) < 2^259
    return _fold256(_carry(cols, LIMBS))


def fe_is_zero(a):
    """a == 0 (mod p) for exact-limb values < 2^257: compare against
    the canonical representations of 0, p and 2p."""
    z = jnp.all(a == 0, axis=-1)
    z |= jnp.all(a == jnp.asarray(_P_L), axis=-1)
    z |= jnp.all(a == jnp.asarray(_2P_L), axis=-1)
    return z


def _limb_gte(a, b_const: np.ndarray):
    """Lexicographic a >= b over exact 13-bit limbs (b a constant row)."""
    decided = jnp.zeros(a.shape[:-1], dtype=bool)
    result = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(LIMBS - 1, -1, -1):
        b_i = int(b_const[i])
        gt = a[..., i] > b_i
        lt = a[..., i] < b_i
        result = jnp.where(~decided & gt, True, result)
        result = jnp.where(~decided & lt, False, result)
        decided = decided | gt | lt
    return result


def _cond_sub(a, b_const: np.ndarray):
    """a - b if a >= b else a (exact limbs, unrolled borrow chain)."""
    take = _limb_gte(a, b_const)
    diff = a - jnp.asarray(b_const)
    limbs = []
    borrow = jnp.zeros(a.shape[:-1], dtype=jnp.int32)
    for i in range(LIMBS):
        t = diff[..., i] - borrow
        borrow = (t < 0).astype(jnp.int32)
        limbs.append(t + (borrow << LB))
    sub = jnp.stack(limbs, axis=-1)
    return jnp.where(take[..., None], sub, a)


def fe_canon(a):
    """Reduce an exact-limb value < 2^257 to canonical [0, p)."""
    return _cond_sub(_cond_sub(a, _2P_L), _P_L)


# Static MSB-first exponent bit schedules: (p+1)/4 (the p = 3 mod 4
# square-root shortcut) and p-2 (Fermat inversion).
_SQRT_EXP_BITS = np.array(
    [(((P + 1) // 4) >> (255 - i)) & 1 for i in range(256)], dtype=np.int32)
_INV_EXP_BITS = np.array(
    [((P - 2) >> (255 - i)) & 1 for i in range(256)], dtype=np.int32)


def _fe_pow_static(base, exp_bits: np.ndarray):
    """base^e for a trace-time-constant exponent bit schedule."""
    bits = jnp.asarray(exp_bits)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), base.shape)

    def body(i, acc):
        acc = fe_mul(acc, acc)
        mul = fe_mul(acc, base)
        return jnp.where(bits[i] == 1, mul, acc)

    return jax.lax.fori_loop(0, 256, body, one)


def fe_sqrt(ysq):
    """(sqrt, is_residue) — canonical root of a quadratic residue."""
    y = fe_canon(_fe_pow_static(ysq, _SQRT_EXP_BITS))
    chk = fe_canon(fe_mul(y, y))
    ok = jnp.all(chk == fe_canon(ysq), axis=-1)
    return y, ok


def fe_inv(a):
    """1/a mod p (0 -> 0), lazy representation."""
    return _fe_pow_static(a, _INV_EXP_BITS)


# --------------------------------------------------------- byte packing
# Device-side (un)packing between 33-byte little-endian field elements
# and 13-bit limbs: transfers over the device tunnel cost ~2.5x less as
# bytes than as int32 limb arrays.

def unpack_fe_bytes(b):
    """(B, 33) uint8 -> (B, 20) int32 limbs (values must be < 2^260)."""
    v = b.astype(jnp.int32)
    limbs = []
    for j in range(LIMBS):
        bit0 = LB * j
        byte0, off = divmod(bit0, 8)
        acc = v[..., byte0] >> off
        acc = acc | (v[..., byte0 + 1] << (8 - off))
        if byte0 + 2 < 33:
            acc = acc | (v[..., byte0 + 2] << (16 - off))
        limbs.append(acc & LM)
    return jnp.stack(limbs, axis=-1)


def pack_fe_bytes(limbs):
    """(B, 20) exact int32 limbs -> (B, 33) uint8 little-endian."""
    out = []
    for k in range(33):
        bit0 = 8 * k
        j, off = divmod(bit0, LB)
        acc = limbs[..., j] >> off
        if j + 1 < LIMBS and LB - off < 8:
            acc = acc | (limbs[..., j + 1] << (LB - off))
        out.append(acc & 255)
    return jnp.stack(out, axis=-1).astype(jnp.uint8)


def fe_bytes_np(values) -> np.ndarray:
    """Python ints -> (n, 33) uint8 little-endian (host side)."""
    blob = b"".join(int(v).to_bytes(33, "little") for v in values)
    return np.frombuffer(blob, dtype=np.uint8).reshape(len(values), 33)


# ---------------------------------------------------------------- points

def pt_double(X, Y, Z):
    """Jacobian doubling (a=0 curve).  Infinity (Z=0) stays Z=0."""
    A = fe_sq(X)
    Bb = fe_sq(Y)
    C = fe_sq(Bb)
    t = fe_sub(fe_sub(fe_sq(fe_add(X, Bb)), A), C)
    D = fe_add(t, t)
    E = fe_add(fe_add(A, A), A)
    F = fe_sq(E)
    nX = fe_sub(F, fe_add(D, D))
    C2 = fe_add(C, C)
    C8 = fe_add(fe_add(C2, C2), fe_add(C2, C2))
    nY = fe_sub(fe_mul(E, fe_sub(D, nX)), C8)
    nZ = fe_mul(fe_add(Y, Y), Z)
    return nX, nY, nZ


def _mixed_add(X, Y, Z, inf, ax, ay, a_inf, do):
    """Complete branchless Jacobian += affine.

    Returns (X', Y', Z', inf', collision): `collision` marks rows where
    the addend equals the accumulator (a doubling case) — statistically
    negligible, the host re-runs those rows on its exact path rather
    than paying 7 extra muls every ladder iteration for all rows."""
    z1z1 = fe_sq(Z)
    u2 = fe_mul(ax, z1z1)
    s2 = fe_mul(ay, fe_mul(Z, z1z1))
    h = fe_sub(u2, X)
    r = fe_sub(s2, Y)
    h0 = fe_is_zero(h)
    r0 = fe_is_zero(r)
    hh = fe_sq(h)
    hhh = fe_mul(h, hh)
    v = fe_mul(X, hh)
    nx = fe_sub(fe_sub(fe_sq(r), hhh), fe_add(v, v))
    ny = fe_sub(fe_mul(r, fe_sub(v, nx)), fe_mul(Y, hhh))
    nz = fe_mul(Z, h)

    eff = do & ~a_inf                    # performing a real add
    take_addend = eff & inf              # inf + Q = Q
    general = eff & ~inf
    collision = general & h0 & r0        # addend == acc -> host redo
    to_inf = general & h0 & ~r0          # addend == -acc

    ta = take_addend[..., None]
    ge = general[..., None]
    one = jnp.asarray(_ONE_L)
    Xo = jnp.where(ta, ax, jnp.where(ge, nx, X))
    Yo = jnp.where(ta, ay, jnp.where(ge, ny, Y))
    Zo = jnp.where(ta, jnp.broadcast_to(one, Z.shape),
                   jnp.where(ge, nz, Z))
    info = jnp.where(take_addend, False,
                     jnp.where(general, to_inf, inf))
    return Xo, Yo, Zo, info, collision


# affine 2G, for the R == G corner of the G+R table entry
_G2_LAM = (3 * GX * GX) * pow(2 * GY, P - 2, P) % P
_G2X = (_G2_LAM * _G2_LAM - 2 * GX) % P
_G2Y = (_G2_LAM * (GX - _G2X) - GY) % P
_G2X_L = _const_limbs(_G2X)
_G2Y_L = _const_limbs(_G2Y)


def _shamir(u1w, u2w, qx, qy, gqx, gqy, gq_inf):
    """u1*G + u2*Q, one shared 256-step ladder over the batch.

    u1w/u2w: (B, 8) int32 little-endian 32-bit scalar words.
    qx/qy:   (B, 20) affine R limbs; gqx/gqy: affine G+R limbs;
    gq_inf: (B,) bool (R == -G).
    Returns (X, Y, Z, inf, collision)."""
    Bsz = qx.shape[0]
    gx = jnp.broadcast_to(jnp.asarray(_GX_L), (Bsz, LIMBS))
    gy = jnp.broadcast_to(jnp.asarray(_GY_L), (Bsz, LIMBS))

    def body(i, st):
        X, Y, Z, inf, bad = st
        X, Y, Z = pt_double(X, Y, Z)
        pos = 255 - i
        w = pos // 32
        s = pos % 32
        b1 = (jax.lax.dynamic_index_in_dim(u1w, w, axis=1,
                                           keepdims=False) >> s) & 1
        b2 = (jax.lax.dynamic_index_in_dim(u2w, w, axis=1,
                                           keepdims=False) >> s) & 1
        both = (b1 & b2).astype(bool)
        q_only = b2.astype(bool)
        ax = jnp.where(both[:, None], gqx,
                       jnp.where(q_only[:, None], qx, gx))
        ay = jnp.where(both[:, None], gqy,
                       jnp.where(q_only[:, None], qy, gy))
        a_inf = both & gq_inf
        do = (b1 | b2).astype(bool)
        X, Y, Z, inf, coll = _mixed_add(X, Y, Z, inf, ax, ay, a_inf, do)
        return X, Y, Z, inf, bad | coll

    zeros = jnp.zeros((Bsz, LIMBS), dtype=jnp.int32)
    init = (zeros, zeros, zeros,
            jnp.ones((Bsz,), dtype=bool), jnp.zeros((Bsz,), dtype=bool))
    return jax.lax.fori_loop(0, 256, body, init)


@jax.jit
def recover_kernel(x_bytes, parity, u1w, u2w):
    """The full device side of batched ECDSA recovery, one call:

      unpack x -> y = sqrt(x^3+7) -> parity-select y -> build the
      G+R table entry (one batched Fermat inversion) -> Shamir ladder
      u1*G + u2*R -> pack.

    x_bytes: (B, 33) uint8 LE canonical x coordinates.
    parity:  (B,) int32 — required y parity (recid & 1).
    u1w/u2w: (B, 8) int32 LE scalar words.
    Returns (B, 102) uint8: X(33) ++ Y(33) ++ Z(33) canonical Jacobian
    bytes ++ [inf, collision, is_residue] flag bytes."""
    x = unpack_fe_bytes(x_bytes)
    Bsz = x.shape[0]
    seven = jnp.broadcast_to(jnp.asarray(_const_limbs(7)), x.shape)
    ysq = fe_add(fe_mul(fe_mul(x, x), x), seven)
    y, residue = fe_sqrt(ysq)
    yneg = fe_canon(fe_sub(jnp.zeros_like(y), y))
    flip = (y[..., 0] & 1) != parity
    y = jnp.where(flip[:, None], yneg, y)

    # G+R affine add, branchless: general case via Fermat inversion;
    # R == G -> constant 2G; R == -G -> infinity flag.
    gx = jnp.broadcast_to(jnp.asarray(_GX_L), x.shape)
    gy = jnp.broadcast_to(jnp.asarray(_GY_L), x.shape)
    dx = fe_sub(x, gx)
    x_eq = fe_is_zero(dx)
    lam = fe_mul(fe_sub(y, gy), fe_inv(dx))
    gqx = fe_sub(fe_sub(fe_mul(lam, lam), gx), x)
    gqy = fe_sub(fe_mul(lam, fe_sub(gx, gqx)), gy)
    y_eq = fe_is_zero(fe_sub(y, gy))
    is_2g = (x_eq & y_eq)[:, None]
    gqx = jnp.where(is_2g, jnp.asarray(_G2X_L), gqx)
    gqy = jnp.where(is_2g, jnp.asarray(_G2Y_L), gqy)
    gq_inf = x_eq & ~y_eq

    X, Y, Z, inf, bad = _shamir(u1w, u2w, x, y, gqx, gqy, gq_inf)
    flags = jnp.stack([inf, bad, residue], axis=-1).astype(jnp.uint8)
    return jnp.concatenate(
        [pack_fe_bytes(fe_canon(X)), pack_fe_bytes(fe_canon(Y)),
         pack_fe_bytes(fe_canon(Z)), flags], axis=-1)
