"""Extended 256-bit arithmetic for the device EVM step machine.

Builds on ops/u256 (16x16-bit limbs in int32, little-endian).  These are
the EVM ALU ops the batched interpreter needs beyond add/sub/compare:
full multiply, division/modulo (restoring bit-serial — branch-free and
bit-exact), signed variants, modular ops over arbitrary moduli, EXP,
shifts, BYTE and SIGNEXTEND (reference semantics:
core/vm/instructions.go opMul/opDiv/opSdiv/opAddmod/opExp/opSHL...).

Everything stays in int32 (no x64 dependence): 16x16-bit limb products
are kept inside int32 by splitting one operand into 8-bit halves, so a
16-term convolution sum is bounded by 16 * 2^24 = 2^28.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from coreth_tpu.ops import u256

L = u256.LIMBS
MASK = u256.LIMB_MASK


def _zeros_like_head(a, extra_shape=()):
    return jnp.zeros(a.shape[:-1] + extra_shape, dtype=jnp.int32)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod 2^256.

    b's limbs split into (low, high) bytes keeps every partial sum under
    2^29 in int32; the high-byte partials contribute 8 bits up, so
    P1_k feeds (P1_k & 0xFF) << 8 into limb k and P1_k >> 8 into k+1.
    """
    bl = b & 0xFF
    bh = (b >> 8) & 0xFF
    outs = []
    carry = _zeros_like_head(a)
    p1_hi = _zeros_like_head(a)
    for k in range(L):
        p0 = _zeros_like_head(a)
        p1 = _zeros_like_head(a)
        for i in range(k + 1):
            ai = a[..., i]
            p0 = p0 + ai * bl[..., k - i]
            p1 = p1 + ai * bh[..., k - i]
        v = p0 + ((p1 & 0xFF) << 8) + p1_hi + carry
        outs.append(v & MASK)
        carry = v >> 16
        p1_hi = p1 >> 8
    return jnp.stack(outs, axis=-1)


def mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full 512-bit product as (..., 32) limbs (for MULMOD)."""
    bl = b & 0xFF
    bh = (b >> 8) & 0xFF
    outs = []
    carry = _zeros_like_head(a)
    p1_hi = _zeros_like_head(a)
    for k in range(2 * L - 1):
        p0 = _zeros_like_head(a)
        p1 = _zeros_like_head(a)
        for i in range(max(0, k - L + 1), min(k + 1, L)):
            ai = a[..., i]
            p0 = p0 + ai * bl[..., k - i]
            p1 = p1 + ai * bh[..., k - i]
        v = p0 + ((p1 & 0xFF) << 8) + p1_hi + carry
        outs.append(v & MASK)
        carry = v >> 16
        p1_hi = p1 >> 8
    outs.append(carry + p1_hi)  # true top limb, already < 2^16
    return jnp.stack(outs, axis=-1)


def _shift1_add_bit(r: jnp.ndarray, bit: jnp.ndarray) -> jnp.ndarray:
    """r*2 + bit with one carry pass (entry limbs are < 2^16, so one
    pass fully renormalizes)."""
    r = r * 2
    r = r.at[..., 0].add(bit)
    c = r >> 16
    r = (r & MASK) + jnp.concatenate(
        [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)
    return r


def _mod_bits(x: jnp.ndarray, nbits: int, n: jnp.ndarray,
              with_quotient: bool = False):
    """x mod n by restoring division over x's top `nbits` bits.

    x: (..., ceil(nbits/16)) limbs; n: (..., 16).  n == 0 -> 0.
    Returns (q[..16 limbs] if with_quotient else None, r (..., 16)).
    Quotient only valid when it fits 256 bits (DIV guarantees this).
    """
    n17 = jnp.concatenate([n, _zeros_like_head(n, (1,))], axis=-1)
    r = _zeros_like_head(n, (17,))
    q = jnp.zeros_like(n) if with_quotient else None

    def body(i, carry):
        q, r = carry
        bitpos = nbits - 1 - i
        limb = bitpos // 16
        sh = bitpos % 16
        bit = (jax.lax.dynamic_index_in_dim(
            x, limb, axis=-1, keepdims=False) >> sh) & 1
        r = _shift1_add_bit(r, bit)
        ge = u256.gte(r, n17)
        r = jnp.where(ge[..., None], u256.sub(r, n17), r)
        if q is not None:
            hot = (jnp.arange(L, dtype=jnp.int32) == limb).astype(jnp.int32)
            q = q + (ge.astype(jnp.int32) << sh)[..., None] * hot
        return q, r

    q, r = jax.lax.fori_loop(0, nbits, body, (q, r))
    nz = ~u256.is_zero(n)
    r16 = jnp.where(nz[..., None], r[..., :L], 0)
    if with_quotient:
        q = jnp.where(nz[..., None], q, 0)
        return q, r16
    return None, r16


def divmod_(a: jnp.ndarray, b: jnp.ndarray):
    """(a // b, a % b); b == 0 -> (0, 0) (EVM DIV/MOD semantics)."""
    q, r = _mod_bits(a, 256, b, with_quotient=True)
    return q, r


def neg(a: jnp.ndarray) -> jnp.ndarray:
    """Two's-complement negation mod 2^256."""
    return u256.sub(jnp.zeros_like(a), a)


def _sign(a: jnp.ndarray) -> jnp.ndarray:
    """True where a's 255th bit is set (negative as signed)."""
    return (a[..., L - 1] >> 15) & 1


def _abs(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(_sign(a)[..., None] == 1, neg(a), a)


def sdiv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Signed division truncating toward zero (instructions.go opSdiv)."""
    q, _ = divmod_(_abs(a), _abs(b))
    negate = _sign(a) ^ _sign(b)
    return jnp.where(negate[..., None] == 1, neg(q), q)


def smod(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Signed modulo: result takes the dividend's sign (opSmod)."""
    _, r = divmod_(_abs(a), _abs(b))
    return jnp.where(_sign(a)[..., None] == 1, neg(r), r)


def addmod(a: jnp.ndarray, b: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """(a + b) % n over the full 257-bit sum (opAddmod)."""
    # widen to 17 limbs BEFORE carrying so the limb-15 carry-out
    # lands; normalize's sequential carry chain handles full ripples
    s = u256.normalize(
        jnp.concatenate([a + b, _zeros_like_head(a, (1,))], axis=-1))
    _, r = _mod_bits(s, 17 * 16, n)
    return r


def mulmod(a: jnp.ndarray, b: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """(a * b) % n over the 512-bit product (opMulmod)."""
    wide = mul_wide(a, b)
    _, r = _mod_bits(wide, 512, n)
    return r


def bit_length(a: jnp.ndarray) -> jnp.ndarray:
    """Bit length per element (0 for zero), via 16-bit limb scan."""
    # bitlen of each limb by binary search (exact, no floats)
    v = a
    bl = jnp.zeros_like(v)
    for shift in (8, 4, 2, 1):
        big = v >= (1 << shift)
        bl = bl + jnp.where(big, shift, 0)
        v = jnp.where(big, v >> shift, v)
    bl = bl + (v > 0)  # v now 0 or 1
    idx = jnp.arange(L, dtype=jnp.int32)
    per_limb = jnp.where(a > 0, idx * 16 + bl, 0)
    return jnp.max(per_limb, axis=-1)


def exp_(b: jnp.ndarray, e: jnp.ndarray) -> jnp.ndarray:
    """b ** e mod 2^256 by right-to-left square-and-multiply, bounded by
    the batch's max exponent bit length (opExp)."""
    maxbits = jnp.max(bit_length(e))
    res = jnp.zeros_like(b).at[..., 0].set(1)
    cur = b

    def cond(carry):
        i, _, _ = carry
        return i < maxbits

    def body(carry):
        i, res, cur = carry
        limb = i // 16
        sh = i % 16
        bit = (jax.lax.dynamic_index_in_dim(
            e, limb, axis=-1, keepdims=False) >> sh) & 1
        res = jnp.where(bit[..., None] == 1, mul(res, cur), res)
        cur = mul(cur, cur)
        return i + 1, res, cur

    _, res, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), res, cur))
    return res


def _shift_amount(n: jnp.ndarray):
    """(effective shift in [0,255], overflow>=256 flag) from a u256."""
    over = (n[..., 0] > 255)
    for i in range(1, L):
        over = over | (n[..., i] != 0)
    return jnp.where(over, 0, n[..., 0]), over


def shl(x: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    s, over = _shift_amount(n)
    limb_sh = s // 16
    bit_sh = s % 16
    idx = jnp.arange(L, dtype=jnp.int32) - limb_sh[..., None]
    g = jnp.take_along_axis(x, jnp.clip(idx, 0, L - 1), axis=-1)
    g = jnp.where(idx >= 0, g, 0)
    prev = jnp.concatenate(
        [jnp.zeros_like(g[..., :1]), g[..., :-1]], axis=-1)
    out = ((g << bit_sh[..., None]) & MASK) | (prev >> (16 - bit_sh)[..., None])
    return jnp.where(over[..., None], 0, out)


def shr(x: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    s, over = _shift_amount(n)
    limb_sh = s // 16
    bit_sh = s % 16
    idx = jnp.arange(L, dtype=jnp.int32) + limb_sh[..., None]
    g = jnp.take_along_axis(x, jnp.clip(idx, 0, L - 1), axis=-1)
    g = jnp.where(idx <= L - 1, g, 0)
    nxt = jnp.concatenate(
        [g[..., 1:], jnp.zeros_like(g[..., :1])], axis=-1)
    out = (g >> bit_sh[..., None]) | ((nxt << (16 - bit_sh)[..., None]) & MASK)
    return jnp.where(over[..., None], 0, out)


def sar(x: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    sign = _sign(x)
    base = shr(x, n)
    s, over = _shift_amount(n)
    # fill bits at positions >= 256 - s with the sign bit
    t = 256 - s  # first filled bit position; s==0 -> t=256 -> no fill
    k16 = jnp.arange(L, dtype=jnp.int32) * 16
    rel = t[..., None] - k16  # bits below rel keep, above fill
    fill_mask = jnp.where(
        rel <= 0, MASK,
        jnp.where(rel >= 16, 0, (MASK << jnp.clip(rel, 0, 16)) & MASK))
    filled = base | jnp.where(sign[..., None] == 1, fill_mask, 0)
    all_ones = jnp.full_like(x, MASK)
    over_val = jnp.where(sign[..., None] == 1, all_ones, jnp.zeros_like(x))
    return jnp.where(over[..., None], over_val, filled)


def byte_op(i: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """BYTE: big-endian byte i of x, 0 when i >= 32 (opByte)."""
    over = (i[..., 0] > 31)
    for k in range(1, L):
        over = over | (i[..., k] != 0)
    p = 31 - jnp.clip(i[..., 0], 0, 31)  # little-endian byte position
    limb = jnp.take_along_axis(x, (p // 2)[..., None], axis=-1)[..., 0]
    byte = (limb >> ((p % 2) * 8)) & 0xFF
    byte = jnp.where(over, 0, byte)
    out = jnp.zeros_like(x)
    return out.at[..., 0].set(byte)


def signextend(b: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """SIGNEXTEND: extend from byte b (0 = lowest byte) (opSignExtend)."""
    over = (b[..., 0] > 30)
    for k in range(1, L):
        over = over | (b[..., k] != 0)
    t = 8 * jnp.clip(b[..., 0], 0, 30) + 7  # sign bit position
    limb = jnp.take_along_axis(x, (t // 16)[..., None], axis=-1)[..., 0]
    sign = (limb >> (t % 16)) & 1
    k16 = jnp.arange(L, dtype=jnp.int32) * 16
    rel = (t + 1)[..., None] - k16  # bits below rel are kept
    keep_mask = jnp.where(
        rel >= 16, MASK,
        jnp.where(rel <= 0, 0, MASK >> jnp.clip(16 - rel, 0, 16)))
    ext = jnp.where(sign[..., None] == 1,
                    x | (keep_mask ^ MASK), x & keep_mask)
    return jnp.where(over[..., None], x, ext)


# ----------------------------------------------------------- comparisons

def eq(a, b):
    return jnp.all(a == b, axis=-1)


def lt(a, b):
    return ~u256.gte(a, b)


def gt(a, b):
    return ~u256.gte(b, a)


def _flip_sign(a):
    return a.at[..., L - 1].set(a[..., L - 1] ^ 0x8000)


def slt(a, b):
    return lt(_flip_sign(a), _flip_sign(b))


def sgt(a, b):
    return gt(_flip_sign(a), _flip_sign(b))


def bool_word(m: jnp.ndarray) -> jnp.ndarray:
    """Bool (...,) -> u256 0/1 word."""
    out = jnp.zeros(m.shape + (L,), dtype=jnp.int32)
    return out.at[..., 0].set(m.astype(jnp.int32))


def not_(a):
    return a ^ MASK
