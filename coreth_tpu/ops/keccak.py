"""Batched keccak-256 on device — uint32 lane pairs, jnp/XLA.

The reference's hot path leans on asm-optimized keccak everywhere: trie node
hashing (reference trie/hasher.go:69,195), tx/receipt roots (core/types/
hashing.go:97), secure-trie keys, the SHA3 opcode (core/vm/instructions.go),
and CREATE2.  On TPU there is no 64-bit integer datapath worth using, so
lanes are represented as (lo, hi) uint32 pairs and the permutation is
expressed with 32-bit XOR/AND/shift — all VPU-friendly element-wise ops that
vectorize across the batch dimension.

Layout: state arrays have shape (..., 25, 2) uint32, last axis = (lo, hi).
All rotation amounts are static Python ints (the rho schedule), so every
shift lowers to a constant-shift VPU op; the 24 rounds are unrolled at trace
time with round constants baked in as literals.

Entry points:
  - keccak_f1600(state): the permutation, batched over leading dims.
  - keccak256_fixed(words, nbytes): single-block messages (<=135 bytes) of a
    length fixed at trace time — the EVM mapping-slot path (64 bytes) and
    most trie leaf/short nodes.
  - keccak256_blocks(blocks, nblocks): variable-block messages, padded on
    host; masked absorb keeps finished items' states frozen.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# --- static schedule (derived, not transcribed) ----------------------------

_MASK64 = (1 << 64) - 1


def _derive_schedule():
    # Round constants via the LFSR, as in the host reference implementation.
    rc = []
    r = 1
    for _ in range(24):
        v = 0
        for j in range(7):
            r = ((r << 1) ^ ((r >> 7) * 0x71)) % 256
            if r & 2:
                v ^= 1 << ((1 << j) - 1)
        rc.append(v)
    # rho rotation per lane index (x + 5*y) and the pi permutation:
    # dest_index[src] after the rho+pi step.
    rho = [0] * 25
    pi_dest = list(range(25))
    x, y = 1, 0
    for t in range(24):
        # rotation amount belongs to the SOURCE lane of walk step t
        rho[x + 5 * y] = ((t + 1) * (t + 2) // 2) % 64
        x, y = y, (2 * x + 3 * y) % 5
    # pi: A'[y, (2x+3y)%5] = A[x, y]  (in (x, y) coords; index = x + 5*y)
    for xx in range(5):
        for yy in range(5):
            pi_dest[xx + 5 * yy] = yy + 5 * ((2 * xx + 3 * yy) % 5)
    return rc, rho, pi_dest


_RC, _RHO, _PI_DEST = _derive_schedule()
# src lane feeding each destination after rho+pi
_PI_SRC = [0] * 25
for _s, _d in enumerate(_PI_DEST):
    _PI_SRC[_d] = _s


# Static per-lane rho/pi vectors (numpy, baked into the graph as constants)
_RHO_ARR = np.array(_RHO, dtype=np.int64)
_PI_SRC_ARR = np.array(_PI_SRC, dtype=np.int32)
_MOVED_RHO = _RHO_ARR[_PI_SRC_ARR]          # rotation of each dest lane
_RC_LO = np.array([rc & 0xFFFFFFFF for rc in _RC], dtype=np.uint32)
_RC_HI = np.array([rc >> 32 for rc in _RC], dtype=np.uint32)


def _rotl_lanes(lo, hi, r: np.ndarray):
    """Rotate (lo, hi) uint32 lane-pair arrays left by static per-lane
    amounts r (numpy vector broadcast over the trailing lane axis).

    A 64-bit rotate by r over (lo, hi) = a conditional word swap
    (r >= 32) followed by a sub-word rotate by r % 32; all masks and
    shift counts are trace-time constants, so this lowers to a handful
    of elementwise VPU ops regardless of lane count."""
    r = r % 64
    swap = jnp.asarray(r >= 32)
    rr = (r % 32).astype(np.uint32)
    sh = jnp.asarray(rr)
    inv = jnp.asarray(np.where(rr == 0, 1, 32 - rr).astype(np.uint32))
    zero = jnp.asarray(rr == 0)
    l1 = jnp.where(swap, hi, lo)
    h1 = jnp.where(swap, lo, hi)
    nlo = jnp.where(zero, l1, (l1 << sh) | (h1 >> inv))
    nhi = jnp.where(zero, h1, (h1 << sh) | (l1 >> inv))
    return nlo, nhi


def _round(lo, hi, rc_lo, rc_hi):
    """One keccak-f[1600] round over (..., 25) uint32 lane-pair arrays."""
    # theta: column parity C[x] = xor over y of lane[x + 5y]
    vlo = lo.reshape(lo.shape[:-1] + (5, 5))    # [..., y, x]
    vhi = hi.reshape(hi.shape[:-1] + (5, 5))
    c_lo = vlo[..., 0, :] ^ vlo[..., 1, :] ^ vlo[..., 2, :] \
        ^ vlo[..., 3, :] ^ vlo[..., 4, :]
    c_hi = vhi[..., 0, :] ^ vhi[..., 1, :] ^ vhi[..., 2, :] \
        ^ vhi[..., 3, :] ^ vhi[..., 4, :]
    r1_lo, r1_hi = _rotl_lanes(jnp.roll(c_lo, -1, axis=-1),
                               jnp.roll(c_hi, -1, axis=-1),
                               np.array([1] * 5))
    d_lo = jnp.roll(c_lo, 1, axis=-1) ^ r1_lo
    d_hi = jnp.roll(c_hi, 1, axis=-1) ^ r1_hi
    lo = (vlo ^ d_lo[..., None, :]).reshape(lo.shape)
    hi = (vhi ^ d_hi[..., None, :]).reshape(hi.shape)
    # rho + pi: moved[d] = rotl(lane[pi_src[d]], rho[pi_src[d]])
    lo, hi = _rotl_lanes(lo[..., _PI_SRC_ARR], hi[..., _PI_SRC_ARR],
                         _MOVED_RHO)
    # chi: a ^ (~a[x+1] & a[x+2]) along x
    vlo = lo.reshape(lo.shape[:-1] + (5, 5))
    vhi = hi.reshape(hi.shape[:-1] + (5, 5))
    a1_lo = jnp.roll(vlo, -1, axis=-1)
    a1_hi = jnp.roll(vhi, -1, axis=-1)
    a2_lo = jnp.roll(vlo, -2, axis=-1)
    a2_hi = jnp.roll(vhi, -2, axis=-1)
    lo = (vlo ^ (~a1_lo & a2_lo)).reshape(lo.shape)
    hi = (vhi ^ (~a1_hi & a2_hi)).reshape(hi.shape)
    # iota
    lo = lo.at[..., 0].set(lo[..., 0] ^ rc_lo)
    hi = hi.at[..., 0].set(hi[..., 0] ^ rc_hi)
    return lo, hi


def keccak_f1600(state):
    """Apply the keccak-f[1600] permutation.

    state: uint32 array (..., 25, 2); returns the same shape.  The 24
    rounds run under lax.fori_loop with the round constants indexed from
    a baked array — the graph is one round body, so CPU compile stays in
    seconds (round 1 unrolled 24 rounds x 25 scalar lanes and took ~10
    minutes to compile; VERDICT.md weak#4)."""
    lo = state[..., 0]
    hi = state[..., 1]
    rc_lo = jnp.asarray(_RC_LO)
    rc_hi = jnp.asarray(_RC_HI)

    def body(rnd, carry):
        lo, hi = carry
        return _round(lo, hi, rc_lo[rnd], rc_hi[rnd])

    lo, hi = jax.lax.fori_loop(0, 24, body, (lo, hi))
    return jnp.stack([lo, hi], axis=-1)


_RATE_WORDS = 34  # 136 bytes / 4


def _absorb_words(state, words):
    """XOR 34 uint32 words (one rate block) into lanes 0..16 and permute."""
    # words: (..., 34) uint32 -> pairs (..., 17, 2)
    pairs = words.reshape(words.shape[:-1] + (17, 2))
    pad = jnp.zeros(words.shape[:-1] + (8, 2), dtype=jnp.uint32)
    full = jnp.concatenate([pairs, pad], axis=-2)
    return keccak_f1600(state ^ full)


def keccak256_fixed(words, nbytes: int):
    """keccak-256 of single-block messages with trace-time-static length.

    words: uint32 array (..., 34) — the message bytes as little-endian
    uint32 words, zero-padded.  nbytes must be <= 135.  Returns (..., 8)
    uint32 digest words (little-endian).
    """
    assert nbytes <= 135
    # keccak pad10*1: suffix 0x01 at nbytes, 0x80 at byte 135.
    w = words
    suffix = np.zeros(34, dtype=np.uint32)
    suffix[nbytes // 4] ^= np.uint32(0x01) << (8 * (nbytes % 4))
    suffix[33] ^= np.uint32(0x80) << 24
    w = w ^ jnp.asarray(suffix)
    state = jnp.zeros(w.shape[:-1] + (25, 2), dtype=jnp.uint32)
    state = _absorb_words(state, w)
    return state[..., :4, :].reshape(state.shape[:-2] + (8,))


@jax.jit
def keccak256_blocks(blocks, nblocks):
    """keccak-256 of host-padded multi-block messages.

    blocks: uint32 (batch, max_blocks, 34) — keccak padding already applied
    on host (suffix 0x01 / 0x80 in the final real block).
    nblocks: int32 (batch,) — real block count per item (>= 1).
    Returns (batch, 8) uint32 digest words.

    Jitted: the 24 unrolled rounds compile to one executable; callers
    should bucket (batch, max_blocks) shapes (pack_blocks pads) so the
    compile cache stays small.
    """
    blocks = jnp.asarray(blocks, dtype=jnp.uint32)
    nblocks = jnp.asarray(nblocks, dtype=jnp.int32)
    batch = blocks.shape[0]
    max_blocks = blocks.shape[1]
    state = jnp.zeros((batch, 25, 2), dtype=jnp.uint32)

    def body(i, st):
        absorbed = _absorb_words(st, blocks[:, i, :])
        keep = (i < nblocks)[:, None, None]
        return jnp.where(keep, absorbed, st)

    state = jax.lax.fori_loop(0, max_blocks, body, state)
    return state[:, :4, :].reshape(batch, 8)


# --- host-side packing helpers ---------------------------------------------


def pack_fixed(msgs: list[bytes], nbytes: int) -> np.ndarray:
    """Pack equal-length messages for keccak256_fixed."""
    buf = np.zeros((len(msgs), 136), dtype=np.uint8)
    for i, m in enumerate(msgs):
        assert len(m) == nbytes
        buf[i, :nbytes] = np.frombuffer(m, dtype=np.uint8)
    return buf.view(np.uint32).reshape(len(msgs), 34)


def pack_blocks(msgs: list[bytes],
                pad_batch: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Pack variable-length messages (keccak padding applied) for
    keccak256_blocks.  Batch and block-count dimensions are padded to
    powers of two so the jitted kernel compiles per bucket, not per
    call."""
    nblocks = np.array([len(m) // 136 + 1 for m in msgs], dtype=np.int32)
    max_blocks = int(nblocks.max()) if len(msgs) else 1
    max_blocks = 1 << (max_blocks - 1).bit_length()
    n = len(msgs)
    batch = 1 << (n - 1).bit_length() if (pad_batch and n) else n
    buf = np.zeros((batch, max_blocks * 136), dtype=np.uint8)
    for i, m in enumerate(msgs):
        buf[i, :len(m)] = np.frombuffer(m, dtype=np.uint8)
        end = nblocks[i] * 136
        buf[i, len(m)] ^= 0x01
        buf[i, end - 1] ^= 0x80
    if batch > n:
        nblocks = np.concatenate(
            [nblocks, np.ones(batch - n, dtype=np.int32)])
        buf[n:, 0] ^= 0x01   # empty-message keccak padding
        buf[n:, 135] ^= 0x80
    return (buf.view(np.uint32).reshape(batch, max_blocks, 34), nblocks)


def digest_words_to_bytes(words: np.ndarray) -> list[bytes]:
    """Convert (batch, 8) uint32 LE digest words to 32-byte digests."""
    w = np.asarray(words, dtype=np.uint32)
    return [w[i].tobytes() for i in range(w.shape[0])]
