"""Device kernels (jnp/XLA + Pallas) for the coreth-tpu hot path.

These replace the native/asm dependencies of the reference's hot loops
(SURVEY.md section 2.7): batched keccak-f[1600] (trie hashing, SHA3 opcode,
DeriveSha), 256-bit limb arithmetic for the EVM (uint256), and bloom-filter
construction.
"""
