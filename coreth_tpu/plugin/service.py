"""Local-socket RPC boundary for the VM — the rpcchainvm twin.

Twin of reference plugin/main.go:33 (rpcchainvm.Serve): the consensus
engine lives in another process and drives the VM over a wire protocol.
Here the transport is a unix domain socket carrying newline-delimited
JSON frames ({"id", "method", "params"} -> {"id", "result"} |
{"id", "error"}); byte-valued fields travel hex-encoded.  The method
surface mirrors the snowman ChainVM + Block interfaces:

  initialize, buildBlock, parseBlock, getBlock, setPreference,
  lastAccepted, issueTx, issueAtomicTx, blockVerify, blockAccept,
  blockReject, blockStatus, mempoolStats, atomicMempoolStats, health,
  shutdown

VMServer hosts a VM instance; VMClient is the in-Python consensus-side
stub (the role AvalancheGo's rpcchainvm client plays).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
from typing import Optional

from coreth_tpu.plugin.vm import VM, VMError
from coreth_tpu.types import Transaction


def _blk_info(blk) -> dict:
    return {
        "id": blk.id.hex(),
        "parentId": blk.parent_id.hex(),
        "height": blk.height,
        "timestamp": blk.timestamp,
        "status": blk.status.value,
        "bytes": blk.bytes().hex(),
    }


class VMServer:
    """Serves one VM over a unix socket (rpcchainvm.Serve role)."""

    def __init__(self, vm: Optional[VM] = None):
        self.vm = vm or VM()
        self._server: Optional[socketserver.ThreadingUnixStreamServer] = None
        self._thread: Optional[threading.Thread] = None
        # one VM, many connections: the real rpcchainvm relies on the
        # VM's internal locks; this VM has none, so serialize here
        self._lock = threading.Lock()
        self._cpu_profiler = None
        # cross-process app network state (appRequest/appGossip seam)
        self._app_handler = None
        self._peers: list = []
        self._gossiper = None

    def _inbound_gossiper(self):
        if self._gossiper is None:
            from coreth_tpu.plugin.gossiper import Gossiper
            self._gossiper = Gossiper(
                None, self.vm.txpool,
                atomic_mempool=getattr(self.vm, "atomic_mempool", None))
        return self._gossiper

    # ------------------------------------------------------------ dispatch
    def handle(self, method: str, params: dict):
        vm = self.vm
        if method == "initialize":
            vm.initialize(params["genesisBytes"],
                          bytes.fromhex(params.get("configBytes", "")))
            return _blk_info(vm.last_accepted())
        if method == "buildBlock":
            return _blk_info(vm.build_block())
        if method == "parseBlock":
            return _blk_info(vm.parse_block(bytes.fromhex(params["bytes"])))
        if method == "getBlock":
            return _blk_info(vm.get_block(bytes.fromhex(params["id"])))
        if method == "setPreference":
            vm.set_preference(bytes.fromhex(params["id"]))
            return {}
        if method == "lastAccepted":
            return _blk_info(vm.last_accepted())
        if method == "issueTx":
            vm.issue_tx(Transaction.decode(bytes.fromhex(params["tx"])))
            return {}
        if method == "issueAtomicTx":
            from coreth_tpu.atomic import Tx as AtomicTx
            vm.issue_atomic_tx(
                AtomicTx.decode(bytes.fromhex(params["tx"])))
            return {}
        if method == "atomicMempoolStats":
            return vm.atomic_mempool_stats()
        if method == "avax.getAtomicTx":
            hit = vm.get_atomic_tx(bytes.fromhex(params["txID"]))
            if hit is None:
                return {"status": "Unknown"}
            tx, height = hit
            return {"tx": tx.encode().hex(),
                    "blockHeight": height,
                    "status": "Accepted" if height is not None
                    else "Processing"}
        if method == "avax.getAtomicTxStatus":
            return {"status": vm.get_atomic_tx_status(
                bytes.fromhex(params["txID"]))}
        if method == "avax.getUTXOs":
            utxos = vm.get_utxos(
                [bytes.fromhex(a) for a in params["addresses"]],
                bytes.fromhex(params["sourceChain"]),
                limit=int(params.get("limit", 100)))
            return {"numFetched": len(utxos),
                    "utxos": [u.hex() for u in utxos]}
        if method == "blockVerify":
            blk = vm.get_block(bytes.fromhex(params["id"]))
            blk.verify()
            return _blk_info(blk)
        if method == "blockAccept":
            blk = vm.get_block(bytes.fromhex(params["id"]))
            blk.accept()
            return _blk_info(blk)
        if method == "blockReject":
            blk = vm.get_block(bytes.fromhex(params["id"]))
            blk.reject()
            return _blk_info(blk)
        if method == "blockStatus":
            return {"status":
                    vm.get_block(bytes.fromhex(params["id"])).status.value}
        if method == "mempoolStats":
            pending, queued = vm.mempool_stats()
            return {"pending": pending, "queued": queued}
        if method == "pollEngineMessage":
            return {"message":
                    vm.to_engine.popleft() if vm.to_engine else None}
        if method == "health":
            return vm.health()
        # ---- cross-process app network (peer/socket_transport.py):
        # the AppRequest/AppGossip seam served over THIS process's
        # socket, so sync/warp/gossip flow between VM processes
        if method == "appRequest":
            if self._app_handler is None:
                self._app_handler = vm.app_request_handler()
            resp = self._app_handler(bytes.fromhex(params["payload"]))
            return {"response": resp.hex()}
        if method == "appGossip":
            self._inbound_gossiper().handle_gossip(
                bytes.fromhex(params["payload"]))
            return {}
        if method == "connectPeer":
            from coreth_tpu.peer.socket_transport import SocketPeer
            self._peers.append(SocketPeer(params["path"]))
            return {"peers": len(self._peers)}
        if method == "getLastStateSummary":
            summary = vm.state_sync_server.get_last_state_summary()
            return {"summary": summary.encode().hex()}
        if method == "stateSyncFromPeer":
            # sync this VM from the last connected peer: fetch the
            # peer's latest summary over its socket, then run the full
            # syncervm client against the cross-process transport
            peer = self._peers[-1]
            raw = bytes.fromhex(peer._client.call(
                "getLastStateSummary")["summary"])
            client = vm.state_sync_client(peer.send_request)
            client.accept_summary(client.parse_state_summary(raw))
            return {"height": vm.chain.last_accepted.number,
                    "stats": client.stats}
        if method == "getBlockByHeight":
            blk = vm.chain.get_block_by_number(int(params["height"]))
            return {"bytes": blk.encode().hex()}
        if method == "gossipTx":
            from coreth_tpu.peer.socket_transport import MultiPeer
            from coreth_tpu.plugin.gossiper import Gossiper
            from coreth_tpu.types import Transaction as _Tx
            g = Gossiper(MultiPeer(self._peers), vm.txpool)
            n = g.gossip_txs(
                [_Tx.decode(bytes.fromhex(params["tx"]))])
            return {"gossiped": n}
        # admin.* (plugin/evm/admin.go surface): profiling control,
        # log level, live VM config
        if method == "admin.startCPUProfiler":
            self._admin_profiler().start(params.get(
                "file", "/tmp/coreth_tpu_cpu.prof"))
            return {}
        if method == "admin.stopCPUProfiler":
            return {"file": self._admin_profiler().stop()}
        if method == "admin.memoryProfile":
            from coreth_tpu.rpc.debugapi import memory_stats
            return memory_stats()
        if method == "admin.setLogLevel":
            import logging
            level = params.get("level", "info").upper()
            if level not in ("DEBUG", "INFO", "WARNING", "ERROR",
                             "CRITICAL"):
                raise VMError(f"unknown log level {level!r}")
            logging.getLogger("coreth_tpu").setLevel(level)
            return {}
        if method == "admin.getVMConfig":
            vm._require_init()
            cfg = vm.config
            return {k: getattr(cfg, k) for k in vars(cfg)
                    if not k.startswith("_")
                    and isinstance(getattr(cfg, k),
                                   (int, float, str, bool, type(None)))}
        if method == "shutdown":
            vm.shutdown()
            return {}
        raise VMError(f"unknown method {method!r}")

    def _admin_profiler(self):
        # one profiler per process: share the instance the Ethereum
        # facade registered for debug_* so the already-in-progress
        # guard spans every surface
        eth = getattr(self.vm, "eth", None)
        if eth is not None:
            return eth.cpu_profiler
        if self._cpu_profiler is None:
            from coreth_tpu.rpc.debugapi import CPUProfiler
            self._cpu_profiler = CPUProfiler()
        return self._cpu_profiler

    # ----------------------------------------------------------- transport
    def serve(self, path: str) -> None:
        """Bind the socket and serve in a daemon thread."""
        if os.path.exists(path):
            os.unlink(path)
        handle = self.handle

        lock = self._lock

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):  # noqa: A003
                for line in self.rfile:
                    req = None
                    try:
                        req = json.loads(line)
                        with lock:
                            result = handle(req["method"],
                                            req.get("params", {}))
                        resp = {"id": req.get("id"), "result": result}
                    except Exception as e:  # noqa: BLE001 — wire error
                        rid = req.get("id") if isinstance(req, dict) \
                            else None
                        resp = {"id": rid,
                                "error": f"{type(e).__name__}: {e}"}
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingUnixStreamServer):
            # handler threads block in rfile reads while clients hold
            # their sockets open; non-daemon threads would deadlock
            # server_close() and interpreter exit
            daemon_threads = True

        self._server = Server(path, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def serve(vm: VM, path: str) -> VMServer:
    """Serve `vm` at the unix-socket `path` (plugin/main.go:33 role)."""
    server = VMServer(vm)
    server.serve(path)
    return server


class VMClient:
    """Consensus-side stub speaking the wire protocol."""

    def __init__(self, path: str):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(path)
        self._file = self.sock.makefile("rwb")
        self._next_id = 0

    def call(self, method: str, **params):
        self._next_id += 1
        frame = {"id": self._next_id, "method": method, "params": params}
        self._file.write((json.dumps(frame) + "\n").encode())
        self._file.flush()
        resp = json.loads(self._file.readline())
        if "error" in resp:
            raise VMError(resp["error"])
        return resp["result"]

    # convenience wrappers mirroring the ChainVM surface
    def initialize(self, genesis_json: str):
        return self.call("initialize", genesisBytes=genesis_json)

    def build_block(self):
        return self.call("buildBlock")

    def parse_block(self, data: bytes):
        return self.call("parseBlock", bytes=data.hex())

    def get_block(self, block_id: bytes):
        return self.call("getBlock", id=block_id.hex())

    def set_preference(self, block_id: bytes):
        return self.call("setPreference", id=block_id.hex())

    def last_accepted(self):
        return self.call("lastAccepted")

    def issue_tx(self, tx_bytes: bytes):
        return self.call("issueTx", tx=tx_bytes.hex())

    def block_verify(self, block_id: bytes):
        return self.call("blockVerify", id=block_id.hex())

    def block_accept(self, block_id: bytes):
        return self.call("blockAccept", id=block_id.hex())

    def block_reject(self, block_id: bytes):
        return self.call("blockReject", id=block_id.hex())

    def issue_atomic_tx(self, tx_bytes: bytes):
        return self.call("issueAtomicTx", tx=tx_bytes.hex())

    def atomic_mempool_stats(self):
        return self.call("atomicMempoolStats")

    def get_atomic_tx(self, tx_id: bytes):
        return self.call("avax.getAtomicTx", txID=tx_id.hex())

    def get_atomic_tx_status(self, tx_id: bytes):
        return self.call("avax.getAtomicTxStatus",
                         txID=tx_id.hex())["status"]

    def get_utxos(self, addresses, source_chain: bytes, limit=100):
        return self.call("avax.getUTXOs",
                         addresses=[a.hex() for a in addresses],
                         sourceChain=source_chain.hex(), limit=limit)

    def poll_engine_message(self):
        return self.call("pollEngineMessage")["message"]

    def close(self) -> None:
        self._file.close()
        self.sock.close()
