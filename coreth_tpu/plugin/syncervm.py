"""State-sync client/server at the consensus seam.

Twin of reference plugin/evm/syncervm_server.go (:19-110 — serve
SyncSummary at commit heights) and syncervm_client.go (:39-412 —
select a summary, sync blocks + atomic trie + state trie over the app
network, then finishSync: pivot the chain to the synced tip and reset
the txpool), with message/syncable.go's SyncSummary codec.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from coreth_tpu.crypto import keccak256
from coreth_tpu.wire import Packer, Unpacker

# how many ancestor blocks the client fetches behind the summary
# (syncervm_client.go parentsToGet = 256)
PARENTS_TO_FETCH = 256


class StateSyncError(Exception):
    pass


@dataclass
class SyncSummary:
    """message/syncable.go SyncSummary: everything a syncing node
    needs to pivot to a trusted height."""
    height: int = 0
    block_hash: bytes = b"\x00" * 32
    block_root: bytes = b"\x00" * 32
    atomic_root: bytes = b"\x00" * 32

    def encode(self) -> bytes:
        p = Packer()
        p.u64(self.height)
        p.fixed(self.block_hash, 32)
        p.fixed(self.block_root, 32)
        p.fixed(self.atomic_root, 32)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "SyncSummary":
        u = Unpacker(data)
        return cls(u.u64(), u.fixed(32), u.fixed(32), u.fixed(32))

    def id(self) -> bytes:
        return keccak256(self.encode())


class StateSyncServer:
    """syncervm_server.go: summaries exist only at commit heights, so
    a syncing peer can resolve the state trie from flushed storage."""

    def __init__(self, vm, sync_interval: Optional[int] = None):
        self.vm = vm
        self.interval = sync_interval \
            or getattr(vm.chain, "commit_interval", None) or 4096

    def _summary_at(self, height: int) -> SyncSummary:
        block = self.vm.chain.get_block_by_number(height)
        if block is None:
            raise StateSyncError(f"no canonical block at {height}")
        atomic_root = b"\x00" * 32
        backend = self.vm.atomic_backend
        if backend is not None:
            root = backend.trie.committed_roots.get(height)
            if root is None:
                raise StateSyncError(
                    f"no committed atomic root at {height}")
            atomic_root = root
        return SyncSummary(height, block.hash(), block.root, atomic_root)

    def get_last_state_summary(self) -> SyncSummary:
        """GetLastStateSummary (:48): the newest commit-height
        summary at or below the last accepted block."""
        last = self.vm.chain.last_accepted.number
        height = last - (last % self.interval)
        if height == 0:
            raise StateSyncError("no summary available yet")
        return self._summary_at(height)

    def get_state_summary(self, height: int) -> SyncSummary:
        """GetStateSummary (:94): a specific commit-height summary."""
        if height == 0 or height % self.interval != 0:
            raise StateSyncError(f"not a summary height: {height}")
        return self._summary_at(height)


class StateSyncClient:
    """syncervm_client.go: drives the whole sync from one summary."""

    def __init__(self, vm, transport):
        """transport: bytes -> bytes against a serving peer (the
        peer.NetworkClient seam — e.g. peer.send_request_any)."""
        from coreth_tpu.sync.client import SyncClient
        self.vm = vm
        self.client = SyncClient(transport)
        self.stats: dict = {}

    @staticmethod
    def parse_state_summary(raw: bytes) -> SyncSummary:
        return SyncSummary.decode(raw)

    # ------------------------------------------------------------ phases
    def _sync_blocks(self, summary: SyncSummary) -> List:
        """syncBlocks (:237): fetch the summary block + up to 256
        parents, hash-chain-verified by the client."""
        from coreth_tpu.types import Block
        want = min(PARENTS_TO_FETCH, summary.height)
        raws = self.client.get_blocks(summary.block_hash, summary.height,
                                      want)
        # the serving peer produced the summary, so it must hold the
        # full ancestor window — a short response is a bad peer, not a
        # shallow pivot (a silent short set would truncate the history
        # this node later serves to other syncers)
        if len(raws) != want:
            raise StateSyncError(
                f"peer served {len(raws)} blocks, wanted {want}")
        blocks = [Block.decode(r) for r in raws]
        self.stats["blocks"] = len(blocks)
        return blocks  # newest first

    def _sync_atomic_trie(self, summary: SyncSummary) -> None:
        """atomic_syncer.go role: page the atomic trie's height-keyed
        leaves, rebuild locally, verify the root, apply the ops to
        shared memory, and swap the backend's trie."""
        backend = self.vm.atomic_backend
        if backend is None or summary.atomic_root == b"\x00" * 32:
            return
        from coreth_tpu.atomic.trie import AtomicTrie
        from coreth_tpu.sync.messages import ATOMIC_TRIE_NODE
        # rebuilt over the SAME (durable) node store as the backend's
        # trie, so the synced trie and the apply cursor survive a
        # crash between sync and full application
        synced = AtomicTrie(node_db=backend.trie.node_db,
                            commit_interval=backend.trie.commit_interval)
        n = 0
        start = b""
        while True:
            keys, vals, more = self.client.get_leafs(
                summary.atomic_root, start=start,
                node_type=ATOMIC_TRIE_NODE)
            for k, v in zip(keys, vals):
                synced.trie.update(k, v)
                n += 1
            if not more or not keys:
                break
            start = _next_key(keys[-1])
        root = synced.trie.commit()
        if root != summary.atomic_root:
            raise StateSyncError(
                f"atomic trie root mismatch: {root.hex()}")
        synced.last_committed_root = root
        synced.last_committed_height = summary.height
        synced.committed_roots[summary.height] = root
        backend.trie = synced
        backend.save_trie_meta()
        # apply ONLY after the full trie verified, through the durable
        # cursor (atomic_backend.go:252/:373): a crash mid-apply leaves
        # a marker the VM resumes from at the next initialize, and
        # tolerant per-height application makes the replay idempotent
        backend.mark_apply_to_shared_memory(summary.height)
        backend.apply_to_shared_memory()
        self.stats["atomic_leafs"] = n

    def _sync_state_trie(self, summary: SyncSummary) -> None:
        """syncStateTrie (:298): verified-range download of the full
        state under the summary root, into the chain's database."""
        from coreth_tpu.sync.statesync import StateSyncer
        syncer = StateSyncer(self.client, db=self.vm.chain.db)
        syncer.sync(summary.block_root)
        self.stats.update(syncer.stats)

    # ------------------------------------------------------------- accept
    def accept_summary(self, summary: SyncSummary) -> None:
        """acceptSyncSummary (:164) + finishSync (:330): run every
        phase, then pivot the chain to the synced tip and re-anchor
        the tx pool on it."""
        blocks = self._sync_blocks(summary)
        self._sync_atomic_trie(summary)
        self._sync_state_trie(summary)
        # pivot fires the chain-head event, which the VM already wires
        # to a txpool reset; blocks[0]'s identity was hash-chain
        # verified against summary.block_hash by get_blocks
        self.vm.chain.reset_to_synced(blocks[0], blocks[1:])
        from coreth_tpu.plugin.block import PluginBlock, Status
        blk = PluginBlock(self.vm, blocks[0])
        blk.status = Status.ACCEPTED
        self.vm._register(blk)
        self.vm.preferred_id = blk.id


def _next_key(key: bytes) -> bytes:
    n = int.from_bytes(key, "big") + 1
    return n.to_bytes(len(key), "big")
