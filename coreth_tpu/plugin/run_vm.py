"""Standalone VM process: `python -m coreth_tpu.plugin.run_vm <socket>`.

The plugin/main.go role for multi-process tests: boots an empty VM,
serves it over the unix socket (rpcchainvm seam), and blocks until
killed.  The consensus side drives everything — including
`initialize` — over the socket.  The clock is synthetic (+10s per
read, like the VM test harnesses) so block building is deterministic
regardless of wall time.
"""

from __future__ import annotations

import itertools
import signal
import sys
import threading


def main(path: str, start_time: int = 1_000) -> None:
    from coreth_tpu.plugin import VM
    from coreth_tpu.plugin.service import serve

    clock = itertools.count(start_time, 10).__next__
    vm = VM(clock=clock)
    server = serve(vm, path)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    print(f"vm serving on {path}", flush=True)
    stop.wait()
    server.close()


if __name__ == "__main__":
    main(sys.argv[1],
         int(sys.argv[2]) if len(sys.argv) > 2 else 1_000)
