"""AppRequest dispatch: sync handlers + warp signature handler.

Twin of reference plugin/evm/network_handler.go: one request handler
registered on the peer network routes incoming messages by type —
leafs/code/block requests to the state-sync server handlers,
signature requests to the warp backend
(warp/handlers/signature_request.go).
"""

from __future__ import annotations

from typing import Optional

from coreth_tpu.sync.messages import SignatureRequest, SignatureResponse


class SignatureRequestHandler:
    """Serves this node's BLS signatures to aggregating validators
    (warp/handlers/signature_request.go:~30 OnSignatureRequest).
    Unknown messages produce an EMPTY signature response, never an
    error — a peer's ignorance must not poison the aggregate."""

    def __init__(self, warp_backend):
        self.backend = warp_backend
        self.served = 0
        self.unknown = 0

    def on_signature_request(self, req: SignatureRequest
                             ) -> SignatureResponse:
        try:
            if req.message_id:
                sig = self.backend.get_message_signature(req.message_id)
            elif req.block_hash:
                sig = self.backend.get_block_signature(req.block_hash)
            else:
                raise KeyError("empty signature request")
        except KeyError:
            self.unknown += 1
            return SignatureResponse(b"")
        self.served += 1
        return SignatureResponse(sig)


class CrossChainHandler:
    """Serves cross-chain eth_call requests against the chain's
    accepted tip (plugin/evm/message/cross_chain_handler.go): errors
    travel in-band so a bad call never poisons the transport."""

    def __init__(self, backend):
        self.backend = backend  # rpc.Backend (eth_call executor)

    def on_eth_call(self, req) -> "EthCallResponse":
        from coreth_tpu.sync.messages import EthCallResponse
        try:
            block = self.backend.chain.last_accepted
            result = self.backend.call(
                {"to": "0x" + req.to.hex(),
                 "data": "0x" + req.data.hex()}, block)
            if result.failed:
                return EthCallResponse(error="execution reverted")
            return EthCallResponse(result=result.return_data)
        except Exception as e:  # noqa: BLE001 — in-band error
            return EthCallResponse(error=f"{type(e).__name__}: {e}")


class NetworkHandler:
    """networkHandler (plugin/evm/network_handler.go): the single
    request_handler joined to the AppNetwork."""

    def __init__(self, sync_handler=None, warp_backend=None,
                 eth_backend=None):
        self.sync_handler = sync_handler
        self.signature_handler = (SignatureRequestHandler(warp_backend)
                                  if warp_backend is not None else None)
        self.cross_chain_handler = (CrossChainHandler(eth_backend)
                                    if eth_backend is not None else None)

    def handle(self, raw: bytes) -> bytes:
        kind = raw[0]
        if kind == 6:
            if self.signature_handler is None:
                return SignatureResponse(b"").encode()
            return self.signature_handler.on_signature_request(
                SignatureRequest.decode(raw)).encode()
        if kind == 8:
            from coreth_tpu.sync.messages import (
                EthCallRequest, EthCallResponse,
            )
            if self.cross_chain_handler is None:
                return EthCallResponse(
                    error="eth_call not served here").encode()
            return self.cross_chain_handler.on_eth_call(
                EthCallRequest.decode(raw)).encode()
        if self.sync_handler is None:
            raise ValueError(f"no handler for message kind {kind}")
        return self.sync_handler.handle(raw)


def network_signature_fetcher(peer, node_ids=None):
    """Build the Aggregator's fetch_signature callable over an
    AppNetwork Peer: request node_id's signature for a message
    (aggregator/signature_getter.go role)."""
    def fetch(node_id: bytes, msg) -> Optional[bytes]:
        raw = peer.send_request(
            node_id, SignatureRequest(message_id=msg.id()).encode())
        resp = SignatureResponse.decode(raw)
        return resp.signature or None
    return fetch
