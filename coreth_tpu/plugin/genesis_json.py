"""Genesis JSON parsing — the plugin's wire format for chain creation.

Twin of reference core/genesis.go UnmarshalJSON + plugin/evm/vm.go:448
(the VM receives genesis bytes from AvalancheGo and decodes them into a
chain config + allocation).  Accepts the geth-style layout:

    {"config": {"chainId": 43111, "apricotPhase1BlockTimestamp": 0, ...},
     "alloc": {"<hex addr>": {"balance": "0x..", "code": "0x..",
                              "nonce": "0x..", "storage": {...}}},
     "gasLimit": "0x7a1200", "timestamp": "0x0", ...}

Unknown config keys are ignored; missing fork keys default to None
(fork inactive), matching the reference's pointer-nil semantics.
"""

from __future__ import annotations

import json
from typing import Optional, Union

from coreth_tpu.chain import Genesis, GenesisAccount
from coreth_tpu.params import ChainConfig

# JSON key -> ChainConfig field.  Block-number forks use geth names;
# Avalanche forks use the network-upgrade timestamp names
# (params/config.go:419-470).
_CONFIG_KEYS = {
    "chainId": "chain_id",
    "homesteadBlock": "homestead_block",
    "eip150Block": "eip150_block",
    "eip155Block": "eip155_block",
    "eip158Block": "eip158_block",
    "byzantiumBlock": "byzantium_block",
    "constantinopleBlock": "constantinople_block",
    "petersburgBlock": "petersburg_block",
    "istanbulBlock": "istanbul_block",
    "muirGlacierBlock": "muir_glacier_block",
    "apricotPhase1BlockTimestamp": "apricot_phase1_time",
    "apricotPhase2BlockTimestamp": "apricot_phase2_time",
    "apricotPhase3BlockTimestamp": "apricot_phase3_time",
    "apricotPhase4BlockTimestamp": "apricot_phase4_time",
    "apricotPhase5BlockTimestamp": "apricot_phase5_time",
    "apricotPhasePre6BlockTimestamp": "apricot_phase_pre6_time",
    "apricotPhase6BlockTimestamp": "apricot_phase6_time",
    "apricotPhasePost6BlockTimestamp": "apricot_phase_post6_time",
    "banffBlockTimestamp": "banff_time",
    "cortinaBlockTimestamp": "cortina_time",
    "durangoBlockTimestamp": "durango_time",
    "cancunTime": "cancun_time",
}


def _num(v, default: int = 0) -> int:
    if v is None:
        return default
    if isinstance(v, str):
        return int(v, 16) if v.startswith("0x") else int(v)
    return int(v)


def _opt_num(v) -> Optional[int]:
    return None if v is None else _num(v)


def _hexb(v: str) -> bytes:
    return bytes.fromhex(v[2:] if v.startswith("0x") else v)


def parse_chain_config(d: dict) -> ChainConfig:
    kwargs = {}
    for json_key, field in _CONFIG_KEYS.items():
        if json_key in d:
            v = d[json_key]
            kwargs[field] = _num(v) if field == "chain_id" else _opt_num(v)
    cfg = ChainConfig()
    for field, value in kwargs.items():
        setattr(cfg, field, value)
    return cfg


def parse_genesis_json(data: Union[bytes, str, dict]) -> Genesis:
    if isinstance(data, (bytes, str)):
        d = json.loads(data)
    else:
        d = data
    config = parse_chain_config(d.get("config", {}))
    alloc = {}
    for addr_hex, acct in d.get("alloc", {}).items():
        addr = _hexb(addr_hex)
        if len(addr) != 20:
            raise ValueError(f"bad alloc address {addr_hex!r}")
        storage = {_hexb(k).rjust(32, b"\x00"):
                   _hexb(v).rjust(32, b"\x00")
                   for k, v in acct.get("storage", {}).items()}
        alloc[addr] = GenesisAccount(
            balance=_num(acct.get("balance")),
            code=_hexb(acct["code"]) if acct.get("code") else b"",
            nonce=_num(acct.get("nonce")),
            storage=storage)
    return Genesis(
        config=config,
        alloc=alloc,
        nonce=_num(d.get("nonce")),
        timestamp=_num(d.get("timestamp")),
        extra_data=_hexb(d["extraData"]) if d.get("extraData") else b"",
        gas_limit=_num(d.get("gasLimit")),
        difficulty=_num(d.get("difficulty")),
        coinbase=_hexb(d["coinbase"]) if d.get("coinbase")
        else b"\x00" * 20,
        base_fee=_opt_num(d.get("baseFeePerGas")),
    )
