"""The snowman ChainVM facade.

Twin of reference plugin/evm/vm.go: Initialize (:368) wires the chain,
tx pool and miner from genesis bytes; buildBlock (:1262) assembles a
block from the mempool; parseBlock (:1317) / getBlock (:1347) /
SetPreference (:1359) complete the consensus-facing surface.  Blocks
returned from here are PluginBlock adapters whose Verify/Accept/Reject
drive the underlying BlockChain.

The engine-notification channel (`to_engine`) carries PendingTxs
messages the way plugin/evm/block_builder.go:91 signals AvalancheGo to
call BuildBlock.
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Deque, Dict, List, Optional, Union

from coreth_tpu.chain import BlockChain
from coreth_tpu.miner import Miner
from coreth_tpu.plugin.block import PluginBlock, Status
from coreth_tpu.plugin.config import parse_config
from coreth_tpu.plugin.genesis_json import parse_genesis_json
from coreth_tpu.txpool import TxPool
from coreth_tpu.types import Block, Transaction

PENDING_TXS = "PendingTxs"  # the message on the toEngine channel


class VMError(Exception):
    pass


class VM:
    """Consensus-driven EVM execution engine (vm.go:242)."""

    def __init__(self, clock=_time.time, shared_memory=None,
                 chain_ctx=None, atomic_store=None):
        """shared_memory/chain_ctx: supplying an atomic.SharedMemory
        (and optionally a ChainContext) wires the full atomic subsystem
        — backend, mempool, ExtData packing at build, accept-time
        shared-memory application (vm.go:986 / :979 / block.go:177).
        atomic_store: durable dict/KVStore for the atomic tx
        repository + the shared-memory apply cursor (the versiondb
        role); pass the same store across restarts for recovery."""
        self.clock = clock
        self.atomic_store = atomic_store if atomic_store is not None \
            else {}
        self.atomic_repository = None
        self.initialized = False
        self.eth = None
        self.chain: Optional[BlockChain] = None
        self.txpool: Optional[TxPool] = None
        self.miner: Optional[Miner] = None
        self._blocks: Dict[bytes, PluginBlock] = {}
        self.to_engine: Deque[str] = deque()
        self.preferred_id: Optional[bytes] = None
        self.shared_memory = shared_memory
        self.chain_ctx = chain_ctx
        self.atomic_backend = None
        self.atomic_mempool = None
        self._building_atomic = []
        from coreth_tpu.plugin.block_verification import (
            SyntacticBlockValidator,
        )
        self.block_validator = SyntacticBlockValidator()
        # set False while consensus bootstraps (SetState analog);
        # UTXO-presence verification is skipped before normal op
        self.bootstrapped = True
        # warp subsystem (vm.go warp backend + handlers): wired by
        # enable_warp() before initialize
        self.warp_backend = None
        self.warp_config = None

    # ------------------------------------------------------------ lifecycle
    def initialize(self, genesis_bytes: Union[bytes, str, dict],
                   config_bytes: bytes = b"") -> None:
        """VM.Initialize (vm.go:368): decode genesis + the per-chain
        JSON config (vm.go:379, plugin/config.py twin) and build the
        chain stack from them."""
        if self.initialized:
            raise VMError("already initialized")
        genesis = parse_genesis_json(genesis_bytes)
        self.config = parse_config(config_bytes)
        engine = None
        if self.shared_memory is not None:
            from coreth_tpu.atomic import (
                AtomicBackend, ChainContext, make_callbacks,
            )
            from coreth_tpu.atomic.mempool import AtomicMempool
            from coreth_tpu.consensus.engine import DummyEngine
            ctx = self.chain_ctx or ChainContext()
            self.chain_ctx = ctx
            from coreth_tpu.atomic.backend import TRIE_META_KEY
            from coreth_tpu.atomic.trie import AtomicTrie
            from coreth_tpu.atomic.repository import (
                AtomicTxRepository, PrefixedStore,
            )
            from coreth_tpu.mpt import EMPTY_ROOT
            # the atomic trie's nodes live in the durable store (its
            # committed root persisted alongside), so the apply cursor
            # always has the trie it refers to after a restart
            meta = self.atomic_store.get(TRIE_META_KEY)
            trie = AtomicTrie(
                node_db=PrefixedStore(self.atomic_store, b"an"),
                root=meta[:32] if meta else EMPTY_ROOT,
                commit_interval=self.config.commit_interval)
            if meta:
                trie.last_committed_root = meta[:32]
                trie.last_committed_height = int.from_bytes(meta[32:],
                                                            "big")
                trie.committed_roots[trie.last_committed_height] = \
                    meta[:32]
            self.atomic_backend = AtomicBackend(
                ctx, self.shared_memory, trie=trie,
                metadata=self.atomic_store)
            self.atomic_repository = AtomicTxRepository(
                self.atomic_store)
            if self.atomic_backend.pending_apply():
                # crashed mid-ApplyToSharedMemory: resume from the
                # durable cursor before serving anything (vm.go init
                # path -> atomic_backend.go:252)
                self.atomic_backend.apply_to_shared_memory()
            self.atomic_mempool = AtomicMempool(ctx)
            cb = make_callbacks(self.atomic_backend, genesis.config,
                                pending_atomic_txs=self._pending_atomic)
            engine = DummyEngine(cb=cb)  # config lands in BlockChain
        # the engine stack comes from ONE constructor (vm.go:694
        # initializeChain -> eth.New): chain + txpool with head-event
        # reset + miner + the assembled RPC surface
        from coreth_tpu.eth import EthConfig, Ethereum
        from coreth_tpu.eth.ethconfig import TxPoolDefaults
        self.eth = Ethereum(
            genesis,
            EthConfig(
                network_id=genesis.config.chain_id,
                commit_interval=self.config.commit_interval,
                tx_pool=TxPoolDefaults(
                    price_limit=self.config.tx_pool_price_limit,
                    account_slots=self.config.tx_pool_account_slots,
                    global_slots=self.config.tx_pool_global_slots,
                    account_queue=self.config.tx_pool_account_queue,
                    global_queue=self.config.tx_pool_global_queue)),
            engine=engine, clock=self.clock)
        self.chain = self.eth.chain
        self.txpool = self.eth.txpool
        self.miner = self.eth.miner
        if self.warp_backend is not None:
            # only accepted blocks may receive block-hash signatures
            def _accepted(h: bytes) -> bool:
                entry = self.chain._blocks.get(h)
                return entry is not None and entry.status == "accepted"
            self.warp_backend.accepted_block_fn = _accepted
        g = self.chain.genesis_block
        gb = PluginBlock(self, g)
        gb.status = Status.ACCEPTED
        self._blocks[gb.id] = gb
        self.preferred_id = gb.id
        from coreth_tpu.plugin.builder import BlockBuilder
        self.builder = BlockBuilder(
            self, clock=self.clock,
            min_interval=self.config.min_block_build_interval_ms / 1000)
        from coreth_tpu.plugin.syncervm import StateSyncServer
        self.state_sync_server = StateSyncServer(self)
        self.initialized = True

    def app_request_handler(self):
        """The request handler this VM joins the app network with
        (network_handler.go): sync handlers over the chain database +
        the warp signature handler."""
        from coreth_tpu.plugin.network_handler import NetworkHandler
        from coreth_tpu.sync.handlers import SyncHandler
        # resolved per request: a state sync swaps the backend's trie
        # (and its node store), and served leaves must follow it
        atomic_db = ((lambda: self.atomic_backend.trie.node_db)
                     if self.atomic_backend is not None else None)
        return NetworkHandler(
            sync_handler=SyncHandler(self.chain.db, self.chain,
                                     atomic_node_db=atomic_db),
            warp_backend=self.warp_backend).handle

    def state_sync_client(self, transport):
        """Build the syncervm client against a peer transport
        (syncervm_client.go)."""
        from coreth_tpu.plugin.syncervm import StateSyncClient
        return StateSyncClient(self, transport)

    def shutdown(self) -> None:
        """vm.go Shutdown -> eth Stop: transports down, acceptor
        drained, chain flushed + closed."""
        if self.initialized and self.eth is not None:
            self.eth.stop()
        self.initialized = False

    def health(self) -> dict:
        out = {"healthy": self.initialized}
        if self.initialized:
            out["lastAcceptedHeight"] = self.chain.last_accepted.number
            out["configWarnings"] = list(self.config.warnings)
        return out

    # -------------------------------------------------------------- blocks
    def _require_init(self) -> None:
        if not self.initialized:
            raise VMError("vm not initialized")

    def _register(self, blk: PluginBlock) -> None:
        self._blocks[blk.id] = blk

    # ------------------------------------------------------------- warp
    def enable_warp(self, network_id: int, source_chain_id: bytes,
                    secret_key: int, validator_set_fn=None,
                    quorum_num: int = 67, quorum_den: int = 100) -> None:
        """Wire the warp subsystem (vm.go warpBackend init + module
        registration): the backend stores/signs this chain's outgoing
        messages; the registered stateful precompile serves
        sendWarpMessage/getVerifiedWarpMessage; validator_set_fn is
        the P-Chain view used to verify inbound predicates.  Call
        before initialize(); the module registry is global, so tests
        must disable_warp() when done."""
        from coreth_tpu.precompile.modules import register_module
        from coreth_tpu.warp.contract import (
            WarpConfig, make_warp_module,
        )
        from coreth_tpu.warp.backend import WarpBackend
        self.warp_config = WarpConfig(
            network_id, source_chain_id,
            validator_set_fn=validator_set_fn,
            quorum_num=quorum_num, quorum_den=quorum_den)
        self.warp_backend = WarpBackend(network_id, source_chain_id,
                                        secret_key)
        register_module(make_warp_module(self.warp_config))

    def disable_warp(self) -> None:
        from coreth_tpu.precompile.modules import unregister_module
        from coreth_tpu.warp.contract import WARP_ADDRESS
        unregister_module(WARP_ADDRESS)
        self.warp_backend = None
        self.warp_config = None

    def _harvest_warp_messages(self, blk: PluginBlock) -> None:
        """Accepted-block hook (block.go:234 handlePrecompileAccept):
        every SendWarpMessage log in the accepted block lands in the
        warp backend, which can then sign it for aggregators."""
        from coreth_tpu.warp.contract import (
            SEND_WARP_MESSAGE_TOPIC, WARP_ADDRESS,
        )
        from coreth_tpu.warp.messages import UnsignedMessage
        receipts = self.chain.get_receipts(blk.id) or []
        for receipt in receipts:
            for log in receipt.logs:
                if log.address == WARP_ADDRESS and log.topics \
                        and log.topics[0] == SEND_WARP_MESSAGE_TOPIC:
                    self.warp_backend.add_message(
                        UnsignedMessage.decode(log.data))

    def _on_accept(self, blk: PluginBlock) -> None:
        if self.warp_backend is not None:
            self._harvest_warp_messages(blk)
        if self.atomic_backend is not None:
            from coreth_tpu.atomic import decode_ext_data
            self.atomic_backend.accept(blk.id, height=blk.height)
            txs = decode_ext_data(blk.block.ext_data())
            if txs:
                # index by tx id + height (atomic_tx_repository.go)
                self.atomic_repository.write(blk.height, txs)
                self.atomic_mempool.remove_accepted(
                    [t.id() for t in txs])
                # local txs spending the same UTXOs can never be valid
                # again — drop them rather than letting the next build
                # pull a guaranteed-to-fail spender
                consumed = [i for t in txs
                            for i in t.unsigned.input_utxos()]
                self.atomic_mempool.remove_conflicts(consumed)

    def _on_reject(self, blk: PluginBlock) -> None:
        if self.atomic_backend is not None:
            from coreth_tpu.atomic import decode_ext_data
            self.atomic_backend.reject(blk.id)
            restored = False
            for t in decode_ext_data(blk.block.ext_data()):
                self.atomic_mempool.cancel_current_tx(t.id())
                restored = True
            if restored:
                # the cancelled txs need a rebuild signal or they could
                # sit in the pool forever (liveness)
                self.builder.signal_txs_ready()

    def _pending_atomic(self):
        """Atomic txs for the next built block (vm.go:979
        onFinalizeAndAssemble pulls from the mempool).  Issued ids are
        tracked so a failed build can discard them instead of leaving
        them stranded in the issued set."""
        if self.atomic_mempool is None:
            return []
        tx = self.atomic_mempool.next_tx()
        if tx is None:
            return []
        self._building_atomic.append(tx.id())
        return [tx]

    def build_block(self) -> PluginBlock:
        """buildBlock (vm.go:1262): assemble from pending txs and verify
        immediately (the built block enters processing state)."""
        self._require_init()
        pending, _ = self.txpool.stats()
        atomic_pending = (self.atomic_mempool.pending_len()
                          if self.atomic_mempool is not None else 0)
        if pending == 0 and atomic_pending == 0:
            raise VMError("no pending transactions")
        self._building_atomic = []
        try:
            block = self.miner.generate_block()
            blk = PluginBlock(self, block)
            blk.verify()
        except Exception:  # noqa: BLE001 — any build failure must unwind issued atomic txs
            # a failed build must not strand issued atomic txs: discard
            # them (onFinalizeAndAssemble-error semantics — the tx was
            # pulled and found unbuildable)
            if self.atomic_mempool is not None:
                for tx_id in self._building_atomic:
                    self.atomic_mempool.discard_current_tx(tx_id)
            raise
        self.builder.handle_generate_block()
        return blk

    def parse_block(self, data: bytes) -> PluginBlock:
        """parseBlock (vm.go:1317): decode wire bytes; returns the
        cached adapter when the block is already known."""
        self._require_init()
        block = Block.decode(data)
        existing = self._blocks.get(block.hash())
        if existing is not None:
            return existing
        blk = PluginBlock(self, block)
        self._blocks[blk.id] = blk
        return blk

    def get_block(self, block_id: bytes) -> PluginBlock:
        """getBlock (vm.go:1347)."""
        self._require_init()
        blk = self._blocks.get(block_id)
        if blk is None:
            raise VMError(f"block {block_id.hex()} not found")
        return blk

    def set_preference(self, block_id: bytes) -> None:
        """SetPreference (vm.go:1359): the chain head used for building."""
        self._require_init()
        self.chain.set_preference(block_id)
        self.preferred_id = block_id

    def last_accepted(self) -> PluginBlock:
        self._require_init()
        return self._blocks[self.chain.last_accepted.hash()]

    # ------------------------------------------------------------- mempool
    def issue_tx(self, tx: Transaction) -> None:
        """Feed a transaction into the pool and, on success, signal the
        consensus engine to build (block_builder.go:129
        signalTxsReady)."""
        self._require_init()
        errs = self.txpool.add_remotes([tx])
        if errs and errs[0] is not None:
            raise errs[0]
        self.builder.signal_txs_ready()

    def issue_atomic_tx(self, tx) -> None:
        """Feed an atomic tx: semantic-verify against the current tip
        fee, pool it, signal the engine (vm.go issueTx for avax.*)."""
        self._require_init()
        if self.atomic_backend is None:
            raise VMError("atomic subsystem not configured")
        rules = self.chain.config.rules(
            self.chain.current_block().number + 1,
            int(self.clock()))
        self.atomic_backend.semantic_verify(
            tx, self.chain.current_block().base_fee, rules)
        self.atomic_mempool.add_tx(tx)
        self.builder.signal_txs_ready()

    def mempool_stats(self):
        self._require_init()
        return self.txpool.stats()

    def atomic_mempool_stats(self):
        self._require_init()
        pool = self.atomic_mempool
        if pool is None:
            return {"pending": 0, "total": 0}
        return {"pending": pool.pending_len(), "total": len(pool)}

    # ------------------------------------------------------- avax queries
    def get_atomic_tx(self, tx_id: bytes):
        """(tx, accepted height | None) or None (service.go
        GetAtomicTx): accepted txs resolve through the repository,
        mempool txs with no height."""
        self._require_init()
        if self.atomic_repository is not None:
            hit = self.atomic_repository.get_by_tx_id(tx_id)
            if hit is not None:
                return hit
        if self.atomic_mempool is not None:
            tx = self.atomic_mempool.get(tx_id)
            if tx is not None:
                return tx, None
        return None

    def get_atomic_tx_status(self, tx_id: bytes) -> str:
        """Accepted | Processing | Unknown (service.go
        GetAtomicTxStatus)."""
        self._require_init()
        if self.atomic_repository is not None \
                and self.atomic_repository.get_by_tx_id(tx_id):
            return "Accepted"
        if self.atomic_mempool is not None \
                and self.atomic_mempool.has(tx_id):
            return "Processing"
        return "Unknown"

    def get_utxos(self, addresses, source_chain: bytes,
                  limit: int = 100):
        """UTXOs in this chain's inbound shared memory owned by the
        given short-id addresses (service.go:506 GetUTXOs)."""
        self._require_init()
        if self.shared_memory is None:
            return []
        return self.shared_memory.indexed(source_chain, list(addresses),
                                          limit=limit)
