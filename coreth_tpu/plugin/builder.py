"""Block-build pacing.

Twin of reference plugin/evm/block_builder.go (:26 blockBuilder, :91
handleGenerateBlock, :104 needToBuild, :129 signalTxsReady): decides
when to tell the consensus engine a block is worth building —
immediately on the first pending tx after a quiet period, then rate-
limited to `min_block_build_interval` between builds.
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Deque, Optional

MIN_BLOCK_BUILD_INTERVAL = 0.5  # seconds (config.go minBlockBuildInterval)

PENDING_TXS = "PendingTxs"


class BlockBuilder:
    def __init__(self, vm, clock=_time.time,
                 min_interval: float = MIN_BLOCK_BUILD_INTERVAL):
        self.vm = vm
        self.clock = clock
        self.min_interval = min_interval
        self.last_build: float = 0.0
        self.to_engine: Deque[str] = vm.to_engine \
            if vm is not None else deque()

    def need_to_build(self) -> bool:
        """needToBuild (:104): pending work exists."""
        pending, _ = self.vm.txpool.stats()
        if pending > 0:
            return True
        mempool = getattr(self.vm, "atomic_mempool", None)
        return mempool is not None and mempool.pending_len() > 0

    def signal_txs_ready(self) -> bool:
        """signalTxsReady (:129): notify the engine unless it is too
        soon after the last build or a signal is already queued.
        Returns True when a PendingTxs message was enqueued."""
        if not self.need_to_build():
            return False
        now = self.clock()
        if now - self.last_build < self.min_interval:
            return False
        if self.to_engine and self.to_engine[-1] == PENDING_TXS:
            return False
        self.to_engine.append(PENDING_TXS)
        return True

    def handle_generate_block(self) -> None:
        """Called after the engine built a block (:91): stamp the build
        time and re-signal if work remains."""
        self.last_build = self.clock()
        self.signal_txs_ready()
