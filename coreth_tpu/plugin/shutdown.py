"""Unclean-shutdown tracking.

Twin of reference internal/shutdowncheck/shutdown_tracker.go (:41-90):
a marker written at startup and removed on clean shutdown; markers
found at startup are previous unclean exits, reported (with their
timestamps) and bounded to the most recent N.
"""

from __future__ import annotations

import time as _time
from typing import List

from coreth_tpu.rawdb.kv import KVStore

MARKER_KEY = b"uncleanShutdowns"
MAX_TRACKED = 10


class ShutdownTracker:
    def __init__(self, kv: KVStore, clock=_time.time):
        self.kv = kv
        self.clock = clock
        self.previous: List[int] = []

    def _load(self) -> List[int]:
        raw = self.kv.get(MARKER_KEY)
        if not raw:
            return []
        return [int.from_bytes(raw[i:i + 8], "big")
                for i in range(0, len(raw), 8)]

    def _store(self, stamps: List[int]) -> None:
        self.kv.put(MARKER_KEY, b"".join(
            s.to_bytes(8, "big") for s in stamps[-MAX_TRACKED:]))
        self.kv.flush()

    def mark_startup(self) -> List[int]:
        """Record this boot; whatever markers already exist are unclean
        shutdowns from previous runs (returned for logging)."""
        self.previous = self._load()
        self._store(self.previous + [int(self.clock())])
        return list(self.previous)

    def mark_clean_shutdown(self) -> None:
        """Remove this run's marker (ShutdownTracker Stop)."""
        stamps = self._load()
        if stamps:
            self._store(stamps[:-1])
