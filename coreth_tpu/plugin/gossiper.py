"""Push gossip of eth + atomic transactions.

Twin of reference plugin/evm/gossiper.go (:57 pushGossiper, :121
queueExecutableTxs — regossip selects executable txs nonce-ordered by
effective price; dedup caches stop re-gossip storms) over the peer
AppNetwork seam.  Incoming gossip feeds the tx pool / atomic mempool
(GossipHandler :449).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

from coreth_tpu.atomic.tx import Tx as AtomicTx
from coreth_tpu.types import Transaction

RECENT_CACHE = 512

# gossip payload kinds
KIND_ETH_TXS = 0
KIND_ATOMIC_TX = 1


def _encode_gossip(kind: int, items: List[bytes]) -> bytes:
    from coreth_tpu.wire import Packer
    p = Packer()
    p.u8(kind)
    p.u32(len(items))
    for raw in items:
        p.var_bytes(raw)
    return p.bytes()


def _decode_gossip(data: bytes):
    from coreth_tpu.wire import Unpacker
    u = Unpacker(data)
    kind = u.u8()
    return kind, [u.var_bytes() for _ in range(u.u32())]


class Gossiper:
    def __init__(self, peer, txpool, atomic_mempool=None,
                 regossip_max: int = 16):
        self.peer = peer
        self.txpool = txpool
        self.atomic_mempool = atomic_mempool
        self.regossip_max = regossip_max
        self._recent: "OrderedDict[bytes, None]" = OrderedDict()

    # --------------------------------------------------------------- dedup
    def _seen(self, h: bytes) -> bool:
        if h in self._recent:
            return True
        self._recent[h] = None
        if len(self._recent) > RECENT_CACHE:
            self._recent.popitem(last=False)
        return False

    # ---------------------------------------------------------------- push
    def gossip_txs(self, txs: List[Transaction]) -> int:
        fresh = [tx for tx in txs if not self._seen(tx.hash())]
        if not fresh:
            return 0
        return self.peer.gossip(_encode_gossip(
            KIND_ETH_TXS, [tx.encode() for tx in fresh]))

    def gossip_atomic_tx(self, tx: AtomicTx) -> int:
        if self._seen(tx.id()):
            return 0
        return self.peer.gossip(_encode_gossip(KIND_ATOMIC_TX,
                                               [tx.encode()]))

    def regossip(self) -> int:
        """Periodic re-announce of our best executable txs
        (queueExecutableTxs :121): nonce-contiguous pending txs ordered
        by effective tip, capped."""
        base_fee = self.txpool.chain.current_block().base_fee
        pending = self.txpool.pending_txs(base_fee)
        flat: List[Transaction] = []
        for _addr, txs in pending.items():
            flat.extend(txs[:2])  # at most 2 per account per round
        flat.sort(key=lambda tx: -self._tip(tx, base_fee))
        chosen = flat[:self.regossip_max]
        if not chosen:
            return 0
        # regossip intentionally bypasses the dedup cache: it exists to
        # re-announce txs the network may have dropped
        return self.peer.gossip(_encode_gossip(
            KIND_ETH_TXS, [tx.encode() for tx in chosen]))

    @staticmethod
    def _tip(tx: Transaction, base_fee: Optional[int]) -> int:
        if base_fee is None:
            return tx.gas_price
        return min(tx.gas_tip_cap, max(tx.gas_fee_cap - base_fee, 0))

    # -------------------------------------------------------------- handle
    def handle_gossip(self, payload: bytes) -> None:
        """Incoming AppGossip (GossipHandler :449)."""
        kind, items = _decode_gossip(payload)
        if kind == KIND_ETH_TXS:
            txs = []
            for raw in items:
                try:
                    tx = Transaction.decode(raw)
                except Exception:  # noqa: BLE001 — bad peer data
                    continue
                if not self._seen(tx.hash()):
                    txs.append(tx)
            if txs:
                self.txpool.add_remotes(txs)
        elif kind == KIND_ATOMIC_TX and self.atomic_mempool is not None:
            for raw in items:
                try:
                    tx = AtomicTx.decode(raw)
                except Exception:  # noqa: BLE001 — undecodable gossip is dropped
                    continue
                if not self._seen(tx.id()):
                    try:
                        self.atomic_mempool.add_tx(tx)
                    except Exception:  # noqa: BLE001 — invalid tx
                        pass
