"""Avalanche VM integration layer — the snowman plugin boundary.

Twin of reference plugin/ (vm.go, block.go, main.go): the consensus
engine drives the chain exclusively through this surface —
initialize / buildBlock / parseBlock / getBlock / setPreference on the
VM, and Verify / Accept / Reject on blocks — optionally across a
process boundary via the local-socket RPC service (service.py, the
rpcchainvm.Serve twin).
"""

from coreth_tpu.plugin.block import PluginBlock, Status
from coreth_tpu.plugin.vm import VM
from coreth_tpu.plugin.genesis_json import parse_genesis_json
from coreth_tpu.plugin.service import VMClient, VMServer, serve

__all__ = [
    "PluginBlock", "Status", "VM", "VMClient", "VMServer",
    "parse_genesis_json", "serve",
]
