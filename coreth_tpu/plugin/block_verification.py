"""Syntactic block verification at the consensus seam.

Twin of reference plugin/evm/block_verification.go (SyntacticVerify
:40-273): the structural checks a block must pass BEFORE the chain
executes it — header sanity, fork-keyed extra/gas-limit/base-fee
shapes, tx/uncle/ext-data hashes, coinbase pinning, minimum gas prices
pre-dynamic-fees, future-timestamp bound, and the AP4 ext-data gas
accounting against the block's atomic txs.
"""

from __future__ import annotations

from typing import Optional

from coreth_tpu.evm.precompiles import BLACKHOLE_ADDR
from coreth_tpu.params import protocol as P
from coreth_tpu.mpt import StackTrie
from coreth_tpu.types import derive_sha
from coreth_tpu.types.block import EMPTY_UNCLE_HASH, calc_ext_data_hash

# Blocks may be at most this far ahead of the wall clock
# (plugin/evm/block_verification.go maxFutureBlockTime)
MAX_FUTURE_BLOCK_TIME = 10

# The burn coinbase is pinned to the blackhole address, matching the
# reference's constants.BlackholeAddr (block_verification.go:171-174)
# so blocks produced here are wire-compatible with reference-network
# coinbase validation
EXPECTED_COINBASE = BLACKHOLE_ADDR


class BlockVerificationError(Exception):
    pass


def _fail(msg: str) -> None:
    raise BlockVerificationError(msg)


class SyntacticBlockValidator:
    """blockValidator (block_verification.go:30)."""

    def __init__(self, expected_coinbase: bytes = EXPECTED_COINBASE,
                 allow_fee_recipients: bool = False):
        self.expected_coinbase = expected_coinbase
        self.allow_fee_recipients = allow_fee_recipients

    def syntactic_verify(self, block, rules, atomic_txs=None,
                         now: Optional[int] = None) -> None:
        header = block.header

        # ext-data hash matches the body (AP1+; pre-AP1 it must be
        # empty — this framework starts its fork schedule at AP1+ for
        # all served networks)
        if rules.is_apricot_phase1:
            if header.ext_data_hash != calc_ext_data_hash(block.ext_data()):
                _fail("ext data hash mismatch")
        elif header.ext_data_hash != b"\x00" * 32:
            _fail("expected empty ext data hash before AP1")

        # header sanity (block_verification.go:89-103)
        if header.number < 0:
            _fail("invalid block number")
        if header.difficulty != 1:
            _fail(f"invalid difficulty {header.difficulty}")
        if header.nonce != b"\x00" * 8:
            _fail(f"invalid nonce {header.nonce.hex()}")
        if header.mix_digest != b"\x00" * 32:
            _fail(f"invalid mix digest {header.mix_digest.hex()}")

        # static gas limit per fork (:107-120)
        if rules.is_cortina:
            if header.gas_limit != P.CORTINA_GAS_LIMIT:
                _fail(f"expected cortina gas limit {P.CORTINA_GAS_LIMIT}, "
                      f"got {header.gas_limit}")
        elif rules.is_apricot_phase1:
            if header.gas_limit != P.APRICOT_PHASE1_GAS_LIMIT:
                _fail(f"expected AP1 gas limit {P.APRICOT_PHASE1_GAS_LIMIT},"
                      f" got {header.gas_limit}")

        # extra-data size per fork (:123-154)
        size = len(header.extra)
        if rules.is_durango:
            if size < P.DYNAMIC_FEE_EXTRA_DATA_SIZE:
                _fail(f"expected extra >= {P.DYNAMIC_FEE_EXTRA_DATA_SIZE},"
                      f" got {size}")
        elif rules.is_apricot_phase3:
            if size != P.DYNAMIC_FEE_EXTRA_DATA_SIZE:
                _fail(f"expected extra == {P.DYNAMIC_FEE_EXTRA_DATA_SIZE},"
                      f" got {size}")
        elif rules.is_apricot_phase1:
            if size != 0:
                _fail(f"expected empty extra, got {size}")
        elif size > P.MAXIMUM_EXTRA_DATA_SIZE:
            _fail(f"extra too large: {size}")

        # body hashes (:161-169); uncles are unsupported so the header
        # hash must be the canonical empty-list hash
        if derive_sha(block.transactions, StackTrie()) != header.tx_hash:
            _fail("tx hash mismatch")
        if block.uncles:
            _fail("uncles unsupported")
        if header.uncle_hash != EMPTY_UNCLE_HASH:
            _fail(f"invalid uncle hash {header.uncle_hash.hex()}")

        # coinbase pinned to the burn address (:171-174)
        if not self.allow_fee_recipients \
                and header.coinbase != self.expected_coinbase:
            _fail(f"invalid coinbase {header.coinbase.hex()}")

        # block must not be empty (:180-184)
        atomic_txs = atomic_txs or []
        if not block.transactions and not atomic_txs:
            _fail("empty block")

        # minimum gas prices before dynamic fees (:186-203)
        if not rules.is_apricot_phase3:
            floor = (P.APRICOT_PHASE1_MIN_GAS_PRICE
                     if rules.is_apricot_phase1
                     else P.LAUNCH_MIN_GAS_PRICE)
            for tx in block.transactions:
                if tx.gas_price < floor:
                    _fail(f"tx gas price below minimum {floor}")

        # future-timestamp bound (:205-210)
        if now is not None and header.time > now + MAX_FUTURE_BLOCK_TIME:
            _fail(f"block timestamp too far in the future: {header.time}")

        # base fee presence (:212-221)
        if rules.is_apricot_phase3 and header.base_fee is None:
            _fail("nil base fee after AP3")

        # AP4 ext-data gas accounting against the atomic txs (:223-262)
        if rules.is_apricot_phase4:
            if header.ext_data_gas_used is None:
                _fail("nil extDataGasUsed after AP4")
            if rules.is_apricot_phase5 \
                    and header.ext_data_gas_used > P.ATOMIC_GAS_LIMIT:
                _fail(f"too large extDataGasUsed "
                      f"{header.ext_data_gas_used}")
            total = 0
            for atx in atomic_txs:
                total += atx.unsigned.gas_used(rules.is_apricot_phase5,
                                               len(atx.encode()))
            if header.ext_data_gas_used != total:
                _fail(f"invalid extDataGasUsed: have "
                      f"{header.ext_data_gas_used}, want {total}")
            if header.block_gas_cost is None:
                _fail("nil blockGasCost after AP4")
