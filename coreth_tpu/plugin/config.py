"""VM configuration.

Twin of reference plugin/evm/config.go (:82-230): the per-chain JSON
config AvalancheGo hands the VM at Initialize — API toggles, cache and
pool sizes, pruning/commit-interval knobs, gossip pacing — parsed with
defaults + deprecation warnings for renamed keys.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, fields
from typing import List, Union

# old key -> new key (config.go Deprecate())
DEPRECATED_KEYS = {
    "corethAdminApiEnabled": "admin-api-enabled",
    "coreth-admin-api-enabled": "admin-api-enabled",
    "net-api-enabled": "eth-apis",
}


@dataclass
class Config:
    # API toggles
    eth_apis: List[str] = field(
        default_factory=lambda: ["eth", "eth-filter", "net", "web3"])
    admin_api_enabled: bool = False
    snowman_api_enabled: bool = False
    warp_api_enabled: bool = False
    # RPC limits (config.go rpc settings)
    rpc_gas_cap: int = 50_000_000
    rpc_tx_fee_cap: int = 100  # AVAX
    api_max_duration_ns: int = 0
    batch_request_limit: int = 40
    # caches / state
    trie_clean_cache_mb: int = 512
    snapshot_cache_mb: int = 256
    pruning_enabled: bool = True
    commit_interval: int = 4096
    state_sync_enabled: bool = False
    state_sync_min_blocks: int = 300_000
    # txpool
    tx_pool_price_limit: int = 1
    tx_pool_account_slots: int = 16
    tx_pool_global_slots: int = 5120
    tx_pool_account_queue: int = 64
    tx_pool_global_queue: int = 1024
    local_txs_enabled: bool = False
    # gossip / building
    min_block_build_interval_ms: int = 500
    push_gossip_num_validators: int = 100
    regossip_frequency_s: int = 60
    # profiling / observability
    metrics_expensive_enabled: bool = False
    continuous_profiler_dir: str = ""
    continuous_profiler_frequency_s: int = 900
    # offline pruning
    offline_pruning_enabled: bool = False
    offline_pruning_data_directory: str = ""

    warnings: List[str] = field(default_factory=list)


_KEYMAP = {
    "eth-apis": "eth_apis",
    "admin-api-enabled": "admin_api_enabled",
    "snowman-api-enabled": "snowman_api_enabled",
    "warp-api-enabled": "warp_api_enabled",
    "rpc-gas-cap": "rpc_gas_cap",
    "rpc-tx-fee-cap": "rpc_tx_fee_cap",
    "api-max-duration": "api_max_duration_ns",
    "batch-request-limit": "batch_request_limit",
    "trie-clean-cache": "trie_clean_cache_mb",
    "snapshot-cache": "snapshot_cache_mb",
    "pruning-enabled": "pruning_enabled",
    "commit-interval": "commit_interval",
    "state-sync-enabled": "state_sync_enabled",
    "state-sync-min-blocks": "state_sync_min_blocks",
    "tx-pool-price-limit": "tx_pool_price_limit",
    "tx-pool-account-slots": "tx_pool_account_slots",
    "tx-pool-global-slots": "tx_pool_global_slots",
    "tx-pool-account-queue": "tx_pool_account_queue",
    "tx-pool-global-queue": "tx_pool_global_queue",
    "local-txs-enabled": "local_txs_enabled",
    "min-block-build-interval": "min_block_build_interval_ms",
    "push-gossip-num-validators": "push_gossip_num_validators",
    "regossip-frequency": "regossip_frequency_s",
    "metrics-expensive-enabled": "metrics_expensive_enabled",
    "continuous-profiler-dir": "continuous_profiler_dir",
    "continuous-profiler-frequency": "continuous_profiler_frequency_s",
    "offline-pruning-enabled": "offline_pruning_enabled",
    "offline-pruning-data-directory": "offline_pruning_data_directory",
}


def parse_config(data: Union[bytes, str, dict, None]) -> Config:
    """Config bytes -> Config with defaults; unknown keys are recorded
    as warnings rather than rejected (config.go behavior), deprecated
    keys map onto their replacements."""
    cfg = Config()
    if not data:
        return cfg
    d = json.loads(data) if isinstance(data, (bytes, str)) else dict(data)
    for key, value in d.items():
        if key in DEPRECATED_KEYS:
            new = DEPRECATED_KEYS[key]
            cfg.warnings.append(
                f"deprecated key {key!r}; use {new!r}")
            key = new
        attr = _KEYMAP.get(key)
        if attr is None:
            cfg.warnings.append(f"unknown config key {key!r}")
            continue
        setattr(cfg, attr, value)
    return cfg
