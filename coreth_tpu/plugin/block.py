"""snowman.Block adapter around types.Block.

Twin of reference plugin/evm/block.go: Verify = validate + insert into
the chain without committing (the chain keeps it as a processing
sibling); Accept / Reject are the consensus decisions
(block.go:177/:269/:325).  Block IDs are the 32-byte block hashes.
"""

from __future__ import annotations

import enum
from typing import Optional

from coreth_tpu.types import Block


class Status(enum.Enum):
    UNKNOWN = "unknown"
    PROCESSING = "processing"
    ACCEPTED = "accepted"
    REJECTED = "rejected"


class PluginBlock:
    """One consensus-facing block (plugin/evm/block.go:149)."""

    def __init__(self, vm, block: Block):
        self.vm = vm
        self.block = block
        self.status = Status.UNKNOWN

    # ------------------------------------------------------------ identity
    @property
    def id(self) -> bytes:
        return self.block.hash()

    @property
    def parent_id(self) -> bytes:
        return self.block.header.parent_hash

    @property
    def height(self) -> int:
        return self.block.number

    @property
    def timestamp(self) -> int:
        return self.block.time

    def bytes(self) -> bytes:
        return self.block.encode()

    # ----------------------------------------------------------- consensus
    def verify(self) -> None:
        """Syntactic + semantic verification and insertion as a
        processing block (block.go:325 Verify -> :366 verify ->
        InsertBlockManual with writes).  Re-verifying a decided block
        is a legal snowman call and must not resurrect it to
        processing (block.go status check)."""
        if self.status in (Status.ACCEPTED, Status.REJECTED):
            return
        self.vm.chain.insert_block(self.block)
        self.status = Status.PROCESSING
        self.vm._register(self)

    def accept(self) -> None:
        """Consensus accepted this block (block.go:177)."""
        self.vm.chain.accept(self.id)
        self.status = Status.ACCEPTED
        self.vm._on_accept(self)

    def reject(self) -> None:
        """Consensus rejected this block (block.go:269)."""
        self.vm.chain.reject(self.id)
        self.status = Status.REJECTED
        self.vm._on_reject(self)

    def __repr__(self) -> str:  # debugging aid
        return (f"PluginBlock(height={self.height}, "
                f"id={self.id.hex()[:12]}, status={self.status.value})")
