"""snowman.Block adapter around types.Block.

Twin of reference plugin/evm/block.go: Verify = validate + insert into
the chain without committing (the chain keeps it as a processing
sibling); Accept / Reject are the consensus decisions
(block.go:177/:269/:325).  Block IDs are the 32-byte block hashes.
"""

from __future__ import annotations

import enum
from typing import Optional

from coreth_tpu.types import Block


class Status(enum.Enum):
    UNKNOWN = "unknown"
    PROCESSING = "processing"
    ACCEPTED = "accepted"
    REJECTED = "rejected"


class PluginBlock:
    """One consensus-facing block (plugin/evm/block.go:149)."""

    def __init__(self, vm, block: Block):
        self.vm = vm
        self.block = block
        self.status = Status.UNKNOWN

    # ------------------------------------------------------------ identity
    @property
    def id(self) -> bytes:
        return self.block.hash()

    @property
    def parent_id(self) -> bytes:
        return self.block.header.parent_hash

    @property
    def height(self) -> int:
        return self.block.number

    @property
    def timestamp(self) -> int:
        return self.block.time

    def bytes(self) -> bytes:
        return self.block.encode()

    # ----------------------------------------------------------- consensus
    def verify(self) -> None:
        """The verification ladder (block.go:325 Verify -> :366
        verify): syntactic validation, block-level predicate
        verification against the header's results bytes, atomic-UTXO
        presence in shared memory, then execution + insertion as a
        processing block (InsertBlockManual with writes).
        Re-verifying a decided block is a legal snowman call and must
        not resurrect it to processing (block.go status check)."""
        if self.status in (Status.ACCEPTED, Status.REJECTED):
            return
        vm = self.vm
        block = self.block
        rules = vm.chain.config.rules(block.number, block.time)
        atomic_txs = []
        if vm.atomic_backend is not None:
            from coreth_tpu.atomic import decode_ext_data
            atomic_txs = decode_ext_data(block.ext_data())
        if block.hash() != vm.chain.genesis_block.hash():
            vm.block_validator.syntactic_verify(
                block, rules, atomic_txs, now=int(vm.clock()))
        self._verify_predicates(rules)
        self._verify_utxos_present(atomic_txs)
        vm.chain.insert_block(block)
        self.status = Status.PROCESSING
        vm._register(self)

    def _verify_predicates(self, rules) -> None:
        """verifyPredicates (block.go:413): recompute every tx's
        predicate bitsets and require the header's carried results to
        match bit-for-bit."""
        from coreth_tpu.plugin.block_verification import (
            BlockVerificationError,
        )
        from coreth_tpu.predicate import (
            PredicateResults, check_tx_predicates,
            results_bytes_from_extra,
        )
        if not rules.is_durango:
            if rules.predicaters:
                raise BlockVerificationError(
                    "cannot enable predicates before Durango")
            return
        results = PredicateResults()
        for i, tx in enumerate(self.block.transactions):
            for addr, bits in check_tx_predicates(rules, tx).items():
                results.set_result(i, addr, bits)
        raw = results_bytes_from_extra(self.block.header.extra)
        if raw is None:
            raise BlockVerificationError(
                "missing predicate results in header extra")
        if raw != results.encode():
            raise BlockVerificationError(
                f"invalid header predicate results (remote {raw.hex()} "
                f"local {results.encode().hex()})")

    def _verify_utxos_present(self, atomic_txs) -> None:
        """verifyUTXOsPresent (block.go:449): every UTXO an import tx
        consumes must exist in shared memory when this node is past
        bootstrap."""
        vm = self.vm
        if not atomic_txs or vm.atomic_backend is None \
                or not vm.bootstrapped:
            return
        from coreth_tpu.atomic.backend import tx_requests
        from coreth_tpu.plugin.block_verification import (
            BlockVerificationError,
        )
        for atx in atomic_txs:
            for chain_id, reqs in tx_requests(atx).items():
                try:
                    vm.atomic_backend.shared_memory.get(
                        chain_id, reqs.remove_requests)
                except KeyError as exc:
                    raise BlockVerificationError(
                        f"missing UTXO for atomic tx: {exc}") from exc

    def accept(self) -> None:
        """Consensus accepted this block (block.go:177)."""
        self.vm.chain.accept(self.id)
        self.status = Status.ACCEPTED
        self.vm._on_accept(self)

    def reject(self) -> None:
        """Consensus rejected this block (block.go:269)."""
        self.vm.chain.reject(self.id)
        self.status = Status.REJECTED
        self.vm._on_reject(self)

    def __repr__(self) -> str:  # debugging aid
        return (f"PluginBlock(height={self.height}, "
                f"id={self.id.hex()[:12]}, status={self.status.value})")
