"""Warp message formats.

Twin of avalanchego's vms/platformvm/warp payload/message types as the
reference consumes them: UnsignedMessage(networkID, sourceChainID,
payload); AddressedCall payload (sourceAddress, payload); the signed
container carries a signer bitset over the canonical validator set
plus one aggregate BLS signature (BitSetSignature).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from coreth_tpu.wire import Packer, Unpacker
from coreth_tpu.crypto import bls


@dataclass
class UnsignedMessage:
    network_id: int = 0
    source_chain_id: bytes = b"\x00" * 32
    payload: bytes = b""

    def encode(self) -> bytes:
        p = Packer()
        p.u16(0)  # codec version
        p.u32(self.network_id)
        p.fixed(self.source_chain_id, 32)
        p.var_bytes(self.payload)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "UnsignedMessage":
        u = Unpacker(data)
        if u.u16() != 0:
            raise ValueError("bad warp codec version")
        return cls(u.u32(), u.fixed(32), u.var_bytes())

    def id(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()


@dataclass
class AddressedCall:
    """The payload carrying an EVM source address (payload/addressed_call)."""
    source_address: bytes = b""
    payload: bytes = b""

    def encode(self) -> bytes:
        p = Packer()
        p.u16(1)  # payload type id
        p.var_bytes(self.source_address)
        p.var_bytes(self.payload)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "AddressedCall":
        u = Unpacker(data)
        if u.u16() != 1:
            raise ValueError("not an addressed call")
        return cls(u.var_bytes(), u.var_bytes())


@dataclass
class BitSetSignature:
    """Aggregate signature addressed by a signer bitset over the
    canonical validator ordering."""
    signers: bytes = b""          # bitset, LSB of byte 0 = validator 0
    signature: bytes = b"\x00" * 96

    def encode(self) -> bytes:
        p = Packer()
        p.var_bytes(self.signers)
        p.fixed(self.signature, 96)
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BitSetSignature":
        u = Unpacker(data)
        return cls(u.var_bytes(), u.fixed(96))

    def signer_indices(self) -> List[int]:
        out = []
        for byte_i, b in enumerate(self.signers):
            for bit in range(8):
                if b & (1 << bit):
                    out.append(byte_i * 8 + bit)
        return out

    @classmethod
    def from_indices(cls, indices: List[int], signature: bytes
                     ) -> "BitSetSignature":
        if indices:
            size = max(indices) // 8 + 1
            bits = bytearray(size)
            for i in indices:
                bits[i // 8] |= 1 << (i % 8)
        else:
            bits = bytearray()
        return cls(bytes(bits), signature)


@dataclass
class SignedMessage:
    message: UnsignedMessage = field(default_factory=UnsignedMessage)
    signature: BitSetSignature = field(default_factory=BitSetSignature)

    def encode(self) -> bytes:
        p = Packer()
        p.var_bytes(self.message.encode())
        p.var_bytes(self.signature.encode())
        return p.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "SignedMessage":
        u = Unpacker(data)
        return cls(UnsignedMessage.decode(u.var_bytes()),
                   BitSetSignature.decode(u.var_bytes()))

    def verify(self, validator_set, quorum_num: int = 67,
               quorum_den: int = 100) -> bool:
        """Quorum check against the canonical validator ordering
        (precompile/contracts/warp verifyPredicate semantics)."""
        indices = self.signature.signer_indices()
        vals = validator_set.canonical()
        if not indices or (indices and indices[-1] >= len(vals)):
            return False
        pks = [vals[i].public_key for i in indices]
        weight = sum(vals[i].weight for i in indices)
        if weight * quorum_den < validator_set.total_weight() * quorum_num:
            return False
        return bls.verify_aggregate(pks, self.message.encode(),
                                    self.signature.signature)
