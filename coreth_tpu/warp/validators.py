"""Validator-set view for warp verification.

Twin of reference warp/validators/state.go: the canonical ordering
(deterministic across every verifier — here sorted by public key
bytes) that signer bitsets index into, plus total weight.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List


@dataclass(frozen=True)
class Validator:
    node_id: bytes
    public_key: bytes  # 48-byte compressed G1
    weight: int


class ValidatorSet:
    def __init__(self, validators: List[Validator]):
        self._canonical = sorted(validators,
                                 key=lambda v: v.public_key)
        self._total = sum(v.weight for v in validators)

    def canonical(self) -> List[Validator]:
        return self._canonical

    def total_weight(self) -> int:
        return self._total

    def index_of(self, public_key: bytes) -> int:
        for i, v in enumerate(self._canonical):
            if v.public_key == public_key:
                return i
        raise KeyError("unknown validator public key")
