"""Warp cross-subnet messaging.

Twin of reference warp/ (backend.go, aggregator/, validators/) +
predicate/ + precompile/contracts/warp: validators BLS-sign warp
messages; an aggregator collects signatures to quorum weight into a
bitset-addressed aggregate; the stateful warp precompile sends
messages from EVM contracts and reads quorum-verified ones back
through block predicates.
"""

from coreth_tpu.warp.messages import (
    AddressedCall, BitSetSignature, SignedMessage, UnsignedMessage,
)
from coreth_tpu.warp.validators import Validator, ValidatorSet
from coreth_tpu.warp.backend import WarpBackend
from coreth_tpu.warp.aggregator import Aggregator, AggregateError
from coreth_tpu.predicate import (
    PredicateResults, pack_predicate, unpack_predicate,
)

__all__ = [
    "AddressedCall", "AggregateError", "Aggregator", "BitSetSignature",
    "PredicateResults", "SignedMessage", "UnsignedMessage", "Validator",
    "ValidatorSet", "WarpBackend", "pack_predicate", "unpack_predicate",
]
