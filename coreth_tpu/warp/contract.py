"""The Warp stateful precompile (0x0200000000000000000000000000000000000005).

Twin of reference precompile/contracts/warp/contract.go:
- sendWarpMessage(bytes payload) (:231): wraps the caller + payload as
  an AddressedCall inside an UnsignedMessage and emits the
  SendWarpMessage log — the accepted-block hook hands the message to
  the warp backend for signing
- getVerifiedWarpMessage(uint32 index) (:190): reads the index-th warp
  predicate this tx presented in its access list; returns the message
  iff block-level predicate verification marked it valid
- predicate verification (module VerifyPredicate): quorum-checks the
  aggregate BLS signature against the P-Chain validator set
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from coreth_tpu.crypto import keccak256
from coreth_tpu import vmerrs
from coreth_tpu.precompile.contract import (
    StatefulPrecompiledContract, abi_pack_bytes, abi_word, deduct_gas,
    selector,
)
from coreth_tpu.precompile.modules import Module
from coreth_tpu.warp.messages import (
    AddressedCall, SignedMessage, UnsignedMessage,
)
from coreth_tpu.predicate import (
    PredicateError, pack_predicate, unpack_predicate,
)

WARP_ADDRESS = b"\x02" + b"\x00" * 18 + b"\x05"

SEND_WARP_MESSAGE = selector("sendWarpMessage(bytes)")
GET_VERIFIED_WARP_MESSAGE = selector("getVerifiedWarpMessage(uint32)")
GET_BLOCKCHAIN_ID = selector("getBlockchainID()")

# keccak256("SendWarpMessage(address,bytes32,bytes)")
SEND_WARP_MESSAGE_TOPIC = keccak256(
    b"SendWarpMessage(address,bytes32,bytes)")

# gas costs (contract.go:40-63)
SEND_WARP_MESSAGE_GAS = 30_000
GET_VERIFIED_WARP_MESSAGE_BASE_GAS = 2
GAS_PER_WARP_MESSAGE_CHUNK = 3_200
GAS_PER_WARP_SIGNER = 500


class WarpConfig:
    """Module config + predicate verifier (config.go + VerifyPredicate).

    network_id/source_chain_id identify this chain; validator_set_fn
    returns the ValidatorSet to verify aggregate signatures against
    (the P-Chain view at the proposer height)."""

    def __init__(self, network_id: int, source_chain_id: bytes,
                 validator_set_fn=None, quorum_num: int = 67,
                 quorum_den: int = 100):
        self.network_id = network_id
        self.source_chain_id = source_chain_id
        self.validator_set_fn = validator_set_fn
        self.quorum_num = quorum_num
        self.quorum_den = quorum_den

    # predicate gas: charged through the access-list hook
    # (state_transition.go:159); per 32-byte chunk + per signer
    def predicate_gas(self, predicate_bytes: bytes) -> int:
        chunks = (len(predicate_bytes) + 31) // 32
        gas = chunks * GAS_PER_WARP_MESSAGE_CHUNK
        try:
            signed = SignedMessage.decode(
                unpack_predicate(predicate_bytes))
            gas += len(signed.signature.signer_indices()) \
                * GAS_PER_WARP_SIGNER
        except (PredicateError, ValueError):
            pass  # verification will fail the predicate anyway
        return gas

    def verify_predicate(self, predicate_bytes: bytes) -> bool:
        """One tx predicate -> valid? (contract VerifyPredicate)."""
        if self.validator_set_fn is None:
            return False
        try:
            signed = SignedMessage.decode(
                unpack_predicate(predicate_bytes))
        except (PredicateError, ValueError):
            return False
        if signed.message.network_id != self.network_id:
            return False
        return signed.verify(self.validator_set_fn(),
                             self.quorum_num, self.quorum_den)


def make_warp_module(config: WarpConfig) -> Module:
    """Build the registered module; the contract closes over config."""

    def send_warp_message(evm, caller, addr, input_, gas, read_only):
        remaining = deduct_gas(gas, SEND_WARP_MESSAGE_GAS)
        if read_only:
            raise vmerrs.ErrWriteProtection()
        if len(input_) < 64:
            raise vmerrs.ErrExecutionReverted()
        offset = int.from_bytes(input_[0:32], "big")
        length = int.from_bytes(input_[offset:offset + 32], "big")
        payload = input_[offset + 32:offset + 32 + length]
        if len(payload) != length:
            raise vmerrs.ErrExecutionReverted()
        unsigned = UnsignedMessage(
            config.network_id, config.source_chain_id,
            AddressedCall(caller, payload).encode())
        from coreth_tpu.types import Log
        evm.statedb.add_log(Log(
            address=WARP_ADDRESS,
            topics=[SEND_WARP_MESSAGE_TOPIC,
                    b"\x00" * 12 + caller,
                    unsigned.id()],
            data=unsigned.encode()))
        return abi_word(unsigned.id()), remaining

    def get_verified_warp_message(evm, caller, addr, input_, gas,
                                  read_only):
        remaining = deduct_gas(gas, GET_VERIFIED_WARP_MESSAGE_BASE_GAS)
        if len(input_) < 32:
            raise vmerrs.ErrExecutionReverted()
        index = int.from_bytes(input_[0:32], "big")
        slots = evm.statedb.get_predicate_storage_slots(WARP_ADDRESS)
        results = evm.block_ctx.predicate_results
        predicates = slots or []
        if index >= len(predicates) or results is None:
            return _no_message(), remaining
        tx_index = getattr(evm.statedb, "_tx_index", 0)
        bitset = results.get_result(tx_index, WARP_ADDRESS)
        failed = index < len(bitset) * 8 \
            and bitset[index // 8] & (1 << (index % 8))
        if failed:
            return _no_message(), remaining
        try:
            signed = SignedMessage.decode(
                unpack_predicate(predicates[index]))
        except (PredicateError, ValueError):
            return _no_message(), remaining
        call = AddressedCall.decode(signed.message.payload)
        # WarpMessage{sourceChainID, originSenderAddress, payload}, valid
        head = abi_word(64)  # offset of the message struct
        msg = (abi_word(signed.message.source_chain_id)
               + abi_word(call.source_address)
               + abi_word(96)
               + abi_pack_bytes(call.payload))
        return head + abi_word(1) + msg, remaining

    def get_blockchain_id(evm, caller, addr, input_, gas, read_only):
        remaining = deduct_gas(gas, GET_VERIFIED_WARP_MESSAGE_BASE_GAS)
        return abi_word(config.source_chain_id), remaining

    contract = StatefulPrecompiledContract({
        SEND_WARP_MESSAGE: send_warp_message,
        GET_VERIFIED_WARP_MESSAGE: get_verified_warp_message,
        GET_BLOCKCHAIN_ID: get_blockchain_id,
    })
    return Module(address=WARP_ADDRESS, config_key="warpConfig",
                  contract=contract, predicater=config)


def _no_message() -> bytes:
    return abi_word(64) + abi_word(0) + abi_word(0) * 3 + abi_word(0)


def verify_block_predicates(config: WarpConfig, block, rules,
                            signer) -> "object":
    """Block-level predicate verification (plugin/evm/block.go:413
    verifyPredicates): for every tx access-list tuple addressed to the
    warp precompile, run VerifyPredicate and record failures in the
    per-tx results bitset."""
    from coreth_tpu.predicate import PredicateResults, slots_to_bytes
    results = PredicateResults()
    for tx_index, tx in enumerate(block.transactions):
        per_addr: dict = {}
        for addr, keys in (tx.access_list or []):
            if addr == WARP_ADDRESS:
                per_addr.setdefault(addr, []).append(keys)
        for addr, tuple_list in per_addr.items():
            bits = bytearray((len(tuple_list) + 7) // 8)
            for i, keys in enumerate(tuple_list):
                ok = config.verify_predicate(slots_to_bytes(keys))
                if not ok:
                    bits[i // 8] |= 1 << (i % 8)
            results.set_result(tx_index, addr, bytes(bits))
    return results
