"""Compatibility shim — predicate packing moved to ``coreth_tpu.predicate``.

Mirrors the reference, where ``predicate/`` is a standalone low-level
package (predicate_bytes.go, predicate_results.go) imported by core,
miner, and the warp precompile alike; keeping it inside ``warp`` forced
processor/chain/miner to import upward across the layer map.
"""

from coreth_tpu.predicate import (  # noqa: F401
    CHUNK,
    DELIMITER,
    PredicateError,
    PredicateResults,
    check_tx_predicates,
    pack_predicate,
    results_bytes_from_extra,
    slots_to_bytes,
    unpack_predicate,
)
