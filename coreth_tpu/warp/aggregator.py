"""Signature aggregation to quorum weight.

Twin of reference warp/aggregator/aggregator.go (:52
AggregateSignatures): fan signature requests out to validators, verify
each response against that validator's registered BLS key, and stop as
soon as accumulated weight crosses the quorum threshold, producing the
bitset-addressed aggregate.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from coreth_tpu.crypto import bls
from coreth_tpu.metrics import Counter, get_or_register
from coreth_tpu.warp.messages import (
    BitSetSignature, SignedMessage, UnsignedMessage,
)
from coreth_tpu.warp.validators import ValidatorSet


class AggregateError(Exception):
    pass


class Aggregator:
    def __init__(self, validator_set: ValidatorSet,
                 fetch_signature: Callable[[bytes, UnsignedMessage],
                                           Optional[bytes]],
                 registry=None):
        """fetch_signature(node_id, msg) -> 96-byte signature or None
        (the peer.NetworkClient seam).  ``registry`` scopes the
        warp/peer_faults metric (default: the process registry)."""
        self.validators = validator_set
        self.fetch = fetch_signature
        self.peer_faults = 0  # per-aggregator twin of warp/peer_faults
        self._fault_counter = get_or_register("warp/peer_faults",
                                              Counter, registry)

    def aggregate(self, msg: UnsignedMessage, quorum_num: int = 67,
                  quorum_den: int = 100) -> SignedMessage:
        payload = msg.encode()
        total = self.validators.total_weight()
        needed = (total * quorum_num + quorum_den - 1) // quorum_den
        weight = 0
        indices: List[int] = []
        sigs: List[bytes] = []
        for i, v in enumerate(self.validators.canonical()):
            try:
                sig = self.fetch(v.node_id, msg)
            except Exception:  # noqa: BLE001 — peer fault: skip the validator, but COUNT it (warp/peer_faults) — dropped signatures must be observable, not silent
                self.peer_faults += 1
                self._fault_counter.inc()
                continue
            if sig is None:
                continue
            if not bls.verify(v.public_key, payload, sig):
                continue  # invalid responses never poison the aggregate
            indices.append(i)
            sigs.append(sig)
            weight += v.weight
            if weight >= needed:
                break
        if weight < needed:
            raise AggregateError(
                f"insufficient weight {weight}/{needed}")
        agg = bls.aggregate_signatures(sigs)
        return SignedMessage(msg, BitSetSignature.from_indices(
            indices, agg))
