"""Warp backend: message store + node signing.

Twin of reference warp/backend.go (:36 Backend, :114 AddMessage, :136
GetMessageSignature, :158 GetBlockSignature): outgoing unsigned
messages persist in a warp store keyed by message id; this node signs
message ids and accepted block hashes with its BLS key on request
(the signature handler seam other validators query), with an LRU of
produced signatures.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional

from coreth_tpu.crypto import bls
from coreth_tpu.warp.messages import UnsignedMessage

SIGNATURE_CACHE = 256


class WarpBackend:
    def __init__(self, network_id: int, source_chain_id: bytes,
                 secret_key: int, store: Optional[dict] = None,
                 accepted_block_fn=None):
        """accepted_block_fn(block_hash) -> bool: when set, block-hash
        signing is limited to ACCEPTED blocks — signing arbitrary
        hashes would let a peer harvest forged acceptance attestations
        (the reference checks its block index in GetBlockSignature)."""
        self.network_id = network_id
        self.source_chain_id = source_chain_id
        self.sk = secret_key
        self.public_key = bls.public_key(secret_key)
        self.store: Dict[bytes, bytes] = store if store is not None else {}
        self.accepted_block_fn = accepted_block_fn
        self._sig_cache: "OrderedDict[bytes, bytes]" = OrderedDict()

    # ------------------------------------------------------------ messages
    def add_message(self, msg: UnsignedMessage) -> bytes:
        """Persist an accepted outgoing message (AddMessage :114)."""
        mid = msg.id()
        self.store[mid] = msg.encode()
        return mid

    def get_message(self, message_id: bytes) -> Optional[UnsignedMessage]:
        raw = self.store.get(message_id)
        return UnsignedMessage.decode(raw) if raw is not None else None

    # ----------------------------------------------------------- signatures
    def _sign_cached(self, key: bytes, payload: bytes) -> bytes:
        hit = self._sig_cache.get(key)
        if hit is not None:
            self._sig_cache.move_to_end(key)
            return hit
        sig = bls.sign(self.sk, payload)
        self._sig_cache[key] = sig
        if len(self._sig_cache) > SIGNATURE_CACHE:
            self._sig_cache.popitem(last=False)
        return sig

    def get_message_signature(self, message_id: bytes) -> bytes:
        """Sign a stored message (GetMessageSignature :136); unknown
        ids are refused — a node only signs what it emitted."""
        raw = self.store.get(message_id)
        if raw is None:
            raise KeyError(f"unknown warp message {message_id.hex()}")
        return self._sign_cached(message_id, raw)

    def get_block_signature(self, block_hash: bytes) -> bytes:
        """Sign an accepted block hash (GetBlockSignature :158) wrapped
        as a block-hash payload message; refuses hashes the chain has
        not accepted when an acceptance check is wired."""
        if self.accepted_block_fn is not None \
                and not self.accepted_block_fn(block_hash):
            raise KeyError(f"block {block_hash.hex()} not accepted")
        msg = UnsignedMessage(self.network_id, self.source_chain_id,
                              block_hash)
        return self._sign_cached(b"blk" + block_hash, msg.encode())
