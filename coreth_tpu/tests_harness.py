"""Ethereum state-test harness.

Twin of reference tests/state_test_util.go (MakePreState :40 + the
StateTest runner): executes fixtures in the upstream GeneralStateTests
JSON layout —

    {"<name>": {
        "env": {"currentCoinbase", "currentGasLimit", "currentNumber",
                 "currentTimestamp", "currentBaseFee"},
        "pre": {"<addr>": {"balance", "nonce", "code", "storage"}},
        "transaction": {"data": [..], "gasLimit": [..], "value": [..],
                         "gasPrice"|("maxFeePerGas","maxPriorityFeePerGas"),
                         "to", "nonce", "secretKey", "accessLists"?},
        "post": {"<Fork>": [{"indexes": {"data","gas","value"},
                              "hash": <state root>,
                              "logs": <keccak(rlp(logs))>,
                              "expectException"?}]}}}

The reference keeps these utilities but not the vendored JSON corpus
(SURVEY.md section 4); with zero egress the upstream corpus cannot be
fetched here either, so tests/statetests/*.json are self-generated
regression vectors in the same format — they pin today's semantics
bit-for-bit against future change rather than anchoring to upstream.
Drop upstream fixture files into the same directory and they run
unmodified (fork names map below).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from coreth_tpu import rlp
from coreth_tpu.crypto import keccak256
from coreth_tpu.evm import EVM, BlockContext, TxContext
from coreth_tpu.mpt import EMPTY_ROOT
from coreth_tpu.params import ChainConfig, TEST_CHAIN_CONFIG
from coreth_tpu.processor.message import Message
from coreth_tpu.processor.state_transition import GasPool, apply_message
from coreth_tpu.state import Database, StateDB
from coreth_tpu.crypto.secp256k1 import priv_to_address

# fork name -> ChainConfig (tests/init.go Forks table role).  Upstream
# Ethereum fork names map onto the Avalanche schedule that activates
# the same EIP set.
FORKS: Dict[str, ChainConfig] = {
    "Coreth": TEST_CHAIN_CONFIG,
    "Durango": TEST_CHAIN_CONFIG,
}


class StateTestError(Exception):
    pass


def _num(v) -> int:
    if isinstance(v, str):
        return int(v, 16) if v.startswith("0x") else int(v)
    return int(v)


def _hx(v: str) -> bytes:
    return bytes.fromhex(v[2:] if v.startswith("0x") else v)


def make_pre_state(db: Database, pre: dict) -> bytes:
    """MakePreState (state_test_util.go:40): alloc -> committed root."""
    statedb = StateDB(EMPTY_ROOT, db)
    for addr_hex, acct in pre.items():
        addr = _hx(addr_hex)
        statedb.add_balance(addr, _num(acct.get("balance", 0)))
        statedb.set_nonce(addr, _num(acct.get("nonce", 0)))
        if acct.get("code"):
            statedb.set_code(addr, _hx(acct["code"]))
        for k, v in (acct.get("storage") or {}).items():
            statedb.set_state(addr, _num(k).to_bytes(32, "big"),
                              _num(v).to_bytes(32, "big"))
    return statedb.commit(delete_empty_objects=False)


def logs_hash(logs: List) -> bytes:
    """keccak(rlp(logs)) — the fixture `logs` field (state_test_util
    rlpHash over the ordered log list)."""
    return keccak256(rlp.encode([l.rlp_items() for l in logs]))


@dataclass
class SubTestResult:
    name: str
    fork: str
    index: int
    ok: bool
    detail: str = ""


def run_state_test(name: str, fixture: dict,
                   fork_filter: Optional[str] = None
                   ) -> List[SubTestResult]:
    env = fixture["env"]
    txspec = fixture["transaction"]
    results: List[SubTestResult] = []
    for fork, posts in fixture["post"].items():
        if fork_filter and fork != fork_filter:
            continue
        config = FORKS.get(fork)
        if config is None:
            continue
        for post in posts:
            idx = post["indexes"]
            res = _run_one(name, config, env, txspec, post, idx)
            results.append(res)
    return results


def _run_one(name, config, env, txspec, post, idx) -> SubTestResult:
    db = Database()
    # fixtures reuse one pre across subtests; rebuild per subtest for
    # isolation
    root = make_pre_state(db, _fixture_pre[name])
    statedb = StateDB(root, db)

    data = _hx(txspec["data"][idx["data"]])
    gas_limit = _num(txspec["gasLimit"][idx["gas"]])
    value = _num(txspec["value"][idx["value"]])
    to = _hx(txspec["to"]) if txspec.get("to") else None
    sender = priv_to_address(int.from_bytes(_hx(txspec["secretKey"]),
                                            "big")) \
        if txspec.get("secretKey") else _hx(txspec["sender"])
    base_fee = _num(env.get("currentBaseFee", 0)) or None
    if "gasPrice" in txspec:
        gas_price = _num(txspec["gasPrice"])
        fee_cap = tip_cap = gas_price
    else:
        fee_cap = _num(txspec.get("maxFeePerGas", 0))
        tip_cap = _num(txspec.get("maxPriorityFeePerGas", 0))
        gas_price = min(fee_cap, (base_fee or 0) + tip_cap)
    access_list = []
    als = txspec.get("accessLists")
    if als and idx["data"] < len(als) and als[idx["data"]]:
        for entry in als[idx["data"]]:
            access_list.append((
                _hx(entry["address"]),
                [_hx(k) for k in entry.get("storageKeys", [])]))

    number = _num(env.get("currentNumber", 1))
    time = _num(env.get("currentTimestamp", 1))
    ctx = BlockContext(
        coinbase=_hx(env["currentCoinbase"]),
        gas_limit=_num(env.get("currentGasLimit", 10_000_000)),
        number=number, time=time, base_fee=base_fee)
    msg = Message(from_=sender, to=to, nonce=_num(txspec.get("nonce", 0)),
                  value=value, gas_limit=gas_limit, gas_price=gas_price,
                  gas_fee_cap=fee_cap, gas_tip_cap=tip_cap, data=data,
                  access_list=access_list)
    evm = EVM(ctx, TxContext(origin=sender, gas_price=gas_price),
              statedb, config)
    statedb.set_tx_context(b"\x00" * 32, 0)
    err: Optional[Exception] = None
    try:
        apply_message(evm, msg, GasPool(ctx.gas_limit))
    except Exception as e:  # noqa: BLE001 — consensus-invalid tx
        err = e
    if post.get("expectException"):
        ok = err is not None
        return SubTestResult(name, "-", 0, ok,
                             "" if ok else "expected exception")
    if err is not None:
        return SubTestResult(name, "-", 0, False, f"tx failed: {err}")
    logs = statedb.tx_logs()
    statedb.finalise(True)
    got_root = statedb.intermediate_root(True)
    want_root = _hx(post["hash"])
    want_logs = _hx(post["logs"])
    got_logs = logs_hash(logs)
    ok = got_root == want_root and got_logs == want_logs
    detail = ""
    if not ok:
        detail = (f"root {got_root.hex()} != {want_root.hex()} | "
                  f"logs {got_logs.hex()} != {want_logs.hex()}")
    return SubTestResult(name, "-", 0, ok, detail)


# per-run cache of the current fixture's pre-alloc (fixtures nest the
# pre under the test name; _run_one needs it per subtest)
_fixture_pre: Dict[str, dict] = {}


def run_fixture_file(path: str,
                     fork_filter: Optional[str] = None
                     ) -> List[SubTestResult]:
    fixtures = json.loads(open(path).read())
    out: List[SubTestResult] = []
    for name, fixture in fixtures.items():
        _fixture_pre[name] = fixture["pre"]
        out.extend(run_state_test(name, fixture, fork_filter))
    return out


def run_corpus(directory: str,
               fork_filter: Optional[str] = None) -> List[SubTestResult]:
    out: List[SubTestResult] = []
    for fn in sorted(os.listdir(directory)):
        if fn.endswith(".json"):
            out.extend(run_fixture_file(os.path.join(directory, fn),
                                        fork_filter))
    return out
