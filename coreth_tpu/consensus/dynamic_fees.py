"""Avalanche dynamic fee algorithm.

Twin of reference consensus/dummy/dynamic_fees.go: a rolling 10-second
window of gas consumption encoded as 10 big-endian u64s in the header's
Extra field drives the base fee up/down around a target
(CalcBaseFee :40, calcBlockGasCost :288, MinRequiredTip :332).
All arithmetic replicates the reference's integer-division order exactly.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from coreth_tpu.params import ChainConfig
from coreth_tpu.params import protocol as P

UINT64_MAX = (1 << 64) - 1
WINDOW_LEN = P.ROLLUP_WINDOW  # 10 u64 slots
AP3_BLOCK_GAS_FEE = 1_000_000  # dynamic_fees.go:27


def _unpack_window(data: bytes) -> list:
    return list(struct.unpack(f">{WINDOW_LEN}Q", data[:WINDOW_LEN * 8]))


def _pack_window(window: list) -> bytes:
    return struct.pack(f">{WINDOW_LEN}Q",
                       *[min(w, UINT64_MAX) for w in window])


def _roll_window(window: list, roll: int) -> list:
    if roll >= WINDOW_LEN:
        return [0] * WINDOW_LEN
    return window[roll:] + [0] * roll


def _sum_window(window: list) -> int:
    return min(sum(window), UINT64_MAX)


def calc_base_fee(config: ChainConfig, parent, timestamp: int
                  ) -> Tuple[bytes, int]:
    """(new fee-window bytes for child Extra, child base fee).

    CalcBaseFee (dynamic_fees.go:40); only call when the child is AP3+.
    """
    is_ap3 = config.is_apricot_phase3(parent.time)
    is_ap4 = config.is_apricot_phase4(parent.time)
    is_ap5 = config.is_apricot_phase5(parent.time)
    if not is_ap3 or parent.number == 0:
        return (b"\x00" * P.DYNAMIC_FEE_EXTRA_DATA_SIZE,
                P.APRICOT_PHASE3_INITIAL_BASE_FEE)
    if len(parent.extra) < P.DYNAMIC_FEE_EXTRA_DATA_SIZE:
        raise ValueError(
            f"parent extra too short: {len(parent.extra)}")
    if timestamp < parent.time:
        raise ValueError("child timestamp before parent")
    roll = timestamp - parent.time
    window = _roll_window(_unpack_window(parent.extra), roll)

    base_fee = parent.base_fee
    if is_ap5:
        denominator = P.APRICOT_PHASE5_BASE_FEE_CHANGE_DENOMINATOR
        gas_target = P.APRICOT_PHASE5_TARGET_GAS
    else:
        denominator = P.APRICOT_PHASE4_BASE_FEE_CHANGE_DENOMINATOR
        gas_target = P.APRICOT_PHASE3_TARGET_GAS

    if roll < WINDOW_LEN:
        block_gas_cost = 0
        parent_extra_gas = 0
        if is_ap5:
            parent_extra_gas = parent.ext_data_gas_used or 0
        elif is_ap4:
            block_gas_cost = calc_block_gas_cost(
                P.AP4_TARGET_BLOCK_RATE,
                P.AP4_MIN_BLOCK_GAS_COST,
                P.AP4_MAX_BLOCK_GAS_COST,
                P.AP4_BLOCK_GAS_COST_STEP,
                parent.block_gas_cost,
                parent.time, timestamp)
            parent_extra_gas = parent.ext_data_gas_used or 0
        else:
            block_gas_cost = AP3_BLOCK_GAS_FEE
        added_gas = min(parent.gas_used + parent_extra_gas, UINT64_MAX)
        if not is_ap5:
            added_gas = min(added_gas + block_gas_cost, UINT64_MAX)
        slot = WINDOW_LEN - 1 - roll
        window[slot] = min(window[slot] + added_gas, UINT64_MAX)

    total_gas = _sum_window(window)
    if total_gas == gas_target:
        return _pack_window(window), base_fee

    if total_gas > gas_target:
        delta = max(base_fee * (total_gas - gas_target)
                    // gas_target // denominator, 1)
        base_fee += delta
    else:
        delta = max(base_fee * (gas_target - total_gas)
                    // gas_target // denominator, 1)
        if roll > WINDOW_LEN:
            delta *= roll // WINDOW_LEN
        base_fee -= delta

    if is_ap5:
        base_fee = max(base_fee, P.APRICOT_PHASE4_MIN_BASE_FEE)
    elif is_ap4:
        base_fee = min(max(base_fee, P.APRICOT_PHASE4_MIN_BASE_FEE),
                       P.APRICOT_PHASE4_MAX_BASE_FEE)
    else:
        base_fee = min(max(base_fee, P.APRICOT_PHASE3_MIN_BASE_FEE),
                       P.APRICOT_PHASE3_MAX_BASE_FEE)
    return _pack_window(window), base_fee


def estimate_next_base_fee(config: ChainConfig, parent, timestamp: int
                           ) -> Tuple[bytes, int]:
    """EstimateNextBaseFee (dynamic_fees.go:195) — estimation only."""
    return calc_base_fee(config, parent, max(timestamp, parent.time))


def calc_block_gas_cost(target_block_rate: int, min_cost: int, max_cost: int,
                        step: int, parent_cost: Optional[int],
                        parent_time: int, current_time: int) -> int:
    """calcBlockGasCost (dynamic_fees.go:288)."""
    if parent_cost is None:
        return min_cost
    elapsed = current_time - parent_time if parent_time <= current_time else 0
    if elapsed < target_block_rate:
        cost = parent_cost + step * (target_block_rate - elapsed)
    else:
        cost = parent_cost - step * (elapsed - target_block_rate)
    return min(max(cost, min_cost), max_cost)


def block_gas_cost(config: ChainConfig, parent, timestamp: int) -> int:
    """The required BlockGasCost for a child of [parent] at [timestamp]
    (dummy/consensus.go BlockGasCost wrapper)."""
    step = (P.AP5_BLOCK_GAS_COST_STEP
            if config.is_apricot_phase5(timestamp)
            else P.AP4_BLOCK_GAS_COST_STEP)
    return calc_block_gas_cost(
        P.AP4_TARGET_BLOCK_RATE, P.AP4_MIN_BLOCK_GAS_COST,
        P.AP4_MAX_BLOCK_GAS_COST, step, parent.block_gas_cost,
        parent.time, timestamp)


def min_required_tip(config: ChainConfig, header) -> Optional[int]:
    """MinRequiredTip (dynamic_fees.go:332)."""
    if not config.is_apricot_phase4(header.time):
        return None
    if (header.base_fee is None or header.block_gas_cost is None
            or header.ext_data_gas_used is None):
        raise ValueError("missing AP4 header fee fields")
    required_block_fee = header.block_gas_cost * header.base_fee
    usage = header.gas_used + header.ext_data_gas_used
    return required_block_fee // usage if usage else 0
