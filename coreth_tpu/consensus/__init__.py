"""Consensus engine (the "dummy" engine twin).

Reference consensus/dummy/: there is no mining — the engine verifies
header gas/fee fields against the Avalanche dynamic-fee algorithm and
finalizes blocks (applying atomic-tx callbacks).  Consensus decisions
come from outside (snowman), see SURVEY.md section 1.
"""

from coreth_tpu.consensus.dynamic_fees import (  # noqa: F401
    calc_base_fee,
    calc_block_gas_cost,
    estimate_next_base_fee,
    min_required_tip,
)
from coreth_tpu.consensus.engine import (  # noqa: F401
    ConsensusCallbacks,
    DummyEngine,
)
