"""The dummy consensus engine.

Twin of reference consensus/dummy/consensus.go: header gas-field
verification (:105), block-fee verification (:289), Finalize (:358) and
FinalizeAndAssemble (:414) with the atomic-tx callbacks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from coreth_tpu.consensus.dynamic_fees import (
    calc_base_fee, calc_block_gas_cost,
)
from coreth_tpu.mpt import StackTrie
from coreth_tpu.params import ChainConfig
from coreth_tpu.params import protocol as P
from coreth_tpu.types import Block, Header, derive_sha, create_bloom
from coreth_tpu.types.block import calc_ext_data_hash

UINT64_MAX = (1 << 64) - 1


class ConsensusError(Exception):
    pass


@dataclass
class Mode:
    """Test fakers (consensus.go:34 Mode)."""
    skip_header_verify: bool = False
    skip_block_fee: bool = False
    skip_coinbase: bool = False


@dataclass
class ConsensusCallbacks:
    """consensus.go:40: atomic-tx hooks wired in by the plugin VM."""
    # (block, statedb) -> (fee contribution, ext_data_gas_used)
    on_extra_state_change: Optional[Callable] = None
    # (header, statedb, txs) -> (extra_data, contribution, ext_gas_used)
    on_finalize_and_assemble: Optional[Callable] = None


class DummyEngine:
    def __init__(self, cb: Optional[ConsensusCallbacks] = None,
                 mode: Optional[Mode] = None, clock=None):
        self.cb = cb or ConsensusCallbacks()
        self.mode = mode or Mode()

    # -------------------------------------------------------------- headers
    def verify_header(self, config: ChainConfig, header: Header,
                      parent: Header) -> None:
        if self.mode.skip_header_verify:
            return
        self._verify_header_gas_fields(config, header, parent)
        # timestamp monotonicity + difficulty/number/extra checks
        # (consensus.go verifyHeader)
        if header.time < parent.time:
            raise ConsensusError("timestamp older than parent")
        if header.number != parent.number + 1:
            raise ConsensusError("invalid block number")
        if header.difficulty != 1:
            raise ConsensusError("invalid difficulty")
        if config.is_apricot_phase3(header.time):
            expected_extra = P.DYNAMIC_FEE_EXTRA_DATA_SIZE
            if config.is_durango(header.time):
                if len(header.extra) < expected_extra:
                    raise ConsensusError("invalid extra length for Durango")
            elif len(header.extra) != expected_extra:
                raise ConsensusError(
                    f"invalid extra length {len(header.extra)}")
        elif len(header.extra) > P.MAXIMUM_EXTRA_DATA_SIZE:
            raise ConsensusError("extra data too long")

    def _verify_header_gas_fields(self, config: ChainConfig, header: Header,
                                  parent: Header) -> None:
        """verifyHeaderGasFields (consensus.go:105)."""
        if header.gas_limit > P.MAX_GAS_LIMIT:
            raise ConsensusError("gas limit above maximum")
        if header.gas_used > header.gas_limit:
            raise ConsensusError(
                f"gasUsed {header.gas_used} > gasLimit {header.gas_limit}")
        if config.is_cortina(header.time):
            if header.gas_limit != P.CORTINA_GAS_LIMIT:
                raise ConsensusError("gas limit must be Cortina constant")
        elif config.is_apricot_phase1(header.time):
            if header.gas_limit != P.APRICOT_PHASE1_GAS_LIMIT:
                raise ConsensusError("gas limit must be AP1 constant")
        else:
            diff = abs(parent.gas_limit - header.gas_limit)
            limit = parent.gas_limit // P.GAS_LIMIT_BOUND_DIVISOR
            if diff >= limit or header.gas_limit < P.MIN_GAS_LIMIT:
                raise ConsensusError("invalid gas limit delta")
        if not config.is_apricot_phase3(header.time):
            if header.base_fee is not None:
                raise ConsensusError("baseFee before AP3")
        else:
            window, expected_base_fee = calc_base_fee(config, parent,
                                                      header.time)
            if (len(header.extra) < len(window)
                    or header.extra[:len(window)] != window):
                raise ConsensusError("invalid fee window bytes")
            if header.base_fee is None:
                raise ConsensusError("baseFee missing")
            if header.base_fee != expected_base_fee:
                raise ConsensusError(
                    f"base fee {header.base_fee} != {expected_base_fee}")
        if not config.is_apricot_phase4(header.time):
            if header.block_gas_cost is not None:
                raise ConsensusError("blockGasCost before AP4")
            if header.ext_data_gas_used is not None:
                raise ConsensusError("extDataGasUsed before AP4")
            return
        expected_cost = self._block_gas_cost(config, parent, header.time)
        if header.block_gas_cost is None:
            raise ConsensusError("blockGasCost missing")
        if header.block_gas_cost > UINT64_MAX:
            raise ConsensusError("blockGasCost too large")
        if header.block_gas_cost != expected_cost:
            raise ConsensusError(
                f"blockGasCost {header.block_gas_cost} != {expected_cost}")
        if header.ext_data_gas_used is None:
            raise ConsensusError("extDataGasUsed missing")
        if header.ext_data_gas_used > UINT64_MAX:
            raise ConsensusError("extDataGasUsed too large")

    @staticmethod
    def _block_gas_cost(config: ChainConfig, parent: Header,
                        timestamp: int) -> int:
        step = (P.AP5_BLOCK_GAS_COST_STEP
                if config.is_apricot_phase5(timestamp)
                else P.AP4_BLOCK_GAS_COST_STEP)
        return calc_block_gas_cost(
            P.AP4_TARGET_BLOCK_RATE, P.AP4_MIN_BLOCK_GAS_COST,
            P.AP4_MAX_BLOCK_GAS_COST, step, parent.block_gas_cost,
            parent.time, timestamp)

    # ------------------------------------------------------------ block fee
    def verify_block_fee(self, base_fee: Optional[int],
                         required_block_gas_cost: Optional[int],
                         txs, receipts,
                         extra_contribution: Optional[int]) -> None:
        """verifyBlockFee (consensus.go:289)."""
        if self.mode.skip_block_fee:
            return
        if base_fee is None or base_fee <= 0:
            raise ConsensusError(f"invalid base fee {base_fee}")
        if (required_block_gas_cost is None
                or required_block_gas_cost > UINT64_MAX):
            raise ConsensusError("invalid block gas cost")
        total_block_fee = 0
        if extra_contribution is not None:
            if extra_contribution < 0:
                raise ConsensusError("negative extra contribution")
            total_block_fee += extra_contribution
        for tx, receipt in zip(txs, receipts):
            premium = tx.effective_gas_tip(base_fee)
            if premium < 0:
                raise ConsensusError("negative effective tip")
            total_block_fee += premium * receipt.gas_used
        block_gas = total_block_fee // base_fee
        if block_gas < required_block_gas_cost:
            raise ConsensusError(
                f"insufficient gas ({block_gas}) to cover block cost "
                f"({required_block_gas_cost}) at base fee ({base_fee})")

    # -------------------------------------------------------------- finalize
    def finalize(self, block: Block, parent: Header, statedb,
                 receipts, config: Optional[ChainConfig] = None) -> None:
        """Finalize (consensus.go:358)."""
        config = config or self._config
        contribution = ext_data_gas_used = None
        if self.cb.on_extra_state_change is not None:
            contribution, ext_data_gas_used = self.cb.on_extra_state_change(
                block, statedb)
        if config.is_apricot_phase4(block.time):
            if ext_data_gas_used is None:
                ext_data_gas_used = 0
            if (block.header.ext_data_gas_used is None
                    or block.header.ext_data_gas_used != ext_data_gas_used):
                raise ConsensusError(
                    f"invalid extDataGasUsed: have "
                    f"{block.header.ext_data_gas_used}, "
                    f"want {ext_data_gas_used}")
            expected_cost = self._block_gas_cost(config, parent, block.time)
            if (block.header.block_gas_cost is None
                    or block.header.block_gas_cost != expected_cost):
                raise ConsensusError("invalid blockGasCost")
            self.verify_block_fee(block.base_fee,
                                  block.header.block_gas_cost,
                                  block.transactions, receipts, contribution)

    _config: Optional[ChainConfig] = None

    def set_config(self, config: ChainConfig) -> None:
        """Bind the chain config used by finalize (the reference reaches it
        through the chain reader argument)."""
        self._config = config

    def finalize_and_assemble(self, config: ChainConfig, header: Header,
                              parent: Header, statedb, txs, uncles,
                              receipts) -> Block:
        """FinalizeAndAssemble (consensus.go:414)."""
        extra_data = b""
        contribution = ext_data_gas_used = None
        if self.cb.on_finalize_and_assemble is not None:
            extra_data, contribution, ext_data_gas_used = \
                self.cb.on_finalize_and_assemble(header, statedb, txs)
        if config.is_apricot_phase4(header.time):
            header.ext_data_gas_used = ext_data_gas_used or 0
            header.block_gas_cost = self._block_gas_cost(config, parent,
                                                         header.time)
            self.verify_block_fee(header.base_fee, header.block_gas_cost,
                                  txs, receipts, contribution)
        header.root = statedb.intermediate_root(
            config.is_eip158(header.number))
        header.tx_hash = derive_sha(txs, StackTrie())
        header.receipt_hash = derive_sha(receipts, StackTrie())
        header.bloom = create_bloom(receipts)
        if config.is_apricot_phase1(header.time):
            header.ext_data_hash = calc_ext_data_hash(extra_data)
        return Block(header, list(txs), list(uncles), version=0,
                     extdata=extra_data if extra_data else None)
