// Test-only sanitizer smoke helper — compiled ONLY into the
// sanitized build (`make sanitize` -> libcoreth_native_asan.so),
// never into the production library.
//
// coreth_sanitize_smoke(idx) heap-allocates 8 bytes and reads
// buf[idx]: in-bounds indices return the byte value (0), and any
// idx >= 8 is a heap-buffer-overflow that AddressSanitizer must
// abort on (-fno-sanitize-recover).  tests/test_sanitize.py calls it
// in a subprocess both ways to prove the trap is actually armed —
// a sanitizer build that silently loads without instrumenting would
// otherwise pass every other test.

#include <cstdint>
#include <cstring>
#include <new>

extern "C" int coreth_sanitize_smoke(int64_t idx) {
  uint8_t* buf = new uint8_t[8];
  std::memset(buf, 0, 8);
  // volatile so the out-of-bounds read cannot be optimized away
  volatile uint8_t v = buf[idx];
  delete[] buf;
  return (int)v;
}
