// Compiled sequential EVM replay baseline.
//
// The honest denominator for the contract workloads (BASELINE.md round
// 5): a single-threaded C++ replay doing the same per-tx work as the
// reference's StateProcessor loop for general contract calls — sender
// ecrecover, nonce/balance checks, a full 256-bit EVM interpreter with
// exact gas (EIP-2929 warm/cold, EIP-2200 SSTORE ladder, quadratic
// memory, copy/log/keccak/exp word costs — the durango rule set the
// bench chains run under), per-block storage-trie + account-trie fold
// and state-root validation.  Mirrors the scope of the value-transfer
// baseline in baseline.cc (state roots validated, receipt roots
// skipped — which favors this baseline, BASELINE.md).
//
// Reference roles: core/vm/interpreter.go:121 (Run),
// core/state_processor.go:95 (tx loop), core/vm/operations_acl.go
// (2929 pricing), trie/hasher.go (per-block rehash).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>
#include <ctime>

typedef unsigned __int128 u128;
typedef std::vector<uint8_t> Bytes;

extern "C" void coreth_keccak256(const uint8_t*, uint64_t, uint8_t*);
extern "C" int coreth_ecrecover(const uint8_t*, const uint8_t*,
                                const uint8_t*, int, uint8_t*);
// trie handle API from baseline.cc (secure MPT over pre-hashed keys)
extern "C" void* coreth_trie_new();
extern "C" void coreth_trie_free(void*);
extern "C" void coreth_trie_update_batch(void*, const uint8_t*,
                                         const uint8_t*,
                                         const uint32_t*, uint64_t);
extern "C" void coreth_trie_hash(void*, uint8_t*);
extern "C" void coreth_trie_fold_accounts(void*, const uint8_t*,
                                          const uint8_t*,
                                          const uint64_t*,
                                          const uint8_t*,
                                          const uint8_t*,
                                          const uint8_t*,
                                          const uint8_t*, uint64_t);

namespace {

// ----------------------------------------------------------------- u256

struct U256 {
  uint64_t w[4] = {0, 0, 0, 0};  // little-endian 64-bit limbs

  bool is_zero() const { return !(w[0] | w[1] | w[2] | w[3]); }
  bool bit(int i) const { return (w[i >> 6] >> (i & 63)) & 1; }
  int bitlen() const {
    for (int i = 3; i >= 0; --i)
      if (w[i]) return 64 * i + 64 - __builtin_clzll(w[i]);
    return 0;
  }
};

U256 from_be(const uint8_t* p, size_t n = 32) {
  U256 v;
  for (size_t i = 0; i < n; ++i) {
    size_t bit = 8 * (n - 1 - i);
    v.w[bit >> 6] |= (uint64_t)p[i] << (bit & 63);
  }
  return v;
}

void to_be(const U256& v, uint8_t out[32]) {
  for (int i = 0; i < 32; ++i) {
    int bit = 8 * (31 - i);
    out[i] = (uint8_t)(v.w[bit >> 6] >> (bit & 63));
  }
}

U256 u256_from64(uint64_t x) { U256 v; v.w[0] = x; return v; }

bool eq(const U256& a, const U256& b) {
  return !std::memcmp(a.w, b.w, 32);
}

int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

U256 add(const U256& a, const U256& b) {
  U256 r;
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.w[i] + b.w[i] + c;
    r.w[i] = (uint64_t)s;
    c = s >> 64;
  }
  return r;
}

U256 sub(const U256& a, const U256& b) {
  U256 r;
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.w[i] - b.w[i] - borrow;
    r.w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return r;
}

U256 mul(const U256& a, const U256& b) {
  uint64_t out[4] = {0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; i + j < 4; ++j) {
      u128 cur = (u128)a.w[i] * b.w[j] + out[i + j] + carry;
      out[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  U256 r;
  std::memcpy(r.w, out, 32);
  return r;
}

U256 shl_k(const U256& a, unsigned k) {
  U256 r;
  if (k >= 256) return r;
  unsigned limb = k / 64, off = k % 64;
  for (int i = 3; i >= 0; --i) {
    uint64_t v = 0;
    int src = i - (int)limb;
    if (src >= 0) v = a.w[src] << off;
    if (off && src - 1 >= 0) v |= a.w[src - 1] >> (64 - off);
    r.w[i] = v;
  }
  return r;
}

U256 shr_k(const U256& a, unsigned k) {
  U256 r;
  if (k >= 256) return r;
  unsigned limb = k / 64, off = k % 64;
  for (int i = 0; i < 4; ++i) {
    uint64_t v = 0;
    unsigned src = i + limb;
    if (src < 4) v = a.w[src] >> off;
    if (off && src + 1 < 4) v |= a.w[src + 1] << (64 - off);
    r.w[i] = v;
  }
  return r;
}

// divides by a divisor that fits 64 bits (the workload-hot path);
// general case falls back to bit-serial restoring division.
void divmod(const U256& a, const U256& b, U256* q, U256* r) {
  *q = U256();
  *r = U256();
  if (b.is_zero()) return;
  if (!(b.w[1] | b.w[2] | b.w[3])) {
    uint64_t d = b.w[0];
    u128 rem = 0;
    for (int i = 3; i >= 0; --i) {
      u128 cur = (rem << 64) | a.w[i];
      q->w[i] = (uint64_t)(cur / d);
      rem = cur % d;
    }
    r->w[0] = (uint64_t)rem;
    return;
  }
  U256 rem;
  for (int i = 255; i >= 0; --i) {
    rem = shl_k(rem, 1);
    rem.w[0] |= a.bit(i) ? 1 : 0;
    if (cmp(rem, b) >= 0) {
      rem = sub(rem, b);
      q->w[i >> 6] |= 1ULL << (i & 63);
    }
  }
  *r = rem;
}

bool sign_neg(const U256& a) { return a.w[3] >> 63; }

U256 neg(const U256& a) { return sub(U256(), a); }

U256 u_abs(const U256& a) { return sign_neg(a) ? neg(a) : a; }

// (a + b) % n and (a * b) % n over the wide intermediate: shift-add /
// shift-mod loops — correctness parity only, never on the bench path.
U256 addmod_(const U256& a, const U256& b, const U256& n) {
  if (n.is_zero()) return U256();
  U256 q, ra, rb;
  divmod(a, n, &q, &ra);
  divmod(b, n, &q, &rb);
  U256 s = add(ra, rb);
  // one conditional subtract handles the possible 257-bit overflow
  if (cmp(s, ra) < 0 || cmp(s, n) >= 0) s = sub(s, n);
  return s;
}

U256 mulmod_(const U256& a, const U256& b, const U256& n) {
  if (n.is_zero()) return U256();
  U256 q, x, result;
  divmod(a, n, &q, &x);
  U256 y;
  divmod(b, n, &q, &y);
  // double-and-add: result = x*y mod n without a 512-bit intermediate
  for (int i = y.bitlen() - 1; i >= 0; --i) {
    result = addmod_(result, result, n);
    if (y.bit(i)) result = addmod_(result, x, n);
  }
  return result;
}

// ------------------------------------------------------------ gas rules
// durango-level constants (params/protocol.py twins)

constexpr int64_t G_QUICK = 2, G_FASTEST = 3, G_FAST = 5, G_MID = 8,
                  G_SLOW = 10;
constexpr int64_t G_KECCAK = 30, G_KECCAK_WORD = 6, G_MEM = 3,
                  G_COPY = 3, G_LOG = 375, G_LOGTOPIC = 375,
                  G_LOGDATA = 8, G_JUMPDEST = 1, G_EXP = 10,
                  G_EXPBYTE = 50;
constexpr int64_t COLD_SLOAD = 2100, WARM_READ = 100,
                  SSTORE_SET = 20000, SSTORE_RESET = 5000,
                  SSTORE_SENTRY = 2300;
constexpr uint64_t QUAD_DIV = 512;

int64_t mem_cost(uint64_t words) {
  return (int64_t)(words * G_MEM + words * words / QUAD_DIV);
}

struct Key32 {
  uint8_t b[32];
  bool operator==(const Key32& o) const {
    return !std::memcmp(b, o.b, 32);
  }
};
struct Key32Hash {
  size_t operator()(const Key32& k) const {
    size_t h;
    std::memcpy(&h, k.b, sizeof(h));
    return h;
  }
};
typedef std::unordered_map<Key32, U256, Key32Hash> SlotMap;

struct Contract {
  Bytes code;
  uint8_t code_hash[32];
  SlotMap storage;               // committed (as of last block)
  std::vector<bool> jumpdest;
  bool dirty = false;            // storage touched since last fold
  SlotMap block_dirty;           // writes since last fold
};

struct Account {
  u128 balance = 0;
  uint64_t nonce = 0;
  Contract* contract = nullptr;
};

struct Env {
  const uint8_t* coinbase;
  uint64_t timestamp, number, gaslimit, chain_id;
  U256 basefee;
};

struct TxCtx {
  const uint8_t* caller;         // 20
  const uint8_t* address;        // 20
  U256 value, gasprice;
  const uint8_t* data;
  uint64_t data_len;
};

U256 addr_word(const uint8_t* a20) {
  uint8_t p[32] = {0};
  std::memcpy(p + 12, a20, 20);
  return from_be(p);
}

// result of one interpreter run
struct RunResult {
  bool ok = false;        // STOP/RETURN
  bool reverted = false;
  int64_t gas_left = 0;
  SlotMap writes;         // applied by caller on ok
};

void analyze_jumpdests(Contract* c) {
  c->jumpdest.assign(c->code.size(), false);
  for (size_t i = 0; i < c->code.size();) {
    uint8_t op = c->code[i];
    if (op == 0x5B) c->jumpdest[i] = true;
    i += (op >= 0x60 && op <= 0x7F) ? op - 0x5F + 1 : 1;
  }
}

// the interpreter: a direct switch loop (the compiled analog of
// interpreter.go Run); durango rule set, no nested calls (the replay
// classifier guarantees flat bytecode for these workloads).
RunResult evm_run(Contract* c, const Env& env, const TxCtx& tx,
                  int64_t gas) {
  RunResult res;
  std::vector<U256> stack;
  stack.reserve(64);
  Bytes mem;
  uint64_t pc = 0;
  const Bytes& code = c->code;
  // per-tx storage view: warm set, tx-origin snapshot, dirty writes
  std::unordered_set<Key32, Key32Hash> warm;
  SlotMap dirty;
  int64_t refund = 0;  // tracked, never paid (AP1+ semantics)
  (void)refund;

#define NEED(n) if (stack.size() < (n)) { res.gas_left = 0; return res; }
#define USE(g) do { if (gas < (int64_t)(g)) { res.gas_left = 0; \
  return res; } gas -= (g); } while (0)

  auto expand = [&](uint64_t need) -> bool {
    if (need <= mem.size()) return true;
    if (need > (1ULL << 25)) return false;
    uint64_t new_words = (need + 31) / 32;
    int64_t cost = mem_cost(new_words) - mem_cost(mem.size() / 32);
    if (gas < cost) return false;
    gas -= cost;
    mem.resize(new_words * 32, 0);
    return true;
  };
  auto u64_arg = [&](const U256& v, bool* okf) -> uint64_t {
    if (v.w[1] | v.w[2] | v.w[3] || v.w[0] > (1ULL << 32)) {
      *okf = false;
      return 1ULL << 32;
    }
    *okf = true;
    return v.w[0];
  };

  while (pc < code.size()) {
    uint8_t op = code[pc];
    switch (op) {
      case 0x00: res.ok = true; res.gas_left = gas;
                 res.writes = dirty; return res;           // STOP
      case 0x01: { NEED(2); USE(G_FASTEST);                // ADD
        U256 a = stack.back(); stack.pop_back();
        stack.back() = add(a, stack.back()); break; }
      case 0x02: { NEED(2); USE(G_FAST);                   // MUL
        U256 a = stack.back(); stack.pop_back();
        stack.back() = mul(a, stack.back()); break; }
      case 0x03: { NEED(2); USE(G_FASTEST);                // SUB
        U256 a = stack.back(); stack.pop_back();
        stack.back() = sub(a, stack.back()); break; }
      case 0x04: { NEED(2); USE(G_FAST);                   // DIV
        U256 a = stack.back(); stack.pop_back();
        U256 q, r; divmod(a, stack.back(), &q, &r);
        stack.back() = q; break; }
      case 0x05: { NEED(2); USE(G_FAST);                   // SDIV
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back();
        U256 q, r; divmod(u_abs(a), u_abs(b), &q, &r);
        stack.back() = (sign_neg(a) != sign_neg(b) && !b.is_zero())
                           ? neg(q) : q;
        break; }
      case 0x06: { NEED(2); USE(G_FAST);                   // MOD
        U256 a = stack.back(); stack.pop_back();
        U256 q, r; divmod(a, stack.back(), &q, &r);
        stack.back() = r; break; }
      case 0x07: { NEED(2); USE(G_FAST);                   // SMOD
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back();
        U256 q, r; divmod(u_abs(a), u_abs(b), &q, &r);
        stack.back() = sign_neg(a) ? neg(r) : r; break; }
      case 0x08: { NEED(3); USE(G_MID);                    // ADDMOD
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back(); stack.pop_back();
        stack.back() = addmod_(a, b, stack.back()); break; }
      case 0x09: { NEED(3); USE(G_MID);                    // MULMOD
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back(); stack.pop_back();
        stack.back() = mulmod_(a, b, stack.back()); break; }
      case 0x0A: { NEED(2);                                // EXP
        U256 b = stack.back(); stack.pop_back();
        U256 e = stack.back();
        USE(G_EXP + G_EXPBYTE * ((e.bitlen() + 7) / 8));
        U256 r = u256_from64(1), cur = b;
        int n = e.bitlen();
        for (int i = 0; i < n; ++i) {
          if (e.bit(i)) r = mul(r, cur);
          cur = mul(cur, cur);
        }
        stack.back() = r; break; }
      case 0x0B: { NEED(2); USE(G_FAST);                   // SIGNEXTEND
        U256 b = stack.back(); stack.pop_back();
        U256 x = stack.back();
        if (b.w[0] < 31 && !(b.w[1] | b.w[2] | b.w[3])) {
          int t = 8 * (int)(b.w[0] + 1);
          bool neg_bit = x.bit(t - 1);
          U256 mask = sub(shl_k(u256_from64(1), t), u256_from64(1));
          if (neg_bit) {
            U256 inv;
            for (int i = 0; i < 4; ++i) inv.w[i] = ~mask.w[i];
            for (int i = 0; i < 4; ++i) x.w[i] |= inv.w[i];
          } else {
            for (int i = 0; i < 4; ++i) x.w[i] &= mask.w[i];
          }
          stack.back() = x;
        }
        break; }
      case 0x10: case 0x11: case 0x12: case 0x13: case 0x14: {
        NEED(2); USE(G_FASTEST);        // LT GT SLT SGT EQ
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back();
        bool r = false;
        if (op == 0x10) r = cmp(a, b) < 0;
        else if (op == 0x11) r = cmp(a, b) > 0;
        else if (op == 0x14) r = eq(a, b);
        else {
          bool sa = sign_neg(a), sb = sign_neg(b);
          int c0 = cmp(a, b);
          bool lt = sa != sb ? sa : c0 < 0;
          r = (op == 0x12) ? lt : (c0 != 0 && !lt);
        }
        stack.back() = u256_from64(r ? 1 : 0); break; }
      case 0x15: { NEED(1); USE(G_FASTEST);                // ISZERO
        stack.back() = u256_from64(stack.back().is_zero() ? 1 : 0);
        break; }
      case 0x16: case 0x17: case 0x18: { NEED(2); USE(G_FASTEST);
        U256 a = stack.back(); stack.pop_back();           // AND OR XOR
        U256& b = stack.back();
        for (int i = 0; i < 4; ++i)
          b.w[i] = op == 0x16 ? (a.w[i] & b.w[i])
                 : op == 0x17 ? (a.w[i] | b.w[i]) : (a.w[i] ^ b.w[i]);
        break; }
      case 0x19: { NEED(1); USE(G_FASTEST);                // NOT
        for (int i = 0; i < 4; ++i)
          stack.back().w[i] = ~stack.back().w[i];
        break; }
      case 0x1A: { NEED(2); USE(G_FASTEST);                // BYTE
        U256 i = stack.back(); stack.pop_back();
        U256 x = stack.back();
        uint64_t v = 0;
        if (i.w[0] < 32 && !(i.w[1] | i.w[2] | i.w[3])) {
          uint8_t be[32];
          to_be(x, be);
          v = be[i.w[0]];
        }
        stack.back() = u256_from64(v); break; }
      case 0x1B: case 0x1C: { NEED(2); USE(G_FASTEST);     // SHL SHR
        U256 s = stack.back(); stack.pop_back();
        U256 x = stack.back();
        unsigned k = (s.w[1] | s.w[2] | s.w[3] || s.w[0] > 255)
                         ? 256 : (unsigned)s.w[0];
        stack.back() = op == 0x1B ? shl_k(x, k) : shr_k(x, k);
        break; }
      case 0x1D: { NEED(2); USE(G_FASTEST);                // SAR
        U256 s = stack.back(); stack.pop_back();
        U256 x = stack.back();
        bool negx = sign_neg(x);
        unsigned k = (s.w[1] | s.w[2] | s.w[3] || s.w[0] > 255)
                         ? 256 : (unsigned)s.w[0];
        if (k >= 256) {
          stack.back() = negx ? neg(u256_from64(1)) : U256();
        } else {
          U256 r = shr_k(x, k);
          if (negx && k) {
            U256 fill = shl_k(neg(u256_from64(1)), 256 - k);
            for (int i = 0; i < 4; ++i) r.w[i] |= fill.w[i];
          }
          stack.back() = r;
        }
        break; }
      case 0x20: { NEED(2); USE(G_KECCAK);                 // KECCAK256
        U256 offv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool okf1, okf2;
        uint64_t off = u64_arg(offv, &okf1), len = u64_arg(lenv, &okf2);
        if (len) {
          if (!okf1 || !okf2 || !expand(off + len)) {
            res.gas_left = 0;
            return res;
          }
        }
        USE(G_KECCAK_WORD * ((len + 31) / 32));
        uint8_t h[32];
        coreth_keccak256(len ? mem.data() + off : nullptr, len, h);
        stack.push_back(from_be(h)); break; }
      case 0x30: USE(G_QUICK);
        stack.push_back(addr_word(tx.address)); ++pc; continue;
      case 0x32: USE(G_QUICK);
        stack.push_back(addr_word(tx.caller)); ++pc; continue;  // ORIGIN==caller (no subcalls)
      case 0x33: USE(G_QUICK);
        stack.push_back(addr_word(tx.caller)); ++pc; continue;
      case 0x34: USE(G_QUICK);
        stack.push_back(tx.value); ++pc; continue;
      case 0x35: { NEED(1); USE(G_FASTEST);                // CALLDATALOAD
        U256 offv = stack.back();
        uint8_t word[32] = {0};
        if (!(offv.w[1] | offv.w[2] | offv.w[3])
            && offv.w[0] < tx.data_len) {
          uint64_t off = offv.w[0];
          uint64_t n = tx.data_len - off < 32 ? tx.data_len - off : 32;
          std::memcpy(word, tx.data + off, n);
        }
        stack.back() = from_be(word); break; }
      case 0x36: USE(G_QUICK);
        stack.push_back(u256_from64(tx.data_len)); ++pc; continue;
      case 0x37: { NEED(3); USE(G_FASTEST);                // CALLDATACOPY
        U256 dstv = stack.back(); stack.pop_back();
        U256 srcv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok3;
        uint64_t dst = u64_arg(dstv, &ok1);
        uint64_t len = u64_arg(lenv, &ok3);
        if (len) {
          if (!ok1 || !ok3 || !expand(dst + len)) {
            res.gas_left = 0;
            return res;
          }
        }
        USE(G_COPY * ((len + 31) / 32));
        for (uint64_t j = 0; j < len; ++j) {
          uint64_t s = (srcv.w[1] | srcv.w[2] | srcv.w[3])
                           ? tx.data_len : srcv.w[0] + j;
          mem[dst + j] = s < tx.data_len ? tx.data[s] : 0;
        }
        break; }
      case 0x38: USE(G_QUICK);
        stack.push_back(u256_from64(code.size())); ++pc; continue;
      case 0x39: { NEED(3); USE(G_FASTEST);                // CODECOPY
        U256 dstv = stack.back(); stack.pop_back();
        U256 srcv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok3;
        uint64_t dst = u64_arg(dstv, &ok1);
        uint64_t len = u64_arg(lenv, &ok3);
        if (len) {
          if (!ok1 || !ok3 || !expand(dst + len)) {
            res.gas_left = 0;
            return res;
          }
        }
        USE(G_COPY * ((len + 31) / 32));
        for (uint64_t j = 0; j < len; ++j) {
          uint64_t s = (srcv.w[1] | srcv.w[2] | srcv.w[3])
                           ? code.size() : srcv.w[0] + j;
          mem[dst + j] = s < code.size() ? code[s] : 0;
        }
        break; }
      case 0x3A: USE(G_QUICK);
        stack.push_back(tx.gasprice); ++pc; continue;
      case 0x41: USE(G_QUICK);
        stack.push_back(addr_word(env.coinbase)); ++pc; continue;
      case 0x42: USE(G_QUICK);
        stack.push_back(u256_from64(env.timestamp)); ++pc; continue;
      case 0x43: USE(G_QUICK);
        stack.push_back(u256_from64(env.number)); ++pc; continue;
      case 0x44: USE(G_QUICK);
        stack.push_back(u256_from64(1)); ++pc; continue;
      case 0x45: USE(G_QUICK);
        stack.push_back(u256_from64(env.gaslimit)); ++pc; continue;
      case 0x46: USE(G_QUICK);
        stack.push_back(u256_from64(env.chain_id)); ++pc; continue;
      case 0x48: USE(G_QUICK);
        stack.push_back(env.basefee); ++pc; continue;
      case 0x50: NEED(1); USE(G_QUICK); stack.pop_back();
        ++pc; continue;
      case 0x51: { NEED(1); USE(G_FASTEST);                // MLOAD
        U256 offv = stack.back();
        bool okf;
        uint64_t off = u64_arg(offv, &okf);
        if (!okf || !expand(off + 32)) { res.gas_left = 0; return res; }
        stack.back() = from_be(mem.data() + off); break; }
      case 0x52: { NEED(2); USE(G_FASTEST);                // MSTORE
        U256 offv = stack.back(); stack.pop_back();
        U256 val = stack.back(); stack.pop_back();
        bool okf;
        uint64_t off = u64_arg(offv, &okf);
        if (!okf || !expand(off + 32)) { res.gas_left = 0; return res; }
        to_be(val, mem.data() + off); break; }
      case 0x53: { NEED(2); USE(G_FASTEST);                // MSTORE8
        U256 offv = stack.back(); stack.pop_back();
        U256 val = stack.back(); stack.pop_back();
        bool okf;
        uint64_t off = u64_arg(offv, &okf);
        if (!okf || !expand(off + 1)) { res.gas_left = 0; return res; }
        mem[off] = (uint8_t)val.w[0]; break; }
      case 0x54: { NEED(1);                                // SLOAD
        U256 keyv = stack.back();
        Key32 k;
        to_be(keyv, k.b);
        k.b[0] &= 0xFE;  // multicoin normal-storage partition
        USE(warm.count(k) ? WARM_READ : COLD_SLOAD);
        warm.insert(k);
        auto it = dirty.find(k);
        if (it != dirty.end()) {
          stack.back() = it->second;
        } else {
          auto ct = c->storage.find(k);
          stack.back() = ct == c->storage.end() ? U256() : ct->second;
        }
        break; }
      case 0x55: { NEED(2);                                // SSTORE
        if (gas <= SSTORE_SENTRY) { res.gas_left = 0; return res; }
        U256 keyv = stack.back(); stack.pop_back();
        U256 val = stack.back(); stack.pop_back();
        Key32 k;
        to_be(keyv, k.b);
        k.b[0] &= 0xFE;
        int64_t cost = 0;
        if (!warm.count(k)) {
          cost += COLD_SLOAD;
          warm.insert(k);
        }
        auto co = c->storage.find(k);
        U256 orig = co == c->storage.end() ? U256() : co->second;
        auto di = dirty.find(k);
        U256 cur = di == dirty.end() ? orig : di->second;
        if (eq(cur, val)) cost += WARM_READ;
        else if (eq(orig, cur))
          cost += orig.is_zero() ? SSTORE_SET
                                 : SSTORE_RESET - COLD_SLOAD;
        else cost += WARM_READ;
        USE(cost);
        dirty[k] = val;
        break; }
      case 0x56: { NEED(1); USE(G_MID);                    // JUMP
        U256 d = stack.back(); stack.pop_back();
        if (d.w[1] | d.w[2] | d.w[3] || d.w[0] >= code.size()
            || !c->jumpdest[d.w[0]]) {
          res.gas_left = 0;
          return res;
        }
        pc = d.w[0];
        continue; }
      case 0x57: { NEED(2); USE(G_SLOW);                   // JUMPI
        U256 d = stack.back(); stack.pop_back();
        U256 cond = stack.back(); stack.pop_back();
        if (!cond.is_zero()) {
          if (d.w[1] | d.w[2] | d.w[3] || d.w[0] >= code.size()
              || !c->jumpdest[d.w[0]]) {
            res.gas_left = 0;
            return res;
          }
          pc = d.w[0];
          continue;
        }
        break; }
      case 0x58: USE(G_QUICK);
        stack.push_back(u256_from64(pc)); ++pc; continue;
      case 0x59: USE(G_QUICK);
        stack.push_back(u256_from64(mem.size())); ++pc; continue;
      case 0x5A: USE(G_QUICK);
        stack.push_back(u256_from64((uint64_t)gas)); ++pc; continue;
      case 0x5B: USE(G_JUMPDEST); ++pc; continue;
      case 0x5F: USE(G_QUICK); stack.push_back(U256());
        ++pc; continue;                                    // PUSH0
      case 0xF3: case 0xFD: {                              // RETURN REVERT
        NEED(2);
        U256 offv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok2;
        uint64_t off = u64_arg(offv, &ok1), len = u64_arg(lenv, &ok2);
        if (len) {
          if (!ok1 || !ok2 || !expand(off + len)) {
            res.gas_left = 0;
            return res;
          }
        }
        res.gas_left = gas;
        if (op == 0xF3) { res.ok = true; res.writes = dirty; }
        else res.reverted = true;
        return res; }
      case 0xFE: res.gas_left = 0; return res;             // INVALID
      default:
        if (op >= 0x60 && op <= 0x7F) {                    // PUSHn
          USE(G_FASTEST);
          unsigned n = op - 0x5F;
          uint8_t buf[32] = {0};
          for (unsigned j = 0; j < n; ++j) {
            size_t src = pc + 1 + j;
            buf[32 - n + j] = src < code.size() ? code[src] : 0;
          }
          stack.push_back(from_be(buf));
          pc += 1 + n;
          if (stack.size() > 1024) { res.gas_left = 0; return res; }
          continue;
        }
        if (op >= 0x80 && op <= 0x8F) {                    // DUPn
          unsigned n = op - 0x7F;
          NEED(n); USE(G_FASTEST);
          stack.push_back(stack[stack.size() - n]);
          if (stack.size() > 1024) { res.gas_left = 0; return res; }
          ++pc;
          continue;
        }
        if (op >= 0x90 && op <= 0x9F) {                    // SWAPn
          unsigned n = op - 0x8F;
          NEED(n + 1); USE(G_FASTEST);
          std::swap(stack.back(), stack[stack.size() - 1 - n]);
          ++pc;
          continue;
        }
        if (op >= 0xA0 && op <= 0xA4) {                    // LOGn
          unsigned n = op - 0xA0;
          NEED(2 + n);
          U256 offv = stack.back(); stack.pop_back();
          U256 lenv = stack.back(); stack.pop_back();
          for (unsigned j = 0; j < n; ++j) stack.pop_back();
          bool ok1, ok2;
          uint64_t off = u64_arg(offv, &ok1),
                   len = u64_arg(lenv, &ok2);
          if (len) {
            if (!ok1 || !ok2 || !expand(off + len)) {
              res.gas_left = 0;
              return res;
            }
          }
          USE(G_LOG + G_LOGTOPIC * n + G_LOGDATA * (int64_t)len);
          ++pc;
          continue;
        }
        res.gas_left = 0;  // undefined opcode
        return res;
    }
    ++pc;
  }
  res.ok = true;  // implicit STOP past code end
  res.gas_left = gas;
  res.writes = dirty;
  return res;
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

// Sequential compiled EVM replay over packed inputs; returns 0 on
// success, 1000+i on a root mismatch at block i, -1/-2 on malformed
// input.  phases: [t_sender, t_exec, t_trie] seconds.
//
// tx record: sighash32 r32 s32 recid1 to20 value32 gas8 price32
//            required32 nonce8 dlen4 data
// block env record (per block): root32 coinbase20 ts8 num8 gaslimit8
//            basefee32 gasused8
// accounts: addr20 bal32 nonce8
// contracts: addr20 codehash32 bal32 nonce8 len4 code nslots4
//            (key32 val32)*
int coreth_evm_replay(const uint8_t* txs, const uint64_t* block_off,
                      uint64_t n_blocks, const uint8_t* block_env,
                      const uint8_t* accounts, uint64_t n_accounts,
                      const uint8_t* contracts, uint64_t n_contracts,
                      uint64_t chain_id, double* phases) {
  std::unordered_map<std::string, Account> state;
  std::vector<Contract> pool(n_contracts);
  state.reserve(n_accounts * 2);
  const uint8_t* p = accounts;
  for (uint64_t i = 0; i < n_accounts; ++i) {
    std::string addr((const char*)p, 20);
    Account a;
    bool too_big = false;
    for (int j = 0; j < 16; ++j)
      if (p[20 + j]) too_big = true;
    for (int j = 16; j < 32; ++j)
      a.balance = (a.balance << 8) | p[20 + j];
    if (too_big) return -1;
    uint64_t nonce = 0;
    for (int j = 0; j < 8; ++j) nonce = (nonce << 8) | p[52 + j];
    a.nonce = nonce;
    state[addr] = a;
    p += 60;
  }
  p = contracts;
  for (uint64_t i = 0; i < n_contracts; ++i) {
    std::string addr((const char*)p, 20);
    Contract& c = pool[i];
    std::memcpy(c.code_hash, p + 20, 32);
    u128 cbal = 0;
    bool cbig = false;
    for (int j = 0; j < 16; ++j)
      if (p[52 + j]) cbig = true;
    for (int j = 16; j < 32; ++j) cbal = (cbal << 8) | p[52 + j];
    if (cbig) return -1;
    uint64_t cnonce = 0;
    for (int j = 0; j < 8; ++j) cnonce = (cnonce << 8) | p[84 + j];
    uint32_t clen;
    std::memcpy(&clen, p + 92, 4);
    c.code.assign(p + 96, p + 96 + clen);
    analyze_jumpdests(&c);
    p += 96 + clen;
    uint32_t nslots;
    std::memcpy(&nslots, p, 4);
    p += 4;
    for (uint32_t j = 0; j < nslots; ++j) {
      Key32 k;
      std::memcpy(k.b, p, 32);
      c.storage[k] = from_be(p + 32);
      p += 64;
    }
    auto& acct = state[addr];
    acct.contract = &c;
    acct.balance = cbal;
    acct.nonce = cnonce;
  }

  // per-contract storage tries built once from initial slots
  std::vector<void*> stries(n_contracts);
  std::vector<uint8_t> sroots(n_contracts * 32);
  auto fold_slots = [&](uint64_t ci, const SlotMap& slots) {
    std::vector<uint8_t> keys, vals;
    std::vector<uint32_t> lens;
    uint8_t hk[32], be[32];
    for (auto& kv : slots) {
      coreth_keccak256(kv.first.b, 32, hk);
      keys.insert(keys.end(), hk, hk + 32);
      if (kv.second.is_zero()) {
        lens.push_back(0);
        continue;
      }
      to_be(kv.second, be);
      int lead = 0;
      while (lead < 32 && be[lead] == 0) ++lead;
      // rlp of the stripped big-endian integer
      Bytes v;
      int n = 32 - lead;
      if (n == 1 && be[31] < 0x80) {
        v.push_back(be[31]);
      } else {
        v.push_back(0x80 + n);
        v.insert(v.end(), be + lead, be + 32);
      }
      lens.push_back((uint32_t)v.size());
      vals.insert(vals.end(), v.begin(), v.end());
    }
    coreth_trie_update_batch(stries[ci], keys.data(), vals.data(),
                             lens.data(), lens.size());
    coreth_trie_hash(stries[ci], sroots.data() + 32 * ci);
  };
  for (uint64_t i = 0; i < n_contracts; ++i) {
    stries[i] = coreth_trie_new();
    fold_slots(i, pool[i].storage);
  }
  void* atrie = coreth_trie_new();
  // empty-storage / empty-code constants (keccak of "" / rlp(""))
  uint8_t empty_root[32], empty_code[32];
  {
    uint8_t rlp_empty = 0x80;
    coreth_keccak256(&rlp_empty, 1, empty_root);
    coreth_keccak256(nullptr, 0, empty_code);
  }
  // seed the account trie with every genesis account
  {
    std::vector<uint8_t> keys, bals, roots, hashes;
    std::vector<uint64_t> nonces;
    std::vector<uint8_t> mc, del;
    for (auto& kv : state) {
      uint8_t hk[32];
      coreth_keccak256((const uint8_t*)kv.first.data(), 20, hk);
      keys.insert(keys.end(), hk, hk + 32);
      uint8_t be[32] = {0};
      u128 b = kv.second.balance;
      for (int j = 31; j >= 0; --j) {
        be[j] = (uint8_t)b;
        b >>= 8;
      }
      bals.insert(bals.end(), be, be + 32);
      nonces.push_back(kv.second.nonce);
      if (kv.second.contract) {
        uint64_t ci = kv.second.contract - pool.data();
        roots.insert(roots.end(), sroots.data() + 32 * ci,
                     sroots.data() + 32 * ci + 32);
        hashes.insert(hashes.end(), kv.second.contract->code_hash,
                      kv.second.contract->code_hash + 32);
      } else {
        roots.insert(roots.end(), empty_root, empty_root + 32);
        hashes.insert(hashes.end(), empty_code, empty_code + 32);
      }
      mc.push_back(0);
      del.push_back(0);
    }
    coreth_trie_fold_accounts(atrie, keys.data(), bals.data(),
                              nonces.data(), roots.data(),
                              hashes.data(), mc.data(), del.data(),
                              nonces.size());
  }

  double t_sender = 0, t_exec = 0, t_trie = 0;
  int rc = 0;
  const uint8_t* tp = txs;
  for (uint64_t bi = 0; bi < n_blocks && rc == 0; ++bi) {
    const uint8_t* be = block_env + bi * 116;
    Env env;
    env.coinbase = be + 32;
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[52 + j];
    env.timestamp = v;
    v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[60 + j];
    env.number = v;
    v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[68 + j];
    env.gaslimit = v;
    env.basefee = from_be(be + 76);
    env.chain_id = chain_id;

    std::unordered_set<std::string> touched;
    std::unordered_set<uint64_t> dirty_contracts;
    touched.insert(std::string((const char*)env.coinbase, 20));
    for (uint64_t ti = block_off[bi]; ti < block_off[bi + 1]; ++ti) {
      // --- sender recovery
      double t0 = now_s();
      uint8_t sender[20];
      if (!coreth_ecrecover(tp, tp + 32, tp + 64, tp[96], sender))
        return -2;
      t_sender += now_s() - t0;
      t0 = now_s();
      const uint8_t* to = tp + 97;
      bool too_big = false;
      u128 value = 0, price = 0, required = 0;
      for (int j = 16; j < 32; ++j)
        value = (value << 8) | tp[117 + j];
      for (int j = 0; j < 16; ++j)
        if (tp[117 + j] | tp[157 + j] | tp[189 + j]) too_big = true;
      uint64_t gas_limit = 0;
      for (int j = 0; j < 8; ++j)
        gas_limit = (gas_limit << 8) | tp[149 + j];
      for (int j = 16; j < 32; ++j)
        price = (price << 8) | tp[157 + j];
      for (int j = 16; j < 32; ++j)
        required = (required << 8) | tp[189 + j];
      uint64_t nonce = 0;
      for (int j = 0; j < 8; ++j)
        nonce = (nonce << 8) | tp[221 + j];
      uint32_t dlen;
      std::memcpy(&dlen, tp + 229, 4);
      const uint8_t* data = tp + 233;
      tp += 233 + dlen;
      if (too_big) return -3;

      std::string saddr((const char*)sender, 20);
      std::string taddr((const char*)to, 20);
      std::string cbaddr((const char*)env.coinbase, 20);
      // insert all three keys BEFORE taking references: operator[]
      // may rehash and invalidate earlier references
      state.try_emplace(taddr);
      state.try_emplace(cbaddr);
      Account& sa = state[saddr];
      if (sa.nonce != nonce) return 2000 + (int)bi;
      if (sa.balance < required) return 3000 + (int)bi;
      Account& ta = state[taddr];
      uint64_t used;
      bool ok_tx = true;
      // intrinsic gas: 21000 + calldata bytes (durango/EIP-2028)
      uint64_t intrinsic = 21000;
      for (uint32_t j = 0; j < dlen; ++j)
        intrinsic += data[j] ? 16 : 4;
      if (gas_limit < intrinsic) return -4;
      if (ta.contract) {
        TxCtx tctx;
        tctx.caller = sender;
        tctx.address = to;
        uint8_t vb[32] = {0};
        u128 vv = value;
        for (int j = 31; j >= 16; --j) {
          vb[j] = (uint8_t)vv;
          vv >>= 8;
        }
        tctx.value = from_be(vb);
        uint8_t pb[32] = {0};
        u128 pv = price;
        for (int j = 31; j >= 16; --j) {
          pb[j] = (uint8_t)pv;
          pv >>= 8;
        }
        tctx.gasprice = from_be(pb);
        tctx.data = data;
        tctx.data_len = dlen;
        RunResult r = evm_run(ta.contract, env, tctx,
                              (int64_t)(gas_limit - intrinsic));
        used = gas_limit - (uint64_t)r.gas_left;
        ok_tx = r.ok;
        if (r.ok) {
          uint64_t ci = ta.contract - pool.data();
          for (auto& kv : r.writes) {
            ta.contract->storage[kv.first] = kv.second;
            ta.contract->block_dirty[kv.first] = kv.second;
          }
          if (!r.writes.empty()) dirty_contracts.insert(ci);
        }
      } else {
        used = intrinsic;
      }
      sa.nonce += 1;
      sa.balance -= (u128)used * price;
      if (ok_tx && value) {
        sa.balance -= value;
        ta.balance += value;
      }
      state[cbaddr].balance += (u128)used * price;
      touched.insert(saddr);
      touched.insert(taddr);
      t_exec += now_s() - t0;
    }

    // --- per-block fold + root check
    double t0 = now_s();
    for (uint64_t ci : dirty_contracts) {
      fold_slots(ci, pool[ci].block_dirty);
      pool[ci].block_dirty.clear();
    }
    {
      std::vector<uint8_t> keys, bals, roots, hashes;
      std::vector<uint64_t> nonces;
      std::vector<uint8_t> mc, del;
      for (auto& addr : touched) {
        Account& a = state[addr];
        uint8_t hk[32];
        coreth_keccak256((const uint8_t*)addr.data(), 20, hk);
        keys.insert(keys.end(), hk, hk + 32);
        uint8_t beb[32] = {0};
        u128 b = a.balance;
        for (int j = 31; j >= 0; --j) {
          beb[j] = (uint8_t)b;
          b >>= 8;
        }
        bals.insert(bals.end(), beb, beb + 32);
        nonces.push_back(a.nonce);
        bool empty = a.balance == 0 && a.nonce == 0 && !a.contract;
        if (a.contract) {
          uint64_t ci = a.contract - pool.data();
          roots.insert(roots.end(), sroots.data() + 32 * ci,
                       sroots.data() + 32 * ci + 32);
          hashes.insert(hashes.end(), a.contract->code_hash,
                        a.contract->code_hash + 32);
        } else {
          roots.insert(roots.end(), empty_root, empty_root + 32);
          hashes.insert(hashes.end(), empty_code, empty_code + 32);
        }
        mc.push_back(0);
        del.push_back(empty ? 1 : 0);
      }
      coreth_trie_fold_accounts(atrie, keys.data(), bals.data(),
                                nonces.data(), roots.data(),
                                hashes.data(), mc.data(), del.data(),
                                nonces.size());
    }
    uint8_t got[32];
    coreth_trie_hash(atrie, got);
    t_trie += now_s() - t0;
    if (std::memcmp(got, be, 32) != 0) rc = 1000 + (int)bi;
  }

  for (void* h : stries) coreth_trie_free(h);
  coreth_trie_free(atrie);
  phases[0] = t_sender;
  phases[1] = t_exec;
  phases[2] = t_trie;
  return rc;
}

}  // extern "C"
