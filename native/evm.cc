// Compiled host EVM: sequential replay baseline + tx-level host
// execution backend.
//
// Two entry points share one frame-based interpreter:
//
// - coreth_evm_replay: the bench denominator (BASELINE.md round 5) — a
//   single-threaded replay of whole contract chains with per-block
//   storage-trie + account-trie folds and bit-identical root checks.
// - coreth_hostexec_*: a session API that executes ONE full transaction
//   against a StateDB-backed host interface (storage/code resolved
//   through Python callbacks) and returns gas, status, logs, return
//   data, and the cross-contract write set — the production executor
//   for the replay engine's host escape paths (evm/hostexec/).
//
// The interpreter models the durango rule set the host jump table
// implements for AP2+ chains (EIP-2929 warm/cold with journaled access
// sets, EIP-2200/3529 SSTORE ladder with the refund counter tracked,
// quadratic memory, copy/log/keccak/exp word costs) plus nested
// value-0 CALL/STATICCALL with EIP-150 63/64 forwarding and
// RETURNDATASIZE/RETURNDATACOPY.  Anything outside that set (defined
// per fork but not compiled here: BALANCE, CREATE, DELEGATECALL,
// value-carrying subcalls, precompile targets, ...) aborts the tx with
// a HOST status so the caller re-runs it on the exact Python
// interpreter — per-tx automatic fallback, never a wrong answer.
//
// Reference roles: core/vm/interpreter.go:121 (Run),
// core/state_processor.go:95 (tx loop), core/vm/operations_acl.go
// (2929 pricing + journaled access lists), trie/hasher.go (per-block
// rehash).

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>
#include <ctime>

typedef unsigned __int128 u128;
typedef std::vector<uint8_t> Bytes;

extern "C" void coreth_keccak256(const uint8_t*, uint64_t, uint8_t*);
extern "C" int coreth_ecrecover(const uint8_t*, const uint8_t*,
                                const uint8_t*, int, uint8_t*);
// trie handle API from baseline.cc (secure MPT over pre-hashed keys)
extern "C" void* coreth_trie_new();
extern "C" void coreth_trie_free(void*);
extern "C" void coreth_trie_update_batch(void*, const uint8_t*,
                                         const uint8_t*,
                                         const uint32_t*, uint64_t);
extern "C" void coreth_trie_hash(void*, uint8_t*);
extern "C" void coreth_trie_fold_accounts(void*, const uint8_t*,
                                          const uint8_t*,
                                          const uint64_t*,
                                          const uint8_t*,
                                          const uint8_t*,
                                          const uint8_t*,
                                          const uint8_t*, uint64_t);

namespace {

// ----------------------------------------------------------------- u256

struct U256 {
  uint64_t w[4] = {0, 0, 0, 0};  // little-endian 64-bit limbs

  bool is_zero() const { return !(w[0] | w[1] | w[2] | w[3]); }
  bool bit(int i) const { return (w[i >> 6] >> (i & 63)) & 1; }
  int bitlen() const {
    for (int i = 3; i >= 0; --i)
      if (w[i]) return 64 * i + 64 - __builtin_clzll(w[i]);
    return 0;
  }
};

U256 from_be(const uint8_t* p, size_t n = 32) {
  U256 v;
  for (size_t i = 0; i < n; ++i) {
    size_t bit = 8 * (n - 1 - i);
    v.w[bit >> 6] |= (uint64_t)p[i] << (bit & 63);
  }
  return v;
}

void to_be(const U256& v, uint8_t out[32]) {
  for (int i = 0; i < 32; ++i) {
    int bit = 8 * (31 - i);
    out[i] = (uint8_t)(v.w[bit >> 6] >> (bit & 63));
  }
}

U256 u256_from64(uint64_t x) { U256 v; v.w[0] = x; return v; }

bool eq(const U256& a, const U256& b) {
  return !std::memcmp(a.w, b.w, 32);
}

int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

U256 add(const U256& a, const U256& b) {
  U256 r;
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.w[i] + b.w[i] + c;
    r.w[i] = (uint64_t)s;
    c = s >> 64;
  }
  return r;
}

U256 sub(const U256& a, const U256& b) {
  U256 r;
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.w[i] - b.w[i] - borrow;
    r.w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return r;
}

U256 mul(const U256& a, const U256& b) {
  uint64_t out[4] = {0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; i + j < 4; ++j) {
      u128 cur = (u128)a.w[i] * b.w[j] + out[i + j] + carry;
      out[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  U256 r;
  std::memcpy(r.w, out, 32);
  return r;
}

U256 shl_k(const U256& a, unsigned k) {
  U256 r;
  if (k >= 256) return r;
  unsigned limb = k / 64, off = k % 64;
  for (int i = 3; i >= 0; --i) {
    uint64_t v = 0;
    int src = i - (int)limb;
    if (src >= 0) v = a.w[src] << off;
    if (off && src - 1 >= 0) v |= a.w[src - 1] >> (64 - off);
    r.w[i] = v;
  }
  return r;
}

U256 shr_k(const U256& a, unsigned k) {
  U256 r;
  if (k >= 256) return r;
  unsigned limb = k / 64, off = k % 64;
  for (int i = 0; i < 4; ++i) {
    uint64_t v = 0;
    unsigned src = i + limb;
    if (src < 4) v = a.w[src] >> off;
    if (off && src + 1 < 4) v |= a.w[src + 1] << (64 - off);
    r.w[i] = v;
  }
  return r;
}

// divides by a divisor that fits 64 bits (the workload-hot path);
// general case falls back to bit-serial restoring division.
void divmod(const U256& a, const U256& b, U256* q, U256* r) {
  *q = U256();
  *r = U256();
  if (b.is_zero()) return;
  if (!(b.w[1] | b.w[2] | b.w[3])) {
    uint64_t d = b.w[0];
    u128 rem = 0;
    for (int i = 3; i >= 0; --i) {
      u128 cur = (rem << 64) | a.w[i];
      q->w[i] = (uint64_t)(cur / d);
      rem = cur % d;
    }
    r->w[0] = (uint64_t)rem;
    return;
  }
  U256 rem;
  for (int i = 255; i >= 0; --i) {
    rem = shl_k(rem, 1);
    rem.w[0] |= a.bit(i) ? 1 : 0;
    if (cmp(rem, b) >= 0) {
      rem = sub(rem, b);
      q->w[i >> 6] |= 1ULL << (i & 63);
    }
  }
  *r = rem;
}

bool sign_neg(const U256& a) { return a.w[3] >> 63; }

U256 neg(const U256& a) { return sub(U256(), a); }

U256 u_abs(const U256& a) { return sign_neg(a) ? neg(a) : a; }

// (a + b) % n and (a * b) % n over the wide intermediate: shift-add /
// shift-mod loops — correctness parity only, never on the bench path.
U256 addmod_(const U256& a, const U256& b, const U256& n) {
  if (n.is_zero()) return U256();
  U256 q, ra, rb;
  divmod(a, n, &q, &ra);
  divmod(b, n, &q, &rb);
  U256 s = add(ra, rb);
  // one conditional subtract handles the possible 257-bit overflow
  if (cmp(s, ra) < 0 || cmp(s, n) >= 0) s = sub(s, n);
  return s;
}

U256 mulmod_(const U256& a, const U256& b, const U256& n) {
  if (n.is_zero()) return U256();
  U256 q, x, result;
  divmod(a, n, &q, &x);
  U256 y;
  divmod(b, n, &q, &y);
  // double-and-add: result = x*y mod n without a 512-bit intermediate
  for (int i = y.bitlen() - 1; i >= 0; --i) {
    result = addmod_(result, result, n);
    if (y.bit(i)) result = addmod_(result, x, n);
  }
  return result;
}

// ------------------------------------------------------------ gas rules
// durango-level constants (params/protocol.py twins)

constexpr int64_t G_QUICK = 2, G_FASTEST = 3, G_FAST = 5, G_MID = 8,
                  G_SLOW = 10;
constexpr int64_t G_KECCAK = 30, G_KECCAK_WORD = 6, G_MEM = 3,
                  G_COPY = 3, G_LOG = 375, G_LOGTOPIC = 375,
                  G_LOGDATA = 8, G_JUMPDEST = 1, G_EXP = 10,
                  G_EXPBYTE = 50;
constexpr int64_t COLD_SLOAD = 2100, WARM_READ = 100,
                  SSTORE_SET = 20000, SSTORE_RESET = 5000,
                  SSTORE_SENTRY = 2300, SSTORE_CLEARS_REFUND = 4800,
                  COLD_ACCOUNT = 2600;
constexpr uint64_t QUAD_DIV = 512;

int64_t mem_cost(uint64_t words) {
  return (int64_t)(words * G_MEM + words * words / QUAD_DIV);
}

struct Key32 {
  uint8_t b[32];
  bool operator==(const Key32& o) const {
    return !std::memcmp(b, o.b, 32);
  }
  bool operator<(const Key32& o) const {
    return std::memcmp(b, o.b, 32) < 0;
  }
};
struct Key32Hash {
  size_t operator()(const Key32& k) const {
    size_t h;
    std::memcpy(&h, k.b, sizeof(h));
    return h;
  }
};
typedef std::unordered_map<Key32, U256, Key32Hash> SlotMap;

struct Contract {
  Bytes code;
  uint8_t code_hash[32];
  SlotMap storage;               // committed (as of last block/fetch)
  std::vector<bool> jumpdest;
  bool dirty = false;            // storage touched since last fold
  SlotMap block_dirty;           // writes since last fold
};

struct Account {
  u128 balance = 0;
  uint64_t nonce = 0;
  Contract* contract = nullptr;
};

struct Env {
  uint8_t coinbase[20] = {0};
  uint64_t timestamp = 0, number = 0, gaslimit = 0, chain_id = 0,
           difficulty = 1;
  U256 basefee;
};

U256 addr_word(const uint8_t* a20) {
  uint8_t p[32] = {0};
  std::memcpy(p + 12, a20, 20);
  return from_be(p);
}

std::string low20(const U256& w) {
  uint8_t be[32];
  to_be(w, be);
  return std::string((const char*)be + 12, 20);
}

void analyze_jumpdests(Contract* c) {
  c->jumpdest.assign(c->code.size(), false);
  for (size_t i = 0; i < c->code.size();) {
    uint8_t op = c->code[i];
    if (op == 0x5B) c->jumpdest[i] = true;
    i += (op >= 0x60 && op <= 0x7F) ? op - 0x5F + 1 : 1;
  }
}

// -------------------------------------------------------- tx-level state

// statuses mirror the device machine codes (evm/device/machine.py)
constexpr int ST_STOP = 1, ST_REVERT = 2, ST_ERR = 3, ST_HOST = 4;

struct LogRec {
  uint8_t addr[20];
  int nt = 0;
  uint8_t topics[4][32];
  Bytes data;
};

// optable entries: 0 undefined (INVALID at runtime), 1 native,
// 2 defined-but-host-only (HOST escape)
constexpr uint8_t OP_UNDEF = 0, OP_NATIVE = 1, OP_HOSTONLY = 2;

typedef int (*FetchSlotCb)(const uint8_t* addr20, const uint8_t* key32,
                           uint8_t* out32);
typedef int (*FetchCodeCb)(const uint8_t* addr20);

struct Sess;

// per-transaction interpreter context: the journaled warm sets, the
// cross-contract dirty overlay, logs, and the refund counter — the
// compiled analog of the StateDB journal scoped to one tx.
struct Exec {
  const Env* env = nullptr;
  const uint8_t* origin = nullptr;  // 20
  U256 gasprice;
  const uint8_t* optable = nullptr;  // 256 entries
  bool refunds_on = false;
  Sess* sess = nullptr;                                   // hostexec mode
  std::unordered_map<std::string, Account>* replay_state = nullptr;
  // tx-mutable
  std::map<std::string, U256> dirty;   // addr20+maskedkey32 -> value
  std::vector<LogRec> logs;
  int64_t refund = 0;
  std::unordered_set<std::string> warm_addr;   // addr20
  std::unordered_set<std::string> warm_slot;   // addr20+RAWkey32
  std::vector<std::string> addr_jour, slot_jour;
  int host_reason = 0;                          // opcode forcing HOST
};

struct Snap {
  std::map<std::string, U256> dirty;
  size_t nlogs, aj, sj;
  int64_t refund;
};

Snap take_snap(Exec& X) {
  return Snap{X.dirty, X.logs.size(), X.addr_jour.size(),
              X.slot_jour.size(), X.refund};
}

void restore_snap(Exec& X, Snap& s) {
  X.dirty = s.dirty;
  X.logs.resize(s.nlogs);
  X.refund = s.refund;
  while (X.addr_jour.size() > s.aj) {
    X.warm_addr.erase(X.addr_jour.back());
    X.addr_jour.pop_back();
  }
  while (X.slot_jour.size() > s.sj) {
    X.warm_slot.erase(X.slot_jour.back());
    X.slot_jour.pop_back();
  }
}

// true when already warm; adds + journals when cold
bool warm_addr_check(Exec& X, const std::string& a) {
  if (X.warm_addr.count(a)) return true;
  X.warm_addr.insert(a);
  X.addr_jour.push_back(a);
  return false;
}

bool warm_slot_check(Exec& X, const std::string& k) {
  if (X.warm_slot.count(k)) return true;
  X.warm_slot.insert(k);
  X.slot_jour.push_back(k);
  return false;
}

struct SessOut {
  int status = 0;
  int64_t gas_left = 0, refund = 0;
  int host_reason = 0;
  std::map<std::string, U256> writes;
  std::vector<LogRec> logs;
  Bytes ret;
};

struct Sess {
  Env env;
  std::unordered_map<std::string, Contract> contracts;
  std::unordered_map<std::string, int> kind;  // 1 contract, 0 eoa
  FetchSlotCb fetch_slot = nullptr;
  FetchCodeCb fetch_code = nullptr;
  uint8_t optable[256] = {0};
  int refunds_on = 0;
  std::vector<std::string> seed_warm_addr, seed_warm_slot;
  SessOut out;
};

// code lookup: 1 contract (out set), 0 EOA, -1 host must handle
int lookup_code(Exec& X, const std::string& addr, Contract** out) {
  if (X.replay_state) {
    auto it = X.replay_state->find(addr);
    if (it == X.replay_state->end() || !it->second.contract) return 0;
    *out = it->second.contract;
    return 1;
  }
  Sess* s = X.sess;
  auto k = s->kind.find(addr);
  if (k == s->kind.end()) {
    if (!s->fetch_code) return -1;
    int r = s->fetch_code((const uint8_t*)addr.data());
    if (r < 0) return -1;
    if (r == 0) {
      s->kind[addr] = 0;
      return 0;
    }
    k = s->kind.find(addr);  // set_code (re-entrant) registered it
    if (k == s->kind.end()) return -1;
  }
  if (k->second == 0) return 0;
  *out = &s->contracts[addr];
  return 1;
}

// committed (pre-tx) value of a masked storage key
U256 committed_read(Exec& X, const std::string& addr, const Key32& mk) {
  if (X.replay_state) {
    auto it = X.replay_state->find(addr);
    if (it == X.replay_state->end() || !it->second.contract)
      return U256();
    auto s = it->second.contract->storage.find(mk);
    return s == it->second.contract->storage.end() ? U256() : s->second;
  }
  Contract& c = X.sess->contracts[addr];
  auto s = c.storage.find(mk);
  if (s != c.storage.end()) return s->second;
  U256 v;
  if (X.sess->fetch_slot) {
    uint8_t out[32] = {0};
    X.sess->fetch_slot((const uint8_t*)addr.data(), mk.b, out);
    v = from_be(out);
  }
  c.storage[mk] = v;
  return v;
}

U256 current_read(Exec& X, const std::string& addr, const Key32& mk) {
  std::string dk = addr + std::string((const char*)mk.b, 32);
  auto it = X.dirty.find(dk);
  if (it != X.dirty.end()) return it->second;
  return committed_read(X, addr, mk);
}

// result of one interpreter frame
struct FrameRes {
  int status = ST_ERR;
  int64_t gas = 0;
  Bytes out;
};

// the interpreter: a direct switch loop (the compiled analog of
// interpreter.go Run).  `depth` counts running frames including this
// one (root == 1); subcall ceilings follow evm.go's depth > 1024.
FrameRes run_frame(Exec& X, const uint8_t* caller,
                   const std::string& self_addr, Contract* c,
                   const uint8_t* input, uint64_t inlen, int64_t gas,
                   const U256& value, bool is_static, int depth) {
  FrameRes res;
  std::vector<U256> stack;
  stack.reserve(64);
  Bytes mem;
  Bytes retdata;  // frame-local last-subcall return data
  uint64_t pc = 0;
  const Bytes& code = c->code;

#define NEED(n) if (stack.size() < (n)) { res.gas = 0; return res; }
#define USE(g) do { if (gas < (int64_t)(g)) { res.gas = 0; \
  return res; } gas -= (g); } while (0)

  auto expand = [&](uint64_t need) -> bool {
    if (need <= mem.size()) return true;
    if (need > (1ULL << 25)) return false;
    uint64_t new_words = (need + 31) / 32;
    int64_t cost = mem_cost(new_words) - mem_cost(mem.size() / 32);
    if (gas < cost) return false;
    gas -= cost;
    mem.resize(new_words * 32, 0);
    return true;
  };
  auto u64_arg = [&](const U256& v, bool* okf) -> uint64_t {
    if (v.w[1] | v.w[2] | v.w[3] || v.w[0] > (1ULL << 32)) {
      *okf = false;
      return 1ULL << 32;
    }
    *okf = true;
    return v.w[0];
  };

  while (pc < code.size()) {
    uint8_t op = code[pc];
    // per-fork dispatch gate BEFORE the switch: an opcode this engine
    // compiles may still be UNDEFINED under the session's fork (PUSH0
    // pre-durango, BASEFEE pre-ap3) — it must INVALID-err exactly like
    // the interpreter, not execute; host-only opcodes escape here too
    if (X.optable) {
      uint8_t cls = X.optable[op];
      if (cls == OP_UNDEF) { res.gas = 0; return res; }
      if (cls == OP_HOSTONLY) {
        X.host_reason = op;
        res.status = ST_HOST;
        return res;
      }
    }
    switch (op) {
      case 0x00: res.status = ST_STOP; res.gas = gas;    // STOP
                 return res;
      case 0x01: { NEED(2); USE(G_FASTEST);                // ADD
        U256 a = stack.back(); stack.pop_back();
        stack.back() = add(a, stack.back()); break; }
      case 0x02: { NEED(2); USE(G_FAST);                   // MUL
        U256 a = stack.back(); stack.pop_back();
        stack.back() = mul(a, stack.back()); break; }
      case 0x03: { NEED(2); USE(G_FASTEST);                // SUB
        U256 a = stack.back(); stack.pop_back();
        stack.back() = sub(a, stack.back()); break; }
      case 0x04: { NEED(2); USE(G_FAST);                   // DIV
        U256 a = stack.back(); stack.pop_back();
        U256 q, r; divmod(a, stack.back(), &q, &r);
        stack.back() = q; break; }
      case 0x05: { NEED(2); USE(G_FAST);                   // SDIV
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back();
        U256 q, r; divmod(u_abs(a), u_abs(b), &q, &r);
        stack.back() = (sign_neg(a) != sign_neg(b) && !b.is_zero())
                           ? neg(q) : q;
        break; }
      case 0x06: { NEED(2); USE(G_FAST);                   // MOD
        U256 a = stack.back(); stack.pop_back();
        U256 q, r; divmod(a, stack.back(), &q, &r);
        stack.back() = r; break; }
      case 0x07: { NEED(2); USE(G_FAST);                   // SMOD
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back();
        U256 q, r; divmod(u_abs(a), u_abs(b), &q, &r);
        stack.back() = sign_neg(a) ? neg(r) : r; break; }
      case 0x08: { NEED(3); USE(G_MID);                    // ADDMOD
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back(); stack.pop_back();
        stack.back() = addmod_(a, b, stack.back()); break; }
      case 0x09: { NEED(3); USE(G_MID);                    // MULMOD
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back(); stack.pop_back();
        stack.back() = mulmod_(a, b, stack.back()); break; }
      case 0x0A: { NEED(2);                                // EXP
        U256 b = stack.back(); stack.pop_back();
        U256 e = stack.back();
        USE(G_EXP + G_EXPBYTE * ((e.bitlen() + 7) / 8));
        U256 r = u256_from64(1), cur = b;
        int n = e.bitlen();
        for (int i = 0; i < n; ++i) {
          if (e.bit(i)) r = mul(r, cur);
          cur = mul(cur, cur);
        }
        stack.back() = r; break; }
      case 0x0B: { NEED(2); USE(G_FAST);                   // SIGNEXTEND
        U256 b = stack.back(); stack.pop_back();
        U256 x = stack.back();
        if (b.w[0] < 31 && !(b.w[1] | b.w[2] | b.w[3])) {
          int t = 8 * (int)(b.w[0] + 1);
          bool neg_bit = x.bit(t - 1);
          U256 mask = sub(shl_k(u256_from64(1), t), u256_from64(1));
          if (neg_bit) {
            U256 inv;
            for (int i = 0; i < 4; ++i) inv.w[i] = ~mask.w[i];
            for (int i = 0; i < 4; ++i) x.w[i] |= inv.w[i];
          } else {
            for (int i = 0; i < 4; ++i) x.w[i] &= mask.w[i];
          }
          stack.back() = x;
        }
        break; }
      case 0x10: case 0x11: case 0x12: case 0x13: case 0x14: {
        NEED(2); USE(G_FASTEST);        // LT GT SLT SGT EQ
        U256 a = stack.back(); stack.pop_back();
        U256 b = stack.back();
        bool r = false;
        if (op == 0x10) r = cmp(a, b) < 0;
        else if (op == 0x11) r = cmp(a, b) > 0;
        else if (op == 0x14) r = eq(a, b);
        else {
          bool sa = sign_neg(a), sb = sign_neg(b);
          int c0 = cmp(a, b);
          bool lt = sa != sb ? sa : c0 < 0;
          r = (op == 0x12) ? lt : (c0 != 0 && !lt);
        }
        stack.back() = u256_from64(r ? 1 : 0); break; }
      case 0x15: { NEED(1); USE(G_FASTEST);                // ISZERO
        stack.back() = u256_from64(stack.back().is_zero() ? 1 : 0);
        break; }
      case 0x16: case 0x17: case 0x18: { NEED(2); USE(G_FASTEST);
        U256 a = stack.back(); stack.pop_back();           // AND OR XOR
        U256& b = stack.back();
        for (int i = 0; i < 4; ++i)
          b.w[i] = op == 0x16 ? (a.w[i] & b.w[i])
                 : op == 0x17 ? (a.w[i] | b.w[i]) : (a.w[i] ^ b.w[i]);
        break; }
      case 0x19: { NEED(1); USE(G_FASTEST);                // NOT
        for (int i = 0; i < 4; ++i)
          stack.back().w[i] = ~stack.back().w[i];
        break; }
      case 0x1A: { NEED(2); USE(G_FASTEST);                // BYTE
        U256 i = stack.back(); stack.pop_back();
        U256 x = stack.back();
        uint64_t v = 0;
        if (i.w[0] < 32 && !(i.w[1] | i.w[2] | i.w[3])) {
          uint8_t be[32];
          to_be(x, be);
          v = be[i.w[0]];
        }
        stack.back() = u256_from64(v); break; }
      case 0x1B: case 0x1C: { NEED(2); USE(G_FASTEST);     // SHL SHR
        U256 s = stack.back(); stack.pop_back();
        U256 x = stack.back();
        unsigned k = (s.w[1] | s.w[2] | s.w[3] || s.w[0] > 255)
                         ? 256 : (unsigned)s.w[0];
        stack.back() = op == 0x1B ? shl_k(x, k) : shr_k(x, k);
        break; }
      case 0x1D: { NEED(2); USE(G_FASTEST);                // SAR
        U256 s = stack.back(); stack.pop_back();
        U256 x = stack.back();
        bool negx = sign_neg(x);
        unsigned k = (s.w[1] | s.w[2] | s.w[3] || s.w[0] > 255)
                         ? 256 : (unsigned)s.w[0];
        if (k >= 256) {
          stack.back() = negx ? neg(u256_from64(1)) : U256();
        } else {
          U256 r = shr_k(x, k);
          if (negx && k) {
            U256 fill = shl_k(neg(u256_from64(1)), 256 - k);
            for (int i = 0; i < 4; ++i) r.w[i] |= fill.w[i];
          }
          stack.back() = r;
        }
        break; }
      case 0x20: { NEED(2); USE(G_KECCAK);                 // KECCAK256
        U256 offv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool okf1, okf2;
        uint64_t off = u64_arg(offv, &okf1), len = u64_arg(lenv, &okf2);
        if (len) {
          if (!okf1 || !okf2 || !expand(off + len)) {
            res.gas = 0;
            return res;
          }
        }
        USE(G_KECCAK_WORD * ((len + 31) / 32));
        uint8_t h[32];
        coreth_keccak256(len ? mem.data() + off : nullptr, len, h);
        stack.push_back(from_be(h)); break; }
      case 0x30: USE(G_QUICK);                             // ADDRESS
        stack.push_back(addr_word((const uint8_t*)self_addr.data()));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x32: USE(G_QUICK);                             // ORIGIN
        stack.push_back(addr_word(X.origin));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x33: USE(G_QUICK);                             // CALLER
        stack.push_back(addr_word(caller));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x34: USE(G_QUICK);                             // CALLVALUE
        stack.push_back(value);
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x35: { NEED(1); USE(G_FASTEST);                // CALLDATALOAD
        U256 offv = stack.back();
        uint8_t word[32] = {0};
        if (!(offv.w[1] | offv.w[2] | offv.w[3])
            && offv.w[0] < inlen) {
          uint64_t off = offv.w[0];
          uint64_t n = inlen - off < 32 ? inlen - off : 32;
          std::memcpy(word, input + off, n);
        }
        stack.back() = from_be(word); break; }
      case 0x36: USE(G_QUICK);                             // CALLDATASIZE
        stack.push_back(u256_from64(inlen));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x37: { NEED(3); USE(G_FASTEST);                // CALLDATACOPY
        U256 dstv = stack.back(); stack.pop_back();
        U256 srcv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok3;
        uint64_t dst = u64_arg(dstv, &ok1);
        uint64_t len = u64_arg(lenv, &ok3);
        if (len) {
          if (!ok1 || !ok3 || !expand(dst + len)) {
            res.gas = 0;
            return res;
          }
        }
        USE(G_COPY * ((len + 31) / 32));
        for (uint64_t j = 0; j < len; ++j) {
          uint64_t s = (srcv.w[1] | srcv.w[2] | srcv.w[3])
                           ? inlen : srcv.w[0] + j;
          mem[dst + j] = s < inlen ? input[s] : 0;
        }
        break; }
      case 0x38: USE(G_QUICK);                             // CODESIZE
        stack.push_back(u256_from64(code.size()));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x39: { NEED(3); USE(G_FASTEST);                // CODECOPY
        U256 dstv = stack.back(); stack.pop_back();
        U256 srcv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok3;
        uint64_t dst = u64_arg(dstv, &ok1);
        uint64_t len = u64_arg(lenv, &ok3);
        if (len) {
          if (!ok1 || !ok3 || !expand(dst + len)) {
            res.gas = 0;
            return res;
          }
        }
        USE(G_COPY * ((len + 31) / 32));
        for (uint64_t j = 0; j < len; ++j) {
          uint64_t s = (srcv.w[1] | srcv.w[2] | srcv.w[3])
                           ? code.size() : srcv.w[0] + j;
          mem[dst + j] = s < code.size() ? code[s] : 0;
        }
        break; }
      case 0x3A: USE(G_QUICK);                             // GASPRICE
        stack.push_back(X.gasprice);
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x3D: USE(G_QUICK);                             // RETURNDATASIZE
        stack.push_back(u256_from64(retdata.size()));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x3E: { NEED(3); USE(G_FASTEST);                // RETURNDATACOPY
        U256 dstv = stack.back(); stack.pop_back();
        U256 srcv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok2, ok3;
        uint64_t dst = u64_arg(dstv, &ok1);
        uint64_t src = u64_arg(srcv, &ok2);
        uint64_t len = u64_arg(lenv, &ok3);
        if (len) {
          if (!ok1 || !ok3 || !expand(dst + len)) {
            res.gas = 0;
            return res;
          }
        }
        USE(G_COPY * ((len + 31) / 32));
        // bounds: src + len must sit inside the last return data
        // (EIP-211; geth opReturnDataCopy -> ErrReturnDataOutOfBounds)
        if (!ok2 || src + len > retdata.size()) {
          res.gas = 0;
          return res;
        }
        if (len) std::memcpy(mem.data() + dst, retdata.data() + src, len);
        break; }
      case 0x41: USE(G_QUICK);                             // COINBASE
        stack.push_back(addr_word(X.env->coinbase));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x42: USE(G_QUICK);
        stack.push_back(u256_from64(X.env->timestamp));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x43: USE(G_QUICK);
        stack.push_back(u256_from64(X.env->number));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x44: USE(G_QUICK);                             // DIFFICULTY
        stack.push_back(u256_from64(X.env->difficulty));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x45: USE(G_QUICK);
        stack.push_back(u256_from64(X.env->gaslimit));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x46: USE(G_QUICK);
        stack.push_back(u256_from64(X.env->chain_id));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x48: USE(G_QUICK);
        stack.push_back(X.env->basefee);
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x50: NEED(1); USE(G_QUICK); stack.pop_back();
        ++pc; continue;
      case 0x51: { NEED(1); USE(G_FASTEST);                // MLOAD
        U256 offv = stack.back();
        bool okf;
        uint64_t off = u64_arg(offv, &okf);
        if (!okf || !expand(off + 32)) { res.gas = 0; return res; }
        stack.back() = from_be(mem.data() + off); break; }
      case 0x52: { NEED(2); USE(G_FASTEST);                // MSTORE
        U256 offv = stack.back(); stack.pop_back();
        U256 val = stack.back(); stack.pop_back();
        bool okf;
        uint64_t off = u64_arg(offv, &okf);
        if (!okf || !expand(off + 32)) { res.gas = 0; return res; }
        to_be(val, mem.data() + off); break; }
      case 0x53: { NEED(2); USE(G_FASTEST);                // MSTORE8
        U256 offv = stack.back(); stack.pop_back();
        U256 val = stack.back(); stack.pop_back();
        bool okf;
        uint64_t off = u64_arg(offv, &okf);
        if (!okf || !expand(off + 1)) { res.gas = 0; return res; }
        mem[off] = (uint8_t)val.w[0]; break; }
      case 0x54: { NEED(1);                                // SLOAD
        U256 keyv = stack.back();
        Key32 rawk, mk;
        to_be(keyv, rawk.b);
        mk = rawk;
        mk.b[0] &= 0xFE;  // multicoin normal-storage partition
        // warm set keyed on the RAW key, exactly like the StateDB
        // access list (gas.py gas_sload_eip2929 peeks the unmasked key)
        std::string wk = self_addr + std::string((const char*)rawk.b, 32);
        // hoisted: USE() evaluates its argument twice (gas check +
        // charge), and warm_slot_check must run exactly once
        int64_t sload_cost =
            warm_slot_check(X, wk) ? WARM_READ : COLD_SLOAD;
        USE(sload_cost);
        stack.back() = current_read(X, self_addr, mk);
        break; }
      case 0x55: { NEED(2);                                // SSTORE
        if (is_static) { res.gas = 0; return res; }  // write protection
        if (gas <= SSTORE_SENTRY) { res.gas = 0; return res; }
        U256 keyv = stack.back(); stack.pop_back();
        U256 val = stack.back(); stack.pop_back();
        Key32 rawk, mk;
        to_be(keyv, rawk.b);
        mk = rawk;
        mk.b[0] &= 0xFE;
        int64_t cost = 0;
        std::string wk = self_addr + std::string((const char*)rawk.b, 32);
        if (!warm_slot_check(X, wk)) cost += COLD_SLOAD;
        U256 orig = committed_read(X, self_addr, mk);
        std::string dk = self_addr + std::string((const char*)mk.b, 32);
        auto di = X.dirty.find(dk);
        U256 cur = di == X.dirty.end() ? orig : di->second;
        if (eq(cur, val)) {
          cost += WARM_READ;
        } else if (eq(orig, cur)) {
          if (orig.is_zero()) {
            cost += SSTORE_SET;
          } else {
            if (X.refunds_on && val.is_zero())
              X.refund += SSTORE_CLEARS_REFUND;
            cost += SSTORE_RESET - COLD_SLOAD;
          }
        } else {
          // dirty slot: EIP-2200/3529 refund ladder (gas.py
          // make_gas_sstore_eip2929 with_refunds branch)
          if (X.refunds_on) {
            if (!orig.is_zero()) {
              if (cur.is_zero()) X.refund -= SSTORE_CLEARS_REFUND;
              else if (val.is_zero()) X.refund += SSTORE_CLEARS_REFUND;
            }
            if (eq(orig, val)) {
              if (orig.is_zero())
                X.refund += SSTORE_SET - WARM_READ;
              else
                X.refund += SSTORE_RESET - COLD_SLOAD - WARM_READ;
            }
          }
          cost += WARM_READ;
        }
        USE(cost);
        X.dirty[dk] = val;
        break; }
      case 0x56: { NEED(1); USE(G_MID);                    // JUMP
        U256 d = stack.back(); stack.pop_back();
        if (d.w[1] | d.w[2] | d.w[3] || d.w[0] >= code.size()
            || !c->jumpdest[d.w[0]]) {
          res.gas = 0;
          return res;
        }
        pc = d.w[0];
        continue; }
      case 0x57: { NEED(2); USE(G_SLOW);                   // JUMPI
        U256 d = stack.back(); stack.pop_back();
        U256 cond = stack.back(); stack.pop_back();
        if (!cond.is_zero()) {
          if (d.w[1] | d.w[2] | d.w[3] || d.w[0] >= code.size()
              || !c->jumpdest[d.w[0]]) {
            res.gas = 0;
            return res;
          }
          pc = d.w[0];
          continue;
        }
        break; }
      case 0x58: USE(G_QUICK);
        stack.push_back(u256_from64(pc));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x59: USE(G_QUICK);
        stack.push_back(u256_from64(mem.size()));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x5A: USE(G_QUICK);
        stack.push_back(u256_from64((uint64_t)gas));
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;
      case 0x5B: USE(G_JUMPDEST); ++pc; continue;
      case 0x5F: USE(G_QUICK); stack.push_back(U256());
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc; continue;                                    // PUSH0
      case 0xF1: case 0xFA: {                              // CALL STATICCALL
        unsigned nargs = op == 0xF1 ? 7 : 6;
        NEED(nargs);
        USE(WARM_READ);  // constant gas (2929 call variants)
        U256 greq = stack.back(); stack.pop_back();
        U256 addrw = stack.back(); stack.pop_back();
        U256 callv;
        if (op == 0xF1) { callv = stack.back(); stack.pop_back(); }
        U256 inoffv = stack.back(); stack.pop_back();
        U256 inszv = stack.back(); stack.pop_back();
        U256 outoffv = stack.back(); stack.pop_back();
        U256 outszv = stack.back(); stack.pop_back();
        std::string target = low20(addrw);
        // cold-account surcharge, deducted before the 63/64 split
        // (gas.py make_gas_call_eip2929)
        int64_t cold = warm_addr_check(X, target)
                           ? 0 : COLD_ACCOUNT - WARM_READ;
        if (gas < cold) { res.gas = 0; return res; }
        gas -= cold;
        bool ok1, ok2, ok3, ok4;
        uint64_t inoff = u64_arg(inoffv, &ok1);
        uint64_t insz = u64_arg(inszv, &ok2);
        uint64_t outoff = u64_arg(outoffv, &ok3);
        uint64_t outsz = u64_arg(outszv, &ok4);
        uint64_t in_end = insz ? inoff + insz : 0;
        uint64_t out_end = outsz ? outoff + outsz : 0;
        if ((insz && (!ok1 || !ok2)) || (outsz && (!ok3 || !ok4))) {
          res.gas = 0;
          return res;
        }
        uint64_t msz = in_end > out_end ? in_end : out_end;
        uint64_t new_words = (msz + 31) / 32;
        if (msz > (1ULL << 25)) { res.gas = 0; return res; }
        int64_t memgas = msz <= mem.size() ? 0
            : mem_cost(new_words) - mem_cost(mem.size() / 32);
        if (op == 0xF1 && !callv.is_zero()) {
          if (is_static) { res.gas = 0; return res; }  // write protect
          // value-carrying subcalls need balances + new-account checks
          // the compiled engine does not model -> host interpreter
          X.host_reason = op;
          res.status = ST_HOST;
          return res;
        }
        if (gas < memgas) { res.gas = 0; return res; }
        int64_t avail = gas - memgas;
        int64_t cap = avail - avail / 64;   // EIP-150 63/64
        int64_t child_gas = cap;
        if (!(greq.w[1] | greq.w[2] | greq.w[3])
            && greq.w[0] < (uint64_t)cap)
          child_gas = (int64_t)greq.w[0];
        gas -= memgas + child_gas;
        if (msz > mem.size()) mem.resize(new_words * 32, 0);
        // resolve callee
        Contract* cc = nullptr;
        int kind = lookup_code(X, target, &cc);
        if (kind < 0) {
          X.host_reason = op;
          res.status = ST_HOST;
          return res;
        }
        Bytes args;
        if (insz) args.assign(mem.begin() + inoff,
                              mem.begin() + inoff + insz);
        FrameRes cres;
        if (depth > 1024) {
          // ErrDepth: the subcall fails but returns its gas untouched
          cres.status = ST_ERR;
          cres.gas = child_gas;
        } else if (kind == 1 && !cc->code.empty()) {
          Snap sn = take_snap(X);
          cres = run_frame(X, (const uint8_t*)self_addr.data(), target,
                           cc, args.data(), args.size(), child_gas,
                           callv, is_static || op == 0xFA, depth + 1);
          if (cres.status == ST_HOST) {
            res.status = ST_HOST;
            return res;
          }
          if (cres.status != ST_STOP) restore_snap(X, sn);
        } else {
          // EOA / empty code: trivially successful subcall
          cres.status = ST_STOP;
          cres.gas = child_gas;
        }
        gas += cres.gas;
        retdata = cres.out;
        stack.push_back(u256_from64(cres.status == ST_STOP ? 1 : 0));
        if (cres.status == ST_STOP || cres.status == ST_REVERT) {
          uint64_t n = cres.out.size() < outsz ? cres.out.size() : outsz;
          if (n) std::memcpy(mem.data() + outoff, cres.out.data(), n);
        }
        if (stack.size() > 1024) { res.gas = 0; return res; }
        ++pc;
        continue; }
      case 0xF3: case 0xFD: {                              // RETURN REVERT
        NEED(2);
        U256 offv = stack.back(); stack.pop_back();
        U256 lenv = stack.back(); stack.pop_back();
        bool ok1, ok2;
        uint64_t off = u64_arg(offv, &ok1), len = u64_arg(lenv, &ok2);
        if (len) {
          if (!ok1 || !ok2 || !expand(off + len)) {
            res.gas = 0;
            return res;
          }
        }
        res.gas = gas;
        if (len) res.out.assign(mem.begin() + off,
                                mem.begin() + off + len);
        res.status = op == 0xF3 ? ST_STOP : ST_REVERT;
        return res; }
      case 0xFE: res.gas = 0; return res;                  // INVALID
      default:
        if (op >= 0x60 && op <= 0x7F) {                    // PUSHn
          USE(G_FASTEST);
          unsigned n = op - 0x5F;
          uint8_t buf[32] = {0};
          for (unsigned j = 0; j < n; ++j) {
            size_t src = pc + 1 + j;
            buf[32 - n + j] = src < code.size() ? code[src] : 0;
          }
          stack.push_back(from_be(buf));
          pc += 1 + n;
          if (stack.size() > 1024) { res.gas = 0; return res; }
          continue;
        }
        if (op >= 0x80 && op <= 0x8F) {                    // DUPn
          unsigned n = op - 0x7F;
          NEED(n); USE(G_FASTEST);
          stack.push_back(stack[stack.size() - n]);
          if (stack.size() > 1024) { res.gas = 0; return res; }
          ++pc;
          continue;
        }
        if (op >= 0x90 && op <= 0x9F) {                    // SWAPn
          unsigned n = op - 0x8F;
          NEED(n + 1); USE(G_FASTEST);
          std::swap(stack.back(), stack[stack.size() - 1 - n]);
          ++pc;
          continue;
        }
        if (op >= 0xA0 && op <= 0xA4) {                    // LOGn
          unsigned n = op - 0xA0;
          NEED(2 + n);
          if (is_static) { res.gas = 0; return res; }  // write protect
          U256 offv = stack.back(); stack.pop_back();
          U256 lenv = stack.back(); stack.pop_back();
          LogRec lg;
          std::memcpy(lg.addr, self_addr.data(), 20);
          lg.nt = (int)n;
          for (unsigned j = 0; j < n; ++j) {
            to_be(stack.back(), lg.topics[j]);
            stack.pop_back();
          }
          bool ok1, ok2;
          uint64_t off = u64_arg(offv, &ok1),
                   len = u64_arg(lenv, &ok2);
          if (len) {
            if (!ok1 || !ok2 || !expand(off + len)) {
              res.gas = 0;
              return res;
            }
          }
          USE(G_LOG + G_LOGTOPIC * n + G_LOGDATA * (int64_t)len);
          if (len) lg.data.assign(mem.begin() + off,
                                  mem.begin() + off + len);
          X.logs.push_back(std::move(lg));
          ++pc;
          continue;
        }
        if (X.optable && X.optable[op] == OP_HOSTONLY) {
          // defined in the fork's jump table but not compiled here:
          // the whole tx re-runs on the Python interpreter
          X.host_reason = op;
          res.status = ST_HOST;
          return res;
        }
        res.gas = 0;  // undefined opcode
        return res;
    }
    ++pc;
  }
  res.status = ST_STOP;  // implicit STOP past code end
  res.gas = gas;
  res.out.clear();
  return res;
}

// native ops the interpreter executes directly (replay optable)
void build_replay_optable(uint8_t* t) {
  std::memset(t, OP_UNDEF, 256);
  static const uint8_t ops[] = {
      0x00, 0x01, 0x02, 0x03, 0x04, 0x05, 0x06, 0x07, 0x08, 0x09,
      0x0A, 0x0B, 0x10, 0x11, 0x12, 0x13, 0x14, 0x15, 0x16, 0x17,
      0x18, 0x19, 0x1A, 0x1B, 0x1C, 0x1D, 0x20, 0x30, 0x32, 0x33,
      0x34, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3A, 0x3D, 0x3E, 0x41,
      0x42, 0x43, 0x44, 0x45, 0x46, 0x48, 0x50, 0x51, 0x52, 0x53,
      0x54, 0x55, 0x56, 0x57, 0x58, 0x59, 0x5A, 0x5B, 0x5F, 0xF1,
      0xF3, 0xFA, 0xFD, 0xFE};
  for (uint8_t op : ops) t[op] = OP_NATIVE;
  for (int op = 0x60; op <= 0xA4; ++op) t[op] = OP_NATIVE;
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

// Sequential compiled EVM replay over packed inputs; returns 0 on
// success, 1000+i on a root mismatch at block i, negative on malformed
// input (-5: a tx needed a host-only feature — never on the bench
// workloads; -10: offsets not monotone or a length-prefixed record
// extending past its blob — txs_len/contracts_len make the decode
// bounds-checked instead of trusted; fuzzed under ASan by
// tests/test_sanitize.py).  phases: [t_sender, t_exec, t_trie].
//
// tx record: sighash32 r32 s32 recid1 to20 value32 gas8 price32
//            required32 nonce8 dlen4 data
// block env record (per block): root32 coinbase20 ts8 num8 gaslimit8
//            basefee32 gasused8
// accounts: addr20 bal32 nonce8
// contracts: addr20 codehash32 bal32 nonce8 len4 code nslots4
//            (key32 val32)*
int coreth_evm_replay(const uint8_t* txs, uint64_t txs_len,
                      const uint64_t* block_off,
                      uint64_t n_blocks, const uint8_t* block_env,
                      const uint8_t* accounts, uint64_t n_accounts,
                      const uint8_t* contracts, uint64_t contracts_len,
                      uint64_t n_contracts,
                      uint64_t chain_id, double* phases) {
  for (uint64_t b = 0; b < n_blocks; ++b)
    if (block_off[b] > block_off[b + 1]) return -10;
  std::unordered_map<std::string, Account> state;
  std::vector<Contract> pool(n_contracts);
  state.reserve(n_accounts * 2);
  const uint8_t* p = accounts;
  for (uint64_t i = 0; i < n_accounts; ++i) {
    std::string addr((const char*)p, 20);
    Account a;
    bool too_big = false;
    for (int j = 0; j < 16; ++j)
      if (p[20 + j]) too_big = true;
    for (int j = 16; j < 32; ++j)
      a.balance = (a.balance << 8) | p[20 + j];
    if (too_big) return -1;
    uint64_t nonce = 0;
    for (int j = 0; j < 8; ++j) nonce = (nonce << 8) | p[52 + j];
    a.nonce = nonce;
    state[addr] = a;
    p += 60;
  }
  p = contracts;
  const uint8_t* cend = contracts + contracts_len;
  for (uint64_t i = 0; i < n_contracts; ++i) {
    // fixed header (addr20 hash32 bal32 nonce8 len4) must fit before
    // its length prefixes are trusted
    if (cend - p < 96) return -10;
    std::string addr((const char*)p, 20);
    Contract& c = pool[i];
    std::memcpy(c.code_hash, p + 20, 32);
    u128 cbal = 0;
    bool cbig = false;
    for (int j = 0; j < 16; ++j)
      if (p[52 + j]) cbig = true;
    for (int j = 16; j < 32; ++j) cbal = (cbal << 8) | p[52 + j];
    if (cbig) return -1;
    uint64_t cnonce = 0;
    for (int j = 0; j < 8; ++j) cnonce = (cnonce << 8) | p[84 + j];
    uint32_t clen;
    std::memcpy(&clen, p + 92, 4);
    if ((uint64_t)(cend - p) < 96 + (uint64_t)clen + 4) return -10;
    c.code.assign(p + 96, p + 96 + clen);
    analyze_jumpdests(&c);
    p += 96 + clen;
    uint32_t nslots;
    std::memcpy(&nslots, p, 4);
    p += 4;
    if ((uint64_t)(cend - p) < 64 * (uint64_t)nslots) return -10;
    for (uint32_t j = 0; j < nslots; ++j) {
      Key32 k;
      std::memcpy(k.b, p, 32);
      c.storage[k] = from_be(p + 32);
      p += 64;
    }
    auto& acct = state[addr];
    acct.contract = &c;
    acct.balance = cbal;
    acct.nonce = cnonce;
  }

  // per-contract storage tries built once from initial slots
  std::vector<void*> stries(n_contracts);
  std::vector<uint8_t> sroots(n_contracts * 32);
  auto fold_slots = [&](uint64_t ci, const SlotMap& slots) {
    std::vector<uint8_t> keys, vals;
    std::vector<uint32_t> lens;
    uint8_t hk[32], be[32];
    for (auto& kv : slots) {
      coreth_keccak256(kv.first.b, 32, hk);
      keys.insert(keys.end(), hk, hk + 32);
      if (kv.second.is_zero()) {
        lens.push_back(0);
        continue;
      }
      to_be(kv.second, be);
      int lead = 0;
      while (lead < 32 && be[lead] == 0) ++lead;
      // rlp of the stripped big-endian integer
      Bytes v;
      int n = 32 - lead;
      if (n == 1 && be[31] < 0x80) {
        v.push_back(be[31]);
      } else {
        v.push_back(0x80 + n);
        v.insert(v.end(), be + lead, be + 32);
      }
      lens.push_back((uint32_t)v.size());
      vals.insert(vals.end(), v.begin(), v.end());
    }
    coreth_trie_update_batch(stries[ci], keys.data(), vals.data(),
                             lens.data(), lens.size());
    coreth_trie_hash(stries[ci], sroots.data() + 32 * ci);
  };
  for (uint64_t i = 0; i < n_contracts; ++i) {
    stries[i] = coreth_trie_new();
    fold_slots(i, pool[i].storage);
  }
  void* atrie = coreth_trie_new();
  // empty-storage / empty-code constants (keccak of "" / rlp(""))
  uint8_t empty_root[32], empty_code[32];
  {
    uint8_t rlp_empty = 0x80;
    coreth_keccak256(&rlp_empty, 1, empty_root);
    coreth_keccak256(nullptr, 0, empty_code);
  }
  // seed the account trie with every genesis account
  {
    std::vector<uint8_t> keys, bals, roots, hashes;
    std::vector<uint64_t> nonces;
    std::vector<uint8_t> mc, del;
    for (auto& kv : state) {
      uint8_t hk[32];
      coreth_keccak256((const uint8_t*)kv.first.data(), 20, hk);
      keys.insert(keys.end(), hk, hk + 32);
      uint8_t be[32] = {0};
      u128 b = kv.second.balance;
      for (int j = 31; j >= 0; --j) {
        be[j] = (uint8_t)b;
        b >>= 8;
      }
      bals.insert(bals.end(), be, be + 32);
      nonces.push_back(kv.second.nonce);
      if (kv.second.contract) {
        uint64_t ci = kv.second.contract - pool.data();
        roots.insert(roots.end(), sroots.data() + 32 * ci,
                     sroots.data() + 32 * ci + 32);
        hashes.insert(hashes.end(), kv.second.contract->code_hash,
                      kv.second.contract->code_hash + 32);
      } else {
        roots.insert(roots.end(), empty_root, empty_root + 32);
        hashes.insert(hashes.end(), empty_code, empty_code + 32);
      }
      mc.push_back(0);
      del.push_back(0);
    }
    coreth_trie_fold_accounts(atrie, keys.data(), bals.data(),
                              nonces.data(), roots.data(),
                              hashes.data(), mc.data(), del.data(),
                              nonces.size());
  }

  uint8_t optable[256];
  build_replay_optable(optable);

  double t_sender = 0, t_exec = 0, t_trie = 0;
  int rc = 0;
  const uint8_t* tp = txs;
  for (uint64_t bi = 0; bi < n_blocks && rc == 0; ++bi) {
    const uint8_t* be = block_env + bi * 116;
    Env env;
    std::memcpy(env.coinbase, be + 32, 20);
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[52 + j];
    env.timestamp = v;
    v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[60 + j];
    env.number = v;
    v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[68 + j];
    env.gaslimit = v;
    env.basefee = from_be(be + 76);
    env.chain_id = chain_id;

    std::unordered_set<std::string> touched;
    std::unordered_set<uint64_t> dirty_contracts;
    touched.insert(std::string((const char*)env.coinbase, 20));
    for (uint64_t ti = block_off[bi]; ti < block_off[bi + 1]; ++ti) {
      // the fixed record head (233 bytes through dlen) and then the
      // dlen-prefixed calldata must both fit inside txs_len
      if ((uint64_t)(txs + txs_len - tp) < 233) return -10;
      {
        uint32_t dl;
        std::memcpy(&dl, tp + 229, 4);
        if ((uint64_t)(txs + txs_len - tp) < 233 + (uint64_t)dl)
          return -10;
      }
      // --- sender recovery
      double t0 = now_s();
      uint8_t sender[20];
      if (!coreth_ecrecover(tp, tp + 32, tp + 64, tp[96], sender))
        return -2;
      t_sender += now_s() - t0;
      t0 = now_s();
      const uint8_t* to = tp + 97;
      bool too_big = false;
      u128 value = 0, price = 0, required = 0;
      for (int j = 16; j < 32; ++j)
        value = (value << 8) | tp[117 + j];
      for (int j = 0; j < 16; ++j)
        if (tp[117 + j] | tp[157 + j] | tp[189 + j]) too_big = true;
      uint64_t gas_limit = 0;
      for (int j = 0; j < 8; ++j)
        gas_limit = (gas_limit << 8) | tp[149 + j];
      for (int j = 16; j < 32; ++j)
        price = (price << 8) | tp[157 + j];
      for (int j = 16; j < 32; ++j)
        required = (required << 8) | tp[189 + j];
      uint64_t nonce = 0;
      for (int j = 0; j < 8; ++j)
        nonce = (nonce << 8) | tp[221 + j];
      uint32_t dlen;
      std::memcpy(&dlen, tp + 229, 4);
      const uint8_t* data = tp + 233;
      tp += 233 + dlen;
      if (too_big) return -3;

      std::string saddr((const char*)sender, 20);
      std::string taddr((const char*)to, 20);
      std::string cbaddr((const char*)env.coinbase, 20);
      // insert all three keys BEFORE taking references: operator[]
      // may rehash and invalidate earlier references
      state.try_emplace(taddr);
      state.try_emplace(cbaddr);
      Account& sa = state[saddr];
      if (sa.nonce != nonce) return 2000 + (int)bi;
      if (sa.balance < required) return 3000 + (int)bi;
      Account& ta = state[taddr];
      uint64_t used;
      bool ok_tx = true;
      // intrinsic gas: 21000 + calldata bytes (durango/EIP-2028)
      uint64_t intrinsic = 21000;
      for (uint32_t j = 0; j < dlen; ++j)
        intrinsic += data[j] ? 16 : 4;
      if (gas_limit < intrinsic) return -4;
      if (ta.contract) {
        Exec X;
        X.env = &env;
        X.origin = sender;
        uint8_t pb[32] = {0};
        u128 pv = price;
        for (int j = 31; j >= 16; --j) {
          pb[j] = (uint8_t)pv;
          pv >>= 8;
        }
        X.gasprice = from_be(pb);
        X.optable = optable;
        X.refunds_on = true;  // durango tracks (never pays) refunds
        X.replay_state = &state;
        // tx-start warm set: sender, target, coinbase (EIP-3651)
        X.warm_addr.insert(saddr);
        X.warm_addr.insert(taddr);
        X.warm_addr.insert(cbaddr);
        uint8_t vb[32] = {0};
        u128 vv = value;
        for (int j = 31; j >= 16; --j) {
          vb[j] = (uint8_t)vv;
          vv >>= 8;
        }
        FrameRes r = run_frame(X, sender, taddr, ta.contract, data,
                               dlen, (int64_t)(gas_limit - intrinsic),
                               from_be(vb), false, 1);
        if (r.status == ST_HOST) return -5;
        used = gas_limit - (uint64_t)r.gas;
        ok_tx = r.status == ST_STOP;
        if (ok_tx) {
          for (auto& kv : X.dirty) {
            std::string caddr = kv.first.substr(0, 20);
            Key32 k;
            std::memcpy(k.b, kv.first.data() + 20, 32);
            auto it = state.find(caddr);
            if (it == state.end() || !it->second.contract) continue;
            Contract* wc = it->second.contract;
            wc->storage[k] = kv.second;
            wc->block_dirty[k] = kv.second;
            dirty_contracts.insert(wc - pool.data());
            touched.insert(caddr);
          }
        }
      } else {
        used = intrinsic;
      }
      sa.nonce += 1;
      sa.balance -= (u128)used * price;
      if (ok_tx && value) {
        sa.balance -= value;
        ta.balance += value;
      }
      state[cbaddr].balance += (u128)used * price;
      touched.insert(saddr);
      touched.insert(taddr);
      t_exec += now_s() - t0;
    }

    // --- per-block fold + root check
    double t0 = now_s();
    for (uint64_t ci : dirty_contracts) {
      fold_slots(ci, pool[ci].block_dirty);
      pool[ci].block_dirty.clear();
    }
    {
      std::vector<uint8_t> keys, bals, roots, hashes;
      std::vector<uint64_t> nonces;
      std::vector<uint8_t> mc, del;
      for (auto& addr : touched) {
        Account& a = state[addr];
        uint8_t hk[32];
        coreth_keccak256((const uint8_t*)addr.data(), 20, hk);
        keys.insert(keys.end(), hk, hk + 32);
        uint8_t beb[32] = {0};
        u128 b = a.balance;
        for (int j = 31; j >= 0; --j) {
          beb[j] = (uint8_t)b;
          b >>= 8;
        }
        bals.insert(bals.end(), beb, beb + 32);
        nonces.push_back(a.nonce);
        bool empty = a.balance == 0 && a.nonce == 0 && !a.contract;
        if (a.contract) {
          uint64_t ci = a.contract - pool.data();
          roots.insert(roots.end(), sroots.data() + 32 * ci,
                       sroots.data() + 32 * ci + 32);
          hashes.insert(hashes.end(), a.contract->code_hash,
                        a.contract->code_hash + 32);
        } else {
          roots.insert(roots.end(), empty_root, empty_root + 32);
          hashes.insert(hashes.end(), empty_code, empty_code + 32);
        }
        mc.push_back(0);
        del.push_back(empty ? 1 : 0);
      }
      coreth_trie_fold_accounts(atrie, keys.data(), bals.data(),
                                nonces.data(), roots.data(),
                                hashes.data(), mc.data(), del.data(),
                                nonces.size());
    }
    uint8_t got[32];
    coreth_trie_hash(atrie, got);
    t_trie += now_s() - t0;
    if (std::memcmp(got, be, 32) != 0) rc = 1000 + (int)bi;
  }

  for (void* h : stries) coreth_trie_free(h);
  coreth_trie_free(atrie);
  phases[0] = t_sender;
  phases[1] = t_exec;
  phases[2] = t_trie;
  return rc;
}

// ------------------------------------------------- hostexec session ABI
//
// Executes full transactions against a StateDB-backed host interface:
// storage slots and callee code resolve through Python callbacks; the
// call returns gas/status and the caller fetches logs + cross-contract
// writes + return data through the out_* getters.  One session holds a
// committed-storage cache that the caller seeds (OCC prefix overlays)
// or invalidates (epoch bumps) explicitly.

void* coreth_hostexec_new(uint64_t chain_id, FetchSlotCb fetch_slot,
                          FetchCodeCb fetch_code,
                          const uint8_t* optable256, int refunds_on) {
  Sess* s = new Sess();
  s->env.chain_id = chain_id;
  s->fetch_slot = fetch_slot;
  s->fetch_code = fetch_code;
  std::memcpy(s->optable, optable256, 256);
  s->refunds_on = refunds_on;
  return s;
}

void coreth_hostexec_free(void* hp) { delete (Sess*)hp; }

void coreth_hostexec_env(void* hp, const uint8_t* coinbase20,
                         uint64_t timestamp, uint64_t number,
                         uint64_t gaslimit, uint64_t difficulty,
                         const uint8_t* basefee32) {
  Sess* s = (Sess*)hp;
  std::memcpy(s->env.coinbase, coinbase20, 20);
  s->env.timestamp = timestamp;
  s->env.number = number;
  s->env.gaslimit = gaslimit;
  s->env.difficulty = difficulty;
  s->env.basefee = from_be(basefee32);
}

void coreth_hostexec_set_code(void* hp, const uint8_t* addr20,
                              const uint8_t* code, uint32_t len) {
  Sess* s = (Sess*)hp;
  std::string addr((const char*)addr20, 20);
  Contract& c = s->contracts[addr];
  c.code.assign(code, code + len);
  analyze_jumpdests(&c);
  s->kind[addr] = len ? 1 : 0;
}

// drop every cached committed slot (underlying state moved: new tx on
// a mutating StateDB, or an engine storage-epoch bump)
void coreth_hostexec_clear_storage(void* hp) {
  Sess* s = (Sess*)hp;
  for (auto& kv : s->contracts) kv.second.storage.clear();
}

// drop EVERYTHING resolved so far — codes, EOA/contract kinds, and
// storage.  The StateDB bridge calls this per tx: a mid-block deploy
// (CREATE on the interpreter path) can turn a cached EOA into a
// contract or swap bytecode, so per-tx resolution must start fresh.
// The serial short-circuit keeps the cheaper clear_storage/commit
// protocol — machine blocks cannot deploy code.
void coreth_hostexec_reset(void* hp) {
  Sess* s = (Sess*)hp;
  s->contracts.clear();
  s->kind.clear();
}

// drop ONLY the cached EOA verdicts (kind == 0): an account can
// spring into existence — or become existing-but-empty — through pure
// balance moves, which the bridge's storage_gen reuse check cannot
// see, and a stale EOA verdict would skip the code_resolver's
// exist-and-empty host guard (EIP-158 touch deletion).  Registered
// contracts keep their code, jumpdest analysis, and storage cache: a
// code change always goes through StateDB.set_code, which bumps
// storage_gen and forces the full reset.
void coreth_hostexec_reset_kinds(void* hp) {
  Sess* s = (Sess*)hp;
  for (auto it = s->kind.begin(); it != s->kind.end();) {
    if (it->second == 0) it = s->kind.erase(it);
    else ++it;
  }
}

// seed a committed value (OCC prefix overlay / sequential carry)
void coreth_hostexec_seed_slot(void* hp, const uint8_t* addr20,
                               const uint8_t* key32,
                               const uint8_t* val32) {
  Sess* s = (Sess*)hp;
  std::string addr((const char*)addr20, 20);
  Key32 k;
  std::memcpy(k.b, key32, 32);
  k.b[0] &= 0xFE;
  s->contracts[addr].storage[k] = from_be(val32);
}

void coreth_hostexec_warm_addr(void* hp, const uint8_t* addr20) {
  ((Sess*)hp)->seed_warm_addr.emplace_back((const char*)addr20, 20);
}

void coreth_hostexec_warm_slot(void* hp, const uint8_t* addr20,
                               const uint8_t* key32) {
  Sess* s = (Sess*)hp;
  std::string k((const char*)addr20, 20);
  k.append((const char*)key32, 32);
  s->seed_warm_slot.push_back(k);
}

// Execute one root call.  Returns the machine status code
// (1 STOP / 2 REVERT / 3 ERR / 4 HOST); out[] = [gas_left, refund,
// n_writes, n_logs, log_data_total, ret_len, host_reason].
// Warm seeds accumulated since the last call are consumed.
int coreth_hostexec_call(void* hp, const uint8_t* caller20,
                         const uint8_t* to20, const uint8_t* value32,
                         const uint8_t* gasprice32, const uint8_t* data,
                         uint32_t dlen, int64_t gas, int64_t* out) {
  Sess* s = (Sess*)hp;
  Exec X;
  X.env = &s->env;
  X.origin = caller20;
  X.gasprice = from_be(gasprice32);
  X.optable = s->optable;
  X.refunds_on = s->refunds_on != 0;
  X.sess = s;
  for (auto& a : s->seed_warm_addr) X.warm_addr.insert(a);
  for (auto& k : s->seed_warm_slot) X.warm_slot.insert(k);
  s->seed_warm_addr.clear();
  s->seed_warm_slot.clear();

  s->out = SessOut();
  std::string target((const char*)to20, 20);
  Contract* c = nullptr;
  int kind = lookup_code(X, target, &c);
  if (kind != 1 || c->code.empty()) {
    // the bridge only routes code-bearing targets here
    s->out.status = ST_HOST;
    s->out.host_reason = 0;
  } else {
    FrameRes r = run_frame(X, caller20, target, c, data, dlen, gas,
                           from_be(value32), false, 1);
    s->out.status = r.status;
    s->out.gas_left = r.gas;
    s->out.refund = X.refund;
    s->out.host_reason = X.host_reason;
    s->out.ret = std::move(r.out);
    if (r.status == ST_STOP) {
      s->out.writes = std::move(X.dirty);
      s->out.logs = std::move(X.logs);
    }
  }
  uint64_t log_data = 0;
  for (auto& lg : s->out.logs) log_data += lg.data.size();
  out[0] = s->out.gas_left;
  out[1] = s->out.refund;
  out[2] = (int64_t)s->out.writes.size();
  out[3] = (int64_t)s->out.logs.size();
  out[4] = (int64_t)log_data;
  out[5] = (int64_t)s->out.ret.size();
  out[6] = s->out.host_reason;
  return s->out.status;
}

// write set of the last successful call, sorted by (address, key) —
// a deterministic writeback order for the StateDB/trie fold
void coreth_hostexec_out_writes(void* hp, uint8_t* addrs20,
                                uint8_t* keys32, uint8_t* vals32) {
  Sess* s = (Sess*)hp;
  size_t i = 0;
  for (auto& kv : s->out.writes) {
    std::memcpy(addrs20 + 20 * i, kv.first.data(), 20);
    std::memcpy(keys32 + 32 * i, kv.first.data() + 20, 32);
    to_be(kv.second, vals32 + 32 * i);
    ++i;
  }
}

void coreth_hostexec_out_logs(void* hp, uint8_t* addrs20,
                              int32_t* ntopics, uint8_t* topics,
                              int32_t* dlens, uint8_t* datablob) {
  Sess* s = (Sess*)hp;
  uint8_t* dp = datablob;
  for (size_t i = 0; i < s->out.logs.size(); ++i) {
    LogRec& lg = s->out.logs[i];
    std::memcpy(addrs20 + 20 * i, lg.addr, 20);
    ntopics[i] = lg.nt;
    for (int j = 0; j < lg.nt; ++j)
      std::memcpy(topics + (4 * i + j) * 32, lg.topics[j], 32);
    dlens[i] = (int32_t)lg.data.size();
    if (!lg.data.empty()) {
      std::memcpy(dp, lg.data.data(), lg.data.size());
      dp += lg.data.size();
    }
  }
}

void coreth_hostexec_out_ret(void* hp, uint8_t* buf) {
  Sess* s = (Sess*)hp;
  if (!s->out.ret.empty())
    std::memcpy(buf, s->out.ret.data(), s->out.ret.size());
}

// fold the last call's writes into the session's committed cache so
// the next call in the same block sees them (sequential carry)
void coreth_hostexec_commit(void* hp) {
  Sess* s = (Sess*)hp;
  for (auto& kv : s->out.writes) {
    std::string addr = kv.first.substr(0, 20);
    Key32 k;
    std::memcpy(k.b, kv.first.data() + 20, 32);
    s->contracts[addr].storage[k] = kv.second;
  }
}

}  // extern "C"
