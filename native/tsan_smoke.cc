// Test-only race helper, compiled ONLY into the TSan build
// (libcoreth_native_tsan.so).  tests/test_tsan.py calls it to prove
// the detector is actually armed before trusting a clean suite run:
// racy=1 hammers a plain int from two threads with no synchronization
// (a certain data race — TSan must report it), racy=0 does the same
// work under a mutex (must stay silent).  Returns the final counter
// so the compiler cannot elide the writes.

#include <mutex>
#include <thread>

namespace {

int g_counter = 0;           // NOLINT: the race IS the point
std::mutex g_mu;

void bump_racy(int n) {
    for (int i = 0; i < n; ++i) g_counter++;
}

void bump_locked(int n) {
    for (int i = 0; i < n; ++i) {
        std::lock_guard<std::mutex> hold(g_mu);
        g_counter++;
    }
}

}  // namespace

extern "C" int coreth_tsan_smoke(int racy) {
    g_counter = 0;
    void (*fn)(int) = racy ? bump_racy : bump_locked;
    std::thread a(fn, 50000);
    std::thread b(fn, 50000);
    a.join();
    b.join();
    std::lock_guard<std::mutex> hold(g_mu);
    return g_counter;
}
