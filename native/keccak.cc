// Native keccak-256 for the coreth-tpu host runtime.
//
// Mirrors the role of the asm-optimized golang.org/x/crypto/sha3 the
// reference hot path uses (see reference trie/hasher.go:195,
// core/types/hashing.go).  Exposed via a plain C ABI consumed through
// ctypes (coreth_tpu/crypto/native.py).  Round constants are derived with
// the rc LFSR at startup (same approach as the Keccak team's compact
// reference code) instead of being transcribed.

#include <cstdint>
#include <cstring>

namespace {

uint64_t RC[24];

struct Init {
  Init() {
    // Round constants via the degree-8 LFSR.  The rho/pi schedule is
    // re-derived inline by the walk in keccak_f1600.
    uint32_t r = 1;
    for (int rnd = 0; rnd < 24; ++rnd) {
      uint64_t rc = 0;
      for (int j = 0; j < 7; ++j) {
        r = ((r << 1) ^ ((r >> 7) * 0x71)) & 0xff;
        if (r & 2) rc ^= 1ULL << ((1 << j) - 1);
      }
      RC[rnd] = rc;
    }
  }
} init_;

inline uint64_t rol(uint64_t v, int n) {
  n &= 63;
  return n ? (v << n) | (v >> (64 - n)) : v;
}

// Rho rotation offsets and pi lane order in walk order — the same
// schedule the removed (x, y) walk produced, precomputed so the round
// body is branch-free constant-indexed code the compiler fully
// unrolls.  The rho/pi walk formulation cost ~3.2us per permutation;
// this one measures ~4x faster, which matters because keccak sits
// under every trie node, receipt bloom, premap digest, and recovered
// address in both engines.
const int RHO[24] = {1,  3,  6,  10, 15, 21, 28, 36, 45, 55, 2,  14,
                     27, 41, 56, 8,  25, 43, 62, 18, 39, 61, 20, 44};
const int PILN[24] = {10, 7,  11, 17, 18, 3, 5,  16, 8,  21, 24, 4,
                      15, 23, 19, 13, 12, 2, 20, 14, 22, 9,  6,  1};

void keccak_f1600(uint64_t a[25]) {
  uint64_t bc[5], t;
  for (int rnd = 0; rnd < 24; ++rnd) {
    // theta
    for (int i = 0; i < 5; ++i)
      bc[i] = a[i] ^ a[i + 5] ^ a[i + 10] ^ a[i + 15] ^ a[i + 20];
    for (int i = 0; i < 5; ++i) {
      t = bc[(i + 4) % 5] ^ rol(bc[(i + 1) % 5], 1);
      for (int j = 0; j < 25; j += 5) a[j + i] ^= t;
    }
    // rho + pi
    t = a[1];
    for (int i = 0; i < 24; ++i) {
      int j = PILN[i];
      bc[0] = a[j];
      a[j] = rol(t, RHO[i]);
      t = bc[0];
    }
    // chi
    for (int j = 0; j < 25; j += 5) {
      for (int i = 0; i < 5; ++i) bc[i] = a[j + i];
      for (int i = 0; i < 5; ++i)
        a[j + i] = bc[i] ^ (~bc[(i + 1) % 5] & bc[(i + 2) % 5]);
    }
    // iota
    a[0] ^= RC[rnd];
  }
}

}  // namespace

extern "C" {

// keccak-256: rate 136, delimited suffix 0x01.
void coreth_keccak256(const uint8_t* data, uint64_t len, uint8_t* out32) {
  uint64_t st[25];
  std::memset(st, 0, sizeof(st));
  const uint64_t rate = 136;
  while (len >= rate) {
    for (int i = 0; i < 17; ++i) {
      uint64_t lane;
      std::memcpy(&lane, data + 8 * i, 8);  // little-endian hosts only
      st[i] ^= lane;
    }
    keccak_f1600(st);
    data += rate;
    len -= rate;
  }
  uint8_t block[136];
  std::memset(block, 0, sizeof(block));
  // len==0 with a null data pointer is a legal call (hash of the
  // empty string); memcpy(dst, nullptr, 0) is formally UB, so guard
  if (len) std::memcpy(block, data, len);
  block[len] = 0x01;
  block[135] ^= 0x80;
  for (int i = 0; i < 17; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    st[i] ^= lane;
  }
  keccak_f1600(st);
  std::memcpy(out32, st, 32);
}

// Batched fixed-stride hashing: n items, each `stride` bytes apart with
// `lens[i]` valid bytes; outputs packed 32-byte digests.
void coreth_keccak256_batch(const uint8_t* data, const uint64_t* lens,
                            uint64_t stride, uint64_t n, uint8_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    coreth_keccak256(data + i * stride, lens[i], out + 32 * i);
}

}  // extern "C"
