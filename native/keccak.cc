// Native keccak-256 for the coreth-tpu host runtime.
//
// Mirrors the role of the asm-optimized golang.org/x/crypto/sha3 the
// reference hot path uses (see reference trie/hasher.go:195,
// core/types/hashing.go).  Exposed via a plain C ABI consumed through
// ctypes (coreth_tpu/crypto/native.py).  Round constants are derived with
// the rc LFSR at startup (same approach as the Keccak team's compact
// reference code) instead of being transcribed.

#include <cstdint>
#include <cstring>

namespace {

uint64_t RC[24];

struct Init {
  Init() {
    // Round constants via the degree-8 LFSR.  The rho/pi schedule is
    // re-derived inline by the walk in keccak_f1600.
    uint32_t r = 1;
    for (int rnd = 0; rnd < 24; ++rnd) {
      uint64_t rc = 0;
      for (int j = 0; j < 7; ++j) {
        r = ((r << 1) ^ ((r >> 7) * 0x71)) & 0xff;
        if (r & 2) rc ^= 1ULL << ((1 << j) - 1);
      }
      RC[rnd] = rc;
    }
  }
} init_;

inline uint64_t rol(uint64_t v, int n) {
  n &= 63;
  return n ? (v << n) | (v >> (64 - n)) : v;
}

void keccak_f1600(uint64_t a[25]) {
  for (int rnd = 0; rnd < 24; ++rnd) {
    // theta
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; ++x)
      d[x] = c[(x + 4) % 5] ^ rol(c[(x + 1) % 5], 1);
    for (int i = 0; i < 25; ++i) a[i] ^= d[i % 5];
    // rho + pi (walk, same as reference python)
    int x = 1, y = 0;
    uint64_t current = a[x + 5 * y];
    for (int t = 0; t < 24; ++t) {
      int nx = y, ny = (2 * x + 3 * y) % 5;
      x = nx; y = ny;
      uint64_t tmp = a[x + 5 * y];
      a[x + 5 * y] = rol(current, ((t + 1) * (t + 2) / 2) % 64);
      current = tmp;
    }
    // chi
    for (int yy = 0; yy < 5; ++yy) {
      uint64_t row[5];
      for (int xx = 0; xx < 5; ++xx) row[xx] = a[xx + 5 * yy];
      for (int xx = 0; xx < 5; ++xx)
        a[xx + 5 * yy] = row[xx] ^ (~row[(xx + 1) % 5] & row[(xx + 2) % 5]);
    }
    // iota
    a[0] ^= RC[rnd];
  }
}

}  // namespace

extern "C" {

// keccak-256: rate 136, delimited suffix 0x01.
void coreth_keccak256(const uint8_t* data, uint64_t len, uint8_t* out32) {
  uint64_t st[25];
  std::memset(st, 0, sizeof(st));
  const uint64_t rate = 136;
  while (len >= rate) {
    for (int i = 0; i < 17; ++i) {
      uint64_t lane;
      std::memcpy(&lane, data + 8 * i, 8);  // little-endian hosts only
      st[i] ^= lane;
    }
    keccak_f1600(st);
    data += rate;
    len -= rate;
  }
  uint8_t block[136];
  std::memset(block, 0, sizeof(block));
  // len==0 with a null data pointer is a legal call (hash of the
  // empty string); memcpy(dst, nullptr, 0) is formally UB, so guard
  if (len) std::memcpy(block, data, len);
  block[len] = 0x01;
  block[135] ^= 0x80;
  for (int i = 0; i < 17; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    st[i] ^= lane;
  }
  keccak_f1600(st);
  std::memcpy(out32, st, 32);
}

// Batched fixed-stride hashing: n items, each `stride` bytes apart with
// `lens[i]` valid bytes; outputs packed 32-byte digests.
void coreth_keccak256_batch(const uint8_t* data, const uint64_t* lens,
                            uint64_t stride, uint64_t n, uint8_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    coreth_keccak256(data + i * stride, lens[i], out + 32 * i);
}

}  // extern "C"
