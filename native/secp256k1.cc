// Native secp256k1 ECDSA public-key recovery for the coreth-tpu host runtime.
//
// Role parity with the reference's cgo libsecp256k1 binding (geth
// crypto/secp256k1), which coreth drives in parallel for every block via
// core/sender_cacher.go.  This implementation: 4x64-bit limbs with __int128
// products, fast reduction mod p = 2^256 - 0x1000003D1, Jacobian points,
// Shamir double-scalar multiplication for u1*G + u2*R, Fermat inversion.
// Keccak for the address derivation comes from keccak.cc.
//
// Correctness is anchored by the test suite: cross-checked against the
// pure-Python implementation, which is itself anchored by the well-known
// privkey=1 -> 0x7E5F4552091A69125d5DfCb7b8C2659029395Bdf vector.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" void coreth_keccak256(const uint8_t*, uint64_t, uint8_t*);
extern "C" int coreth_ecrecover(const uint8_t*, const uint8_t*,
                                const uint8_t*, int, uint8_t*);

namespace {

typedef unsigned __int128 u128;

struct U256 {
  uint64_t v[4];  // little-endian limbs
};

const U256 ZERO = {{0, 0, 0, 0}};
const U256 ONE = {{1, 0, 0, 0}};

// p = 2^256 - 2^32 - 977
const U256 PRIME = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                     0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
const uint64_t P_C = 0x1000003D1ULL;  // 2^256 - p

// group order n
const U256 ORDER = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                     0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};

const U256 GX = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                  0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
const U256 GY = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                  0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

inline bool is_zero(const U256& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] < b.v[i]) return -1;
    if (a.v[i] > b.v[i]) return 1;
  }
  return 0;
}

// returns carry out
inline uint64_t add_raw(U256& r, const U256& a, const U256& b) {
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (u128)a.v[i] + b.v[i];
    r.v[i] = (uint64_t)c;
    c >>= 64;
  }
  return (uint64_t)c;
}

// returns borrow out
inline uint64_t sub_raw(U256& r, const U256& a, const U256& b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;  // two's complement: top bit set iff underflow
  }
  return (uint64_t)borrow;
}

inline void mod_add(U256& r, const U256& a, const U256& b, const U256& m) {
  uint64_t carry = add_raw(r, a, b);
  if (carry || cmp(r, m) >= 0) {
    U256 t;
    sub_raw(t, r, m);
    r = t;
  }
}

inline void mod_sub(U256& r, const U256& a, const U256& b, const U256& m) {
  U256 t;
  if (sub_raw(t, a, b)) {
    U256 t2;
    add_raw(t2, t, m);  // wraps back into range
    r = t2;
  } else {
    r = t;
  }
}

// ---- field arithmetic mod p (fast reduction using p = 2^256 - P_C) ----

void fe_mul(U256& r, const U256& a, const U256& b) {
  uint64_t w[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[i] * b.v[j] + w[i + j] + carry;
      w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w[i + 4] += (uint64_t)carry;
  }
  // fold hi*2^256 -> hi*P_C twice
  U256 lo = {{w[0], w[1], w[2], w[3]}};
  U256 hi = {{w[4], w[5], w[6], w[7]}};
  // acc = lo + hi * P_C  (result fits in 256 + ~33 bits)
  uint64_t w2[5] = {0};
  {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)hi.v[j] * P_C + lo.v[j] + carry;
      w2[j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w2[4] = (uint64_t)carry;
  }
  // fold again: w2[4] * P_C.  A carry can still ripple out of limb 3
  // (acc + w2[4]*P_C may reach 2^256); the dropped 2^256 == P_C (mod p),
  // so a third conditional fold is required.
  U256 acc = {{w2[0], w2[1], w2[2], w2[3]}};
  {
    u128 cur = (u128)w2[4] * P_C + acc.v[0];
    acc.v[0] = (uint64_t)cur;
    uint64_t carry = (uint64_t)(cur >> 64);
    for (int j = 1; j < 4; ++j) {
      u128 c2 = (u128)acc.v[j] + carry;
      acc.v[j] = (uint64_t)c2;
      carry = (uint64_t)(c2 >> 64);
    }
    if (carry) {  // acc wrapped to a tiny value; adding P_C cannot overflow
      u128 c3 = (u128)acc.v[0] + P_C;
      acc.v[0] = (uint64_t)c3;
      uint64_t c = (uint64_t)(c3 >> 64);
      for (int j = 1; j < 4 && c; ++j) {
        u128 c4 = (u128)acc.v[j] + c;
        acc.v[j] = (uint64_t)c4;
        c = (uint64_t)(c4 >> 64);
      }
    }
  }
  while (cmp(acc, PRIME) >= 0) {
    U256 t;
    sub_raw(t, acc, PRIME);
    acc = t;
  }
  r = acc;
}

inline void fe_sqr(U256& r, const U256& a) { fe_mul(r, a, a); }

void fe_pow(U256& r, const U256& a, const U256& e) {
  U256 acc = ONE, base = a;
  for (int i = 0; i < 256; ++i) {
    if ((e.v[i / 64] >> (i % 64)) & 1) {
      U256 t;
      fe_mul(t, acc, base);
      acc = t;
    }
    U256 t;
    fe_sqr(t, base);
    base = t;
  }
  r = acc;
}

void fe_inv(U256& r, const U256& a) {
  U256 e;
  sub_raw(e, PRIME, {{2, 0, 0, 0}});
  fe_pow(r, a, e);
}

// ---- scalar arithmetic mod n ----
//
// 4x4-limb schoolbook product + fold reduction: with K = 2^256 - n
// (129 bits), hi*2^256 + lo == hi*K + lo (mod n); three folds bring any
// 512-bit value under ~2^257, then conditional subtracts finish.

// K = 2^256 - n, little-endian limbs (third limb = 1, fourth = 0)
const uint64_t ORDER_K[3] = {0x402DA1732FC9BEBFULL, 0x4551231950B75FC4ULL,
                             1ULL};

// w[0..7] = a * b (little-endian 64-bit limbs)
inline void mul_wide(uint64_t w[8], const U256& a, const U256& b) {
  for (int i = 0; i < 8; ++i) w[i] = 0;
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[i] * b.v[j] + w[i + j] + carry;
      w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w[i + 4] += (uint64_t)carry;
  }
}

// fold an 8-limb value once: out(<= 7 limbs) = lo(4) + hi(4) * K
inline int fold_once(uint64_t out[8], const uint64_t in[8], int limbs) {
  uint64_t hiK[8] = {0};
  int hi_limbs = limbs - 4;
  for (int i = 0; i < hi_limbs; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 3; ++j) {
      u128 cur = (u128)in[4 + i] * ORDER_K[j] + hiK[i + j] + carry;
      hiK[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    int k = i + 3;
    while (carry) {
      u128 cur = (u128)hiK[k] + carry;
      hiK[k] = (uint64_t)cur;
      carry = cur >> 64;
      ++k;
    }
  }
  u128 carry = 0;
  for (int i = 0; i < 8; ++i) {
    u128 cur = (u128)hiK[i] + (i < 4 ? in[i] : 0) + carry;
    out[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  int top = 8;
  while (top > 4 && out[top - 1] == 0) --top;
  return top;
}

void sc_reduce_wide(U256& r, const uint64_t w[8]) {
  uint64_t a[8], b[8];
  int limbs = 8;
  for (int i = 0; i < 8; ++i) a[i] = w[i];
  // each fold strictly shrinks the value; 8 passes is a safe bound
  for (int pass = 0; pass < 8 && limbs > 4; ++pass) {
    limbs = fold_once(b, a, limbs);
    for (int i = 0; i < 8; ++i) a[i] = b[i];
  }
  U256 t = {{a[0], a[1], a[2], a[3]}};
  while (cmp(t, ORDER) >= 0) {
    U256 t2;
    sub_raw(t2, t, ORDER);
    t = t2;
  }
  r = t;
}

void sc_mul(U256& r, const U256& a, const U256& b, const U256& /*m*/) {
  uint64_t w[8];
  mul_wide(w, a, b);
  sc_reduce_wide(r, w);
}

void sc_pow(U256& r, const U256& a, const U256& e, const U256& m) {
  U256 acc = ONE, base = a;
  for (int i = 0; i < 256; ++i) {
    if ((e.v[i / 64] >> (i % 64)) & 1) {
      U256 t;
      sc_mul(t, acc, base, m);
      acc = t;
    }
    U256 t;
    sc_mul(t, base, base, m);
    base = t;
  }
  r = acc;
}

void sc_inv(U256& r, const U256& a) {
  U256 e;
  sub_raw(e, ORDER, {{2, 0, 0, 0}});
  sc_pow(r, a, e, ORDER);
}

// ---- Jacobian point arithmetic over fe ----

struct Point {
  U256 x, y, z;  // z == 0 => infinity
};

inline bool pt_is_inf(const Point& p) { return is_zero(p.z); }

void pt_double(Point& r, const Point& p) {
  if (pt_is_inf(p) || is_zero(p.y)) {
    r = {ZERO, ONE, ZERO};
    return;
  }
  U256 ysq, s, m, t;
  fe_sqr(ysq, p.y);
  fe_mul(s, p.x, ysq);
  mod_add(s, s, s, PRIME);
  mod_add(s, s, s, PRIME);  // s = 4*x*y^2
  fe_sqr(m, p.x);
  U256 m3;
  mod_add(m3, m, m, PRIME);
  mod_add(m, m3, m, PRIME);  // m = 3*x^2
  U256 nx;
  fe_sqr(nx, m);
  mod_sub(nx, nx, s, PRIME);
  mod_sub(nx, nx, s, PRIME);
  U256 ysq2, y4;
  fe_sqr(ysq2, ysq);  // y^4
  // 8*y^4
  mod_add(y4, ysq2, ysq2, PRIME);
  mod_add(y4, y4, y4, PRIME);
  mod_add(y4, y4, y4, PRIME);
  U256 ny;
  mod_sub(t, s, nx, PRIME);
  fe_mul(ny, m, t);
  mod_sub(ny, ny, y4, PRIME);
  U256 nz;
  fe_mul(nz, p.y, p.z);
  mod_add(nz, nz, nz, PRIME);
  r.x = nx;
  r.y = ny;
  r.z = nz;
}

void pt_add(Point& r, const Point& p1, const Point& p2) {
  if (pt_is_inf(p1)) {
    r = p2;
    return;
  }
  if (pt_is_inf(p2)) {
    r = p1;
    return;
  }
  U256 z1sq, z2sq, u1, u2, s1, s2, t;
  fe_sqr(z1sq, p1.z);
  fe_sqr(z2sq, p2.z);
  fe_mul(u1, p1.x, z2sq);
  fe_mul(u2, p2.x, z1sq);
  fe_mul(t, z2sq, p2.z);
  fe_mul(s1, p1.y, t);
  fe_mul(t, z1sq, p1.z);
  fe_mul(s2, p2.y, t);
  if (cmp(u1, u2) == 0) {
    if (cmp(s1, s2) != 0) {
      r = {ZERO, ONE, ZERO};
      return;
    }
    pt_double(r, p1);
    return;
  }
  U256 h, rr, hsq, hcu, v;
  mod_sub(h, u2, u1, PRIME);
  mod_sub(rr, s2, s1, PRIME);
  fe_sqr(hsq, h);
  fe_mul(hcu, hsq, h);
  fe_mul(v, u1, hsq);
  U256 nx;
  fe_sqr(nx, rr);
  mod_sub(nx, nx, hcu, PRIME);
  mod_sub(nx, nx, v, PRIME);
  mod_sub(nx, nx, v, PRIME);
  U256 ny;
  mod_sub(t, v, nx, PRIME);
  fe_mul(ny, rr, t);
  U256 s1h;
  fe_mul(s1h, s1, hcu);
  mod_sub(ny, ny, s1h, PRIME);
  U256 nz;
  fe_mul(t, p1.z, p2.z);
  fe_mul(nz, t, h);
  r.x = nx;
  r.y = ny;
  r.z = nz;
}

// Shamir: k1*G + k2*Q in one double-and-add ladder.
void pt_shamir(Point& r, const U256& k1, const U256& k2, const Point& q) {
  Point g = {GX, GY, ONE};
  Point gq;
  pt_add(gq, g, q);
  Point acc = {ZERO, ONE, ZERO};
  for (int i = 255; i >= 0; --i) {
    Point t;
    pt_double(t, acc);
    acc = t;
    int b1 = (k1.v[i / 64] >> (i % 64)) & 1;
    int b2 = (k2.v[i / 64] >> (i % 64)) & 1;
    if (b1 && b2)
      pt_add(t, acc, gq);
    else if (b1)
      pt_add(t, acc, g);
    else if (b2)
      pt_add(t, acc, q);
    else
      continue;
    acc = t;
  }
  r = acc;
}

void load_be(U256& r, const uint8_t* p) {
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; ++j) limb = (limb << 8) | p[(3 - i) * 8 + j];
    r.v[i] = limb;
  }
}

void store_be(uint8_t* p, const U256& a) {
  for (int i = 0; i < 4; ++i)
    for (int j = 0; j < 8; ++j)
      p[(3 - i) * 8 + j] = (uint8_t)(a.v[i] >> (56 - 8 * j));
}

// ---- batch-only fast recovery (coreth_ecrecover_batch) ----
//
// The sequential coreth_ecrecover above is the native baseline's
// primitive (one Shamir ladder per call) and stays untouched.  The
// batch entry point amortizes what a per-call API cannot:
//   - u1*G via a once-built 32x255 affine comb table (8-bit windows):
//     32 mixed additions, zero doublings, per signature;
//   - u2*R via the GLV endomorphism (R -> (beta*x, y) realizes
//     scalar lambda): u2 splits into two ~128-bit halves, halving the
//     ladder doublings; each half walks a wNAF(5) over the
//     signature's odd-multiple table;
//   - ONE scalar inversion for every r^-1 and ONE field inversion for
//     every Jacobian->affine conversion (Montgomery batch trick), and
//     one shared batch normalization of all wNAF tables so the ladder
//     runs on mixed (affine) additions.
// Every GLV split is verified on the spot (k1 + k2*lambda == k mod n
// and both halves < 2^129); any mismatch — and any signature the fast
// path cannot finish — falls back to coreth_ecrecover for that index,
// so a constant or carry bug degrades to the slow path, never to a
// wrong address.  CORETH_FAST_RECOVER=0 forces the per-signature
// fallback everywhere (the A/B and bisection knob).

// lambda/beta: the cube roots of 1 realizing the curve endomorphism
// (x, y) -> (beta*x, y) == lambda * P; lattice basis and the rounded
// 384-bit division constants g1/g2 are the standard secp256k1 values
// (verified exhaustively against the Python twin in tests).
const U256 GLV_LAMBDA = {{0xDF02967C1B23BD72ULL, 0x122E22EA20816678ULL,
                          0xA5261C028812645AULL, 0x5363AD4CC05C30E0ULL}};
const U256 GLV_BETA = {{0xC1396C28719501EEULL, 0x9CF0497512F58995ULL,
                        0x6E64479EAC3434E9ULL, 0x7AE96A2B657C0710ULL}};
// a1 == b2 (128 bits), B1 == -b1 (128 bits), a2 (129 bits)
const U256 GLV_A1 = {{0xE86C90E49284EB15ULL, 0x3086D221A7D46BCDULL, 0, 0}};
const U256 GLV_B1 = {{0x6F547FA90ABFE4C3ULL, 0xE4437ED6010E8828ULL, 0, 0}};
const U256 GLV_A2 = {{0x57C1108D9D44CFD8ULL, 0x14CA50F7A8E2F3F6ULL,
                      1ULL, 0}};
// g1 = round(2^384 * b2 / n), g2 = round(2^384 * (-b1) / n)
const U256 GLV_G1 = {{0xE893209A45DBB031ULL, 0x3DAA8A1471E8CA7FULL,
                      0xE86C90E49284EB15ULL, 0x3086D221A7D46BCDULL}};
const U256 GLV_G2 = {{0x1571B4AE8AC47F71ULL, 0x221208AC9DF506C6ULL,
                      0x6F547FA90ABFE4C4ULL, 0xE4437ED6010E8828ULL}};

// a^((p+1)/4) by addition chain (255 squarings + 13 multiplies vs
// ~506 multiplies for the generic bit-scan fe_pow — the exponent is
// almost all ones).  Chain verified against (p+1)/4 in tests.
void fe_sqrt_chain(U256& r, const U256& a) {
  auto sqr_n = [](U256& x, int n) {
    for (int i = 0; i < n; ++i) {
      U256 t;
      fe_sqr(t, x);
      x = t;
    }
  };
  U256 x2, x3, x6, x9, x11, x22, x44, x88, x176, x220, x223, t1, t;
  fe_sqr(x2, a);
  fe_mul(t, x2, a);
  x2 = t;                       // a^3
  fe_sqr(x3, x2);
  fe_mul(t, x3, a);
  x3 = t;                       // a^7
  x6 = x3;
  sqr_n(x6, 3);
  fe_mul(t, x6, x3);
  x6 = t;
  x9 = x6;
  sqr_n(x9, 3);
  fe_mul(t, x9, x3);
  x9 = t;
  x11 = x9;
  sqr_n(x11, 2);
  fe_mul(t, x11, x2);
  x11 = t;
  x22 = x11;
  sqr_n(x22, 11);
  fe_mul(t, x22, x11);
  x22 = t;
  x44 = x22;
  sqr_n(x44, 22);
  fe_mul(t, x44, x22);
  x44 = t;
  x88 = x44;
  sqr_n(x88, 44);
  fe_mul(t, x88, x44);
  x88 = t;
  x176 = x88;
  sqr_n(x176, 88);
  fe_mul(t, x176, x88);
  x176 = t;
  x220 = x176;
  sqr_n(x220, 44);
  fe_mul(t, x220, x44);
  x220 = t;
  x223 = x220;
  sqr_n(x223, 3);
  fe_mul(t, x223, x3);
  x223 = t;
  t1 = x223;
  sqr_n(t1, 23);
  fe_mul(t, t1, x22);
  t1 = t;
  sqr_n(t1, 6);
  fe_mul(t, t1, x2);
  t1 = t;
  sqr_n(t1, 2);
  r = t1;
}

struct APoint {
  U256 x, y;
  bool inf;
};

// p1 (Jacobian) + p2 (affine): the 8M+3S mixed addition every table
// hit uses.  Equal-x inputs degrade to pt_double / infinity exactly
// like pt_add.
void pt_add_mixed(Point& r, const Point& p1, const APoint& p2) {
  if (p2.inf) {
    r = p1;
    return;
  }
  if (pt_is_inf(p1)) {
    r = {p2.x, p2.y, ONE};
    return;
  }
  U256 z1sq, u2, s2, t;
  fe_sqr(z1sq, p1.z);
  fe_mul(u2, p2.x, z1sq);
  fe_mul(t, z1sq, p1.z);
  fe_mul(s2, p2.y, t);
  if (cmp(p1.x, u2) == 0) {
    if (cmp(p1.y, s2) != 0) {
      r = {ZERO, ONE, ZERO};
      return;
    }
    pt_double(r, p1);
    return;
  }
  U256 h, rr, hsq, hcu, v;
  mod_sub(h, u2, p1.x, PRIME);
  mod_sub(rr, s2, p1.y, PRIME);
  fe_sqr(hsq, h);
  fe_mul(hcu, hsq, h);
  fe_mul(v, p1.x, hsq);
  U256 nx;
  fe_sqr(nx, rr);
  mod_sub(nx, nx, hcu, PRIME);
  mod_sub(nx, nx, v, PRIME);
  mod_sub(nx, nx, v, PRIME);
  U256 ny;
  mod_sub(t, v, nx, PRIME);
  fe_mul(ny, rr, t);
  U256 yh;
  fe_mul(yh, p1.y, hcu);
  mod_sub(ny, ny, yh, PRIME);
  U256 nz;
  fe_mul(nz, p1.z, h);
  r.x = nx;
  r.y = ny;
  r.z = nz;
}

// Normalize Jacobian points to affine with ONE field inversion
// (Montgomery prefix products).  Infinity rows come back inf.
void batch_to_affine(const Point* pts, APoint* out, size_t n) {
  std::vector<U256> prefix(n);
  std::vector<size_t> live;
  live.reserve(n);
  U256 acc = ONE;
  for (size_t i = 0; i < n; ++i) {
    out[i].inf = pt_is_inf(pts[i]);
    if (out[i].inf) continue;
    U256 t;
    fe_mul(t, acc, pts[i].z);
    acc = t;
    prefix[i] = acc;
    live.push_back(i);
  }
  if (live.empty()) return;
  U256 inv;
  fe_inv(inv, acc);
  for (size_t k = live.size(); k-- > 0;) {
    size_t i = live[k];
    U256 zinv;
    if (k == 0) {
      zinv = inv;
    } else {
      fe_mul(zinv, inv, prefix[live[k - 1]]);
    }
    U256 t;
    fe_mul(t, inv, pts[i].z);
    inv = t;
    U256 zi2;
    fe_sqr(zi2, zinv);
    fe_mul(out[i].x, pts[i].x, zi2);
    fe_mul(t, zi2, zinv);
    fe_mul(out[i].y, pts[i].y, t);
  }
}

// u1*G comb: TBL[w][v-1] = v * 2^(8w) * G, affine.  522KB, built once
// under std::call_once on first batch call (the warm replay rep pays
// it, like an XLA compile).
constexpr int COMB_WINDOWS = 32;
constexpr int COMB_VALS = 255;
std::vector<APoint> g_comb;
std::once_flag g_comb_once;

void build_g_comb() {
  std::vector<Point> jac(COMB_WINDOWS * COMB_VALS);
  Point base = {GX, GY, ONE};
  for (int w = 0; w < COMB_WINDOWS; ++w) {
    jac[w * COMB_VALS] = base;
    for (int v = 2; v <= COMB_VALS; ++v)
      pt_add(jac[w * COMB_VALS + v - 1], jac[w * COMB_VALS + v - 2],
             base);
    for (int d = 0; d < 8; ++d) {
      Point t;
      pt_double(t, base);
      base = t;
    }
  }
  g_comb.resize(jac.size());
  batch_to_affine(jac.data(), g_comb.data(), jac.size());
}

// c = round((k * g) / 2^384): the mulhi step of the GLV division.
// k, g < 2^256 so c < 2^128 — two limbs.
inline void glv_mulhi(uint64_t c[2], const U256& k, const U256& g) {
  uint64_t w[8];
  mul_wide(w, k, g);
  uint64_t lo = w[6], hi = w[7];
  if (w[5] >> 63) {  // round up on bit 383
    if (++lo == 0) ++hi;
  }
  c[0] = lo;
  c[1] = hi;
}

// r = a*b for 128-bit a (two limbs) x up-to-129-bit b; result < 2^258
// fits U256 for our constants (|k1|,|k2| construction keeps every
// product near 2^256; overflow would fail the split check and fall
// back).  Returns the carry out of limb 3 so the caller can reject.
inline uint64_t mul_128_u256(U256& r, const uint64_t a[2], const U256& b) {
  uint64_t w[6] = {0};
  for (int i = 0; i < 2; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a[i] * b.v[j] + w[i + j] + carry;
      w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w[i + 4] += (uint64_t)carry;
  }
  r = {{w[0], w[1], w[2], w[3]}};
  return w[4] | w[5];
}

// Split k = k1 + k2*lambda (mod n) with |k1|,|k2| < 2^129.  Magnitudes
// and signs come back separately; returns false (caller falls back to
// the sequential path) if the self-check k1 + k2*lambda == k fails or
// a magnitude exceeds 129 bits.
bool glv_split(const U256& k, U256& k1, int& s1, U256& k2, int& s2) {
  uint64_t c1[2], c2[2];
  glv_mulhi(c1, k, GLV_G1);
  glv_mulhi(c2, k, GLV_G2);
  U256 t1, t2, sum;
  if (mul_128_u256(t1, c1, GLV_A1)) return false;
  if (mul_128_u256(t2, c2, GLV_A2)) return false;
  if (add_raw(sum, t1, t2)) return false;
  if (sub_raw(k1, k, sum)) {  // negative: magnitude is sum - k
    U256 m;
    sub_raw(m, sum, k);
    k1 = m;
    s1 = -1;
  } else {
    s1 = 1;
  }
  U256 u, v;
  if (mul_128_u256(u, c1, GLV_B1)) return false;  // c1 * (-b1)
  if (mul_128_u256(v, c2, GLV_A1)) return false;  // c2 * b2
  if (cmp(u, v) >= 0) {
    sub_raw(k2, u, v);
    s2 = 1;
  } else {
    sub_raw(k2, v, u);
    s2 = -1;
  }
  // both halves must fit 129 bits for the wNAF ladder length
  if ((k1.v[3] | k2.v[3]) || (k1.v[2] >> 1) || (k2.v[2] >> 1))
    return false;
  // self-check mod n: (±k1) + (±k2)*lambda == k
  U256 k1m = k1, k2m = k2, chk;
  if (s1 < 0 && !is_zero(k1)) sub_raw(k1m, ORDER, k1);
  if (s2 < 0 && !is_zero(k2)) sub_raw(k2m, ORDER, k2);
  sc_mul(chk, k2m, GLV_LAMBDA, ORDER);
  mod_add(chk, chk, k1m, ORDER);
  return cmp(chk, k) == 0;
}

// wNAF(5): digits in {0, ±1, ±3, ..., ±15}, at most 131 of them for a
// 129-bit magnitude.  Returns the digit count.
int wnaf5(int8_t* digits, const U256& mag) {
  U256 k = mag;
  int len = 0;
  while (!is_zero(k)) {
    int8_t d = 0;
    if (k.v[0] & 1) {
      int w = (int)(k.v[0] & 31);
      d = (int8_t)(w > 16 ? w - 32 : w);
      // k -= d
      U256 dd = {{(uint64_t)(d < 0 ? -d : d), 0, 0, 0}};
      U256 t;
      if (d > 0) {
        sub_raw(t, k, dd);
      } else {
        add_raw(t, k, dd);
      }
      k = t;
    }
    digits[len++] = d;
    // k >>= 1
    for (int i = 0; i < 4; ++i) {
      k.v[i] >>= 1;
      if (i < 3) k.v[i] |= k.v[i + 1] << 63;
    }
  }
  return len;
}

// Everything the fast path precomputes per signature before the
// shared batch-normalization barrier.
struct FastSig {
  U256 u1, u2;          // -z/r, s/r mod n
  U256 k1, k2;          // |GLV halves| of u2
  int s1, s2;           // their signs
  Point tbl[8];         // {1,3,...,15} * R, Jacobian (then affine)
  bool ready;
};

// One signature's validation + R + scalars; rinv comes from the batch
// inversion.  Returns false -> caller routes index to the fallback.
bool fast_prep(const uint8_t* hash32, const uint8_t* s32, const U256& r,
               const U256& rinv, int recid, FastSig& fs) {
  U256 s, z;
  load_be(s, s32);
  load_be(z, hash32);
  U256 x = r;
  if (recid & 2) {
    if (add_raw(x, r, ORDER)) return false;
    if (cmp(x, PRIME) >= 0) return false;
  }
  U256 xsq, ysq, seven = {{7, 0, 0, 0}};
  fe_sqr(xsq, x);
  fe_mul(ysq, xsq, x);
  mod_add(ysq, ysq, seven, PRIME);
  U256 y;
  fe_sqrt_chain(y, ysq);
  U256 chk;
  fe_sqr(chk, y);
  if (cmp(chk, ysq) != 0) return false;
  if ((y.v[0] & 1) != (uint64_t)(recid & 1)) mod_sub(y, PRIME, y, PRIME);
  while (cmp(z, ORDER) >= 0) {
    U256 t;
    sub_raw(t, z, ORDER);
    z = t;
  }
  sc_mul(fs.u1, z, rinv, ORDER);
  if (!is_zero(fs.u1)) mod_sub(fs.u1, ORDER, fs.u1, ORDER);
  sc_mul(fs.u2, s, rinv, ORDER);
  if (!glv_split(fs.u2, fs.k1, fs.s1, fs.k2, fs.s2)) return false;
  // odd multiples of R
  Point rpt = {x, y, ONE};
  Point d2;
  pt_double(d2, rpt);
  fs.tbl[0] = rpt;
  for (int i = 1; i < 8; ++i) pt_add(fs.tbl[i], fs.tbl[i - 1], d2);
  return true;
}

// The per-signature ladder over affine tables: two wNAF halves of
// u2*R (the second through the beta endomorphism), then the u1*G comb
// — no doublings past the 129 shared ones.
void fast_ladder(Point& acc, const FastSig& fs, const APoint* tbl_aff) {
  int8_t d1[132], d2[132];
  int l1 = wnaf5(d1, fs.k1);
  int l2 = wnaf5(d2, fs.k2);
  int len = l1 > l2 ? l1 : l2;
  acc = {ZERO, ONE, ZERO};
  for (int i = len - 1; i >= 0; --i) {
    Point t;
    pt_double(t, acc);
    acc = t;
    if (i < l1 && d1[i]) {
      int8_t d = d1[i];
      bool neg = (d < 0) != (fs.s1 < 0);
      APoint p = tbl_aff[(d < 0 ? -d : d) >> 1];
      if (neg && !p.inf) mod_sub(p.y, PRIME, p.y, PRIME);
      pt_add_mixed(t, acc, p);
      acc = t;
    }
    if (i < l2 && d2[i]) {
      int8_t d = d2[i];
      bool neg = (d < 0) != (fs.s2 < 0);
      APoint p = tbl_aff[(d < 0 ? -d : d) >> 1];
      if (!p.inf) {
        U256 bx;
        fe_mul(bx, p.x, GLV_BETA);  // phi: (x,y) -> (beta x, y)
        p.x = bx;
        if (neg) mod_sub(p.y, PRIME, p.y, PRIME);
      }
      pt_add_mixed(t, acc, p);
      acc = t;
    }
  }
  for (int w = 0; w < COMB_WINDOWS; ++w) {
    int v = (int)((fs.u1.v[w / 8] >> (8 * (w % 8))) & 0xFF);
    if (!v) continue;
    Point t;
    pt_add_mixed(t, acc, g_comb[w * COMB_VALS + v - 1]);
    acc = t;
  }
}

// Fast batch over [lo, hi): shared r^-1 batch inversion, shared wNAF
// table normalization, per-signature ladders, shared final affine
// conversion.  Each index the fast path cannot carry falls back to
// the sequential coreth_ecrecover.
void fast_recover_range(const uint8_t* hashes, const uint8_t* rs,
                        const uint8_t* ss, const uint8_t* recids,
                        uint64_t lo, uint64_t hi, uint8_t* out,
                        uint8_t* ok) {
  std::call_once(g_comb_once, build_g_comb);
  const uint64_t n = hi - lo;
  std::vector<U256> r_l(n), prefix(n);
  std::vector<uint64_t> live;
  live.reserve(n);
  std::vector<uint8_t> state(n, 0);  // 0 invalid, 1 fast, 2 fallback
  U256 acc = ONE;
  for (uint64_t j = 0; j < n; ++j) {
    uint64_t i = lo + j;
    ok[i] = 0;
    U256 r, s;
    load_be(r, rs + 32 * i);
    load_be(s, ss + 32 * i);
    if (recids[i] > 3 || is_zero(r) || is_zero(s)) continue;
    if (cmp(r, ORDER) >= 0 || cmp(s, ORDER) >= 0) continue;
    r_l[j] = r;
    state[j] = 1;
    U256 t;
    sc_mul(t, acc, r, ORDER);
    acc = t;
    prefix[j] = acc;
    live.push_back(j);
  }
  std::vector<FastSig> sigs(n);
  if (!live.empty()) {
    U256 inv;
    sc_inv(inv, acc);
    for (size_t k = live.size(); k-- > 0;) {
      uint64_t j = live[k];
      uint64_t i = lo + j;
      U256 rinv;
      if (k == 0) {
        rinv = inv;
      } else {
        sc_mul(rinv, inv, prefix[live[k - 1]], ORDER);
      }
      U256 t;
      sc_mul(t, inv, r_l[j], ORDER);
      inv = t;
      if (!fast_prep(hashes + 32 * i, ss + 32 * i, r_l[j], rinv,
                     recids[i], sigs[j]))
        state[j] = 2;  // residue failures land here too; fallback
                       // re-checks and reports ok=0 for those
    }
  }
  // one affine normalization across every signature's wNAF table
  std::vector<Point> flat;
  flat.reserve(8 * n);
  for (uint64_t j = 0; j < n; ++j)
    if (state[j] == 1)
      for (int v = 0; v < 8; ++v) flat.push_back(sigs[j].tbl[v]);
  std::vector<APoint> flat_aff(flat.size());
  batch_to_affine(flat.data(), flat_aff.data(), flat.size());
  // ladders; results collect for one final batch affine conversion
  std::vector<Point> res(n);
  size_t cursor = 0;
  for (uint64_t j = 0; j < n; ++j) {
    if (state[j] != 1) continue;
    fast_ladder(res[j], sigs[j], flat_aff.data() + cursor);
    cursor += 8;
    if (pt_is_inf(res[j])) state[j] = 0;
  }
  std::vector<APoint> res_aff(n);
  batch_to_affine(res.data(), res_aff.data(), n);
  for (uint64_t j = 0; j < n; ++j) {
    uint64_t i = lo + j;
    if (state[j] == 2) {
      ok[i] = (uint8_t)coreth_ecrecover(hashes + 32 * i, rs + 32 * i,
                                        ss + 32 * i, recids[i],
                                        out + 20 * i);
      continue;
    }
    if (state[j] != 1 || res_aff[j].inf) continue;
    uint8_t pub[64], digest[32];
    store_be(pub, res_aff[j].x);
    store_be(pub + 32, res_aff[j].y);
    coreth_keccak256(pub, 64, digest);
    std::memcpy(out + 20 * i, digest + 12, 20);
    ok[i] = 1;
  }
}

bool fast_recover_disabled() {
  const char* v = std::getenv("CORETH_FAST_RECOVER");
  return v && v[0] == '0' && v[1] == '\0';
}

}  // namespace

extern "C" {

// Recover the 20-byte address from (msg_hash, r, s, recid).
// Returns 1 on success, 0 on invalid signature.
int coreth_ecrecover(const uint8_t* hash32, const uint8_t* r32,
                     const uint8_t* s32, int recid, uint8_t* out20) {
  if (recid < 0 || recid > 3) return 0;
  U256 r, s, z;
  load_be(r, r32);
  load_be(s, s32);
  load_be(z, hash32);
  if (is_zero(r) || is_zero(s)) return 0;
  if (cmp(r, ORDER) >= 0 || cmp(s, ORDER) >= 0) return 0;
  // x = r (+ n when recid & 2)
  U256 x = r;
  if (recid & 2) {
    if (add_raw(x, r, ORDER)) return 0;
    if (cmp(x, PRIME) >= 0) return 0;
  }
  // y^2 = x^3 + 7
  U256 xsq, ysq, seven = {{7, 0, 0, 0}};
  fe_sqr(xsq, x);
  fe_mul(ysq, xsq, x);
  mod_add(ysq, ysq, seven, PRIME);
  // y = ysq^((p+1)/4)
  U256 e = PRIME;
  {  // (p+1)/4: p+1 overflows 256 bits? p < 2^256-1 so p+1 fits.
    U256 p1;
    add_raw(p1, PRIME, ONE);
    // shift right by 2
    for (int i = 0; i < 4; ++i) {
      uint64_t hi = (i < 3) ? p1.v[i + 1] : 0;
      e.v[i] = (p1.v[i] >> 2) | (hi << 62);
    }
  }
  U256 y;
  fe_pow(y, ysq, e);
  U256 chk;
  fe_sqr(chk, y);
  if (cmp(chk, ysq) != 0) return 0;  // non-residue: invalid r
  if ((y.v[0] & 1) != (uint64_t)(recid & 1)) mod_sub(y, PRIME, y, PRIME);
  // u1 = -z/r mod n ; u2 = s/r mod n
  U256 rinv, u1, u2, zmod = z;
  while (cmp(zmod, ORDER) >= 0) {
    U256 t;
    sub_raw(t, zmod, ORDER);
    zmod = t;
  }
  sc_inv(rinv, r);
  sc_mul(u1, zmod, rinv, ORDER);
  if (!is_zero(u1)) mod_sub(u1, ORDER, u1, ORDER);
  sc_mul(u2, s, rinv, ORDER);
  Point q = {x, y, ONE}, res;
  pt_shamir(res, u1, u2, q);
  if (pt_is_inf(res)) return 0;
  // to affine
  U256 zinv, zinv2, ax, ay, t;
  fe_inv(zinv, res.z);
  fe_sqr(zinv2, zinv);
  fe_mul(ax, res.x, zinv2);
  fe_mul(t, zinv2, zinv);
  fe_mul(ay, res.y, t);
  uint8_t pub[64];
  store_be(pub, ax);
  store_be(pub + 32, ay);
  uint8_t digest[32];
  coreth_keccak256(pub, 64, digest);
  std::memcpy(out20, digest + 12, 20);
  return 1;
}

// Host-side prep for the DEVICE recovery kernel (crypto/secp_device):
// validates ranges, computes x = r (+n) and the scalars
// u1 = -z/r, u2 = s/r mod n with ONE Montgomery batch inversion.
// Outputs: xs 33-byte LE each, u1/u2 32-byte LE each, ok bytes.
// Keeps the Python driver off the critical path (bigint modmuls).
void coreth_recover_prep(const uint8_t* hashes, const uint8_t* rs,
                         const uint8_t* ss, const uint8_t* recids,
                         uint64_t n, uint8_t* xs_le33, uint8_t* u1_le32,
                         uint8_t* u2_le32, uint8_t* ok) {
  std::vector<U256> r_l(n), prefix(n);
  std::vector<uint64_t> live;
  live.reserve(n);
  U256 acc = ONE;
  std::memset(xs_le33, 0, 33 * n);
  std::memset(u1_le32, 0, 32 * n);
  std::memset(u2_le32, 0, 32 * n);
  for (uint64_t i = 0; i < n; ++i) {
    ok[i] = 0;
    U256 r, s;
    load_be(r, rs + 32 * i);
    load_be(s, ss + 32 * i);
    r_l[i] = r;
    if (recids[i] > 3 || is_zero(r) || is_zero(s)) continue;
    if (cmp(r, ORDER) >= 0 || cmp(s, ORDER) >= 0) continue;
    U256 x = r;
    if (recids[i] & 2) {
      if (add_raw(x, r, ORDER)) continue;
      if (cmp(x, PRIME) >= 0) continue;
    }
    // store x as 33-byte little-endian
    uint8_t be[32];
    store_be(be, x);
    for (int j = 0; j < 32; ++j) xs_le33[33 * i + j] = be[31 - j];
    ok[i] = 1;
    U256 t;
    sc_mul(t, acc, r, ORDER);
    acc = t;
    prefix[i] = acc;
    live.push_back(i);
  }
  if (live.empty()) return;
  U256 inv;
  sc_inv(inv, acc);
  for (size_t k = live.size(); k-- > 0;) {
    uint64_t i = live[k];
    U256 rinv;
    if (k == 0) {
      rinv = inv;
    } else {
      sc_mul(rinv, inv, prefix[live[k - 1]], ORDER);
    }
    U256 t;
    sc_mul(t, inv, r_l[i], ORDER);
    inv = t;
    // u2 = s/r ; u1 = -(z/r)
    U256 s, z, u1, u2;
    load_be(s, ss + 32 * i);
    load_be(z, hashes + 32 * i);
    while (cmp(z, ORDER) >= 0) {
      U256 t2;
      sub_raw(t2, z, ORDER);
      z = t2;
    }
    sc_mul(u2, s, rinv, ORDER);
    sc_mul(u1, z, rinv, ORDER);
    if (!is_zero(u1)) {
      U256 t2;
      sub_raw(t2, ORDER, u1);
      u1 = t2;
    }
    uint8_t be[32];
    store_be(be, u1);
    for (int j = 0; j < 32; ++j) u1_le32[32 * i + j] = be[31 - j];
    store_be(be, u2);
    for (int j = 0; j < 32; ++j) u2_le32[32 * i + j] = be[31 - j];
  }
}

// Finish for the device kernel: rows = X(33)||Y(33)||Z(33)||flags(3)
// little-endian Jacobian coordinates (102 bytes/row).  Batch-inverts Z
// mod p, converts to affine, keccaks to addresses.  Rows whose flags
// mark a ladder doubling-collision get ok=2 so the Python driver can
// re-run them on the exact path.
void coreth_recover_finish(const uint8_t* rows, uint64_t n,
                           const uint8_t* ok_in, uint8_t* out20,
                           uint8_t* ok) {
  auto load_le33 = [](U256& v, const uint8_t* p) {
    uint8_t be[32];
    for (int j = 0; j < 32; ++j) be[j] = p[31 - j];
    load_be(v, be);
  };
  std::vector<U256> z_l(n), prefix(n);
  std::vector<uint64_t> fin;
  fin.reserve(n);
  U256 acc = ONE;
  for (uint64_t i = 0; i < n; ++i) {
    ok[i] = 0;
    const uint8_t* row = rows + 102 * i;
    uint8_t inf = row[99], bad = row[100], residue = row[101];
    if (!ok_in[i] || !residue) continue;
    if (bad) {
      ok[i] = 2;  // caller re-runs on the exact host path
      continue;
    }
    if (inf) continue;
    U256 z;
    load_le33(z, row + 66);
    if (is_zero(z)) continue;
    z_l[i] = z;
    U256 t;
    fe_mul(t, acc, z);
    acc = t;
    prefix[i] = acc;
    fin.push_back(i);
  }
  if (fin.empty()) return;
  U256 inv;
  fe_inv(inv, acc);
  for (size_t k = fin.size(); k-- > 0;) {
    uint64_t i = fin[k];
    U256 zinv;
    if (k == 0) {
      zinv = inv;
    } else {
      fe_mul(zinv, inv, prefix[fin[k - 1]]);
    }
    U256 t;
    fe_mul(t, inv, z_l[i]);
    inv = t;
    const uint8_t* row = rows + 102 * i;
    U256 xj, yj, zi2, ax, ay;
    load_le33(xj, row);
    load_le33(yj, row + 33);
    fe_sqr(zi2, zinv);
    fe_mul(ax, xj, zi2);
    fe_mul(t, zi2, zinv);
    fe_mul(ay, yj, t);
    uint8_t pub[64], digest[32];
    store_be(pub, ax);
    store_be(pub + 32, ay);
    coreth_keccak256(pub, 64, digest);
    std::memcpy(out20 + 20 * i, digest + 12, 20);
    ok[i] = 1;
  }
}

// Test hook: field multiplication mod p over big-endian 32-byte operands.
// Exists so the carry-fold edge cases of fe_mul stay regression-tested from
// Python (see tests/test_crypto.py).
void coreth_test_fe_mul(const uint8_t* a32, const uint8_t* b32,
                        uint8_t* out32) {
  U256 a, b, r;
  load_be(a, a32);
  load_be(b, b32);
  fe_mul(r, a, b);
  store_be(out32, r);
}

// Batched recovery: packed 32-byte hashes / r / s, recid bytes.
// out: packed 20-byte addresses; ok[i] = 1 on success.
// Strided across hardware threads — the C++ twin of the reference's
// GOMAXPROCS sender cacher (core/sender_cacher.go:49-80).  Degenerates
// to the sequential loop on single-core hosts.
void coreth_ecrecover_batch(const uint8_t* hashes, const uint8_t* rs,
                            const uint8_t* ss, const uint8_t* recids,
                            uint64_t n, uint8_t* out, uint8_t* ok) {
  if (fast_recover_disabled()) {
    // A/B knob: the sequential per-signature loop (striding threads
    // kept for multi-core hosts — the pre-PR-13 shape)
    unsigned nthreads = std::thread::hardware_concurrency();
    if (nthreads < 2 || n < 2 * nthreads) {
      for (uint64_t i = 0; i < n; ++i)
        ok[i] = (uint8_t)coreth_ecrecover(hashes + 32 * i, rs + 32 * i,
                                          ss + 32 * i, recids[i],
                                          out + 20 * i);
      return;
    }
    std::vector<std::thread> workers;
    workers.reserve(nthreads);
    for (unsigned w = 0; w < nthreads; ++w) {
      workers.emplace_back([=]() {
        for (uint64_t i = w; i < n; i += nthreads)
          ok[i] = (uint8_t)coreth_ecrecover(hashes + 32 * i,
                                            rs + 32 * i, ss + 32 * i,
                                            recids[i], out + 20 * i);
      });
    }
    for (auto& t : workers) t.join();
    return;
  }
  unsigned nthreads = std::thread::hardware_concurrency();
  if (nthreads < 2 || n < 16 * nthreads) {
    fast_recover_range(hashes, rs, ss, recids, 0, n, out, ok);
    return;
  }
  // contiguous chunks (not strides): each worker runs its own batch
  // inversions over a dense range
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  uint64_t chunk = (n + nthreads - 1) / nthreads;
  for (unsigned w = 0; w < nthreads; ++w) {
    uint64_t lo = (uint64_t)w * chunk;
    uint64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      fast_recover_range(hashes, rs, ss, recids, lo, hi, out, ok);
    });
  }
  for (auto& t : workers) t.join();
}

}  // extern "C"
