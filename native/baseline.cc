// Compiled sequential value-transfer processor — the Go-proxy baseline.
//
// The ≥20x north-star target (BASELINE.md) is defined against the Go
// reference's single-threaded StateProcessor, but no Go toolchain exists
// in this image (and there is no network egress to install one), so the
// reference cannot be built here.  This file is the closest honest
// stand-in: a single-threaded, compiled (C++ -O3) replay of the same
// transfer workload doing the same per-tx and per-block work the Go hot
// path does (reference core/state_processor.go:95 loop +
// core/state/statedb.go IntermediateRoot):
//
//   per tx:    ecrecover (libsecp-style ladder, secp256k1.cc) -> sender,
//              nonce check, balance-requirement check, balance moves
//   per block: fold touched accounts into a secure Merkle-Patricia trie
//              (keccak-hashed keys, RLP account encoding, memoized
//              incremental rehash — the hasher.go/statedb analog) and
//              compare the root against the block header.
//
// Exposed via the C ABI for bench.py.  Big-int balances are unsigned
// __int128 — ample for the bench workload; inputs above 2^127 are
// rejected so the Python caller can fall back.

#include <cstdint>
#include <algorithm>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" void coreth_keccak256(const uint8_t*, uint64_t, uint8_t*);
extern "C" int coreth_ecrecover(const uint8_t*, const uint8_t*,
                                const uint8_t*, int, uint8_t*);

namespace {

using u128 = unsigned __int128;
using Bytes = std::vector<uint8_t>;

// ------------------------------------------------------------------ RLP

void rlp_append_len(Bytes& out, size_t len, uint8_t short_base,
                    uint8_t long_base) {
  if (len < 56) {
    out.push_back(short_base + (uint8_t)len);
  } else {
    uint8_t be[8];
    int n = 0;
    size_t v = len;
    while (v) {
      be[n++] = (uint8_t)(v & 0xff);
      v >>= 8;
    }
    out.push_back(long_base + n);
    for (int i = n - 1; i >= 0; --i) out.push_back(be[i]);
  }
}

void rlp_string(Bytes& out, const uint8_t* data, size_t len) {
  if (len == 1 && data[0] < 0x80) {
    out.push_back(data[0]);
    return;
  }
  rlp_append_len(out, len, 0x80, 0xb7);
  out.insert(out.end(), data, data + len);
}

void rlp_uint(Bytes& out, u128 v) {
  uint8_t be[16];
  int n = 0;
  while (v) {
    be[n++] = (uint8_t)(v & 0xff);
    v >>= 8;
  }
  // big-endian, no leading zeros; zero encodes as empty string
  uint8_t tmp[16];
  for (int i = 0; i < n; ++i) tmp[i] = be[n - 1 - i];
  rlp_string(out, tmp, n);
}

Bytes rlp_list(const Bytes& payload) {
  Bytes out;
  rlp_append_len(out, payload.size(), 0xc0, 0xf7);
  out.insert(out.end(), payload.begin(), payload.end());
  return out;
}

// keccak256(rlp("")) / keccak256(rlp(empty list)) constants
const uint8_t EMPTY_ROOT[32] = {
    0x56, 0xe8, 0x1f, 0x17, 0x1b, 0xcc, 0x55, 0xa6, 0xff, 0x83, 0x45,
    0xe6, 0x92, 0xc0, 0xf8, 0x6e, 0x5b, 0x48, 0xe0, 0x1b, 0x99, 0x6c,
    0xad, 0xc0, 0x01, 0x62, 0x2f, 0xb5, 0xe3, 0x63, 0xb4, 0x21};
const uint8_t EMPTY_CODE[32] = {
    0xc5, 0xd2, 0x46, 0x01, 0x86, 0xf7, 0x23, 0x3c, 0x92, 0x7e, 0x7d,
    0xb2, 0xdc, 0xc7, 0x03, 0xc0, 0xe5, 0x00, 0xb6, 0x53, 0xca, 0x82,
    0x27, 0x3b, 0x7b, 0xfa, 0xd8, 0x04, 0x5d, 0x85, 0xa4, 0x70};

Bytes account_rlp(u128 balance, uint64_t nonce) {
  Bytes payload;
  rlp_uint(payload, nonce);
  rlp_uint(payload, balance);
  rlp_string(payload, EMPTY_ROOT, 32);
  rlp_string(payload, EMPTY_CODE, 32);
  rlp_uint(payload, 0);  // is_multi_coin
  return rlp_list(payload);
}

// ------------------------------------------------------- secure MPT

// Node kinds; keys are 64 uniform-depth nibbles (keccak-hashed
// addresses) so the trie only ever needs leaf/ext/branch inserts into
// prefix-free keys — exactly the shape statedb's account trie has.
struct Node {
  enum Kind { LEAF, EXT, BRANCH } kind;
  Bytes path;                      // leaf/ext nibbles
  Bytes value;                     // leaf value
  std::unique_ptr<Node> child;     // ext child
  std::unique_ptr<Node> kids[16];  // branch children
  // memo: rlp encoding + ref (hash or inline); dirty => recompute
  Bytes enc;
  Bytes ref;  // 32-byte hash, or inline rlp (< 32 bytes)
  bool dirty = true;
  bool exported = false;  // emitted by export_nodes since last change

  explicit Node(Kind k) : kind(k) {}
};

Bytes hex_prefix(const Bytes& nibbles, bool leaf) {
  Bytes out;
  uint8_t flag = leaf ? 2 : 0;
  if (nibbles.size() % 2) {
    out.push_back((uint8_t)(((flag | 1) << 4) | nibbles[0]));
    for (size_t i = 1; i + 1 < nibbles.size() + 1; i += 2)
      out.push_back((uint8_t)((nibbles[i] << 4) | nibbles[i + 1]));
  } else {
    out.push_back((uint8_t)(flag << 4));
    for (size_t i = 0; i + 1 < nibbles.size() + 1 && i < nibbles.size();
         i += 2)
      out.push_back((uint8_t)((nibbles[i] << 4) | nibbles[i + 1]));
  }
  return out;
}

struct Trie {
  std::unique_ptr<Node> root;

  void insert(const uint8_t* nibbles, size_t depth, const Bytes& value) {
    root = insert_node(std::move(root), nibbles, depth, value);
  }

  std::unique_ptr<Node> insert_node(std::unique_ptr<Node> n,
                                    const uint8_t* key, size_t len,
                                    const Bytes& value) {
    if (!n) {
      auto leaf = std::make_unique<Node>(Node::LEAF);
      leaf->path.assign(key, key + len);
      leaf->value = value;
      return leaf;
    }
    n->dirty = true;
    n->exported = false;
    if (n->kind == Node::BRANCH) {
      uint8_t idx = key[0];
      n->kids[idx] =
          insert_node(std::move(n->kids[idx]), key + 1, len - 1, value);
      return n;
    }
    // common prefix with leaf/ext path
    size_t cp = 0;
    while (cp < n->path.size() && cp < len && n->path[cp] == key[cp]) ++cp;
    if (n->kind == Node::LEAF) {
      if (cp == n->path.size() && cp == len) {
        n->value = value;  // overwrite
        return n;
      }
    } else if (cp == n->path.size()) {  // ext fully matched
      n->child = insert_node(std::move(n->child), key + cp, len - cp, value);
      return n;
    }
    // split at cp
    auto branch = std::make_unique<Node>(Node::BRANCH);
    // old side
    uint8_t old_idx = n->path[cp];
    if (n->kind == Node::LEAF) {
      auto old_leaf = std::make_unique<Node>(Node::LEAF);
      old_leaf->path.assign(n->path.begin() + cp + 1, n->path.end());
      old_leaf->value = std::move(n->value);
      branch->kids[old_idx] = std::move(old_leaf);
    } else {
      if (cp + 1 == n->path.size()) {
        branch->kids[old_idx] = std::move(n->child);
      } else {
        auto old_ext = std::make_unique<Node>(Node::EXT);
        old_ext->path.assign(n->path.begin() + cp + 1, n->path.end());
        old_ext->child = std::move(n->child);
        branch->kids[old_idx] = std::move(old_ext);
      }
    }
    // new side (uniform-depth keys => never exhausted at a split)
    auto new_leaf = std::make_unique<Node>(Node::LEAF);
    new_leaf->path.assign(key + cp + 1, key + len);
    new_leaf->value = value;
    branch->kids[key[cp]] = std::move(new_leaf);
    if (cp > 0) {
      auto ext = std::make_unique<Node>(Node::EXT);
      ext->path.assign(key, key + cp);
      ext->child = std::move(branch);
      return ext;
    }
    return branch;
  }

  // ------------------------------------------------------------ get
  const Bytes* get(const uint8_t* key, size_t len) const {
    const Node* n = root.get();
    while (n) {
      if (n->kind == Node::BRANCH) {
        if (len == 0) return nullptr;
        n = n->kids[key[0]].get();
        ++key;
        --len;
        continue;
      }
      size_t pl = n->path.size();
      if (pl > len || !std::equal(n->path.begin(), n->path.end(), key))
        return nullptr;
      if (n->kind == Node::LEAF)
        return pl == len ? &n->value : nullptr;
      key += pl;
      len -= pl;
      n = n->child.get();
    }
    return nullptr;
  }

  // --------------------------------------------------------- delete
  void erase(const uint8_t* nibbles, size_t len) {
    root = erase_node(std::move(root), nibbles, len);
  }

  // collapse helper: absorb a lone child into its parent slot
  static std::unique_ptr<Node> collapse(uint8_t idx,
                                        std::unique_ptr<Node> child) {
    if (child->kind == Node::BRANCH) {
      auto ext = std::make_unique<Node>(Node::EXT);
      ext->path.push_back(idx);
      ext->child = std::move(child);
      return ext;
    }
    // leaf/ext: prepend the branch nibble to its path
    child->path.insert(child->path.begin(), idx);
    child->dirty = true;
    child->exported = false;
    child->ref.clear();
    return child;
  }

  std::unique_ptr<Node> erase_node(std::unique_ptr<Node> n,
                                   const uint8_t* key, size_t len) {
    if (!n) return nullptr;
    n->dirty = true;
    n->exported = false;
    n->ref.clear();
    if (n->kind == Node::BRANCH) {
      if (len == 0) return n;  // no branch values in secure tries
      uint8_t idx = key[0];
      n->kids[idx] = erase_node(std::move(n->kids[idx]), key + 1,
                                len - 1);
      int live = -1, count = 0;
      for (int i = 0; i < 16; ++i)
        if (n->kids[i]) {
          live = i;
          ++count;
        }
      if (count == 0) return nullptr;
      if (count == 1) {
        auto merged = collapse((uint8_t)live, std::move(n->kids[live]));
        return merged;
      }
      return n;
    }
    size_t pl = n->path.size();
    if (pl > len || !std::equal(n->path.begin(), n->path.end(), key))
      return n;  // key absent
    if (n->kind == Node::LEAF) {
      if (pl == len) return nullptr;
      return n;
    }
    n->child = erase_node(std::move(n->child), key + pl, len - pl);
    if (!n->child) return nullptr;
    if (n->child->kind != Node::BRANCH) {
      // merge ext with its short child
      n->child->path.insert(n->child->path.begin(), n->path.begin(),
                            n->path.end());
      n->child->dirty = true;
      n->child->exported = false;
      n->child->ref.clear();
      return std::move(n->child);
    }
    return n;
  }

  // ------------------------------------------------------- export
  // Incremental: a clean, already-exported node encodes an unchanged
  // subtree, so the walk prunes there — repeat exports cost O(changed)
  // instead of O(trie).
  void export_nodes(std::vector<std::pair<Bytes, Bytes>>& out, Node* n,
                    bool mark) {
    if (!n) return;
    if (!n->dirty && n->exported) return;
    encode(n);
    if (n->enc.size() >= 32) out.emplace_back(n->ref, n->enc);
    if (mark) n->exported = true;  // size probe must not mutate
    if (n->kind == Node::EXT) {
      export_nodes(out, n->child.get(), mark);
    } else if (n->kind == Node::BRANCH) {
      for (int i = 0; i < 16; ++i)
        export_nodes(out, n->kids[i].get(), mark);
    }
  }

  // memoized encode: fills enc/ref, clears dirty
  const Bytes& encode(Node* n) {
    if (!n->dirty && !n->ref.empty()) return n->ref;
    Bytes payload;
    if (n->kind == Node::LEAF) {
      Bytes hp = hex_prefix(n->path, true);
      rlp_string(payload, hp.data(), hp.size());
      rlp_string(payload, n->value.data(), n->value.size());
    } else if (n->kind == Node::EXT) {
      Bytes hp = hex_prefix(n->path, false);
      rlp_string(payload, hp.data(), hp.size());
      const Bytes& cref = encode(n->child.get());
      if (cref.size() == 32) {
        rlp_string(payload, cref.data(), 32);
      } else {
        payload.insert(payload.end(), cref.begin(), cref.end());
      }
    } else {
      for (int i = 0; i < 16; ++i) {
        if (!n->kids[i]) {
          payload.push_back(0x80);
          continue;
        }
        const Bytes& cref = encode(n->kids[i].get());
        if (cref.size() == 32) {
          rlp_string(payload, cref.data(), 32);
        } else {
          payload.insert(payload.end(), cref.begin(), cref.end());
        }
      }
      payload.push_back(0x80);  // empty branch value
    }
    n->enc = rlp_list(payload);
    if (n->enc.size() >= 32) {
      n->ref.resize(32);
      coreth_keccak256(n->enc.data(), n->enc.size(), n->ref.data());
    } else {
      n->ref = n->enc;  // inline
    }
    n->dirty = false;
    return n->ref;
  }

  void hash_root(uint8_t out[32]) {
    if (!root) {
      std::memcpy(out, EMPTY_ROOT, 32);
      return;
    }
    const Bytes& ref = encode(root.get());
    if (ref.size() == 32) {
      std::memcpy(out, ref.data(), 32);
    } else {
      coreth_keccak256(root->enc.data(), root->enc.size(), out);
    }
  }
};

struct AddrHash {
  size_t operator()(const std::string& k) const {
    size_t h;
    std::memcpy(&h, k.data(), sizeof(h));
    return h;
  }
};

struct Account {
  u128 balance = 0;
  uint64_t nonce = 0;
};

u128 load_u128_be32(const uint8_t* p, bool* too_big) {
  for (int i = 0; i < 16; ++i)
    if (p[i]) *too_big = true;
  u128 v = 0;
  for (int i = 16; i < 32; ++i) v = (v << 8) | p[i];
  if (p[16] & 0x80) *too_big = true;  // keep headroom for sums
  return v;
}

}  // namespace

extern "C" {

// ------------------------------------------------------ trie handle API
//
// The engine's account/storage-trie fold in C++ (the hasher.go +
// statedb updateTrie role): handle-based secure-trie operations over
// pre-hashed 32-byte keys.  Batch update/delete amortizes the ctypes
// boundary; export dumps (hash, rlp) node pairs for interop with the
// Python node store.

void* coreth_trie_new() { return new Trie(); }

void coreth_trie_free(void* h) { delete (Trie*)h; }

static void key_to_nibs(const uint8_t* key32, uint8_t nib[64]) {
  for (int i = 0; i < 32; ++i) {
    nib[2 * i] = key32[i] >> 4;
    nib[2 * i + 1] = key32[i] & 0x0F;
  }
}

// records: n entries of key_hash32; vals packed with u32 lengths
// (length 0 = delete)
void coreth_trie_update_batch(void* h, const uint8_t* keys32,
                              const uint8_t* vals,
                              const uint32_t* val_lens, uint64_t n) {
  Trie* t = (Trie*)h;
  uint8_t nib[64];
  size_t off = 0;
  for (uint64_t i = 0; i < n; ++i) {
    key_to_nibs(keys32 + 32 * i, nib);
    uint32_t vl = val_lens[i];
    if (vl == 0) {
      t->erase(nib, 64);
    } else {
      t->insert(nib, 64, Bytes(vals + off, vals + off + vl));
      off += vl;
    }
  }
}

// returns 1 + copies value when present (cap bytes available), else 0
int coreth_trie_get(void* h, const uint8_t* key32, uint8_t* out,
                    uint32_t cap, uint32_t* out_len) {
  Trie* t = (Trie*)h;
  uint8_t nib[64];
  key_to_nibs(key32, nib);
  const Bytes* v = t->get(nib, 64);
  if (!v) return 0;
  *out_len = (uint32_t)v->size();
  if (v->size() <= cap) std::memcpy(out, v->data(), v->size());
  return 1;
}

void coreth_trie_hash(void* h, uint8_t out32[32]) {
  ((Trie*)h)->hash_root(out32);
}

// Ordered (derive_sha-shaped) batch insert: VARIABLE-length keys — the
// rlp(index) keys of tx/receipt tries are 1..9 bytes, not the
// pre-hashed 32-byte secure keys above.  Insert order is free (the
// handle trie is pointer-based, not streaming); one crossing folds a
// whole block's receipts, coreth_trie_hash reads the root.
void coreth_trie_update_ordered(void* h, const uint8_t* keys,
                                const uint32_t* key_lens,
                                const uint8_t* vals,
                                const uint32_t* val_lens, uint64_t n) {
  Trie* t = (Trie*)h;
  uint8_t nib[32];
  size_t ko = 0, vo = 0;
  for (uint64_t i = 0; i < n; ++i) {
    uint32_t kl = key_lens[i];
    uint32_t use = kl > 16 ? 16 : kl;  // rlp(u64 index) caps at 9
    for (uint32_t j = 0; j < use; ++j) {
      nib[2 * j] = keys[ko + j] >> 4;
      nib[2 * j + 1] = keys[ko + j] & 0x0F;
    }
    ko += kl;
    uint32_t vl = val_lens[i];
    t->insert(nib, 2 * use, Bytes(vals + vo, vals + vo + vl));
    vo += vl;
  }
}

// Batched account fold (the statedb updateTrie + IntermediateRoot hot
// loop in one call): n records of pre-hashed key, 32-byte BE balance,
// nonce, storage root, code hash, multicoin flag; del[i] != 0 deletes.
void coreth_trie_fold_accounts(void* h, const uint8_t* keys32,
                               const uint8_t* balances32,
                               const uint64_t* nonces,
                               const uint8_t* roots32,
                               const uint8_t* code_hashes32,
                               const uint8_t* mc, const uint8_t* del,
                               uint64_t n) {
  Trie* t = (Trie*)h;
  uint8_t nib[64];
  for (uint64_t i = 0; i < n; ++i) {
    key_to_nibs(keys32 + 32 * i, nib);
    if (del[i]) {
      t->erase(nib, 64);
      continue;
    }
    Bytes payload;
    rlp_uint(payload, nonces[i]);
    {  // arbitrary-width balance from 32-byte BE
      const uint8_t* b = balances32 + 32 * i;
      int lead = 0;
      while (lead < 32 && b[lead] == 0) ++lead;
      rlp_string(payload, b + lead, 32 - lead);
    }
    rlp_string(payload, roots32 + 32 * i, 32);
    rlp_string(payload, code_hashes32 + 32 * i, 32);
    rlp_uint(payload, mc[i] ? 1 : 0);
    t->insert(nib, 64, rlp_list(payload));
  }
}

// Explicit single-key deletion (zeroed slot / EIP-158 empty-account
// removal) — the one-record form of the len==0 update_batch path.
void coreth_trie_delete(void* h, const uint8_t* key32) {
  Trie* t = (Trie*)h;
  uint8_t nib[64];
  key_to_nibs(key32, nib);
  t->erase(nib, 64);
}

// Batched storage fold-and-root: ONE call per contract per commit
// window.  n records of pre-hashed slot key + raw 32-byte BE value;
// an all-zero value deletes the slot (slot zeroing), otherwise the
// stored leaf is RLP(value stripped of leading zeros) — the exact
// encoding state_object.go updateTrie writes.  The new storage root
// lands in root_out, so the caller pays one ctypes crossing for the
// whole deduped window instead of one per slot plus a hash call.
void coreth_trie_fold_storage(void* h, const uint8_t* keys32,
                              const uint8_t* vals32, uint64_t n,
                              uint8_t root_out[32]) {
  Trie* t = (Trie*)h;
  uint8_t nib[64];
  for (uint64_t i = 0; i < n; ++i) {
    key_to_nibs(keys32 + 32 * i, nib);
    const uint8_t* v = vals32 + 32 * i;
    int lead = 0;
    while (lead < 32 && v[lead] == 0) ++lead;
    if (lead == 32) {
      t->erase(nib, 64);
      continue;
    }
    Bytes payload;
    rlp_string(payload, v + lead, 32 - lead);
    t->insert(nib, 64, payload);
  }
  t->hash_root(root_out);
}

// Account fold-and-root: fold_accounts + rehash in one crossing (the
// per-window account-trie commit).
void coreth_trie_fold_accounts_root(
    void* h, const uint8_t* keys32, const uint8_t* balances32,
    const uint64_t* nonces, const uint8_t* roots32,
    const uint8_t* code_hashes32, const uint8_t* mc, const uint8_t* del,
    uint64_t n, uint8_t root_out[32]) {
  coreth_trie_fold_accounts(h, keys32, balances32, nonces, roots32,
                            code_hashes32, mc, del, n);
  ((Trie*)h)->hash_root(root_out);
}

// export all hashed nodes: returns byte size written into `out`
// ([hash32][u32 len][rlp])*, or the required size when out == NULL.
uint64_t coreth_trie_export(void* h, uint8_t* out, uint64_t cap) {
  Trie* t = (Trie*)h;
  std::vector<std::pair<Bytes, Bytes>> nodes;
  if (t->root) t->export_nodes(nodes, t->root.get(), out != nullptr);
  uint64_t need = 0;
  for (auto& kv : nodes) need += 32 + 4 + kv.second.size();
  if (!out || cap < need) return need;
  uint64_t off = 0;
  for (auto& kv : nodes) {
    std::memcpy(out + off, kv.first.data(), 32);
    off += 32;
    uint32_t l = (uint32_t)kv.second.size();
    std::memcpy(out + off, &l, 4);
    off += 4;
    std::memcpy(out + off, kv.second.data(), l);
    off += l;
  }
  return need;
}

// ------------------------------------------------- receipt root builder
//
// The replay engine's per-block receipt root + header bloom in ONE
// ctypes call (the DeriveSha/StackTrie + CreateBloom role, reference
// core/types/hashing.go:97 + bloom9.go): the Python loop paid ~7us of
// ctypes keccak overhead per hash across receipt blooms, receipt
// encodings and trie nodes.  Device-path receipts are uniform: status
// 1, cumulative gas, and 0 or 1 log of the ERC-20 Transfer shape
// (address20 ++ 3 topics32 ++ data32 — 148 bytes packed per log).
//
// cum_gas:  n cumulative-gas values
// tx_types: n bytes (0 = legacy untyped, else typed prefix byte)
// has_log:  n bytes (0/1); log_blob: 148 bytes per has_log entry
// Writes root32 and the block bloom (OR of receipt blooms, 256B BE).

static void bloom_or(uint8_t bloom[256], const uint8_t* value,
                     size_t len) {
  uint8_t h[32];
  coreth_keccak256(value, len, h);
  for (int i = 0; i < 6; i += 2) {
    uint32_t bit = (((uint32_t)h[i] << 8) | h[i + 1]) & 0x7FF;
    bloom[255 - bit / 8] |= (uint8_t)(1u << (bit % 8));
  }
}

void coreth_receipt_root(const uint64_t* cum_gas, const uint8_t* tx_types,
                         const uint8_t* has_log, const uint8_t* log_blob,
                         uint64_t n, uint8_t root_out[32],
                         uint8_t bloom_out[256]) {
  Trie trie;
  std::memset(bloom_out, 0, 256);
  size_t log_off = 0;
  uint8_t nib[24];  // rlp(u64) is at most 9 bytes = 18 nibbles
  for (uint64_t i = 0; i < n; ++i) {
    uint8_t rbloom[256];
    std::memset(rbloom, 0, 256);
    Bytes logs_payload;
    if (has_log[i]) {
      const uint8_t* lg = log_blob + log_off;
      log_off += 148;
      bloom_or(rbloom, lg, 20);  // address
      Bytes one;                 // [addr, [t0,t1,t2], data]
      rlp_string(one, lg, 20);
      Bytes topics;
      for (int t = 0; t < 3; ++t) {
        bloom_or(rbloom, lg + 20 + 32 * t, 32);
        rlp_string(topics, lg + 20 + 32 * t, 32);
      }
      Bytes tl = rlp_list(topics);
      one.insert(one.end(), tl.begin(), tl.end());
      rlp_string(one, lg + 116, 32);
      Bytes ol = rlp_list(one);
      logs_payload.insert(logs_payload.end(), ol.begin(), ol.end());
      for (int b = 0; b < 256; ++b) bloom_out[b] |= rbloom[b];
    }
    // receipt payload: [status=1, cum_gas, bloom, logs]
    Bytes payload;
    rlp_uint(payload, 1);
    rlp_uint(payload, cum_gas[i]);
    rlp_string(payload, rbloom, 256);
    Bytes ll = rlp_list(logs_payload);
    payload.insert(payload.end(), ll.begin(), ll.end());
    Bytes enc = rlp_list(payload);
    if (tx_types[i]) enc.insert(enc.begin(), tx_types[i]);
    // trie key: rlp(uint i) — 1 byte below 0x80, 0x81/0x82-prefixed
    // above (prefix-free across lengths, so the uniform-depth insert
    // in Trie applies)
    Bytes key;
    rlp_uint(key, i);
    size_t kn = 0;
    for (uint8_t byte : key) {
      nib[kn++] = byte >> 4;
      nib[kn++] = byte & 0x0F;
    }
    trie.insert(nib, kn, enc);
  }
  trie.hash_root(root_out);
}

// Packed tx record layout (byte offsets):
//   sighash 0:32 | r 32:64 | s 64:96 | recid 96 | to 97:117
//   | value 117:149 | fee 149:181 | required 181:213 | nonce 213:221
//   => 221 bytes per record
// accounts: addr20 | balance32 | nonce8 => 60 bytes
// Returns 0 on success; 1 root mismatch; 2 invalid sig; 3 nonce/balance
// check failed; 4 unsupported big value; 5 malformed input (offsets
// not monotone, or a record extending past txs_len — the explicit
// length makes the packed-blob decode bounds-checked instead of
// trusted; fuzzed under ASan by tests/test_sanitize.py).
int coreth_baseline_replay(const uint8_t* txs, uint64_t txs_len,
                           const uint64_t* block_off,
                           uint64_t n_blocks, const uint8_t* roots,
                           const uint8_t* coinbases,
                           const uint8_t* genesis_accounts,
                           uint64_t n_accounts, double* phases) {
  constexpr size_t REC = 221;
  for (uint64_t b = 0; b < n_blocks; ++b)
    if (block_off[b] > block_off[b + 1]) return 5;
  // overflow-safe: compare counts, not byte products
  if (n_blocks > 0 && block_off[n_blocks] > txs_len / REC) return 5;
  std::unordered_map<std::string, Account, AddrHash> state;
  state.reserve(1 << 14);
  bool too_big = false;
  for (uint64_t i = 0; i < n_accounts; ++i) {
    const uint8_t* p = genesis_accounts + 60 * i;
    Account a;
    a.balance = load_u128_be32(p + 20, &too_big);
    uint64_t nonce = 0;
    for (int j = 0; j < 8; ++j) nonce = (nonce << 8) | p[52 + j];
    a.nonce = nonce;
    state.emplace(std::string((const char*)p, 20), a);
  }
  if (too_big) return 4;

  // seed the trie with genesis accounts (hashed keys)
  Trie trie;
  uint8_t nib[64], hk[32];
  for (auto& kv : state) {
    coreth_keccak256((const uint8_t*)kv.first.data(), 20, hk);
    for (int i = 0; i < 32; ++i) {
      nib[2 * i] = hk[i] >> 4;
      nib[2 * i + 1] = hk[i] & 0x0f;
    }
    trie.insert(nib, 64, account_rlp(kv.second.balance, kv.second.nonce));
  }
  uint8_t root[32];
  trie.hash_root(root);

  double t_sender = 0, t_exec = 0, t_trie = 0;
  auto now = []() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + 1e-9 * ts.tv_nsec;
  };

  std::vector<std::string> touched;
  for (uint64_t b = 0; b < n_blocks; ++b) {
    touched.clear();
    std::string coinbase((const char*)(coinbases + 20 * b), 20);
    for (uint64_t t = block_off[b]; t < block_off[b + 1]; ++t) {
      const uint8_t* r = txs + REC * t;
      // --- sender recovery (the sender_cacher.go work, sequential)
      double t0 = now();
      uint8_t sender[20];
      if (!coreth_ecrecover(r, r + 32, r + 64, r[96], sender)) return 2;
      t_sender += now() - t0;
      // --- state transition (state_transition.go TransitionDb scalar)
      t0 = now();
      std::string from((const char*)sender, 20);
      std::string to((const char*)(r + 97), 20);
      bool big = false;
      u128 value = load_u128_be32(r + 117, &big);
      u128 fee = load_u128_be32(r + 149, &big);
      // required (buyGas pre-check, gas_limit*cap + value) is passed
      // precomputed; still compared against the live balance here
      u128 required = load_u128_be32(r + 181, &big);
      if (big) return 4;
      uint64_t tx_nonce = 0;
      for (int i = 0; i < 8; ++i) tx_nonce = (tx_nonce << 8) | r[213 + i];
      Account& fa = state[from];
      if (fa.nonce != tx_nonce) return 3;
      if (fa.balance < required || fa.balance < value + fee) return 3;
      fa.nonce += 1;
      fa.balance -= value + fee;
      state[to].balance += value;
      state[coinbase].balance += fee;
      touched.push_back(from);
      touched.push_back(to);
      t_exec += now() - t0;
    }
    touched.push_back(coinbase);
    // --- per-block trie fold + incremental rehash (IntermediateRoot)
    // dedupe first: the statedb analog folds a deduped dirty set, and
    // duplicate folds would inflate this baseline's trie phase
    double t0 = now();
    std::sort(touched.begin(), touched.end());
    touched.erase(std::unique(touched.begin(), touched.end()),
                  touched.end());
    for (const auto& addr : touched) {
      const Account& a = state[addr];
      coreth_keccak256((const uint8_t*)addr.data(), 20, hk);
      for (int i = 0; i < 32; ++i) {
        nib[2 * i] = hk[i] >> 4;
        nib[2 * i + 1] = hk[i] & 0x0f;
      }
      trie.insert(nib, 64, account_rlp(a.balance, a.nonce));
    }
    trie.hash_root(root);
    t_trie += now() - t0;
    if (std::memcmp(root, roots + 32 * b, 32) != 0) return 1;
  }
  if (phases) {
    phases[0] = t_sender;
    phases[1] = t_exec;
    phases[2] = t_trie;
  }
  return 0;
}

}  // extern "C"
