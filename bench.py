#!/usr/bin/env python
"""Benchmark: batched TPU replay vs the sequential compiled baselines.

Workloads:
- transfer (BASELINE config[2] shape): value-transfer chain, the
  reference's core/bench_test.go:45 InsertChain shape, replayed from
  wire bytes with full sender recovery + per-block root validation.
- erc20 (BASELINE config[1] shape): transfer() call spam on the
  workloads/erc20 token — batched storage-slot read/modify/write +
  Transfer logs/bloom + storage-trie rehash, bit-identical roots.
  Measured twice: through the token fast path, and (erc20_machine)
  forced through the GENERAL device step machine.
- swap (BASELINE config[3] shape): shared-slot constant-product pool —
  every tx conflicts through reserve slots 0/1 (the Uniswap-V2/ring
  contention analog, reference core/bench_test.go:64); exercises the
  optimistic scheduler's device rounds + host conflict-suffix.

Baselines:
- py host: BlockChain.insert_chain (the Python twin of the Go
  StateProcessor loop).
- native: compiled C++ replays — baseline.cc for transfers, evm.cc
  (a real C++ EVM interpreter) for the contract workloads — so every
  vs_baseline ratio has a compiled denominator (BASELINE.md round 5).

Prints ONE json line; the primary metric is the transfer workload.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent XLA compile cache: the replay-window kernels compile once per
# machine, not once per bench run (remote compile over the tunnel is slow).
import jax  # noqa: E402

_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", ".jax_cache")
os.makedirs(_cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# Default shape: a 1024-block replay (VERDICT r2 #8 — the bench must
# move toward the 10k-block north star) with 1024 senders and a
# growing account table (half of every block's recipients are fresh
# addresses, ~65k accounts by the end of the chain).
# Recovery split re-measured round 4 on the uncontended host AFTER the
# pipelining changes (256-block sweep, best-of-2): transfer 5639 tps
# @0.8 -> 6666 @0.95; erc20 5090 @0.8 -> 5352 @0.95 (5564 @1.0).  The
# 1-core host is the straggler, so nearly all signatures belong on the
# device ladder; 0.95 keeps a small host share that still overlaps.
os.environ.setdefault("CORETH_RECOVER_SPLIT", "0.95")
N_BLOCKS = int(os.environ.get("BENCH_BLOCKS", "1024"))
TXS_PER_BLOCK = int(os.environ.get("BENCH_TXS", "128"))
# >=64 blocks so the extrapolated py-host denominator is not a ~1s
# noise-dominated sample (round-3 verdict weak #9)
BASELINE_BLOCKS = int(os.environ.get("BENCH_BASELINE_BLOCKS", "64"))
# ~45k avg gas/tx against the 15M Cortina block gas limit caps token
# blocks at ~300 txs; 256 keeps a pow2 batch shape
ERC20_TXS = int(os.environ.get("BENCH_ERC20_TXS", "256"))
# erc20 chain BUILD costs ~1.2 s/block (signing + host EVM): 256
# blocks (~65k txs) keeps a cold-cache build inside the section slice
# while the timed region still spans two engine windows
ERC20_BLOCKS = int(os.environ.get("BENCH_ERC20_BLOCKS", "256"))
ERC20_BASELINE_BLOCKS = int(
    os.environ.get("BENCH_ERC20_BASELINE_BLOCKS", "32"))
# contention + general-machine shapes: the fused OCC kernel re-executes
# every still-pending lane each device round, so a fully-conflicting
# L-lane block costs O(L^2) lane-execs — 16x16 measures the contention
# semantics (and the O(1)-dispatch tentpole) without the quadratic
# blow-up that kept round 5's 64x32 shape from ever completing
SWAP_BLOCKS = int(os.environ.get("BENCH_SWAP_BLOCKS", "16"))
SWAP_TXS = int(os.environ.get("BENCH_SWAP_TXS", "8"))
MACHINE_BLOCKS = int(os.environ.get("BENCH_MACHINE_BLOCKS", "16"))
MIXED_BLOCKS = int(os.environ.get("BENCH_MIXED_BLOCKS", "128"))
MIXED_TXS = int(os.environ.get("BENCH_MIXED_TXS", "32"))
_DIR = os.path.dirname(os.path.abspath(__file__))

GWEI = 10**9
N_KEYS = int(os.environ.get("BENCH_KEYS", "1024"))
TOKEN = bytes([0x77]) * 20
POOL = bytes([0x78]) * 20

# Single-run ratios on this contended 1-core host proved unfalsifiable
# (round-3 recorded 0.29x while reruns gave 1.30x and 2.61x) — every
# timed region now runs BENCH_REPS times and the JSON reports the
# median with min/max spread.
REPS = int(os.environ.get("BENCH_REPS", "3"))

# Time budget: round 5's bench (5 workloads x 3 reps over 1024-block
# chains) blew the driver's budget — BENCH_r05.json recorded rc 124
# and NO result line, despite the in-process watchdog thread: a wedged
# section holding the GIL (a C call that never returns) starves every
# Python thread, timer included.  Four layers of defense now:
# 1. per-SECTION deadlines: each workload owns a slice of the budget;
#    its rep loops degrade to fewer reps (never below 1) and its chain
#    build truncates at a chunk boundary when the slice runs out;
# 2. later sections are skipped outright (fields emit null);
# 3. incremental emission: after EVERY section the accumulated RESULT
#    is flushed to a state file AND printed as a partial JSON line on
#    stderr — progress survives any later catastrophe;
# 4. a CHILD-PROCESS watchdog (immune to the parent's GIL) that, at
#    the deadline, SIGKILLs the parent and prints the last recorded
#    state as the stdout JSON line itself.  The in-process timer
#    thread stays as the faster, richer path for non-GIL wedges.
T0 = time.monotonic()
DEADLINE = float(os.environ.get("BENCH_DEADLINE", "600"))
STATE_PATH = os.path.join(_DIR, ".bench_cache",
                          f"partial_{os.getpid()}.json")

# one stdout JSON line, exactly once — main() on success, a watchdog
# on overrun.  The lock makes check-and-set atomic AND holds through
# the print, so the watchdog firing while main() finishes cannot
# double-print or os._exit mid-line.
RESULT = {}
_EMITTED = False
_EMIT_LOCK = threading.Lock()
_WD_CHILD = None


def _snapshot_json(extra=None):
    """Serialize RESULT, retrying across concurrent mutation (a timer
    thread may race a main-thread RESULT.update())."""
    for _ in range(5):
        try:
            obj = dict(RESULT)
            if extra:
                obj.update(extra)
            return json.dumps(obj)
        except RuntimeError:
            time.sleep(0.05)
    return json.dumps({"metric": "transfer_replay_throughput",
                       "value": None, "unit": "txs/s",
                       "error": "result emit race"})


def _write_state(tag):
    """Persist the accumulated RESULT for the child watchdog; called
    after every completed section (and at startup)."""
    try:
        os.makedirs(os.path.dirname(STATE_PATH), exist_ok=True)
        line = _snapshot_json({"partial": tag})
        tmp = STATE_PATH + ".tmp"
        with open(tmp, "w") as f:
            f.write(line)
        os.replace(tmp, STATE_PATH)
    except OSError:
        pass


def _section_done(name):
    """Incremental emission (defense layer 3): state file + a partial
    JSON line on stderr as each section completes."""
    RESULT.setdefault("sections_done", []).append(name)
    _write_state(name)
    print(_snapshot_json({"partial": True}), file=sys.stderr, flush=True)


def _emit():
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        _EMITTED = True
        RESULT["elapsed_s"] = round(time.monotonic() - T0, 1)
        print(_snapshot_json(), flush=True)
        if _WD_CHILD is not None:
            try:
                _WD_CHILD.kill()
            except OSError:
                pass
        try:
            os.unlink(STATE_PATH)
        except OSError:
            pass


def _watchdog():
    try:
        _emit()
    finally:
        os._exit(0)


_WATCHDOG = threading.Timer(max(5.0, DEADLINE - 12.0), _watchdog)
_WATCHDOG.daemon = True

# The child watchdog: a separate interpreter sharing our stdout.  It
# polls until its deadline; if the parent is still alive then, it
# SIGKILLs it and prints the state file as the result line (leading
# newline: the parent may have died mid-write, and the child's line
# must still start fresh).  A GIL-holding wedge cannot touch it.
_WD_CODE = (
    "import json,os,signal,sys,time\n"
    "pid=int(sys.argv[1]); path=sys.argv[2]\n"
    "end=time.monotonic()+float(sys.argv[3])\n"
    "while time.monotonic()<end:\n"
    "    time.sleep(0.5)\n"
    "    try: os.kill(pid,0)\n"
    "    except OSError: sys.exit(0)\n"  # parent exited (and emitted)
    "try: payload=open(path).read()\n"
    "except OSError: payload=''\n"
    "try: obj=json.loads(payload)\n"
    "except ValueError: obj={}\n"
    "if not obj:\n"
    "    obj={'metric':'transfer_replay_throughput','value':None,\n"
    "         'unit':'txs/s','error':'watchdog: no state recorded'}\n"
    "obj['watchdog']='child'\n"
    "try: os.kill(pid,signal.SIGKILL)\n"
    "except OSError: pass\n"
    "time.sleep(0.3)\n"
    "sys.stdout.write('\\n'+json.dumps(obj)+'\\n')\n"
    "sys.stdout.flush()\n"
    "try: os.unlink(path)\n"
    "except OSError: pass\n"
)


def _spawn_watchdog_child():
    global _WD_CHILD
    import subprocess
    _write_state("init")
    budget = max(4.0, DEADLINE - 6.0 - (time.monotonic() - T0))
    _WD_CHILD = subprocess.Popen(
        [sys.executable, "-c", _WD_CODE, str(os.getpid()), STATE_PATH,
         str(budget)])


def _maybe_wedge():
    """BENCH_WEDGE deliberately wedges the run (watchdog regression
    harness): 'gil' blocks the main thread INSIDE a C call that never
    releases the GIL — the timer thread starves and only the child
    watchdog can produce the JSON line; any other value parks the
    main thread GIL-free, exercising the in-process timer path."""
    mode = os.environ.get("BENCH_WEDGE")
    if not mode:
        return
    if mode == "gil":
        import ctypes
        libc = ctypes.PyDLL(None)  # PyDLL: calls DO hold the GIL
        while True:
            libc.sleep(1 << 20)
    threading.Event().wait()

# end of the CURRENT workload's budget slice (absolute monotonic time);
# main() advances it section by section
SECTION_END = T0 + DEADLINE


def _remaining():
    return DEADLINE - (time.monotonic() - T0)


def _section_left():
    return min(SECTION_END, T0 + DEADLINE) - time.monotonic()


def _deadline_tight(margin=30.0):
    """True once the current section's slice (or the tail of the global
    budget) is nearly spent — rep loops stop early, keeping at least
    the one rep they already ran."""
    return _section_left() < margin or _remaining() < 30.0


def _median(xs):
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2


def _spread(xs):
    return [round(min(xs), 1), round(max(xs), 1)]


def _txs_per_block(workload):
    if workload == "erc20":
        return ERC20_TXS
    if workload == "swap":
        return SWAP_TXS
    return TXS_PER_BLOCK


def _n_blocks(workload):
    if workload == "swap":
        return SWAP_BLOCKS
    if workload == "erc20":
        return ERC20_BLOCKS
    return N_BLOCKS


def _cache_path(workload, n=None):
    n = _n_blocks(workload) if n is None else n
    return os.path.join(
        _DIR, ".bench_cache",
        f"{workload}_{n}x{_txs_per_block(workload)}"
        f"k{N_KEYS}.bin")


def _partial_cache(workload):
    """Largest partial-chain cache for this shape (a deadline-truncated
    earlier build), or None."""
    import glob
    pat = _cache_path(workload, n="*").replace("*", "[0-9]*")
    best, best_n = None, 0
    for path in glob.glob(pat):
        stem = os.path.basename(path)
        try:
            n = int(stem.split("_")[-1].split("x")[0])
        except ValueError:
            continue
        # never a LARGER chain than configured: this path only runs
        # when the budget slice is nearly spent, and a bigger cached
        # shape would inflate the very work the deadline is rationing
        if best_n < n <= _n_blocks(workload):
            best, best_n = path, n
    return best


def _genesis(workload):
    from coreth_tpu.chain import Genesis, GenesisAccount
    from coreth_tpu.params import TEST_CHAIN_CONFIG
    from coreth_tpu.crypto.secp256k1 import priv_to_address
    keys = [0xC0FFEE + i for i in range(N_KEYS)]
    addrs = [priv_to_address(k) for k in keys]
    alloc = {a: GenesisAccount(balance=10**27) for a in addrs}
    if workload == "erc20":
        from coreth_tpu.workloads.erc20 import token_genesis_account
        alloc[TOKEN] = token_genesis_account({a: 10**24 for a in addrs})
    elif workload == "swap":
        from coreth_tpu.workloads.swap import pool_genesis_account
        alloc[POOL] = pool_genesis_account(10**24, 10**24)
    genesis = Genesis(config=TEST_CHAIN_CONFIG, gas_limit=8_000_000,
                      alloc=alloc)
    return genesis, keys, addrs


def build_or_load_chain(workload):
    """Build the chain once, cache the wire bytes (signing + host EVM
    execution dominate chain construction).  The build is CHUNKED and
    deadline-guarded: when the section's budget slice runs out the
    chain truncates at a chunk boundary (identical prefix — the gen
    callbacks are offset-wrapped) and the partial chain is cached under
    its actual length, so a later run resumes from a shorter-but-valid
    chain instead of timing out with nothing."""
    from coreth_tpu import rlp
    from coreth_tpu.types import Block
    genesis, keys, addrs = _genesis(workload)
    cache = _cache_path(workload)
    if not os.path.exists(cache):
        partial = _partial_cache(workload)
        if partial is not None and _section_left() < 60:
            # not enough slice left to extend the build: run on the
            # truncated chain from the previous attempt
            cache = partial
    if os.path.exists(cache):
        blob = open(cache, "rb").read()
        blocks = [Block.decode(b) for b in rlp.decode(blob)]
        return genesis, blocks
    from coreth_tpu.chain import generate_chain
    from coreth_tpu.state import Database
    from coreth_tpu.types import DynamicFeeTx, sign_tx
    from coreth_tpu.params import TEST_CHAIN_CONFIG as CFG
    db = Database()
    gblock = genesis.to_block(db)
    nonces = [0] * N_KEYS

    def gen_transfer(i, bg):
        for j in range(TXS_PER_BLOCK):
            n = i * TXS_PER_BLOCK + j
            k = n % N_KEYS
            if j % 2 == 0:
                # fresh recipient: the account table grows all chain
                to = b"\xf0" + n.to_bytes(4, "big") * 4 + b"\xf0" * 3
            else:
                to = bytes([0x10 + (j % 199)]) * 20
            # fee cap above the AP4 max base fee (1000 gwei) so the
            # chain stays valid as sustained load drives the fee up
            bg.add_tx(sign_tx(DynamicFeeTx(
                chain_id_=CFG.chain_id, nonce=nonces[k],
                gas_tip_cap_=GWEI, gas_fee_cap_=2000 * GWEI, gas=21_000,
                to=to, value=10**12 + j,
            ), keys[k], CFG.chain_id))
            nonces[k] += 1

    def gen_erc20(i, bg):
        from coreth_tpu.workloads.erc20 import transfer_calldata
        for j in range(ERC20_TXS):
            k = (i * ERC20_TXS + j) % N_KEYS
            # mix of repeat token holders (SSTORE reset) and a rotating
            # pool of fresh recipients (SSTORE set)
            if j % 3 == 0:
                to = addrs[(k + 1) % N_KEYS]
            else:
                to = (0x5000 + (i * 7 + j) % 1999).to_bytes(2, "big") * 10
            bg.add_tx(sign_tx(DynamicFeeTx(
                chain_id_=CFG.chain_id, nonce=nonces[k],
                gas_tip_cap_=GWEI, gas_fee_cap_=2000 * GWEI, gas=100_000,
                to=TOKEN, value=0, data=transfer_calldata(to, 10 + j),
            ), keys[k], CFG.chain_id))
            nonces[k] += 1

    def gen_swap(i, bg):
        from coreth_tpu.workloads.swap import swap_calldata
        for j in range(SWAP_TXS):
            k = (i * SWAP_TXS + j) % N_KEYS
            bg.add_tx(sign_tx(DynamicFeeTx(
                chain_id_=CFG.chain_id, nonce=nonces[k],
                gas_tip_cap_=GWEI, gas_fee_cap_=2000 * GWEI, gas=100_000,
                to=POOL, value=0,
                data=swap_calldata(10**6 + i * 131 + j),
            ), keys[k], CFG.chain_id))
            nonces[k] += 1

    gen = {"erc20": gen_erc20, "swap": gen_swap}.get(
        workload, gen_transfer)
    # gap=10s: one block per fee window keeps the chain under the AP5
    # gas target so the base fee stays bounded over any chain length.
    # Chunked so the deadline check lands every few seconds; the wrapped
    # gen offsets the block index, so a chunked build emits the exact
    # blocks a single-shot build would
    target = _n_blocks(workload)
    blocks = []
    parent = gblock
    chunk = 8
    while len(blocks) < target:
        done = len(blocks)
        m = min(chunk, target - done)
        part, _ = generate_chain(
            CFG, parent, db, m,
            lambda i, bg, _o=done: gen(_o + i, bg), gap=10)
        blocks.extend(part)
        parent = part[-1]
        if len(blocks) < target and _deadline_tight(margin=45.0) \
                and len(blocks) >= 16:
            if os.environ.get("BENCH_VERBOSE"):
                print(f"[{workload}] chain build truncated at "
                      f"{len(blocks)}/{target} blocks (deadline)",
                      file=sys.stderr)
            cache = _cache_path(workload, n=len(blocks))
            break
    os.makedirs(os.path.dirname(cache), exist_ok=True)
    with open(cache, "wb") as f:
        f.write(rlp.encode([b.encode() for b in blocks]))
    return genesis, blocks


def run_native_baseline(genesis, wire_blocks):
    """Compiled single-threaded C++ replay (native/baseline.cc) — the
    Go-proxy denominator for the north-star ratio; validates the same
    bit-identical roots.  Python packing below is prep, excluded from
    the timed region (which favors the baseline)."""
    from coreth_tpu.crypto import native
    from coreth_tpu.types import Block, LatestSigner
    blocks = [Block.decode(w) for w in wire_blocks]
    signer = LatestSigner(genesis.config.chain_id)
    recs, offs, roots, cbs = bytearray(), [0], bytearray(), bytearray()
    for b in blocks:
        for tx in b.transactions:
            r, s, recid = tx.inner.raw_signature()
            price = min(tx.gas_fee_cap, b.base_fee + tx.gas_tip_cap)
            fee = 21_000 * price
            required = tx.gas * tx.gas_fee_cap + tx.value
            recs += signer.sig_hash(tx)
            recs += r.to_bytes(32, "big") + s.to_bytes(32, "big") \
                + bytes([recid])
            recs += tx.to
            recs += tx.value.to_bytes(32, "big") + fee.to_bytes(32, "big") \
                + required.to_bytes(32, "big")
            recs += tx.nonce.to_bytes(8, "big")
        offs.append(offs[-1] + len(b.transactions))
        roots += b.root
        cbs += b.header.coinbase
    accounts = b"".join(
        addr + acct.balance.to_bytes(32, "big")
        + acct.nonce.to_bytes(8, "big")
        for addr, acct in genesis.alloc.items())
    txs = sum(len(b.transactions) for b in blocks)
    return _native_reps(
        native.baseline_replay,
        (bytes(recs), offs, bytes(roots), bytes(cbs), accounts,
         len(genesis.alloc)), txs, "transfer")


def _native_reps(native_fn, args, txs, label):
    """REPS timed runs of a compiled baseline entry point; rc != 0 is
    a root/validation failure."""
    tps_runs, phases = [], None
    for _ in range(REPS):
        t0 = time.monotonic()
        rc, phases = native_fn(*args)
        dt = time.monotonic() - t0
        if rc != 0:
            raise RuntimeError(f"native {label} baseline failed rc={rc}")
        tps_runs.append(txs / dt)
        if _deadline_tight():
            break
    return tps_runs, {"t_sender": round(phases[0], 3),
                      "t_exec": round(phases[1], 3),
                      "t_trie": round(phases[2], 3)}


def run_native_evm(genesis, wire_blocks):
    """Compiled single-threaded C++ EVM replay (native/evm.cc) — the
    contract-workload denominator; validates bit-identical roots."""
    from coreth_tpu.crypto import native
    from coreth_tpu.types import Block
    from coreth_tpu.workloads.pack_native import pack_evm_replay
    blocks = [Block.decode(w) for w in wire_blocks]
    txs = sum(len(b.transactions) for b in blocks)
    return _native_reps(native.evm_replay,
                        pack_evm_replay(genesis, blocks), txs, "evm")


def _native_evm_rep(genesis, blocks, sink):
    """One timed native-EVM rep per call (chain packed once up
    front), appending txs/s into ``sink``; None when the native build
    is unavailable.  Passed as ``run_tpu(interleave=...)`` so native
    and device reps ALTERNATE within one section: a ratio's numerator
    and denominator then sample the same machine-load window instead
    of sections minutes apart — the PR-15 noise rule that fixed the
    mesh-scaling curve, applied to the vs_native denominators."""
    from coreth_tpu.crypto import native
    from coreth_tpu.workloads.pack_native import pack_evm_replay
    if native.load() is None:
        return None
    args = pack_evm_replay(genesis, blocks)
    txs = sum(len(b.transactions) for b in blocks)

    def one_rep():
        t0 = time.monotonic()
        rc, _phases = native.evm_replay(*args)
        dt = time.monotonic() - t0
        if rc != 0:
            raise RuntimeError(f"native evm interleave failed rc={rc}")
        sink.append(txs / dt)
    return one_rep


def run_baseline(genesis, wire_blocks, n_blocks):
    """Sequential host insert (fresh sender cache) over a block subset."""
    from coreth_tpu.chain import BlockChain
    from coreth_tpu.types import Block
    tps_runs, timers = [], None
    for _ in range(REPS):
        blocks = [Block.decode(w) for w in wire_blocks[:n_blocks]]
        chain = BlockChain(genesis)
        t0 = time.monotonic()
        chain.insert_chain(blocks)
        dt = time.monotonic() - t0
        txs = sum(len(b.transactions) for b in blocks)
        tps_runs.append(txs / dt)
        timers = chain.timers.row()
        if _deadline_tight():
            break
    return tps_runs, timers


def _fresh_engine(genesis, txs_per_block):
    from coreth_tpu.replay import ReplayEngine
    from coreth_tpu.state import Database
    db = Database()
    gblock = genesis.to_block(db)
    # size the device account table for the workload's growth up front:
    # capacity is a static arg of the compiled window kernels, so
    # in-flight growth would recompile at every pow2 step
    need = N_KEYS + N_BLOCKS * TXS_PER_BLOCK // 2 + 1024
    capacity = 1 << max(14, (need - 1).bit_length())
    return ReplayEngine(genesis.config, db, gblock.root,
                        parent_header=gblock.header,
                        batch_pad=txs_per_block, capacity=capacity,
                        slot_capacity=1 << 14,
                        window=int(os.environ.get("BENCH_WINDOW", "128")))


def run_tpu(genesis, wire_blocks, txs_per_block, machine_stats=None,
            interleave=None):
    from coreth_tpu.types import Block

    # Warm-up pass on throwaway blocks/engine: compiles (or cache-loads)
    # every device executable this workload shape needs — the recover
    # kernel bucket, the window scan buckets, the rehash kernel.  XLA
    # compile/load is a per-process one-time cost, excluded from timing
    # exactly like the first-block warm-up the round-1 bench did.
    # A PREFIX suffices: every bucket the full chain exercises appears
    # within the first two engine windows (the shapes are constant per
    # workload), so warming 2*window+1 blocks compiles everything while
    # costing ~1/4 of a timed rep instead of a whole one.
    window = int(os.environ.get("BENCH_WINDOW", "128"))
    warm_n = min(len(wire_blocks),
                 int(os.environ.get("BENCH_WARM_BLOCKS",
                                    str(2 * window + 1))))
    warm_blocks = [Block.decode(w) for w in wire_blocks[:warm_n]]
    warm = _fresh_engine(genesis, txs_per_block)
    warm.replay_block(warm_blocks[0])
    warm.replay(warm_blocks[1:])
    assert warm.root == warm_blocks[-1].header.root
    assert warm.stats.blocks_fallback == 0, warm.stats.row()

    # Timed passes: fresh Block objects (no cached senders), fresh state
    # each rep; compiled executables are shared via the XLA cache.
    from coreth_tpu.evm.device import adapter as _adapter
    tps_runs, stats = [], None
    for r in range(REPS):
        # interleave (when given) runs one rep of the section's OTHER
        # engine — the compiled denominator — between device reps,
        # alternating device-first/native-first per round so neither
        # side systematically samples a colder machine; both calls sit
        # OUTSIDE the timed region below
        if interleave is not None and r % 2 == 1:
            interleave()
        blocks = [Block.decode(w) for w in wire_blocks]
        engine = _fresh_engine(genesis, txs_per_block)
        engine.replay_block(blocks[0])
        d0 = _adapter.DISPATCH_COUNT
        # snapshot commit counters AFTER block 0: the attribution
        # below must cover exactly the timed region
        cp = engine.commit_pipe
        trie0, fold_s0 = engine.stats.t_trie, cp.fold_s
        fold_b0, fold_c0 = cp.fold_blocks, cp.fold_calls
        t0 = time.monotonic()
        engine.replay(blocks[1:])
        dt = time.monotonic() - t0
        txs = sum(len(b.transactions) for b in blocks[1:])
        assert engine.root == blocks[-1].header.root
        assert engine.stats.blocks_fallback == 0, engine.stats.row()
        tps_runs.append(txs / dt)
        stats = engine.stats.row()
        # commit-phase attribution (replay/commit.py): pure fold+rehash
        # time per block and the t_trie share of replay wall time pin
        # the window-batched trie-commit speedup in the JSON
        stats["fold_ms_per_block"] = round(
            1000 * (cp.fold_s - fold_s0)
            / max(1, cp.fold_blocks - fold_b0), 3)
        stats["fold_windows"] = cp.fold_calls - fold_c0
        stats["t_trie_share"] = round(
            (stats["t_trie"] - trie0) / dt, 3)
        if machine_stats is not None and hasattr(engine, "_machine"):
            mx = engine._machine
            disp = _adapter.DISPATCH_COUNT - d0
            mc = mx.machine_counters()
            machine_stats.update(
                occ_rounds=mx.rounds,
                host_txs=mx.host_txs,
                # predicted premaps + recompile-free growth (the CI
                # gates pin kernel_retraces == 0 and the erc20
                # dispatches_per_block bound in tier-1)
                discovery_dispatches=mc["discovery_dispatches"],
                premap_predicted=mc["premap_predicted"],
                premap_hit_rate=round(
                    mc["premap_hits"]
                    / max(1, mc["premap_predicted"]), 3),
                premap_array=mc["premap_array"],
                kernel_retraces=mc["kernel_retraces"],
                # key-range placement surface (0 on single-device /
                # cold-contract runs): lanes placed by key range and
                # the max/mean per-shard occupancy ratio
                kr_lanes=mc["kr_lanes"],
                load_imbalance=round(
                    mc["load_imb_sum"]
                    / max(1, mc["load_imb_windows"]) / 1000, 3),
                # per-contract traced specialization (ISSUE 13): how
                # many lanes ran straight-line sub-programs vs the
                # generic interpreter escape hatch
                lanes_specialized=mc["lanes_specialized"],
                specialize_escapes=mc["specialize_escapes"],
                programs_traced=mc["programs_traced"],
                # which executor served host-side txs: native_txs ran
                # on the compiled backend (evm/hostexec — serial
                # short-circuit blocks + natively-served conflict
                # suffix), host_txs - suffix natives on the Python
                # interpreter
                native_txs=mx.native_txs,
                serial_blocks=mx.serial_blocks,
                machine_blocks=mx.blocks,
                dirty_blocks=mx.dirty_blocks,
                occ_windows=mx.windows,
                window_attempts=mx.window_attempts,
                # the tentpole metric: device dispatches per machine
                # block (round-5 host OCC loop paid O(txs); the fused
                # device-resident loop pays O(1))
                dispatches=disp,
                dispatches_per_block=round(disp / max(1, mx.blocks), 2))
        if interleave is not None and r % 2 == 0 \
                and not _deadline_tight():
            interleave()
        if _deadline_tight():
            break
    return tps_runs, stats


def run_trie_backend_compare(workload, n_blocks=64):
    """fold_ms_per_block per trie backend, ONE rep each on the same
    truncated chain — pins the native-vs-python commit-path ratio
    (ISSUE 4 acceptance: >= 3x) in the JSON instead of claiming it."""
    from coreth_tpu.types import Block
    from coreth_tpu.mpt import native_trie
    genesis, blocks = build_or_load_chain(workload)
    wire = [b.encode() for b in blocks[:n_blocks]]
    txs_per_block = _txs_per_block(workload)
    out = {}
    saved = os.environ.get("CORETH_TRIE")
    try:
        for backend in ("native", "py"):
            if backend == "native" and not native_trie.available():
                continue
            os.environ["CORETH_TRIE"] = backend
            blks = [Block.decode(w) for w in wire]
            engine = _fresh_engine(genesis, txs_per_block)
            engine.replay_block(blks[0])
            cp = engine.commit_pipe
            fold_s0, fold_b0 = cp.fold_s, cp.fold_blocks
            engine.replay(blks[1:])
            assert engine.root == blks[-1].header.root
            # a host-fallback block would shrink this backend's fold
            # coverage and skew the published ratio — fail loudly
            assert engine.stats.blocks_fallback == 0, engine.stats.row()
            out[f"fold_ms_per_block_{backend}"] = round(
                1000 * (cp.fold_s - fold_s0)
                / max(1, cp.fold_blocks - fold_b0), 3)
            if _deadline_tight():
                break
    finally:
        if saved is None:
            os.environ.pop("CORETH_TRIE", None)
        else:
            os.environ["CORETH_TRIE"] = saved
    native_ms = out.get("fold_ms_per_block_native")
    py_ms = out.get("fold_ms_per_block_py")
    if native_ms and py_ms:
        out["fold_speedup"] = round(py_ms / native_ms, 2)
    return out


def run_workload(workload, baseline_blocks, tpu_blocks=None,
                 machine_stats=None, skip_baselines=False,
                 commit_stats=None, interleave=None):
    genesis, blocks = build_or_load_chain(workload)
    wire = [b.encode() for b in blocks]
    base_runs = base_timers = None
    native_runs = native_phases = None
    from coreth_tpu.crypto import native as _native
    if not skip_baselines:
        base_runs, base_timers = run_baseline(genesis, wire,
                                              baseline_blocks)
    # the TPU reps run BEFORE the native baseline: when the section
    # slice is tight, the primary measurement degrades last — the
    # compiled denominator gives up reps first
    tpu_wire = wire[:tpu_blocks] if tpu_blocks else wire
    tpu_runs, tpu_stats = run_tpu(genesis, tpu_wire,
                                  _txs_per_block(workload),
                                  machine_stats=machine_stats,
                                  interleave=interleave)
    if commit_stats is not None and tpu_stats is not None:
        from coreth_tpu.mpt import native_trie
        commit_stats.update(
            trie_backend=native_trie.backend(),
            fold_ms_per_block=tpu_stats.get("fold_ms_per_block"),
            fold_windows=tpu_stats.get("fold_windows"),
            t_trie_share=tpu_stats.get("t_trie_share"))
    if not skip_baselines and _native.load() is not None:
        if workload == "transfer":
            native_runs, native_phases = run_native_baseline(
                genesis, wire)
        else:
            native_runs, native_phases = run_native_evm(genesis, wire)
    if os.environ.get("BENCH_VERBOSE"):
        if base_runs:
            print(f"[{workload}] py-host baseline",
                  [round(x) for x in base_runs], "txs/s", base_timers,
                  file=sys.stderr)
        if native_runs:
            print(f"[{workload}] native baseline",
                  [round(x) for x in native_runs], "txs/s", native_phases,
                  file=sys.stderr)
        print(f"[{workload}] tpu", [round(x) for x in tpu_runs], "txs/s",
              tpu_stats, file=sys.stderr)
    return base_runs, tpu_runs, native_runs


def run_specialize():
    """Specialization section (ISSUE 13 / ROADMAP direction 1): the
    erc20-machine path replayed with CORETH_SPECIALIZE=1 and =0, each
    under an installed tracer, so the before/after is ATTRIBUTED — the
    dispatch (machine/window_issue), fetch (machine/window_complete)
    and fold (commit/flush) span shares of replay wall time — instead
    of argued from aggregate txs/s.  The regression signal is the
    spec/generic RATIO (the bench-drift rule: ratios, never absolute
    txs/s); the tentpole acceptance gate (erc20-machine >= 1x the
    native sequential engine) is recorded next to it in main()."""
    from coreth_tpu import obs
    from coreth_tpu.evm.census import jump_profile
    from coreth_tpu.types import Block
    from coreth_tpu.workloads.erc20 import TOKEN_RUNTIME
    genesis, blocks = build_or_load_chain("erc20")
    n = min(len(blocks), MACHINE_BLOCKS)
    wire = [b.encode() for b in blocks[:n]]
    # static eligibility profile of the hot contract: how much of its
    # jump structure is the direct-push idiom the tracer resolves
    jumps, push_jumps = jump_profile(TOKEN_RUNTIME)
    out = {"blocks": n,
           "eligibility": {"jumps": jumps, "push_jumps": push_jumps}}
    os.environ["CORETH_NO_TOKEN_FASTPATH"] = "1"
    prev_env = os.environ.pop("CORETH_TRACE", None)
    try:
        for label, spec in (("specialized", "1"), ("generic", "0")):
            os.environ["CORETH_SPECIALIZE"] = spec
            # warm rep: each side owns distinct kernel buckets (the
            # program set is part of the kernel key), so compiles must
            # not skew the A/B
            warm = [Block.decode(w) for w in wire]
            engine = _fresh_engine(genesis, ERC20_TXS)
            engine.replay_block(warm[0])
            engine.replay(warm[1:])
            assert engine.root == warm[-1].header.root
            tracer = obs.install()
            try:
                fresh = [Block.decode(w) for w in wire]
                engine = _fresh_engine(genesis, ERC20_TXS)
                engine.replay_block(fresh[0])
                t0 = time.monotonic()
                engine.replay(fresh[1:])
                dt = time.monotonic() - t0
            finally:
                obs.uninstall()
            assert engine.root == fresh[-1].header.root
            txs = sum(len(b.transactions) for b in fresh[1:])
            mc = engine._machine.machine_counters()
            sums = {}
            for ev in tracer.export()["traceEvents"]:
                if ev.get("ph") == "X":
                    sums[ev["name"]] = sums.get(ev["name"], 0.0) \
                        + float(ev.get("dur", 0.0))
            total = max(dt * 1e6, 1e-9)
            out[label] = {
                "txs_s": round(txs / dt, 1),
                "lanes_specialized": mc["lanes_specialized"],
                "specialize_escapes": mc["specialize_escapes"],
                "programs_traced": mc["programs_traced"],
                "kernel_retraces": mc["kernel_retraces"],
                "shares": {
                    "dispatch": round(
                        sums.get("machine/window_issue", 0) / total, 3),
                    "fetch": round(
                        sums.get("machine/window_complete", 0) / total,
                        3),
                    "fold": round(
                        sums.get("commit/flush", 0) / total, 3),
                },
            }
            if _deadline_tight():
                break
    finally:
        os.environ.pop("CORETH_SPECIALIZE", None)
        del os.environ["CORETH_NO_TOKEN_FASTPATH"]
        if prev_env is not None:
            os.environ["CORETH_TRACE"] = prev_env
    if "specialized" in out and "generic" in out:
        out["spec_vs_generic"] = round(
            out["specialized"]["txs_s"]
            / max(out["generic"]["txs_s"], 1e-9), 3)
    return out


def run_mixed():
    """BASELINE config[4]: Avalanche-semantics segment (atomic ExtData
    imports + nativeAssetCall + transfer spam) under the AP5 rule set.
    Atomic/multicoin blocks ride the exact host path via the engine
    callbacks; the fallback fraction is part of the result."""
    from coreth_tpu.params import TEST_APRICOT_PHASE5_CONFIG
    from coreth_tpu.workloads import mixed as MX
    from coreth_tpu.types import Block
    keys = [0xB0B + i for i in range(64)]
    genesis, blocks = MX.build_mixed_chain(
        TEST_APRICOT_PHASE5_CONFIG, MIXED_BLOCKS, MIXED_TXS, keys)
    # reps decode fresh Block objects from wire so every run pays full
    # sender recovery — same methodology as the other workloads
    wire = [b.encode() for b in blocks]
    want_root = blocks[-1].root
    txs = sum(len(b.transactions) for b in blocks)
    del blocks
    py_runs = []
    for _ in range(REPS):
        fresh = [Block.decode(w) for w in wire]
        chain = MX.host_chain(genesis, MIXED_BLOCKS, keys[0])
        t0 = time.monotonic()
        chain.insert_chain(fresh)
        py_runs.append(txs / (time.monotonic() - t0))
        if _deadline_tight():
            break
    tpu_runs, stats = [], None
    from coreth_tpu.evm import hostexec as _hx
    for _ in range(REPS):
        fresh = [Block.decode(w) for w in wire]
        eng, _g = MX.replay_engine(genesis, MIXED_BLOCKS, keys[0],
                                   window=int(os.environ.get(
                                       "BENCH_WINDOW", "128")))
        _hx.reset_counters()
        t0 = time.monotonic()
        eng.replay(fresh)
        dt = time.monotonic() - t0
        assert eng.root == want_root
        tpu_runs.append(txs / dt)
        stats = eng.stats.row()
        # which executor served the host-fallback blocks' txs
        # (evm/hostexec bridge counters for this rep)
        stats["host_exec"] = _hx.counters()
        if _deadline_tight():
            break
    if os.environ.get("BENCH_VERBOSE"):
        print("[mixed] py-host", [round(x) for x in py_runs], "txs/s",
              file=sys.stderr)
        print("[mixed] tpu", [round(x) for x in tpu_runs], "txs/s",
              stats, file=sys.stderr)
    return py_runs, tpu_runs, stats


def run_streaming():
    """Streaming-ingestion section: the transfer chain through the
    serve pipeline (feed -> prefetch -> execute -> commit), reporting
    p50/p99/max enqueue->committed block latency and sustained txs/s —
    once in backlog mode (feed released as fast as consumed: pipeline
    capacity) and once paced at ~70% of that rate (service latency
    under sustained arrival, the SLO-honest number)."""
    from coreth_tpu.serve import ChainFeed, StreamingPipeline
    from coreth_tpu.types import Block
    genesis, blocks = build_or_load_chain("transfer")
    n = min(len(blocks),
            int(os.environ.get("BENCH_STREAM_BLOCKS", "512")))
    wire = [b.encode() for b in blocks[:n]]
    window = int(os.environ.get("BENCH_STREAM_WINDOW", "32"))
    out = {"blocks": n, "window": window}
    from coreth_tpu import obs

    def one_run(rate=None):
        fresh = [Block.decode(w) for w in wire]
        engine = _fresh_engine(genesis, TXS_PER_BLOCK)
        engine.window = window
        pipe = StreamingPipeline(engine, ChainFeed(fresh, rate=rate),
                                 window_wait=0.005)
        rep = pipe.run()
        assert engine.root == fresh[-1].header.root
        assert engine.stats.blocks_fallback == 0, engine.stats.row()
        return rep

    # the section owns the tracer state: a CORETH_TRACE=1 env must not
    # silently arm the backlog (capacity) rep through arm_from_env
    prev_env = os.environ.pop("CORETH_TRACE", None)
    try:
        obs.uninstall()
        rep = one_run()
        out["backlog"] = rep.row()
        if not _deadline_tight(margin=45.0):
            bps = rep.blocks / max(rep.wall_s, 1e-9)
            rate = round(0.7 * bps, 2)
            out["paced_rate_blocks_s"] = rate
            # the paced (SLO-honest) run carries the tracer so its row
            # records stage_breakdown — where the p50 actually goes at
            # a sustained arrival rate (the tracing section owns the
            # overhead A/B; gated >= 0.95, so attributing here is safe)
            obs.install()
            try:
                out["paced"] = one_run(rate=rate).row()
            finally:
                obs.uninstall()
    finally:
        if prev_env is not None:
            os.environ["CORETH_TRACE"] = prev_env
    return out


def run_tracing():
    """Tracing section (coreth_tpu/obs): per-stage latency attribution
    for a paced streaming run — the tracer's ``stage_breakdown``
    (shares of enqueue->committed time; sums to ~1.0) — plus the
    tracing OVERHEAD ratio: traced vs untraced sustained txs/s on the
    SAME backlog shape, interleaved reps so box drift hits both sides
    equally.  The ratio is the regression signal (the bench-drift
    rule) and must stay >= 0.95: tracing must never become the new
    bottleneck.  The Perfetto export is validated structurally (it
    must load) and its size recorded."""
    from coreth_tpu import obs
    from coreth_tpu.serve import ChainFeed, StreamingPipeline
    from coreth_tpu.types import Block
    genesis, blocks = build_or_load_chain("transfer")
    n = min(len(blocks),
            int(os.environ.get("BENCH_TRACE_BLOCKS", "96")))
    wire = [b.encode() for b in blocks[:n]]
    out = {"blocks": n}

    def one_run(traced, rate=None):
        fresh = [Block.decode(w) for w in wire]
        # CORETH_TRACE=1 in the caller's env would silently arm the
        # "untraced" side through the engine/pipeline constructors'
        # arm_from_env and make the A/B vacuous (traced/traced ~ 1.0):
        # the A/B owns the tracer state for both sides
        prev_env = os.environ.pop("CORETH_TRACE", None)
        tracer = None
        try:
            if traced:
                tracer = obs.install()
            else:
                obs.uninstall()
            engine = _fresh_engine(genesis, TXS_PER_BLOCK)
            pipe = StreamingPipeline(engine, ChainFeed(fresh, rate=rate),
                                     window_wait=0.005)
            rep = pipe.run()
        finally:
            if traced:
                obs.uninstall()
            if prev_env is not None:
                os.environ["CORETH_TRACE"] = prev_env
        assert engine.root == fresh[-1].header.root
        return rep, tracer

    one_run(False)  # warm-up: XLA compiles must not skew the A/B
    plain, traced = [], []
    rep_t = tracer = None
    for _ in range(3):
        rep_p, _none = one_run(False)
        plain.append(rep_p.sustained_txs_s)
        rep_t, tracer = one_run(True)
        traced.append(rep_t.sustained_txs_s)
        if _deadline_tight():
            break
    out["stage_breakdown"] = rep_t.stage_breakdown
    # best-of each side: the gate asks whether tracing lowers the
    # path's CAPACITY, so one straggler rep (GC, a background compile)
    # must not fake a regression on this 1-core box
    out["untraced_txs_s"] = round(max(plain), 1)
    out["traced_txs_s"] = round(max(traced), 1)
    ratio = round(max(traced) / max(max(plain), 1e-9), 3)
    # the acceptance gate: tracing-enabled throughput >= 0.95x
    out["trace_overhead"] = ratio
    out["overhead_ok"] = ratio >= 0.95
    doc = tracer.export()
    out["trace_events"] = len(doc["traceEvents"])
    out["ring_dropped"] = tracer.dropped
    # shares must cover the latency (a breakdown that doesn't sum to
    # ~1.0 means a stage went unattributed)
    share_sum = sum(v for k, v in rep_t.stage_breakdown.items()
                    if not k.startswith("_"))
    out["breakdown_sum"] = round(share_sum, 4)
    return out


def run_forensics():
    """Forensics section (obs/recorder): the divergence flight
    recorder armed vs unarmed on the SAME backlog streaming shape,
    interleaved reps so box drift hits both sides equally.  The
    ``recorder_overhead`` RATIO is the regression signal (bench-drift
    rule) and must stay >= 0.95 — the witness ring must never become
    the new bottleneck.  Plus one INJECTED trip: a poison block
    quarantines, freezes a bundle, and the section records the
    bundle's on-disk size and drain-thread write latency."""
    import shutil
    import tempfile
    from coreth_tpu.obs import recorder as _rec
    from coreth_tpu.serve import ChainFeed, StreamingPipeline
    from coreth_tpu.serve.pipeline import _corrupt_block
    from coreth_tpu.types import Block
    genesis, blocks = build_or_load_chain("transfer")
    n = min(len(blocks),
            int(os.environ.get("BENCH_FORENSICS_BLOCKS", "96")))
    wire = [b.encode() for b in blocks[:n]]
    out = {"blocks": n}
    tmp = tempfile.mkdtemp(prefix="bench_forensics_")

    def one_run(armed, feed_wire=wire, expect_root=True):
        fresh = [Block.decode(w) for w in feed_wire]
        # a CORETH_FORENSICS=1 env must not silently arm the
        # "unarmed" side through arm_from_env (the tracing-A/B rule)
        prev_env = os.environ.pop("CORETH_FORENSICS", None)
        try:
            if armed:
                rec = _rec.install(out_dir=tmp)
            else:
                rec = None
                _rec.uninstall()
            engine = _fresh_engine(genesis, TXS_PER_BLOCK)
            pipe = StreamingPipeline(engine, ChainFeed(fresh),
                                     window_wait=0.005)
            rep = pipe.run()
        finally:
            _rec.uninstall()
            if prev_env is not None:
                os.environ["CORETH_FORENSICS"] = prev_env
        if expect_root:
            assert engine.root == fresh[-1].header.root
        return rep, rec

    try:
        one_run(False)  # warm-up: XLA compiles must not skew the A/B
        plain, armed = [], []
        for r in range(4):
            # alternate which side goes first: on this 1-core box the
            # second run of a pair measures systematically slower
            # (scheduler/GC debt from the first), which read as a fake
            # ~5% recorder overhead when armed always went second
            order = (False, True) if r % 2 == 0 else (True, False)
            for is_armed in order:
                rep_x, _rec0 = one_run(is_armed)
                (armed if is_armed else plain).append(
                    rep_x.sustained_txs_s)
            if _deadline_tight():
                break
        out["unarmed_txs_s"] = round(max(plain), 1)
        out["armed_txs_s"] = round(max(armed), 1)
        ratio = round(max(armed) / max(max(plain), 1e-9), 3)
        # the acceptance gate: recorder-armed throughput >= 0.95x
        out["recorder_overhead"] = ratio
        out["overhead_ok"] = ratio >= 0.95
        # ---- one injected trip -> bundle size / write latency
        if not _deadline_tight():
            trip_wire = list(wire[:8])
            bad = _corrupt_block(Block.decode(trip_wire[-1]))
            trip_wire[-1] = bad.encode()
            rep_t, rec = one_run(True, feed_wire=trip_wire,
                                 expect_root=False)
            snap = rep_t.forensics
            out["trip"] = {
                "quarantined": len(rep_t.quarantined),
                "bundle_writes": snap.get("bundle_writes", 0),
                "bundle_failures": snap.get("bundle_failures", 0),
                "write_ms": snap.get("write_ms", 0.0),
            }
            paths = [b["path"] for b in snap.get("bundles", [])]
            if paths:
                size = sum(
                    os.path.getsize(os.path.join(dp, f))
                    for dp, _dn, fns in os.walk(paths[-1])
                    for f in fns)
                out["trip"]["bundle_bytes"] = size
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def run_flat_state():
    """Flat-state section (state/flat): the cold-read microbench —
    the SAME key population resolved through the flat store vs the
    trie-walk path it replaced — plus checkpoint durability cost ON vs
    OFF the execute thread (background stamp vs synchronous write,
    both recorded) and the layer's hit/miss counters.  All regression
    signals are RATIOS (speedup, stamp-vs-export), never absolute
    txs/s (the bench-drift rule: boxes differ, ratios travel)."""
    from coreth_tpu.replay.checkpoint import CheckpointManager
    from coreth_tpu.serve import ChainFeed, StreamingPipeline
    from coreth_tpu.types import Block
    genesis, blocks = build_or_load_chain("erc20")
    n = min(len(blocks), int(os.environ.get("BENCH_FLAT_BLOCKS", "48")))
    wire = [b.encode() for b in blocks[:n]]
    out = {"blocks": n}

    # ---- replay once with the layer on: counters + key population
    fresh = [Block.decode(w) for w in wire]
    engine = _fresh_engine(genesis, ERC20_TXS)
    if engine.flat is None:
        return {"skipped": "CORETH_FLAT=0"}
    engine.replay_block(fresh[0])
    engine.replay(fresh[1:])
    assert engine.root == fresh[-1].header.root
    engine.commit_pipe.flush()
    flat = engine.flat
    out["counters"] = flat.snapshot()

    # ---- cold-read microbench: flat dict vs the trie-walk path
    # (engine.trie / storage tries — native C++ when built, so the
    # denominator is the FAST pre-flat path, not a strawman)
    addrs = sorted(flat.accounts)[:512]
    slots = sorted((a, k) for a, sub in flat.storage.items()
                   for k in sub)[:512]
    reads = len(addrs) + len(slots)
    reps = max(1, 100_000 // max(1, reads))
    t0 = time.monotonic()
    for _ in range(reps):
        for a in addrs:
            flat.account(a)
        for c, k in slots:
            flat.storage_value(c, k)
    t_flat = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(reps):
        for a in addrs:
            engine.trie.get(a)
        for c, k in slots:
            engine._storage_trie(c).get(k)
    t_trie = time.monotonic() - t0
    out["cold_read"] = {
        "reads": reads * reps,
        "flat_us_per_read": round(1e6 * t_flat / (reads * reps), 3),
        "trie_us_per_read": round(1e6 * t_trie / (reads * reps), 3),
        # the acceptance ratio: >= 3x over the replaced trie-walk path
        "speedup": round(t_trie / max(t_flat, 1e-9), 2),
        "trie_backend": "native" if engine._native else "py",
    }

    # ---- checkpoint durability: background stamp vs sync write, on
    # a real disk-backed store (tempdir FileDB + PersistentNodeDict)
    import shutil
    import tempfile
    from coreth_tpu.rawdb.kv import FileDB
    from coreth_tpu.rawdb.state_manager import (
        PersistentCodeDict, PersistentNodeDict)
    from coreth_tpu.replay import ReplayEngine
    from coreth_tpu.state import Database

    def ckpt_run(sync: bool):
        td = tempfile.mkdtemp(prefix="bench_flat_")
        try:
            kv = FileDB(os.path.join(td, "chain.db"))
            db = Database(node_db=PersistentNodeDict(kv),
                          code_db=PersistentCodeDict(kv))
            gblock = genesis.to_block(db)
            eng = ReplayEngine(genesis.config, db, gblock.root,
                               parent_header=gblock.header,
                               batch_pad=ERC20_TXS,
                               window=int(os.environ.get(
                                   "BENCH_STREAM_WINDOW", "32")))
            if sync:
                os.environ["CORETH_CHECKPOINT_SYNC"] = "1"
            try:
                pipe = StreamingPipeline(
                    eng, ChainFeed([Block.decode(w) for w in wire]),
                    window_wait=0.005, checkpoint_every=8)
                t0 = time.monotonic()
                rep = pipe.run()
                wall = time.monotonic() - t0
            finally:
                os.environ.pop("CORETH_CHECKPOINT_SYNC", None)
            assert eng.root == fresh[-1].header.root
            kv.close()
            return rep, wall
        finally:
            shutil.rmtree(td, ignore_errors=True)

    rep_bg, wall_bg = ckpt_run(sync=False)
    ck = rep_bg.checkpoint
    out["checkpoint_background"] = {
        "wall_s": round(wall_bg, 3),
        "records": ck["written"],
        # the execute thread only pays the stamps...
        "stamp_us": ck["stamp_us"],
        # ...while the exporter thread pays the Merkleization + fsync
        "export_ms": ck["exporter"]["export_ms"],
        "entries": ck["exporter"]["entries_written"],
    }
    if not _deadline_tight():
        rep_sy, wall_sy = ckpt_run(sync=True)
        cks = rep_sy.checkpoint
        out["checkpoint_sync"] = {
            "wall_s": round(wall_sy, 3),
            "records": cks["written"],
            "write_ms": cks["write_ms"],   # on the execute thread
        }
        # the tentpole ratio: execute-thread durability cost,
        # background stamps vs synchronous exports
        stamp_ms = max(ck["stamp_us"] / 1000.0, 1e-3)
        out["execute_thread_cost_ratio"] = round(
            cks["write_ms"] / stamp_ms, 1)
    return out


def run_faults():
    """Fault-tolerance section: canned fault plans over a small
    transfer chain, reporting what the supervisor DID about them —
    demotion counts, retry counts, the demote latency (wall seconds
    from first strike to routing around the dead backend), the
    recovery wall (completing the whole chain on the host ladder), and
    the quarantine path's behavior on a poison block."""
    from coreth_tpu import faults as F
    from coreth_tpu.serve import ChainFeed, StreamingPipeline
    from coreth_tpu.types import Block
    genesis, blocks = build_or_load_chain("transfer")
    n = min(len(blocks), int(os.environ.get("BENCH_FAULT_BLOCKS", "64")))
    wire = [b.encode() for b in blocks[:n]]
    out = {"blocks": n}

    def one_run(plan, **pipe_kw):
        fresh = [Block.decode(w) for w in wire]
        engine = _fresh_engine(genesis, TXS_PER_BLOCK)
        with F.armed(plan):
            pipe = StreamingPipeline(engine, ChainFeed(fresh),
                                     window_wait=0.005, **pipe_kw)
            t0 = time.monotonic()
            rep = pipe.run()
            wall = time.monotonic() - t0
        assert engine.root == fresh[-1].header.root, "faulted run root"
        return engine, rep, wall

    # persistent device-dispatch failure: demote, finish on the host
    eng, rep, wall = one_run(F.FaultPlan(
        {"device/dispatch": F.FaultSpec()}))
    sup = rep.supervisor
    out["persistent_device"] = {
        "wall_s": round(wall, 3),
        "demotions": sup["demotions"],
        "retries": sup["retries"],
        "demote_latency_s": sup["demote_latency_s"].get("device"),
        "blocks_fallback": eng.stats.blocks_fallback,
        "sustained_txs_s": rep.sustained_txs_s,
    }

    # transient fault: retries absorb it, no demotion, device path kept
    eng, rep, wall = one_run(F.FaultPlan(
        {"device/dispatch": F.FaultSpec(times=2, transient=True)}))
    out["transient_device"] = {
        "wall_s": round(wall, 3),
        "retries": rep.supervisor["retries"],
        "demotions": rep.supervisor["demotions"],
        "blocks_device": eng.stats.blocks_device,
    }

    # poison block: quarantined + the stream keeps moving
    eng, rep, wall = one_run(F.FaultPlan(
        {"serve/malformed_block": F.FaultSpec(after=n // 2, times=1)}))
    out["poison_block"] = {
        "wall_s": round(wall, 3),
        "quarantined": len(rep.quarantined),
        "halted": rep.halted,
        "blocks": rep.blocks,
    }
    return out


def run_multichip_section(env_extra=None, out_name="multichip_bench"):
    """Fold the virtual-mesh scaling curve (tools/mesh_scaling.py)
    into the same deadline budget: a truncated shape in a subprocess
    (the virtual device count must be set before jax initializes, so
    it cannot run in-process), parsed from its stdout JSON."""
    import subprocess
    budget = max(20.0, min(_section_left(), _remaining() - 12.0))
    env = dict(os.environ)
    env.setdefault("SCALE_BLOCKS", "4")
    env.setdefault("SCALE_TXS", "128")
    env.setdefault("SCALE_REPS", "1")
    env.update(env_extra or {})
    # the truncated in-bench shape must not clobber the standalone
    # harness's committed artifact
    env["SCALE_OUT"] = os.path.join(_DIR, ".bench_cache",
                                    f"{out_name}.json")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(_DIR, "tools",
                                          "mesh_scaling.py")],
            capture_output=True, text=True, timeout=budget, env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"deadline: mesh_scaling exceeded {budget:.0f}s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}",
                "tail": (r.stderr or r.stdout)[-300:]}
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError) as exc:
        return {"error": f"parse: {exc}"}


def run_hot_contract():
    """Single-hot-contract section (ISSUE 14): ONE ERC-20-shaped
    contract takes 100% of txs with Zipf sender/recipient skew, forced
    through the general machine path (the key-range placement shape).
    Per the bench-drift rule the section reports sustained txs/s plus
    RATIOS only: vs_native (compiled C++ EVM replay of the same chain)
    and vs_1dev (2-device / 1-device sustained txs/s from the
    mesh-scaling subprocess — the flat-curve acceptance number),
    plus the load_imbalance placement counter."""
    from coreth_tpu import rlp
    from coreth_tpu.params import TEST_CHAIN_CONFIG as CFG
    from coreth_tpu.replay import ReplayEngine
    from coreth_tpu.state import Database
    from coreth_tpu.types import Block
    from coreth_tpu.workloads import hot_contract as HC
    n_blocks = int(os.environ.get("BENCH_HOT_BLOCKS", "64"))
    txs = int(os.environ.get("BENCH_HOT_TXS", "128"))
    if _section_left() < 120:
        n_blocks = min(n_blocks, 16)
    n_keys = min(256, N_KEYS)
    seed, alpha = 20260804, 1.1
    # genesis comes from the workload module (one key-derivation
    # site), and the cache name carries every chain parameter so a
    # workload-default change can never replay a stale cached chain
    # against a fresh genesis
    genesis, _keys, _addrs = HC.hot_genesis(CFG, n_keys)
    cache = os.path.join(
        _DIR, ".bench_cache",
        f"hot_{n_blocks}x{txs}k{n_keys}s{seed}a{alpha}.bin")
    if os.path.exists(cache):
        blocks = [Block.decode(b)
                  for b in rlp.decode(open(cache, "rb").read())]
    else:
        _g, blocks = HC.build_hot_chain(CFG, n_blocks, txs,
                                        n_keys=n_keys, alpha=alpha,
                                        seed=seed)
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        with open(cache, "wb") as f:
            f.write(rlp.encode([b.encode() for b in blocks]))
    wire = [b.encode() for b in blocks]
    out = {"blocks": len(blocks), "txs_per_block": txs}

    saved = os.environ.get("CORETH_NO_TOKEN_FASTPATH")
    os.environ["CORETH_NO_TOKEN_FASTPATH"] = "1"
    try:
        def one_rep():
            fresh = [Block.decode(w) for w in wire]
            db = Database()
            gb = genesis.to_block(db)
            eng = ReplayEngine(CFG, db, gb.root,
                               parent_header=gb.header,
                               capacity=1 << 13,
                               slot_capacity=1 << 13,
                               batch_pad=txs, window=16)
            eng.replay_block(fresh[0])
            t0 = time.monotonic()
            eng.replay(fresh[1:])
            dt = time.monotonic() - t0
            assert eng.root == fresh[-1].header.root
            assert eng.stats.blocks_fallback == 0, eng.stats.row()
            n_txs = sum(len(b.transactions) for b in fresh[1:])
            return n_txs / dt, eng

        one_rep()  # compile warm-up, untimed
        # native denominator reps interleaved with the device reps
        # (device-first on even rounds, native-first on odd — the
        # PR-15 alternation): vs_native then compares two samples of
        # the SAME load window instead of a device phase followed by
        # a native phase
        nat_runs = []
        nat_rep = _native_evm_rep(genesis, blocks, nat_runs)
        tps_runs = []
        eng = None
        for r in range(REPS):
            if nat_rep is not None and r % 2 == 1:
                nat_rep()
            tps, eng = one_rep()
            tps_runs.append(tps)
            if nat_rep is not None and r % 2 == 0 \
                    and not _deadline_tight():
                nat_rep()
            if _deadline_tight():
                break
        mc = eng._machine.machine_counters()
        out.update({
            "txs_s": round(_median(tps_runs), 1),
            "spread_txs_s": _spread(tps_runs),
            # single-device in-process reps: key-range placement only
            # exists on a mesh, so kr_lanes/load_imbalance here would
            # read as a structural 0 — the placement surface comes
            # from the multichip subprocess below
            "machine": {
                "kernel_retraces": mc["kernel_retraces"],
                "premap_hit_rate": round(
                    mc["premap_hits"]
                    / max(1, mc["premap_predicted"]), 3),
                "lanes_specialized": mc["lanes_specialized"],
            },
        })
        if nat_runs:
            out["vs_native"] = round(
                _median(tps_runs) / _median(nat_runs), 3)
    finally:
        if saved is None:
            os.environ.pop("CORETH_NO_TOKEN_FASTPATH", None)
        else:
            os.environ["CORETH_NO_TOKEN_FASTPATH"] = saved

    # the flat-curve acceptance ratio: 2-device vs 1-device sustained
    # txs/s on the SAME hot shape (machine path, key-range placement),
    # measured by the mesh-scaling subprocess on the virtual mesh
    if not _deadline_tight(45.0):
        curve = run_multichip_section(
            env_extra={"SCALE_WORKLOAD": "hot_contract",
                       "SCALE_POINTS": "1,2",
                       "SCALE_BLOCKS": "4",
                       "SCALE_TXS": str(min(txs, 128)),
                       "SCALE_REPS": "2"},
            out_name="hot_multichip_bench")
        pts = {p["n_devices"]: p for p in curve.get("points", [])}
        if 1 in pts and 2 in pts:
            out["vs_1dev"] = round(
                pts[2]["txs_s_median"] / pts[1]["txs_s_median"], 3)
            # max/mean per-shard lane occupancy at 2 devices (the
            # key-range placement surface; n == collapse)
            out["load_imbalance_2dev"] = pts[2].get("load_imbalance")
        elif "error" in curve:
            out["multichip_error"] = curve["error"]
    return out


def run_cluster():
    """Distributed-serving section (serve/cluster): the transfer
    chain's head range-partitioned across subprocess workers over the
    length-prefixed control protocol, every boundary root verified by
    the aggregator.  Per the bench-drift rule the section leads with
    RATIOS: scale_2w_vs_1w compares cluster sustained txs/s at two
    worker widths (serve span from the federated lane reports —
    sequential lanes SUM their pipeline walls, concurrent lanes take
    the MAX), next to p99 block latency at both widths and a recovery
    probe (injected SIGKILL mid-stream; the outage window is read off
    the coordinator's event log).  Workers run host-platform jax (an
    accelerator is single-owner; N processes cannot share it), so on
    an N-core host the ratio measures real lane parallelism — on ONE
    core it honestly reads ~1x and scaling_evaluable marks the >=1.5x
    gate unratable rather than failed."""
    import shutil
    import tempfile
    from dataclasses import replace as _dc_replace
    from coreth_tpu import rlp
    from coreth_tpu.serve.cluster import (
        ClusterCoordinator, bootstrap_stores, partition_ranges,
    )
    n_blocks = int(os.environ.get("BENCH_CLUSTER_BLOCKS", "64"))
    genesis, blocks = build_or_load_chain("transfer")
    blocks = blocks[:n_blocks]
    cpus = os.cpu_count() or 1
    out = {"blocks": len(blocks), "txs_per_block": TXS_PER_BLOCK,
           "host_cpus": cpus, "scaling_evaluable": cpus >= 2}
    need = N_KEYS + len(blocks) * TXS_PER_BLOCK // 2 + 1024
    ekw = dict(capacity=1 << max(13, (need - 1).bit_length()),
               batch_pad=TXS_PER_BLOCK, window=8)
    base = tempfile.mkdtemp(prefix="coreth_cluster_bench_")
    try:
        chain_path = os.path.join(base, "chain.rlp")
        with open(chain_path, "wb") as f:
            f.write(rlp.encode([b.encode() for b in blocks]))
        # ONE bootstrap replay (untimed — the warm-start a real
        # cluster gets from state sync); every run below gets fresh
        # COPIES of the seeded lane stores so a finished run can
        # never leak tip state into the next one's resume
        seeds = bootstrap_stores(genesis.config, genesis, blocks,
                                 partition_ranges(len(blocks), 2),
                                 base, engine_kw=ekw)
        env = {
            "JAX_PLATFORMS": os.environ.get("BENCH_CLUSTER_PLATFORM",
                                            "cpu"),
            "JAX_COMPILATION_CACHE_DIR": _cache_dir,
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1.0",
            "CORETH_CHECKPOINT_SYNC": "1",  # deterministic records
            "CORETH_TELEMETRY_PORT": "",    # no per-worker server
            "CORETH_TRACE": "1",            # federated stage rows
        }

        def fresh_seeds(tag):
            copies = []
            for s in seeds:
                dst = os.path.join(base, tag, s.lane)
                os.makedirs(dst, exist_ok=True)
                shutil.copyfile(os.path.join(s.db_dir, "chain.db"),
                                os.path.join(dst, "chain.db"))
                copies.append(_dc_replace(s, db_dir=dst))
            return copies

        def one_run(tag, n_workers, victim_env=None):
            coord = ClusterCoordinator(
                fresh_seeds(tag), chain_path, config="test",
                expected_tip=blocks[-1].header.root, engine_kw=ekw,
                checkpoint_every=4,
                # grace covers subprocess startup (imports + compile
                # cache load); the timeout POLICY itself is pinned by
                # the stepped-clock units in tests/test_cluster.py
                heartbeat_timeout=90.0,
                worker_env={"*": env, **({"w0": victim_env}
                                         if victim_env else {})})
            coord.start(n_workers)
            return coord.run(deadline_s=max(
                60.0, min(240.0, _section_left() - 5.0)))

        def width_row(summary):
            lanes = [l for l in summary["lanes"] if l["report"]]
            walls = [l["report"].get("wall_s") or 0.0 for l in lanes]
            served_by = {(l["history"] or [None])[-1] for l in lanes}
            # one worker serves lanes back-to-back (walls add up);
            # distinct workers overlap (the longest lane bounds)
            serve_s = (max(walls) if len(served_by) > 1
                       else sum(walls)) or None
            return {
                "txs": summary["txs"],
                "wall_s": round(summary["wall_s"], 2),
                "serve_s": round(serve_s, 2) if serve_s else None,
                "txs_s": (round(summary["txs"] / serve_s, 1)
                          if serve_s else None),
                "p99_ms": max((l["report"]["latency_ms"]["p99"]
                               for l in lanes), default=None),
                "verified": summary["verified"],
                "lanes": [{
                    "lane": l["lane"],
                    "worker": (l["history"] or [None])[-1],
                    "sustained_txs_s":
                        l["report"].get("sustained_txs_s"),
                    "wall_s": l["report"].get("wall_s"),
                    "p99_ms": l["report"]["latency_ms"]["p99"],
                    "stage_breakdown":
                        l["report"].get("stage_breakdown"),
                } for l in lanes],
            }

        # 1-worker first: it pays the workers' compile-cache
        # population the 2-worker and recovery runs then reload
        for n in (1, 2):
            key = f"{n}w"
            if n > 1 and _deadline_tight(45.0):
                out.setdefault("deadline_skipped", []).append(key)
                break
            try:
                out[key] = width_row(one_run(key, n))
            except Exception as exc:  # noqa: BLE001 — a failed width must not sink the section (partial emission keeps the rest)
                out[key] = {"error": f"{type(exc).__name__}: {exc}"}
        r1, r2 = out.get("1w", {}), out.get("2w", {})
        if r1.get("txs_s") and r2.get("txs_s"):
            ratio = round(r2["txs_s"] / r1["txs_s"], 3)
            out["scale_2w_vs_1w"] = ratio
            # the >=1.5x gate needs real cores to scale onto; a
            # 1-core host reports the honest ~1x wall-clock ratio
            # and marks itself core-bound instead of failing
            out["scale_2w_vs_1w_ok"] = (
                ratio >= 1.5 if out["scaling_evaluable"] else None)
            if not out["scaling_evaluable"]:
                out["core_bound"] = True

        # recovery probe: the victim carries an armed SIGKILL on its
        # 9th committed block — one full window PAST the first
        # durable record (window=8, every=4, sync writes), the same
        # timing argument as tests/test_cluster_handoff.py.  That
        # timing needs the victim lane to outlive its first full
        # window: serve/crash fires before the checkpoint cadence
        # inside a commit batch, so on a lane of <= window blocks the
        # kill either never fires or lands with nothing durable past
        # the seed — report that honestly instead of a no-op "crash"
        s0, e0 = partition_ranges(len(blocks), 2)[0]
        if e0 - s0 <= ekw["window"]:
            out["recovery"] = {
                "skipped": "victim lane has <= window blocks; the "
                           "injected kill cannot land past a durable "
                           "record (raise BENCH_CLUSTER_BLOCKS)"}
        elif not _deadline_tight(45.0):
            victim = {"CORETH_FAULT_PLAN": json.dumps(
                {"serve/crash": {"action": "sigkill",
                                 "after": ekw["window"]}})}
            try:
                summary = one_run("recovery", 2, victim_env=victim)

                def first_t(name):
                    for e in summary["events"]:
                        if e["event"] == name:
                            return e.get("t")
                    return None

                t_crash = first_t("worker_crash")
                t_assign = first_t("reassigned")
                t_first = first_t("first_commit_after_recovery")
                lane0 = summary["lanes"][0]
                out["recovery"] = {
                    "verified": summary["verified"],
                    "resumed_from": lane0["resumed_from"],
                    "failures": lane0["failures"],
                    # outage = crash detection to the lane's first
                    # post-handoff commit; resume_s isolates the
                    # handoff itself (assign -> first commit)
                    "recovery_s": (round(t_first - t_crash, 2)
                                   if t_crash is not None
                                   and t_first is not None else None),
                    "resume_s": (round(t_first - t_assign, 2)
                                 if t_assign is not None
                                 and t_first is not None else None),
                }
            except Exception as exc:  # noqa: BLE001 — same partial-emission argument as the width runs
                out["recovery"] = {
                    "error": f"{type(exc).__name__}: {exc}"}
        else:
            out.setdefault("deadline_skipped", []).append("recovery")
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return out


def _begin_section(frac_end):
    """Advance the section budget slice; its rep loops and chain build
    stop when the slice (T0 + frac_end * DEADLINE) is spent."""
    global SECTION_END
    SECTION_END = T0 + DEADLINE * frac_end


def main():
    # every section is deadline-guarded; whatever finished by the
    # budget is what the JSON line reports (missing sections -> null);
    # the watchdog guarantees the line prints even if a section wedges
    RESULT.update({
        "metric": "transfer_replay_throughput",
        "value": None,
        "unit": "txs/s",
        "reps": REPS,
        "deadline_s": DEADLINE,
        "host": {"cpus": os.cpu_count(),
                 "loadavg": [round(x, 2) for x in os.getloadavg()]},
    })
    _WATCHDOG.start()
    _spawn_watchdog_child()
    _maybe_wedge()  # BENCH_WEDGE: watchdog regression harness
    result = RESULT
    skipped = []
    try:
        _begin_section(0.30)
        commit_stats = {}
        py_runs, tpu_runs, native_runs = run_workload(
            "transfer", BASELINE_BLOCKS, commit_stats=commit_stats)
        py_tps, tpu_tps = _median(py_runs), _median(tpu_runs)
        native_tps = _median(native_runs) if native_runs else None
        if _remaining() > 60:
            # native-vs-python trie backend on the same chain: the
            # commit-path ratio the window-batched fold is judged by
            commit_stats.update(run_trie_backend_compare("transfer"))
        result.update({
            "commit": commit_stats,
            "value": round(tpu_tps, 1),
            # primary ratio: median TPU / median compiled sequential
            # C++ replay (the Go-proxy baseline, BASELINE.md) — the
            # honest denominator; falls back to the Python host path
            # where the native build is unavailable
            "vs_baseline": round(tpu_tps / (native_tps or py_tps), 2),
            "tpu_spread_txs_s": _spread(tpu_runs),
            "native_baseline_txs_s":
                round(native_tps, 1) if native_tps else None,
            "native_spread_txs_s":
                _spread(native_runs) if native_runs else None,
            "vs_py_host": round(tpu_tps / py_tps, 2),
        })
        _section_done("transfer")

        erc20_native_tps = None
        _begin_section(0.48)
        if _remaining() > 45:
            e20_commit = {}
            erc20_py, erc20_tpu, erc20_native = run_workload(
                "erc20", ERC20_BASELINE_BLOCKS, commit_stats=e20_commit)
            erc20_native_tps = _median(erc20_native) if erc20_native \
                else None
            if _remaining() > 60:
                e20_commit.update(run_trie_backend_compare("erc20"))
            result.update({
                "erc20_commit": e20_commit,
                "erc20_txs_s": round(_median(erc20_tpu), 1),
                "erc20_spread_txs_s": _spread(erc20_tpu),
                "erc20_vs_native": (
                    round(_median(erc20_tpu) / erc20_native_tps, 3)
                    if erc20_native_tps else None),
                "erc20_native_txs_s": (round(erc20_native_tps, 1)
                                       if erc20_native_tps else None),
                "erc20_vs_py_host": round(
                    _median(erc20_tpu) / _median(erc20_py), 2),
            })
            _section_done("erc20")
        else:
            skipped.append("erc20")

        _begin_section(0.63)
        if _remaining() > 45:
            # the SAME erc20 chain forced through the general step
            # machine (no fast-path classification): config[1] through
            # SURVEY 7.4 + the fused device-resident OCC windows
            os.environ["CORETH_NO_TOKEN_FASTPATH"] = "1"
            mstats = {}
            # the native denominator reps run INTERLEAVED with the
            # machine-path device reps (the A/B/A/B pattern): the
            # earlier-section erc20_native_tps was measured minutes
            # before on a possibly different machine-load window,
            # which made this section's headline ratio drift run to
            # run; it survives only as the fallback when the native
            # build is absent
            em_genesis, em_blocks = build_or_load_chain("erc20")
            em_native_runs = []
            em_rep = _native_evm_rep(em_genesis,
                                     em_blocks[:MACHINE_BLOCKS],
                                     em_native_runs)
            _, erc20m_tpu, _ = run_workload(
                "erc20", ERC20_BASELINE_BLOCKS,
                tpu_blocks=MACHINE_BLOCKS,
                machine_stats=mstats, skip_baselines=True,
                interleave=em_rep)
            del os.environ["CORETH_NO_TOKEN_FASTPATH"]
            em_native_tps = (_median(em_native_runs)
                             if em_native_runs else erc20_native_tps)
            emv = (round(_median(erc20m_tpu) / em_native_tps, 3)
                   if em_native_tps else None)
            result.update({
                "erc20_machine_txs_s": round(_median(erc20m_tpu), 1),
                "erc20_machine_native_txs_s": (
                    round(em_native_tps, 1) if em_native_tps else None),
                "erc20_machine_vs_native": emv,
                # THE tentpole acceptance gate (ISSUE 13 / ROADMAP
                # direction 1): the fused OCC path with per-contract
                # specialization must be at least the native
                # sequential engine on the same chain (a RATIO per
                # the bench-drift rule)
                "erc20_machine_vs_native_ok": (
                    emv is not None and emv >= 1.0),
                "erc20_machine_stats": mstats,
            })
            _section_done("erc20_machine")
            if not _deadline_tight(margin=60.0):
                # specialization A/B with traced dispatch/fetch/fold
                # attribution (the CORETH_SPECIALIZE=0|1 before/after)
                result["specialize"] = run_specialize()
                _section_done("specialize")
        else:
            skipped.append("erc20_machine")

        _begin_section(0.74)
        if _remaining() > 45:
            # contention workload (config[3]): fully serial conflict
            # chains — the OCC rounds now run INSIDE one dispatch per
            # window of blocks; swap_stats.dispatches_per_block is the
            # before/after tentpole metric (round 5: O(txs) ~ one
            # dispatch per round; now O(1))
            sstats = {}
            swap_py, swap_tpu, swap_native = run_workload(
                "swap", min(16, SWAP_BLOCKS), machine_stats=sstats)
            swap_native_tps = _median(swap_native) if swap_native \
                else None
            result.update({
                "swap_txs_s": round(_median(swap_tpu), 1),
                "swap_vs_native": (
                    round(_median(swap_tpu) / swap_native_tps, 3)
                    if swap_native_tps else None),
                "swap_native_txs_s": (round(swap_native_tps, 1)
                                      if swap_native_tps else None),
                "swap_vs_py_host": round(
                    _median(swap_tpu) / _median(swap_py), 2),
                "swap_stats": sstats,
            })
            _section_done("swap")
        else:
            skipped.append("swap")

        _begin_section(0.82)
        if _remaining() > 45:
            # Avalanche-semantics segment (config[4]): atomic ExtData +
            # nativeAssetCall blocks fall back to the exact host path;
            # fallback_fraction records how much of the segment that is
            mixed_py, mixed_tpu, mixed_stats = run_mixed()
            result.update({
                "mixed_txs_s": round(_median(mixed_tpu), 1),
                "mixed_host_exec": mixed_stats.pop("host_exec", {}),
                "mixed_vs_py_host": round(
                    _median(mixed_tpu) / _median(mixed_py), 2),
                "mixed_fallback_fraction": round(
                    mixed_stats["blocks_fallback"]
                    / max(1, mixed_stats["blocks_fallback"]
                          + mixed_stats["blocks_device"]), 3),
                "mixed_phase_split": {
                    k: round(mixed_stats[k], 2)
                    for k in ("t_classify", "t_sender", "t_device",
                              "t_trie", "t_fallback")},
            })
            _section_done("mixed")
        else:
            skipped.append("mixed")

        _begin_section(0.84)
        if _remaining() > 45:
            # streaming ingestion (serve/): sustained-rate p50/p99
            # block latency through the bounded-queue pipeline — the
            # SLO surface, next to the one-shot throughput above
            result["streaming"] = run_streaming()
            _section_done("streaming")
        else:
            skipped.append("streaming")

        _begin_section(0.91)
        if _remaining() > 60:
            # distributed serving (serve/cluster): the 2w-vs-1w
            # scaling ratio, federated per-lane p99 + stage rows, and
            # the injected-kill recovery probe
            result["cluster"] = run_cluster()
            _section_done("cluster")
        else:
            skipped.append("cluster")

        _begin_section(0.93)
        if _remaining() > 30:
            # fault tolerance: demotion counts + recovery latency
            # under canned fault plans (supervisor + quarantine)
            result["faults"] = run_faults()
            _section_done("faults")
        else:
            skipped.append("faults")

        _begin_section(0.945)
        if _remaining() > 30:
            # span tracing: per-stage latency attribution + the
            # traced-vs-untraced overhead ratio (coreth_tpu/obs)
            result["tracing"] = run_tracing()
            _section_done("tracing")
        else:
            skipped.append("tracing")

        _begin_section(0.955)
        if _remaining() > 30:
            # divergence forensics: recorder-armed vs unarmed A/B
            # (>= 0.95 gated) + an injected trip's bundle size/write
            result["forensics"] = run_forensics()
            _section_done("forensics")
        else:
            skipped.append("forensics")

        _begin_section(0.965)
        if _remaining() > 30:
            # flat-state layer: cold-read speedup ratio + checkpoint
            # stamp-vs-export attribution (state/flat)
            result["flat_state"] = run_flat_state()
            _section_done("flat_state")
        else:
            skipped.append("flat_state")

        _begin_section(0.985)
        if _remaining() > 40:
            # single-hot-contract (ISSUE 14): sustained txs/s +
            # vs_native/vs_1dev ratios + load_imbalance — the
            # key-range flat-curve acceptance surface
            result["hot_contract"] = run_hot_contract()
            _section_done("hot_contract")
        else:
            skipped.append("hot_contract")

        _begin_section(0.99)
        if _remaining() > 40:
            result["multichip"] = run_multichip_section()
            _section_done("multichip")
        else:
            skipped.append("multichip")
    except Exception as exc:  # noqa: BLE001 — the JSON line must emit
        result["error"] = f"{type(exc).__name__}: {exc}"
    if skipped:
        result["deadline_skipped"] = skipped
    _WATCHDOG.cancel()
    _emit()


if __name__ == "__main__":
    main()
