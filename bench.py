#!/usr/bin/env python
"""Benchmark: batched TPU replay vs the sequential host processor.

Two workloads:
- transfer (BASELINE config[2] shape): value-transfer chain, the
  reference's core/bench_test.go:45 InsertChain shape, replayed from
  wire bytes with full sender recovery + per-block root validation.
- erc20 (BASELINE config[1] shape): transfer() call spam on the
  workloads/erc20 token — the M2 minimum end-to-end slice: batched
  storage-slot read/modify/write + Transfer logs/bloom + storage-trie
  rehash folded into the account trie, bit-identical roots.

- baseline: the sequential host path (BlockChain.insert_chain — the
  semantic twin of the Go StateProcessor loop; BASELINE.md records why
  the Go reference itself cannot run here).
- measured: coreth_tpu.replay.ReplayEngine.

Prints ONE json line; the primary metric is the transfer workload,
with the erc20 numbers carried as extra fields.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent XLA compile cache: the replay-window kernels compile once per
# machine, not once per bench run (remote compile over the tunnel is slow).
import jax  # noqa: E402

_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", ".jax_cache")
os.makedirs(_cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# Default shape: a 1024-block replay (VERDICT r2 #8 — the bench must
# move toward the 10k-block north star) with 1024 senders and a
# growing account table (half of every block's recipients are fresh
# addresses, ~65k accounts by the end of the chain).
# Recovery split re-measured round 4 on the uncontended host AFTER the
# pipelining changes (256-block sweep, best-of-2): transfer 5639 tps
# @0.8 -> 6666 @0.95; erc20 5090 @0.8 -> 5352 @0.95 (5564 @1.0).  The
# 1-core host is the straggler, so nearly all signatures belong on the
# device ladder; 0.95 keeps a small host share that still overlaps.
os.environ.setdefault("CORETH_RECOVER_SPLIT", "0.95")
N_BLOCKS = int(os.environ.get("BENCH_BLOCKS", "1024"))
TXS_PER_BLOCK = int(os.environ.get("BENCH_TXS", "128"))
# >=64 blocks so the extrapolated py-host denominator is not a ~1s
# noise-dominated sample (round-3 verdict weak #9)
BASELINE_BLOCKS = int(os.environ.get("BENCH_BASELINE_BLOCKS", "64"))
# ~45k avg gas/tx against the 15M Cortina block gas limit caps token
# blocks at ~300 txs; 256 keeps a pow2 batch shape
ERC20_TXS = int(os.environ.get("BENCH_ERC20_TXS", "256"))
ERC20_BASELINE_BLOCKS = int(
    os.environ.get("BENCH_ERC20_BASELINE_BLOCKS", "32"))
_DIR = os.path.dirname(os.path.abspath(__file__))

GWEI = 10**9
N_KEYS = int(os.environ.get("BENCH_KEYS", "1024"))
TOKEN = bytes([0x77]) * 20

# Single-run ratios on this contended 1-core host proved unfalsifiable
# (round-3 recorded 0.29x while reruns gave 1.30x and 2.61x) — every
# timed region now runs BENCH_REPS times and the JSON reports the
# median with min/max spread.
REPS = int(os.environ.get("BENCH_REPS", "3"))


def _median(xs):
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2


def _spread(xs):
    return [round(min(xs), 1), round(max(xs), 1)]


def _txs_per_block(workload):
    return ERC20_TXS if workload == "erc20" else TXS_PER_BLOCK


def _cache_path(workload):
    return os.path.join(
        _DIR, ".bench_cache",
        f"{workload}_{N_BLOCKS}x{_txs_per_block(workload)}k{N_KEYS}.bin")


def _genesis(workload):
    from coreth_tpu.chain import Genesis, GenesisAccount
    from coreth_tpu.params import TEST_CHAIN_CONFIG
    from coreth_tpu.crypto.secp256k1 import priv_to_address
    keys = [0xC0FFEE + i for i in range(N_KEYS)]
    addrs = [priv_to_address(k) for k in keys]
    alloc = {a: GenesisAccount(balance=10**27) for a in addrs}
    if workload == "erc20":
        from coreth_tpu.workloads.erc20 import token_genesis_account
        alloc[TOKEN] = token_genesis_account({a: 10**24 for a in addrs})
    genesis = Genesis(config=TEST_CHAIN_CONFIG, gas_limit=8_000_000,
                      alloc=alloc)
    return genesis, keys, addrs


def build_or_load_chain(workload):
    """Build the chain once, cache the wire bytes (signing + host EVM
    execution dominate chain construction)."""
    from coreth_tpu import rlp
    from coreth_tpu.types import Block
    genesis, keys, addrs = _genesis(workload)
    cache = _cache_path(workload)
    if os.path.exists(cache):
        blob = open(cache, "rb").read()
        blocks = [Block.decode(b) for b in rlp.decode(blob)]
        return genesis, blocks
    from coreth_tpu.chain import generate_chain
    from coreth_tpu.state import Database
    from coreth_tpu.types import DynamicFeeTx, sign_tx
    from coreth_tpu.params import TEST_CHAIN_CONFIG as CFG
    db = Database()
    gblock = genesis.to_block(db)
    nonces = [0] * N_KEYS

    def gen_transfer(i, bg):
        for j in range(TXS_PER_BLOCK):
            n = i * TXS_PER_BLOCK + j
            k = n % N_KEYS
            if j % 2 == 0:
                # fresh recipient: the account table grows all chain
                to = b"\xf0" + n.to_bytes(4, "big") * 4 + b"\xf0" * 3
            else:
                to = bytes([0x10 + (j % 199)]) * 20
            # fee cap above the AP4 max base fee (1000 gwei) so the
            # chain stays valid as sustained load drives the fee up
            bg.add_tx(sign_tx(DynamicFeeTx(
                chain_id_=CFG.chain_id, nonce=nonces[k],
                gas_tip_cap_=GWEI, gas_fee_cap_=2000 * GWEI, gas=21_000,
                to=to, value=10**12 + j,
            ), keys[k], CFG.chain_id))
            nonces[k] += 1

    def gen_erc20(i, bg):
        from coreth_tpu.workloads.erc20 import transfer_calldata
        for j in range(ERC20_TXS):
            k = (i * ERC20_TXS + j) % N_KEYS
            # mix of repeat token holders (SSTORE reset) and a rotating
            # pool of fresh recipients (SSTORE set)
            if j % 3 == 0:
                to = addrs[(k + 1) % N_KEYS]
            else:
                to = (0x5000 + (i * 7 + j) % 1999).to_bytes(2, "big") * 10
            bg.add_tx(sign_tx(DynamicFeeTx(
                chain_id_=CFG.chain_id, nonce=nonces[k],
                gas_tip_cap_=GWEI, gas_fee_cap_=2000 * GWEI, gas=100_000,
                to=TOKEN, value=0, data=transfer_calldata(to, 10 + j),
            ), keys[k], CFG.chain_id))
            nonces[k] += 1

    gen = gen_erc20 if workload == "erc20" else gen_transfer
    # gap=10s: one block per fee window keeps the chain under the AP5
    # gas target so the base fee stays bounded over any chain length
    blocks, _ = generate_chain(CFG, gblock, db, N_BLOCKS, gen, gap=10)
    os.makedirs(os.path.dirname(cache), exist_ok=True)
    with open(cache, "wb") as f:
        f.write(rlp.encode([b.encode() for b in blocks]))
    return genesis, blocks


def run_native_baseline(genesis, wire_blocks):
    """Compiled single-threaded C++ replay (native/baseline.cc) — the
    Go-proxy denominator for the north-star ratio; validates the same
    bit-identical roots.  Python packing below is prep, excluded from
    the timed region (which favors the baseline)."""
    from coreth_tpu.crypto import native
    from coreth_tpu.types import Block, LatestSigner
    blocks = [Block.decode(w) for w in wire_blocks]
    signer = LatestSigner(genesis.config.chain_id)
    recs, offs, roots, cbs = bytearray(), [0], bytearray(), bytearray()
    for b in blocks:
        for tx in b.transactions:
            r, s, recid = tx.inner.raw_signature()
            price = min(tx.gas_fee_cap, b.base_fee + tx.gas_tip_cap)
            fee = 21_000 * price
            required = tx.gas * tx.gas_fee_cap + tx.value
            recs += signer.sig_hash(tx)
            recs += r.to_bytes(32, "big") + s.to_bytes(32, "big") \
                + bytes([recid])
            recs += tx.to
            recs += tx.value.to_bytes(32, "big") + fee.to_bytes(32, "big") \
                + required.to_bytes(32, "big")
            recs += tx.nonce.to_bytes(8, "big")
        offs.append(offs[-1] + len(b.transactions))
        roots += b.root
        cbs += b.header.coinbase
    accounts = b"".join(
        addr + acct.balance.to_bytes(32, "big")
        + acct.nonce.to_bytes(8, "big")
        for addr, acct in genesis.alloc.items())
    txs = sum(len(b.transactions) for b in blocks)
    tps_runs, phases = [], None
    for _ in range(REPS):
        t0 = time.monotonic()
        rc, phases = native.baseline_replay(
            bytes(recs), offs, bytes(roots), bytes(cbs), accounts,
            len(genesis.alloc))
        dt = time.monotonic() - t0
        if rc != 0:
            raise RuntimeError(f"native baseline failed rc={rc}")
        tps_runs.append(txs / dt)
    return tps_runs, {"t_sender": round(phases[0], 3),
                      "t_exec": round(phases[1], 3),
                      "t_trie": round(phases[2], 3)}


def run_baseline(genesis, wire_blocks, n_blocks):
    """Sequential host insert (fresh sender cache) over a block subset."""
    from coreth_tpu.chain import BlockChain
    from coreth_tpu.types import Block
    tps_runs, timers = [], None
    for _ in range(REPS):
        blocks = [Block.decode(w) for w in wire_blocks[:n_blocks]]
        chain = BlockChain(genesis)
        t0 = time.monotonic()
        chain.insert_chain(blocks)
        dt = time.monotonic() - t0
        txs = sum(len(b.transactions) for b in blocks)
        tps_runs.append(txs / dt)
        timers = chain.timers.row()
    return tps_runs, timers


def _fresh_engine(genesis, txs_per_block):
    from coreth_tpu.replay import ReplayEngine
    from coreth_tpu.state import Database
    db = Database()
    gblock = genesis.to_block(db)
    # size the device account table for the workload's growth up front:
    # capacity is a static arg of the compiled window kernels, so
    # in-flight growth would recompile at every pow2 step
    need = N_KEYS + N_BLOCKS * TXS_PER_BLOCK // 2 + 1024
    capacity = 1 << max(14, (need - 1).bit_length())
    return ReplayEngine(genesis.config, db, gblock.root,
                        parent_header=gblock.header,
                        batch_pad=txs_per_block, capacity=capacity,
                        slot_capacity=1 << 14,
                        window=int(os.environ.get("BENCH_WINDOW", "128")))


def run_tpu(genesis, wire_blocks, txs_per_block):
    from coreth_tpu.types import Block

    # Warm-up pass on throwaway blocks/engine: compiles (or cache-loads)
    # every device executable this workload shape needs — the recover
    # kernel bucket, the window scan buckets, the rehash kernel.  XLA
    # compile/load is a per-process one-time cost, excluded from timing
    # exactly like the first-block warm-up the round-1 bench did.
    warm_blocks = [Block.decode(w) for w in wire_blocks]
    warm = _fresh_engine(genesis, txs_per_block)
    warm.replay_block(warm_blocks[0])
    warm.replay(warm_blocks[1:])
    assert warm.root == warm_blocks[-1].header.root
    assert warm.stats.blocks_fallback == 0, warm.stats.row()

    # Timed passes: fresh Block objects (no cached senders), fresh state
    # each rep; compiled executables are shared via the XLA cache.
    tps_runs, stats = [], None
    for _ in range(REPS):
        blocks = [Block.decode(w) for w in wire_blocks]
        engine = _fresh_engine(genesis, txs_per_block)
        engine.replay_block(blocks[0])
        t0 = time.monotonic()
        engine.replay(blocks[1:])
        dt = time.monotonic() - t0
        txs = sum(len(b.transactions) for b in blocks[1:])
        assert engine.root == blocks[-1].header.root
        assert engine.stats.blocks_fallback == 0, engine.stats.row()
        tps_runs.append(txs / dt)
        stats = engine.stats.row()
    return tps_runs, stats


def run_workload(workload, baseline_blocks):
    genesis, blocks = build_or_load_chain(workload)
    wire = [b.encode() for b in blocks]
    base_runs, base_timers = run_baseline(genesis, wire, baseline_blocks)
    native_runs = None
    from coreth_tpu.crypto import native as _native
    if workload == "transfer" and _native.load() is not None:
        native_runs, native_phases = run_native_baseline(genesis, wire)
    tpu_runs, tpu_stats = run_tpu(genesis, wire, _txs_per_block(workload))
    if os.environ.get("BENCH_VERBOSE"):
        print(f"[{workload}] py-host baseline", [round(x) for x in base_runs],
              "txs/s", base_timers, file=sys.stderr)
        if native_runs:
            print(f"[{workload}] native baseline",
                  [round(x) for x in native_runs], "txs/s", native_phases,
                  file=sys.stderr)
        print(f"[{workload}] tpu", [round(x) for x in tpu_runs], "txs/s",
              tpu_stats, file=sys.stderr)
    return base_runs, tpu_runs, native_runs


def main():
    py_runs, tpu_runs, native_runs = run_workload(
        "transfer", BASELINE_BLOCKS)
    erc20_py, erc20_tpu, _ = run_workload("erc20", ERC20_BASELINE_BLOCKS)
    py_tps, tpu_tps = _median(py_runs), _median(tpu_runs)
    native_tps = _median(native_runs) if native_runs else None
    result = {
        "metric": "transfer_replay_throughput",
        "value": round(tpu_tps, 1),
        "unit": "txs/s",
        # primary ratio: median TPU / median compiled sequential C++
        # replay (the Go-proxy baseline, BASELINE.md) — the honest
        # denominator; falls back to the Python host path where the
        # native build is unavailable
        "vs_baseline": round(tpu_tps / (native_tps or py_tps), 2),
        "reps": REPS,
        "tpu_spread_txs_s": _spread(tpu_runs),
        "native_baseline_txs_s":
            round(native_tps, 1) if native_tps else None,
        "native_spread_txs_s": _spread(native_runs) if native_runs else None,
        "vs_py_host": round(tpu_tps / py_tps, 2),
        "erc20_txs_s": round(_median(erc20_tpu), 1),
        "erc20_spread_txs_s": _spread(erc20_tpu),
        "erc20_vs_py_host": round(_median(erc20_tpu) / _median(erc20_py), 2),
        "host": {"cpus": os.cpu_count(),
                 "loadavg": [round(x, 2) for x in os.getloadavg()]},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
